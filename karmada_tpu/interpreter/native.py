"""Default native interpreters (I2) — the reference's built-in per-kind
hooks (pkg/resourceinterpreter/default/native/{replica,revisereplica,
aggregatestatus,reflectstatus,healthy,retain,dependencies}.go), kind for
kind:

  replicas:   Deployment, StatefulSet, Job, Pod
  revise:     Deployment, StatefulSet, Job
  aggregate:  Deployment, Service, Ingress, Job, CronJob, DaemonSet,
              StatefulSet, Pod, PersistentVolume, PersistentVolumeClaim,
              PodDisruptionBudget, HorizontalPodAutoscaler
  reflect:    Deployment, Service, Ingress, Job, DaemonSet, StatefulSet,
              PodDisruptionBudget, HorizontalPodAutoscaler
  health:     Deployment, StatefulSet, ReplicaSet, DaemonSet, Service,
              Ingress, PersistentVolumeClaim, Pod, PodDisruptionBudget
  retain:     Deployment, Pod, Service, ServiceAccount,
              PersistentVolumeClaim, PersistentVolume, Job, Secret
  deps:       Deployment, Job, CronJob, Pod, DaemonSet, StatefulSet,
              Ingress, ServiceImport

The workload aggregations carry the federated-generation protocol: members
report their generation + the `resourcetemplate.karmada.io/generation`
annotation, and the aggregated observedGeneration advances to the template
generation only when EVERY member caught up (aggregatestatus.go:81-87).
"""
from __future__ import annotations

from typing import Callable, Optional

from ..api.unstructured import Unstructured
from ..api.work import AggregatedStatusItem
from .interpreter import (
    HEALTHY,
    KindInterpreter,
    RESOURCE_TEMPLATE_GENERATION_ANNOTATION,
    UNHEALTHY,
    _pod_template_requirements,
)

RETAIN_REPLICAS_LABEL = "resourcetemplate.karmada.io/retain-replicas"


def _statuses(items):
    return [(it.cluster_name, it.status) for it in items if it.status is not None]


def _set_status(template: Unstructured, status: dict) -> Unstructured:
    template.set("status", status)
    return template


def _int(v) -> int:
    return int(v or 0)


def _aggregated_observed_generation(template: Unstructured, items) -> int:
    """aggregatestatus.go:81-87 — member caught up when its own status is
    current (observedGeneration >= generation) AND it runs the latest
    federated revision (resourceTemplateGeneration >= template generation).

    NOTE: deliberately >= like the Go native tier; the thirdparty tier's
    twin (thirdparty._aggregate_observed_generation) uses the == form its
    Lua scripts carry — the reference's own two tiers diverge here."""
    generation = template.metadata.generation or 0
    prev = _int(template.get("status", "observedGeneration", default=0))
    count = 0
    for _, st in _statuses(items):
        if (
            _int(st.get("observedGeneration")) >= _int(st.get("generation"))
            and _int(st.get("resourceTemplateGeneration")) >= generation
        ):
            count += 1
    return generation if count == len(items) else prev


def _sum_aggregate(fields: tuple, observed_generation: bool = True):
    """The workload shape: member counters sum; observedGeneration advances
    via the caught-up count (Deployment/DaemonSet/StatefulSet)."""

    def aggregate(template: Unstructured, items) -> Unstructured:
        status = {f: 0 for f in fields}
        for _, st in _statuses(items):
            for f in fields:
                status[f] += _int(st.get(f))
        if observed_generation:
            status["observedGeneration"] = _aggregated_observed_generation(
                template, items
            )
        return _set_status(template, status)

    return aggregate


def _reflect_fields(fields: tuple, with_generation: bool = True):
    """reflectstatus.go shape: the field subset, plus the member generation
    and the resource-template generation lifted from the annotation."""

    def reflect(obj: Unstructured) -> Optional[dict]:
        observed = obj.get("status") or {}
        status = {f: observed[f] for f in fields if f in observed}
        if with_generation:
            status["generation"] = obj.metadata.generation
            rtg = obj.metadata.annotations.get(
                RESOURCE_TEMPLATE_GENERATION_ANNOTATION
            )
            if rtg is not None:
                try:
                    status["resourceTemplateGeneration"] = int(float(rtg))
                except (TypeError, ValueError):
                    pass
        return status or None

    return reflect


# ---------------------------------------------------------------------------
# replicas / revise
# ---------------------------------------------------------------------------


def _replicas_from(path: tuple, template_path=("spec", "template")):
    def get_replicas(obj: Unstructured):
        v = obj.get(*path)
        replicas = _int(v) if v is not None else 1
        tpl = obj.get(*template_path, default={}) or {}
        pod_spec = tpl.get("spec", {}) or {}
        return replicas, _pod_template_requirements(pod_spec, obj.namespace)

    return get_replicas


def _pod_get_replicas(obj: Unstructured):
    """A bare Pod is one replica carrying its own spec (replica.go)."""
    return 1, _pod_template_requirements(obj.get("spec") or {}, obj.namespace)


def _revise(path: tuple):
    def revise(obj: Unstructured, n: int) -> Unstructured:
        obj.set(*path, n)
        return obj

    return revise


# ---------------------------------------------------------------------------
# health
# ---------------------------------------------------------------------------


def _workload_health(obj: Unstructured) -> str:
    """Deployment/StatefulSet: caught up + fully updated + all updated
    available (healthy.go:47-83)."""
    st = obj.get("status") or {}
    if _int(st.get("observedGeneration")) != obj.metadata.generation:
        return UNHEALTHY
    spec_replicas = obj.get("spec", "replicas")
    if spec_replicas is not None and _int(st.get("updatedReplicas")) < spec_replicas:
        return UNHEALTHY
    if _int(st.get("availableReplicas")) < _int(st.get("updatedReplicas")):
        return UNHEALTHY
    return HEALTHY


def _replicaset_health(obj: Unstructured) -> str:
    st = obj.get("status") or {}
    if _int(st.get("observedGeneration")) != obj.metadata.generation:
        return UNHEALTHY
    spec_replicas = obj.get("spec", "replicas")
    if spec_replicas is not None and _int(st.get("availableReplicas")) < spec_replicas:
        return UNHEALTHY
    return HEALTHY


def _daemonset_health(obj: Unstructured) -> str:
    st = obj.get("status") or {}
    if _int(st.get("observedGeneration")) != obj.metadata.generation:
        return UNHEALTHY
    if _int(st.get("updatedNumberScheduled")) < _int(st.get("desiredNumberScheduled")):
        return UNHEALTHY
    if _int(st.get("numberAvailable")) < _int(st.get("updatedNumberScheduled")):
        return UNHEALTHY
    return HEALTHY


def _lb_ingress_present(obj: Unstructured) -> bool:
    for ing in obj.get("status", "loadBalancer", "ingress", default=[]) or []:
        if ing.get("hostname") or ing.get("ip"):
            return True
    return False


def _service_health(obj: Unstructured) -> str:
    if obj.get("spec", "type") != "LoadBalancer":
        return HEALTHY
    return HEALTHY if _lb_ingress_present(obj) else UNHEALTHY


def _ingress_health(obj: Unstructured) -> str:
    return HEALTHY if _lb_ingress_present(obj) else UNHEALTHY


def _pvc_health(obj: Unstructured) -> str:
    return HEALTHY if obj.get("status", "phase") == "Bound" else UNHEALTHY


def _pod_health(obj: Unstructured) -> str:
    st = obj.get("status") or {}
    if st.get("phase") == "Succeeded":
        return HEALTHY
    if st.get("phase") == "Running":
        for cond in st.get("conditions") or []:
            if cond.get("type") == "Ready" and cond.get("status") == "True":
                return HEALTHY
    return UNHEALTHY


def _pdb_health(obj: Unstructured) -> str:
    st = obj.get("status") or {}
    return (
        HEALTHY
        if _int(st.get("currentHealthy")) >= _int(st.get("desiredHealthy"))
        else UNHEALTHY
    )


def _job_health(obj: Unstructured) -> str:
    for cond in obj.get("status", "conditions", default=[]) or []:
        if cond.get("type") == "Failed" and cond.get("status") == "True":
            return UNHEALTHY
    return HEALTHY


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------


def _lb_aggregate(template: Unstructured, items) -> Unstructured:
    """Service/Ingress: concatenate + dedupe + sort member load-balancer
    ingress entries (aggregatestatus.go:123-192)."""
    if template.kind == "Service" and template.get("spec", "type") != "LoadBalancer":
        return template
    entries = []
    for _, st in _statuses(items):
        entries.extend((st.get("loadBalancer") or {}).get("ingress") or [])
    seen, deduped = set(), []
    for e in entries:
        key = (e.get("ip", ""), e.get("hostname", ""))
        if key not in seen:
            seen.add(key)
            deduped.append(e)
    deduped.sort(key=lambda e: (e.get("ip", ""), e.get("hostname", "")))
    return _set_status(template, {"loadBalancer": {"ingress": deduped}})


def _job_finished(status: dict) -> Optional[str]:
    for cond in status.get("conditions") or []:
        if cond.get("status") == "True" and cond.get("type") in ("Complete", "Failed"):
            return cond["type"]
    return None


def _job_aggregate(template: Unstructured, items) -> Unstructured:
    """helper.ParsingJobStatus (job.go:35-99): sums + earliest start /
    latest completion + Failed/Complete conditions; a finished Job never
    updates again."""
    if _job_finished(template.get("status") or {}) is not None:
        return template
    status: dict = {"active": 0, "succeeded": 0, "failed": 0}
    failed_clusters = []
    successful = 0
    start_time = completion_time = None
    for cluster, st in _statuses(items):
        status["active"] += _int(st.get("active"))
        status["succeeded"] += _int(st.get("succeeded"))
        status["failed"] += _int(st.get("failed"))
        finished = _job_finished(st)
        if finished == "Complete":
            successful += 1
        elif finished == "Failed":
            failed_clusters.append(cluster)
        ts = st.get("startTime")
        if ts is not None and (start_time is None or ts < start_time):
            start_time = ts
        tc = st.get("completionTime")
        if tc is not None and (completion_time is None or completion_time < tc):
            completion_time = tc
    conditions = []
    if failed_clusters:
        conditions.append({
            "type": "Failed", "status": "True", "reason": "JobFailed",
            "message": "Job executed failed in member clusters "
                       + ",".join(failed_clusters),
        })
    if successful == len(items) and successful > 0:
        conditions.append({
            "type": "Complete", "status": "True", "reason": "Completed",
            "message": "Job completed",
        })
        if start_time is not None:
            status["startTime"] = start_time
        if completion_time is not None:
            status["completionTime"] = completion_time
    if conditions:
        status["conditions"] = conditions
    return _set_status(template, status)


def _cronjob_aggregate(template: Unstructured, items) -> Unstructured:
    """Active refs concatenate; schedule/success times take the LATEST
    (aggregatestatus.go:220-259)."""
    active: list = []
    last_schedule = last_successful = None
    for _, st in _statuses(items):
        active.extend(st.get("active") or [])
        ts = st.get("lastScheduleTime")
        if ts is not None and (last_schedule is None or last_schedule < ts):
            last_schedule = ts
        tc = st.get("lastSuccessfulTime")
        if tc is not None and (last_successful is None or last_successful < tc):
            last_successful = tc
    status: dict = {"active": active}
    if last_schedule is not None:
        status["lastScheduleTime"] = last_schedule
    if last_successful is not None:
        status["lastSuccessfulTime"] = last_successful
    return _set_status(template, status)


def _pod_aggregate(template: Unstructured, items) -> Unstructured:
    """Container statuses concatenate; the aggregated phase checks
    Failed → Pending → Running → Succeeded (aggregatestatus.go:384-453; a
    member without status counts as Pending)."""
    if not items:
        return template
    phases = set()
    containers: list = []
    init_containers: list = []
    for it in items:
        st = it.status
        if st is None:
            phases.add("Pending")
            continue
        phases.add(st.get("phase"))
        for cs in st.get("containerStatuses") or []:
            containers.append({"ready": cs.get("ready", False),
                               "state": cs.get("state", {})})
        for cs in st.get("initContainerStatuses") or []:
            init_containers.append({"ready": cs.get("ready", False),
                                    "state": cs.get("state", {})})
    phase = ""
    for candidate in ("Failed", "Pending", "Running", "Succeeded"):
        if candidate in phases:
            phase = candidate
            break
    status: dict = {"phase": phase, "containerStatuses": containers}
    if init_containers:
        status["initContainerStatuses"] = init_containers
    return _set_status(template, status)


def _pv_aggregate(template: Unstructured, items) -> Unstructured:
    """Phase precedence Failed → Pending → Available → Bound → Released
    (aggregatestatus.go:456-507; missing member status counts Pending)."""
    phases = set()
    for it in items:
        if it.status is None:
            phases.add("Pending")
        else:
            phases.add(it.status.get("phase"))
    phase = ""
    for candidate in ("Failed", "Pending", "Available", "Bound", "Released"):
        if candidate in phases:
            phase = candidate
            break
    return _set_status(template, {"phase": phase})


def _pvc_aggregate(template: Unstructured, items) -> Unstructured:
    """Bound unless any member disagrees; Lost short-circuits
    (aggregatestatus.go:509-545)."""
    phase = "Bound"
    for _, st in _statuses(items):
        p = st.get("phase")
        if p == "Lost":
            phase = "Lost"
            break
        if p != "Bound":
            phase = p
    return _set_status(template, {"phase": phase})


def _pdb_aggregate(template: Unstructured, items) -> Unstructured:
    """Counters sum; disruptedPods key by '{cluster}/{pod}'
    (aggregatestatus.go:547-588)."""
    status = {"currentHealthy": 0, "desiredHealthy": 0, "expectedPods": 0,
              "disruptionsAllowed": 0, "disruptedPods": {}}
    for cluster, st in _statuses(items):
        status["currentHealthy"] += _int(st.get("currentHealthy"))
        status["desiredHealthy"] += _int(st.get("desiredHealthy"))
        status["expectedPods"] += _int(st.get("expectedPods"))
        status["disruptionsAllowed"] += _int(st.get("disruptionsAllowed"))
        for pod, t in (st.get("disruptedPods") or {}).items():
            status["disruptedPods"][f"{cluster}/{pod}"] = t
    return _set_status(template, status)


def _hpa_aggregate(template: Unstructured, items) -> Unstructured:
    status = {"currentReplicas": 0, "desiredReplicas": 0}
    for _, st in _statuses(items):
        status["currentReplicas"] += _int(st.get("currentReplicas"))
        status["desiredReplicas"] += _int(st.get("desiredReplicas"))
    return _set_status(template, status)


# ---------------------------------------------------------------------------
# retain
# ---------------------------------------------------------------------------


def _retain_workload_replicas(desired: Unstructured, observed: Unstructured):
    """With the retain-replicas label, member-side replica counts (e.g. an
    HPA's) win over the template's (retain.go:145-163)."""
    if desired.metadata.labels.get(RETAIN_REPLICAS_LABEL) == "true":
        replicas = observed.get("spec", "replicas")
        if replicas is not None:
            desired.set("spec", "replicas", replicas)
    return desired


def _retain_pod_fields(desired: Unstructured, observed: Unstructured):
    """nodeName / serviceAccountName / volumes / per-container volumeMounts
    are member-cluster-managed (retain.go:64-106)."""
    for field in ("nodeName", "serviceAccountName", "volumes"):
        v = observed.get("spec", field)
        if v is not None:
            desired.set("spec", field, v)
    for key in ("containers", "initContainers"):
        observed_cs = {c.get("name"): c for c in observed.get("spec", key, default=[]) or []}
        for c in desired.get("spec", key, default=[]) or []:
            oc = observed_cs.get(c.get("name"))
            if oc is not None and "volumeMounts" in oc:
                c["volumeMounts"] = oc["volumeMounts"]
    return desired


def _retain_service_fields(desired: Unstructured, observed: Unstructured):
    """clusterIP + healthCheckNodePort are member-allocated
    (lifted RetainServiceFields)."""
    hc = observed.get("spec", "healthCheckNodePort")
    if hc:
        desired.set("spec", "healthCheckNodePort", hc)
    cluster_ip = observed.get("spec", "clusterIP")
    if cluster_ip:
        desired.set("spec", "clusterIP", cluster_ip)
    return desired


def _retain_serviceaccount_fields(desired: Unstructured, observed: Unstructured):
    """Merge member-generated token secrets into the desired list
    (lifted RetainServiceAccountFields)."""
    merged = []
    seen = set()
    for s in (desired.get("secrets") or []) + (observed.get("secrets") or []):
        name = s.get("name")
        if name in seen:
            continue
        seen.add(name)
        merged.append(s)
    if merged:
        desired.set("secrets", merged)
    return desired


def _retain_pvc_fields(desired: Unstructured, observed: Unstructured):
    volume_name = observed.get("spec", "volumeName")
    if volume_name:
        desired.set("spec", "volumeName", volume_name)
    return desired


def _retain_pv_fields(desired: Unstructured, observed: Unstructured):
    claim_ref = observed.get("spec", "claimRef")
    if claim_ref is not None:
        desired.set("spec", "claimRef", claim_ref)
    return desired


def _retain_job_selector(desired: Unstructured, observed: Unstructured):
    """Job selector + template labels carry member-generated uids
    (retain.go:120-144)."""
    match = observed.get("spec", "selector", "matchLabels")
    if match is not None:
        desired.set("spec", "selector", "matchLabels", match)
    tpl_labels = observed.get("spec", "template", "metadata", "labels")
    if tpl_labels is not None:
        desired.set("spec", "template", "metadata", "labels", tpl_labels)
    return desired


def _retain_secret_sa_token(desired: Unstructured, observed: Unstructured):
    if desired.get("type") == "kubernetes.io/service-account-token":
        data = observed.get("data")
        if data is not None:
            desired.set("data", data)
    return desired


# ---------------------------------------------------------------------------
# dependencies
# ---------------------------------------------------------------------------


def _pod_template_deps(template_path=("spec", "template")):
    from .thirdparty import _pod_spec_dependencies

    def deps(obj: Unstructured) -> list[dict]:
        tpl = obj.get(*template_path, default={}) or {}
        return _pod_spec_dependencies(tpl.get("spec", {}) or {}, obj.namespace)

    return deps


def _pod_deps(obj: Unstructured) -> list[dict]:
    from .thirdparty import _pod_spec_dependencies

    return _pod_spec_dependencies(obj.get("spec") or {}, obj.namespace)


def _statefulset_deps(obj: Unstructured) -> list[dict]:
    """Pod-template deps minus PVCs that the StatefulSet's own
    volumeClaimTemplates will create (dependencies.go:126-166)."""
    deps = _pod_template_deps()(obj)
    claim_names = {
        (t.get("metadata") or {}).get("name")
        for t in obj.get("spec", "volumeClaimTemplates", default=[]) or []
    }
    return [
        d for d in deps
        if d["kind"] != "PersistentVolumeClaim" or d["name"] not in claim_names
    ]


def _ingress_deps(obj: Unstructured) -> list[dict]:
    return [
        {"apiVersion": "v1", "kind": "Secret", "namespace": obj.namespace,
         "name": tls.get("secretName", "")}
        for tls in obj.get("spec", "tls", default=[]) or []
    ]


def _serviceimport_deps(obj: Unstructured) -> list[dict]:
    """The derived service + its EndpointSlices
    (dependencies.go:190-211; names.GenerateDerivedServiceName)."""
    derived = f"derived-{obj.name}"
    return [
        {"apiVersion": "v1", "kind": "Service", "namespace": obj.namespace,
         "name": derived},
        {"apiVersion": "discovery.k8s.io/v1", "kind": "EndpointSlice",
         "namespace": obj.namespace,
         "labelSelector": {"matchLabels": {
             "kubernetes.io/service-name": derived}}},
    ]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def default_native_tier() -> dict[str, KindInterpreter]:
    deployment_reflect = _reflect_fields((
        "replicas", "updatedReplicas", "readyReplicas", "availableReplicas",
        "unavailableReplicas", "observedGeneration",
    ))
    statefulset_reflect = _reflect_fields((
        "replicas", "readyReplicas", "currentReplicas", "updatedReplicas",
        "availableReplicas", "observedGeneration",
    ))
    return {
        "apps/v1/Deployment": KindInterpreter(
            get_replicas=_replicas_from(("spec", "replicas")),
            revise_replica=_revise(("spec", "replicas")),
            aggregate_status=_sum_aggregate((
                "replicas", "readyReplicas", "updatedReplicas",
                "availableReplicas", "unavailableReplicas",
            )),
            reflect_status=deployment_reflect,
            interpret_health=_workload_health,
            retain=_retain_workload_replicas,
            get_dependencies=_pod_template_deps(),
        ),
        "apps/v1/StatefulSet": KindInterpreter(
            get_replicas=_replicas_from(("spec", "replicas")),
            revise_replica=_revise(("spec", "replicas")),
            aggregate_status=_sum_aggregate((
                "availableReplicas", "currentReplicas", "readyReplicas",
                "replicas", "updatedReplicas",
            )),
            reflect_status=statefulset_reflect,
            interpret_health=_workload_health,
            get_dependencies=_statefulset_deps,
        ),
        "apps/v1/ReplicaSet": KindInterpreter(
            interpret_health=_replicaset_health,
        ),
        "apps/v1/DaemonSet": KindInterpreter(
            aggregate_status=_sum_aggregate((
                "currentNumberScheduled", "desiredNumberScheduled",
                "numberAvailable", "numberMisscheduled", "numberReady",
                "updatedNumberScheduled", "numberUnavailable",
            )),
            reflect_status=_reflect_fields((
                "currentNumberScheduled", "desiredNumberScheduled",
                "numberAvailable", "numberMisscheduled", "numberReady",
                "updatedNumberScheduled", "numberUnavailable",
                "observedGeneration",
            )),
            interpret_health=_daemonset_health,
            get_dependencies=_pod_template_deps(),
        ),
        "batch/v1/Job": KindInterpreter(
            get_replicas=_replicas_from(("spec", "parallelism")),
            revise_replica=_revise(("spec", "parallelism")),
            aggregate_status=_job_aggregate,
            reflect_status=_reflect_fields((
                "active", "succeeded", "failed", "conditions", "startTime",
                "completionTime",
            ), with_generation=False),
            interpret_health=_job_health,
            retain=_retain_job_selector,
            get_dependencies=_pod_template_deps(),
        ),
        "batch/v1/CronJob": KindInterpreter(
            aggregate_status=_cronjob_aggregate,
            get_dependencies=_pod_template_deps(
                ("spec", "jobTemplate", "spec", "template")
            ),
        ),
        "v1/Pod": KindInterpreter(
            get_replicas=_pod_get_replicas,
            aggregate_status=_pod_aggregate,
            interpret_health=_pod_health,
            retain=_retain_pod_fields,
            get_dependencies=_pod_deps,
        ),
        "v1/Service": KindInterpreter(
            aggregate_status=_lb_aggregate,
            reflect_status=lambda obj: (
                {"loadBalancer": obj.get("status", "loadBalancer") or {}}
                if obj.get("spec", "type") == "LoadBalancer"
                else None
            ),
            interpret_health=_service_health,
            retain=_retain_service_fields,
        ),
        "networking.k8s.io/v1/Ingress": KindInterpreter(
            aggregate_status=_lb_aggregate,
            interpret_health=_ingress_health,
            get_dependencies=_ingress_deps,
        ),
        "v1/PersistentVolume": KindInterpreter(
            aggregate_status=_pv_aggregate,
            retain=_retain_pv_fields,
        ),
        "v1/PersistentVolumeClaim": KindInterpreter(
            aggregate_status=_pvc_aggregate,
            interpret_health=_pvc_health,
            retain=_retain_pvc_fields,
        ),
        "v1/ServiceAccount": KindInterpreter(
            retain=_retain_serviceaccount_fields,
        ),
        "v1/Secret": KindInterpreter(
            retain=_retain_secret_sa_token,
        ),
        "policy/v1/PodDisruptionBudget": KindInterpreter(
            aggregate_status=_pdb_aggregate,
            reflect_status=_reflect_fields((
                "currentHealthy", "desiredHealthy", "expectedPods",
                "disruptionsAllowed", "disruptedPods",
            ), with_generation=False),
            interpret_health=_pdb_health,
        ),
        "autoscaling/v2/HorizontalPodAutoscaler": KindInterpreter(
            aggregate_status=_hpa_aggregate,
            reflect_status=_reflect_fields((
                "currentReplicas", "desiredReplicas", "currentMetrics",
            ), with_generation=False),
        ),
        "multicluster.x-k8s.io/v1alpha1/ServiceImport": KindInterpreter(
            get_dependencies=_serviceimport_deps,
        ),
    }

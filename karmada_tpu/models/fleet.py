"""Device-resident fleet state: the TPU reframing of the scheduler's cluster
cache.

The reference deep-copies every Cluster on every schedule attempt
(pkg/scheduler/cache/cache.go:62-77 — O(N) per binding). Here the fleet is
encoded ONCE into dense arrays kept on device; schedule rounds reuse them, and
cluster changes re-encode incrementally. All strings (names, taint keys, label
keys/values, GVKs, topology values) are interned to int32 ids.

Array layout (C clusters, R resources, T max taints, L max labels):
  capacity[C,R]    available = allocatable − allocated − allocating
                   (GeneralEstimator input, estimator/client/general.go:96-114)
  allocatable[C,R]
  alive[C]         Ready condition (cluster_status_controller.go health probe)
  taint_key/value/effect[C,T]   effect codes: 0 none, 1 NoSchedule,
                   2 PreferNoSchedule, 3 NoExecute
  api_ok[C,G]      GVK enablement bitmap (api_enablement.go:52)
  topo[C,4]        provider/region/zone/name ids (spread constraint axes)
  name_id[C]
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..api.cluster import (
    Cluster,
    EFFECT_NO_EXECUTE,
    EFFECT_NO_SCHEDULE,
    EFFECT_PREFER_NO_SCHEDULE,
    cluster_ready,
)
from ..utils.interner import Interner

EFFECT_CODES = {
    "": 0,
    EFFECT_NO_SCHEDULE: 1,
    EFFECT_PREFER_NO_SCHEDULE: 2,
    EFFECT_NO_EXECUTE: 3,
}

# Fixed resource vocabulary; index = column in capacity arrays. Extend via
# FleetEncoder(resources=...). Order matters for encoded batches.
DEFAULT_RESOURCES = ("cpu", "memory", "pods", "ephemeral-storage")

TOPO_PROVIDER, TOPO_REGION, TOPO_ZONE, TOPO_CLUSTER = 0, 1, 2, 3


def to_int_units(resource: str, value: float) -> int:
    """Canonical integer units, mirroring resource.Quantity math in the
    estimators (general.go:180-186): cpu in millicores (MilliValue), all other
    resources in raw integer value. Integer division over these units is what
    gives bit-exact replica estimates."""
    if resource == "cpu":
        return int(round(value * 1000))
    return int(value)


@dataclass
class FleetArrays:
    """Numpy-side encoding; `.device()` uploads to jax."""

    names: list[str]
    name_id: np.ndarray  # i32[C]
    alive: np.ndarray  # bool[C]
    capacity: np.ndarray  # i64[C,R] integer units (cpu milli)
    allocatable: np.ndarray  # i64[C,R]
    has_summary: np.ndarray  # bool[C]
    taint_key: np.ndarray  # i32[C,T]
    taint_value: np.ndarray  # i32[C,T]
    taint_effect: np.ndarray  # i32[C,T]
    api_ok: np.ndarray  # bool[C,G]
    topo: np.ndarray  # i32[C,4]

    @property
    def n_clusters(self) -> int:
        return len(self.names)

    def index_of(self, name: str) -> int:
        return self.names.index(name)


class FleetEncoder:
    """Encodes Cluster objects into FleetArrays with a shared interner.

    The interner and the GVK vocabulary grow monotonically; re-encoding with
    the same encoder keeps ids stable (device caches never need string
    rewrites)."""

    def __init__(
        self,
        resources: Sequence[str] = DEFAULT_RESOURCES,
        max_taints: int = 4,
    ) -> None:
        self.resources = list(resources)
        self.max_taints = max_taints
        self.strings = Interner()
        self.gvks = Interner()

    def gvk_id(self, api_version: str, kind: str) -> int:
        return self.gvks.id(f"{api_version}/{kind}")

    def encode(self, clusters: Sequence[Cluster]) -> FleetArrays:
        C, R = len(clusters), len(self.resources)
        # Size the taint axis to the actual fleet maximum (bucketed to bound
        # jit recompiles) — truncating would silently unfilter tainted clusters.
        widest = max((len(c.spec.taints) for c in clusters), default=0)
        T = self.max_taints
        while T < widest:
            T *= 2
        # Pre-register every GVK so api_ok has stable width this round.
        for c in clusters:
            for en in c.status.api_enablements:
                for kind in en.resources:
                    self.gvk_id(en.group_version, kind)
        G = len(self.gvks)

        names = [c.name for c in clusters]
        name_id = np.array([self.strings.id(n) for n in names], np.int32)
        alive = np.array([cluster_ready(c) for c in clusters], bool)
        capacity = np.zeros((C, R), np.int64)
        allocatable = np.zeros((C, R), np.int64)
        has_summary = np.zeros(C, bool)
        taint_key = np.zeros((C, T), np.int32)
        taint_value = np.zeros((C, T), np.int32)
        taint_effect = np.zeros((C, T), np.int32)
        api_ok = np.zeros((C, G), bool)
        topo = np.zeros((C, 4), np.int32)

        for i, c in enumerate(clusters):
            self._fill_cluster_row(
                i, c, capacity, allocatable, has_summary,
                taint_key, taint_value, taint_effect, api_ok, topo, name_id,
            )

        return FleetArrays(
            names=names,
            name_id=name_id,
            alive=alive,
            capacity=capacity,
            allocatable=allocatable,
            has_summary=has_summary,
            taint_key=taint_key,
            taint_value=taint_value,
            taint_effect=taint_effect,
            api_ok=api_ok,
            topo=topo,
        )

    def _fill_cluster_row(
        self, i: int, c: Cluster, capacity, allocatable, has_summary,
        taint_key, taint_value, taint_effect, api_ok, topo, name_id,
    ) -> None:
        """Write one cluster's encoding into row i of the fleet arrays —
        the single source of truth shared by the full encode() and the
        dirty-column encode_cols() refresh."""
        rs = c.status.resource_summary
        if rs is not None:
            has_summary[i] = True
            for r, rname in enumerate(self.resources):
                alloc = to_int_units(rname, rs.allocatable.get(rname, 0.0))
                used = to_int_units(rname, rs.allocated.get(rname, 0.0))
                pending = to_int_units(rname, rs.allocating.get(rname, 0.0))
                allocatable[i, r] = alloc
                capacity[i, r] = max(alloc - used - pending, 0)
        for t, taint in enumerate(c.spec.taints):
            taint_key[i, t] = self.strings.id(taint.key)
            taint_value[i, t] = self.strings.id(taint.value)
            taint_effect[i, t] = EFFECT_CODES.get(taint.effect, 1)
        for en in c.status.api_enablements:
            for kind in en.resources:
                api_ok[i, self.gvk_id(en.group_version, kind)] = True
        topo[i, TOPO_PROVIDER] = self.strings.id(c.spec.provider)
        topo[i, TOPO_REGION] = self.strings.id(c.spec.region)
        topo[i, TOPO_ZONE] = self.strings.id(c.spec.zone)
        topo[i, TOPO_CLUSTER] = name_id[i]

    def encode_cols(
        self, prev: FleetArrays, clusters: Sequence[Cluster], idx: Sequence[int]
    ) -> Optional[FleetArrays]:
        """Dirty-column re-encode: new FleetArrays sharing `prev`'s layout
        with only the rows in `idx` re-encoded from `clusters`. Returns None
        when the delta does not fit the previous layout — the membership
        changed, a dirty cluster's taints outgrow the taint axis, or it
        enables a GVK outside the encoded vocabulary (api_ok would need a
        new column) — and the caller must run the full encode()."""
        if len(clusters) != prev.n_clusters:
            return None
        T = prev.taint_key.shape[1]
        G = prev.api_ok.shape[1]
        for i in idx:
            c = clusters[i]
            if c.name != prev.names[i]:
                return None
            if len(c.spec.taints) > T:
                return None
            for en in c.status.api_enablements:
                for kind in en.resources:
                    gid = self.gvks.peek(f"{en.group_version}/{kind}")
                    if gid is None or gid >= G:
                        return None
        name_id = prev.name_id
        alive = prev.alive.copy()
        capacity = prev.capacity.copy()
        allocatable = prev.allocatable.copy()
        has_summary = prev.has_summary.copy()
        taint_key = prev.taint_key.copy()
        taint_value = prev.taint_value.copy()
        taint_effect = prev.taint_effect.copy()
        api_ok = prev.api_ok.copy()
        topo = prev.topo.copy()
        for i in idx:
            c = clusters[i]
            alive[i] = cluster_ready(c)
            has_summary[i] = False
            capacity[i] = 0
            allocatable[i] = 0
            taint_key[i] = 0
            taint_value[i] = 0
            taint_effect[i] = 0
            api_ok[i] = False
            self._fill_cluster_row(
                i, c, capacity, allocatable, has_summary,
                taint_key, taint_value, taint_effect, api_ok, topo, name_id,
            )
        return FleetArrays(
            names=prev.names,
            name_id=name_id,
            alive=alive,
            capacity=capacity,
            allocatable=allocatable,
            has_summary=has_summary,
            taint_key=taint_key,
            taint_value=taint_value,
            taint_effect=taint_effect,
            api_ok=api_ok,
            topo=topo,
        )

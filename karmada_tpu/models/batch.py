"""Per-round binding batch encoding: dirty ResourceBindings → dense arrays.

The reference schedules one binding at a time (scheduler.go:375-443); the TPU
build gathers all dirty bindings of a round into one [B,...] batch. String
work (affinity/label selectors, static-weight rule matching) happens here on
host with per-policy dedup; the device sees only ids, masks and integers.

Strategy codes mirror newAssignState's dispatch (core/assignment.go:89-117):
  0 NON_WORKLOAD (spec.replicas <= 0 → all candidates, no counts,
    core/common.go:68-75)
  1 DUPLICATED
  2 STATIC_WEIGHT
  3 DYNAMIC_WEIGHT
  4 AGGREGATED
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..api.policy import (
    DIVISION_PREFERENCE_AGGREGATED,
    DIVISION_PREFERENCE_WEIGHTED,
    Placement,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
)
from ..api.work import ResourceBinding
from ..sched.affinity import AffinityMaskCache, affinity_key
from .fleet import EFFECT_CODES, FleetArrays, FleetEncoder, to_int_units
from ..ops.filters import TOL_OP_EQUAL, TOL_OP_EXISTS

NON_WORKLOAD = 0
DUPLICATED = 1
STATIC_WEIGHT = 2
DYNAMIC_WEIGHT = 3
AGGREGATED = 4


def strategy_code(placement: Optional[Placement], replicas: int) -> int:
    if replicas <= 0:
        return NON_WORKLOAD
    if placement is None or placement.replica_scheduling is None:
        return DUPLICATED
    rs = placement.replica_scheduling
    if rs.replica_scheduling_type == REPLICA_SCHEDULING_DUPLICATED:
        return DUPLICATED
    if rs.replica_scheduling_type == REPLICA_SCHEDULING_DIVIDED:
        if rs.replica_division_preference == DIVISION_PREFERENCE_AGGREGATED:
            return AGGREGATED
        if rs.replica_division_preference == DIVISION_PREFERENCE_WEIGHTED:
            if rs.weight_preference is not None and rs.weight_preference.dynamic_weight:
                return DYNAMIC_WEIGHT
            return STATIC_WEIGHT
    return DUPLICATED


def pow2_bucket(n: int, lo: int = 2) -> int:
    """Smallest power of two >= n, starting at lo — THE jit-cache bucketing
    rule (shared so the policy can't drift between call sites)."""
    b = lo
    while b < n:
        b *= 2
    return b


def shape_bucket(n: int, lo: int = 8) -> int:
    """Smallest pow2/1.5×pow2 lattice point >= n, switching to 1024-multiples
    past 4096 — THE shape-bucketing rule for the batch row axis B and the
    fleet column axis C (sched/core.py pads both to it). The 1.5× midpoints
    cap pad waste at 25% (pure pow2 wastes up to 50%) while the lattice stays
    small enough to bound the jit cache AND to be enumerable by the AOT
    prewarm pass (sched/aot.py); above 4096 the 1024-step keeps waste under
    ~2.5% where the solve volume — O(B·C) — makes pad rows wall-clock."""
    b = lo
    while b < n and b < 4096:
        h = b + b // 2
        if n <= h:
            return h
        b *= 2
    if n <= b:
        return b
    return ((n + 1023) // 1024) * 1024


def shape_floor(cap: int, lo: int = 8) -> int:
    """Largest shape_bucket lattice point <= cap (never below lo) — row caps
    floor to it so every full chunk of a chunked round hits one compiled
    shape."""
    if cap >= 4096:
        return (cap // 1024) * 1024
    b, best = lo, lo
    while b <= cap:
        best = b
        if b + b // 2 <= cap:
            best = b + b // 2
        b *= 2
    return best


def uid_seed(uid: str) -> np.uint64:
    return np.frombuffer(hashlib.blake2b(uid.encode(), digest_size=8).digest(), np.uint64)[0]


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — stateless deterministic tie-break randomness."""
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def tie_matrix(uids: Sequence[str], n_clusters: int) -> np.ndarray:
    """Deterministic replacement for the crypto-rand tie-break
    (binding.go:74-79): per-(binding,cluster) pseudo-random i32 derived from
    the binding UID, independent of batch composition."""
    seeds = np.array([uid_seed(u) for u in uids], np.uint64)[:, None]
    idx = np.arange(1, n_clusters + 1, dtype=np.uint64)[None, :]
    return (_mix64(seeds ^ idx) >> np.uint64(33)).astype(np.int32)


@dataclass
class BindingBatch:
    """Transfer-compact batch: the [B,C] tensors the solve needs are stored
    factored — policy-level tables + per-binding indices + sparse previous/
    eviction entries + a tie seed — and reconstructed ON DEVICE
    (sched.core._schedule_kernel decompression). Host→device traffic per round
    is O(B·K + P·C) instead of O(B·C); at 10k×5k that is ~3 MB instead of
    ~1.3 GB, which is what makes the tunnel-attached TPU viable.

    Dense views (`affinity_ok`, `static_weight`, ...) are materialized lazily
    for the mesh path and tests."""

    keys: list[str]  # namespace/name per row
    uids: list[str]
    # core tensors
    replicas: np.ndarray  # i32[B]
    unknown_request: np.ndarray  # bool[B] request names outside the resource
    #   vocabulary ⇒ estimators must report 0 (missing allocatable key → 0,
    #   general.go:166-169)
    gvk: np.ndarray  # i32[B]
    strategy: np.ndarray  # i32[B]
    fresh: np.ndarray  # bool[B]
    # tolerations, factored like the policy tables: distinct toleration ROWS
    # (key/value/effect/op stacked) in one [T,4,K] table + a per-row index —
    # the dense [B,K]x4 form was >1 MB of host→device upload per flagship
    # round on a ~40 MB/s tunnel link
    tol_tables: np.ndarray  # i32[T,4,K] (row 0 = no tolerations)
    tol_idx: np.ndarray  # i32[B]
    # factored policy tables (deduped across the batch)
    aff_masks: np.ndarray  # bool[P,C] unique affinity masks
    aff_idx: np.ndarray  # i32[B] row → mask row
    weight_tables: np.ndarray  # i64[W,C] unique static-weight tables (row 0 = zeros)
    weight_idx: np.ndarray  # i32[B]
    # sparse previous-placement / eviction entries; column index C = padding
    prev_idx: np.ndarray  # i32[B,Kp]
    prev_rep: np.ndarray  # i32[B,Kp]
    evict_idx: np.ndarray  # i32[B,Ke]
    # tie-break randomness: per-binding seed, expanded on device
    seeds: np.ndarray  # u64[B]
    n_clusters: int = 0
    # deduped request vectors: the [.,C,R] estimator divisions run once per
    # DISTINCT request (policies are few); rows gather via req_idx. The
    # dense [B,R] form is the `request` property.
    req_unique: "np.ndarray | None" = None  # i64[U,R]
    req_idx: "np.ndarray | None" = None  # i32[B]

    @property
    def size(self) -> int:
        return len(self.keys)

    # -- dense views (mesh path, oracle parity tests) ---------------------

    @property
    def request(self) -> np.ndarray:  # i64[B,R]
        if self.req_unique is None or self.req_idx is None:
            raise ValueError(
                "BindingBatch.request needs req_unique/req_idx — hand-built "
                "batches must carry the deduped request tables; use "
                "BatchEncoder.encode() to construct batches"
            )
        return self.req_unique[self.req_idx]

    @property
    def tol_key(self) -> np.ndarray:  # i32[B,K]
        return self.tol_tables[self.tol_idx, 0]

    @property
    def tol_value(self) -> np.ndarray:  # i32[B,K]
        return self.tol_tables[self.tol_idx, 1]

    @property
    def tol_effect(self) -> np.ndarray:  # i32[B,K]
        return self.tol_tables[self.tol_idx, 2]

    @property
    def tol_op(self) -> np.ndarray:  # i32[B,K]
        return self.tol_tables[self.tol_idx, 3]

    @property
    def affinity_ok(self) -> np.ndarray:  # bool[B,C]
        return self.aff_masks[self.aff_idx]

    @property
    def static_weight(self) -> np.ndarray:  # i64[B,C]
        return self.weight_tables[self.weight_idx]

    @property
    def prev_member(self) -> np.ndarray:  # bool[B,C]
        out = np.zeros((len(self.replicas), self.n_clusters), bool)
        rows, cols = np.nonzero(self.prev_idx < self.n_clusters)
        out[rows, self.prev_idx[rows, cols]] = True
        return out

    @property
    def prev_replicas(self) -> np.ndarray:  # i32[B,C]
        out = np.zeros((len(self.replicas), self.n_clusters), np.int32)
        rows, cols = np.nonzero(self.prev_idx < self.n_clusters)
        out[rows, self.prev_idx[rows, cols]] = self.prev_rep[rows, cols]
        return out

    @property
    def eviction_ok(self) -> np.ndarray:  # bool[B,C]
        out = np.ones((len(self.replicas), self.n_clusters), bool)
        rows, cols = np.nonzero(self.evict_idx < self.n_clusters)
        out[rows, self.evict_idx[rows, cols]] = False
        return out

    @property
    def tie(self) -> np.ndarray:  # i32[B,C]
        idx = np.arange(1, self.n_clusters + 1, dtype=np.uint64)[None, :]
        return (_mix64(self.seeds[:, None] ^ idx) >> np.uint64(33)).astype(np.int32)


class BatchEncoder:
    """Encodes bindings against one fleet encoding. Create a new instance
    when the fleet changes (affinity masks depend on cluster labels)."""

    def __init__(self, encoder: FleetEncoder, fleet: FleetArrays, clusters, max_tolerations: int = 6):
        self.encoder = encoder
        self.fleet = fleet
        self.clusters = list(clusters)
        self.max_tolerations = max_tolerations
        self.affinity_cache = AffinityMaskCache(self.clusters)
        self._weight_cache: dict[str, np.ndarray] = {}
        self._cluster_index = {c.name: i for i, c in enumerate(self.clusters)}
        self._res_index = {r: i for i, r in enumerate(encoder.resources)}
        # Persistent interners + per-binding row cache. The reference never
        # re-parses an object per schedule attempt — the informer cache hands
        # the scheduler pre-decoded structs; this cache is that decode step.
        # A row is reused only while (generation, term, replicas) match AND
        # the placement/requirements/resource objects are the SAME objects
        # (`is` — the cache holds strong refs, so ids cannot recycle);
        # store-managed updates replace objects and bump generation, which
        # invalidates naturally. prev/eviction entries and `fresh` are
        # re-read every round (status-driven, cheap).
        self._row_cache: dict[str, tuple] = {}
        # per-call identity memos over policy objects (reassigned fresh at
        # every encode() and cleared at its end — stale ids are never read)
        self._call_aff_memo: dict[int, np.ndarray] = {}
        self._call_weight_memo: dict[int, tuple] = {}
        self._tol_width = max_tolerations
        self._tol_rows: list[np.ndarray] = [
            np.zeros((4, self._tol_width), np.int32)
        ]
        # high-water marks for the content-dependent table axes (sparse
        # prev/evict widths, policy-table row counts): each batch pads to
        # the pow2 bucket of the LARGEST value this encoder has seen, not
        # just this batch's. A per-batch bucket makes the program shape a
        # function of batch COMPOSITION — under the streaming scheduler,
        # where micro-batches are arbitrary queue slices, that axis would
        # wobble (e.g. a batch with vs without a 33-target binding flips
        # Kp 32↔64) and each flip is a fresh XLA compile mid-stream. The
        # marks only grow (bounded by pow2(C) / pow2(P)), convergence is
        # one warm pass, pad entries are never indexed ⇒ decisions are
        # bit-identical either way.
        self._kp_hwm = 0
        self._ke_hwm = 1
        self._pp_hwm = 2
        self._wp_hwm = 2
        self._tol_by_key: dict[bytes, int] = {}
        self._tol_stack: Optional[np.ndarray] = None
        self._req_rows: list[np.ndarray] = []
        self._req_by_key: dict[bytes, int] = {}
        self._req_stack: Optional[np.ndarray] = None

    def _static_weights(self, placement: Optional[Placement]) -> np.ndarray:
        """weight[c] = max over matching rules (division_algorithm.go:40-55);
        0 where no rule matches. The all-zero → all-ones fallback happens on
        device against the *candidate* set."""
        C = len(self.clusters)
        if (
            placement is None
            or placement.replica_scheduling is None
            or placement.replica_scheduling.weight_preference is None
            or not placement.replica_scheduling.weight_preference.static_weight_list
        ):
            return np.zeros(C, np.int64)
        rules = placement.replica_scheduling.weight_preference.static_weight_list
        key = "&".join(f"{affinity_key(r.target_cluster)}#{r.weight}" for r in rules)
        w = self._weight_cache.get(key)
        if w is None:
            w = np.zeros(C, np.int64)
            for r in rules:
                m = self.affinity_cache.mask(r.target_cluster)
                w = np.where(m, np.maximum(w, r.weight), w)
            self._weight_cache[key] = w
        return w

    def active_affinity(self, rb: ResourceBinding, term_index: int = -1):
        """Single affinity, or the term_index-th ordered affinity term
        (scheduler.go:562-625 failover loop)."""
        p = rb.spec.placement
        if p is None:
            return None
        if p.cluster_affinities:
            i = max(term_index, 0)
            return p.cluster_affinities[i].affinity
        return p.cluster_affinity

    # growth caps: the interners/row cache trade memory for encode speed;
    # past these bounds (a pathological churn of distinct policy values)
    # everything is dropped and rebuilt from the live rows of the next
    # encode — a one-round re-encode, not a leak
    MAX_REQ_ROWS = 1024
    MAX_TOL_ROWS = 512

    def _reset_interners(self) -> None:
        self._row_cache.clear()  # cached rows hold req/tol ids → must drop
        self._req_rows = []
        self._req_by_key = {}
        self._req_stack = None
        self._tol_width = self.max_tolerations
        self._tol_rows = [np.zeros((4, self._tol_width), np.int32)]
        self._tol_by_key = {}
        self._tol_stack = None

    def _intern_req(self, req: np.ndarray) -> int:
        key = req.tobytes()
        rid = self._req_by_key.get(key)
        if rid is None:
            rid = len(self._req_rows)
            self._req_rows.append(req)
            self._req_by_key[key] = rid
            self._req_stack = None
        return rid

    def _req_table(self) -> np.ndarray:
        """Request table padded to a pow2 bucket (jit cache bound)."""
        if self._req_stack is None:
            Up = pow2_bucket(max(len(self._req_rows), 1), lo=1)
            tab = np.zeros((Up, len(self.encoder.resources)), np.int64)
            if self._req_rows:
                tab[: len(self._req_rows)] = np.stack(self._req_rows)
            self._req_stack = tab
        return self._req_stack

    def _intern_tol(self, tols) -> int:
        if not tols:
            return 0
        if len(tols) > self._tol_width:
            # widen the whole table (capping would wrongly reject bindings
            # whose matching toleration is dropped); ids stay stable
            w = pow2_bucket(len(tols), lo=self._tol_width)
            self._tol_rows = [
                np.pad(r, [(0, 0), (0, w - self._tol_width)])
                for r in self._tol_rows
            ]
            self._tol_width = w
            self._tol_by_key = {
                r.tobytes(): i for i, r in enumerate(self._tol_rows)
            }
            self._tol_stack = None
        trow = np.zeros((4, self._tol_width), np.int32)
        for k, tol in enumerate(tols):
            trow[0, k] = self.encoder.strings.id(tol.key)
            trow[1, k] = self.encoder.strings.id(tol.value)
            trow[2, k] = EFFECT_CODES.get(tol.effect, 0)
            trow[3, k] = (
                TOL_OP_EXISTS if tol.operator == "Exists" else TOL_OP_EQUAL
            )
        key = trow.tobytes()
        tid = self._tol_by_key.get(key)
        if tid is None:
            tid = len(self._tol_rows)
            self._tol_rows.append(trow)
            self._tol_by_key[key] = tid
            self._tol_stack = None
        return tid

    def _tol_table(self) -> np.ndarray:
        """Toleration table with T padded to a pow2 bucket — tol_tables is a
        traced kernel arg, so an unpadded T would recompile the schedule
        kernel every time one new distinct toleration set appears."""
        if self._tol_stack is None:
            T = len(self._tol_rows)
            Tp = pow2_bucket(T, lo=1)
            tab = np.zeros((Tp, 4, self._tol_width), np.int32)
            tab[:T] = np.stack(self._tol_rows)
            self._tol_stack = tab
        return self._tol_stack

    _DEFAULT_PLACEMENT = Placement()

    def _encode_row(self, rb: ResourceBinding, term: int) -> tuple:
        """Everything about a row that does not change while its
        (generation, placement, requirements, resource) stay the same."""
        meta = rb.metadata
        spec = rb.spec
        uid = meta.uid or meta.key()
        req = np.zeros(len(self.encoder.resources), np.int64)
        unknown = False
        if spec.replica_requirements is not None:
            for rname, val in spec.replica_requirements.resource_request.items():
                r = self._res_index.get(rname)
                if r is None:
                    # outside the vocabulary ⇒ estimators must report 0
                    # (missing allocatable key → 0, general.go:166-169)
                    if to_int_units(rname, val) > 0:
                        unknown = True
                else:
                    req[r] = to_int_units(rname, val)
        placement = spec.placement or self._DEFAULT_PLACEMENT
        # per-CALL identity memos (reset at every encode()): thousands of
        # rows share a handful of policy objects, and within one call the
        # objects cannot change — so the canonical-key string builds run
        # once per distinct object, not once per row. Safe against in-place
        # mutation between rounds (the generation-bump contract): the memo
        # never outlives the call.
        aff = self.active_affinity(rb, term)
        mask = self._call_aff_memo.get(id(aff))
        if mask is None:
            mask = self.affinity_cache.mask(aff)
            self._call_aff_memo[id(aff)] = mask
        went = self._call_weight_memo.get(id(placement))
        if went is None:
            w = self._static_weights(placement)
            if not w.any():
                w = None  # row 0 of the weight table
            self._call_weight_memo[id(placement)] = (w,)
        else:
            (w,) = went
        return (
            meta.key(),
            uid,
            uid_seed(uid),
            self.encoder.gvk_id(spec.resource.api_version, spec.resource.kind),
            strategy_code(spec.placement, spec.replicas),
            unknown,
            self._intern_req(req),
            self._intern_tol(placement.cluster_tolerations),
            mask,
            w,
        )

    def encode(
        self,
        bindings: Sequence[ResourceBinding],
        term_indices: Optional[Sequence[int]] = None,
    ) -> BindingBatch:
        B = len(bindings)
        C = len(self.clusters)

        keys, uids = [], []
        replicas = np.zeros(B, np.int32)
        unknown_request = np.zeros(B, bool)
        gvk = np.zeros(B, np.int32)
        strategy = np.zeros(B, np.int32)
        fresh = np.zeros(B, bool)
        tol_idx = np.zeros(B, np.int32)
        req_idx_arr = np.zeros(B, np.int32)
        seeds = np.zeros(B, np.uint64)

        # factored tables: dedup masks/weights per policy signature (few
        # distinct policies, many bindings); indices per row
        aff_rows: list[np.ndarray] = []
        aff_by_id: dict[int, int] = {}  # id(mask buffer) → table row
        aff_idx = np.zeros(B, np.int32)
        weight_rows: list[np.ndarray] = [np.zeros(C, np.int64)]  # row 0 = zeros
        weight_by_id: dict[int, int] = {}
        weight_idx = np.zeros(B, np.int32)

        prev_lists: list = []
        evict_lists: list = []

        # bound the caches: entries for deleted bindings (and pathological
        # churn of distinct request/toleration values) must not accumulate
        # forever — reset costs one round of re-encode
        if (
            len(self._req_rows) > self.MAX_REQ_ROWS
            or len(self._tol_rows) > self.MAX_TOL_ROWS
        ):
            self._reset_interners()
        elif len(self._row_cache) > max(4 * B, 16384):
            self._row_cache.clear()

        row_cache = self._row_cache
        # fresh per-call memos; id(None) maps the no-affinity case safely
        # (None is immortal and its mask is constant). Cleared again at the
        # end of the call so entries never outlive it.
        self._call_aff_memo = {}
        self._call_weight_memo = {}
        for b, rb in enumerate(bindings):
            meta = rb.metadata
            spec = rb.spec
            term = -1 if term_indices is None else term_indices[b]
            ent = row_cache.get(meta.uid) if meta.uid else None
            if (
                ent is not None
                and ent[0] == meta.generation
                and ent[1] == term
                and ent[2] == spec.replicas
                # strong refs held below ⇒ `is` cannot false-positive on a
                # recycled id; store updates swap objects + bump generation
                and ent[3] is spec.placement
                and ent[4] is spec.replica_requirements
                and ent[5] is spec.resource
            ):
                data = ent[6]
            else:
                data = self._encode_row(rb, term)
                if meta.uid:
                    row_cache[meta.uid] = (
                        meta.generation, term, spec.replicas,
                        spec.placement, spec.replica_requirements,
                        spec.resource, data,
                    )
            key, uid, seed, g, strat, unknown, rid, tid, mask, w = data
            keys.append(key)
            uids.append(uid)
            seeds[b] = seed
            gvk[b] = g
            strategy[b] = strat
            unknown_request[b] = unknown
            req_idx_arr[b] = rid
            tol_idx[b] = tid
            replicas[b] = spec.replicas
            fresh[b] = _reschedule_required(spec, rb.status)

            row = aff_by_id.get(id(mask))
            if row is None:
                row = len(aff_rows)
                aff_rows.append(mask)
                aff_by_id[id(mask)] = row
            aff_idx[b] = row
            if w is None:
                wrow = 0
            else:
                wrow = weight_by_id.get(id(w))
                if wrow is None:
                    wrow = len(weight_rows)
                    weight_rows.append(w)
                    weight_by_id[id(w)] = wrow
            weight_idx[b] = wrow

            # previous placement / eviction entries are status-driven per
            # round — never cached
            prev_lists.append(
                [
                    (i, tc.replicas)
                    for tc in spec.clusters
                    if (i := self._cluster_index.get(tc.name)) is not None
                ]
                if spec.clusters
                else ()
            )
            evict_lists.append(
                [
                    i
                    for task in spec.graceful_eviction_tasks
                    if (i := self._cluster_index.get(task.from_cluster)) is not None
                ]
                if spec.graceful_eviction_tasks
                else ()
            )

        # sparse axes bucketed to powers of two (jit cache bound), floored
        # at the encoder's high-water mark so batch composition cannot
        # shrink (and later re-grow ⇒ recompile) the shape
        self._kp_hwm = Kp = max(
            pow2_bucket(max(map(len, prev_lists), default=0)), self._kp_hwm
        )
        self._ke_hwm = Ke = max(
            pow2_bucket(max(map(len, evict_lists), default=0), lo=1),
            self._ke_hwm,
        )
        prev_idx = np.full((B, Kp), C, np.int32)  # C = drop sentinel
        prev_rep = np.zeros((B, Kp), np.int32)
        evict_idx = np.full((B, Ke), C, np.int32)
        for b in range(B):
            for k, (i, rep) in enumerate(prev_lists[b]):
                prev_idx[b, k] = i
                prev_rep[b, k] = rep
            for k, i in enumerate(evict_lists[b]):
                evict_idx[b, k] = i

        self._call_aff_memo = {}
        self._call_weight_memo = {}
        # policy-table row axes pad to pow2 buckets (lo=2 so the ubiquitous
        # one-policy and two-policy rounds share a shape): aff_masks and
        # weight_tables are traced kernel args, and an unpadded P/W would
        # recompile the round whenever the BATCH COMPOSITION changes — the
        # exact churn the shape-bucket lattice exists to absorb. Pad rows
        # are never indexed (aff_idx/weight_idx point at real rows only).
        aff = np.stack(aff_rows) if aff_rows else np.ones((1, C), bool)
        self._pp_hwm = Pp = max(pow2_bucket(len(aff), lo=2), self._pp_hwm)
        if Pp > len(aff):
            aff = np.pad(aff, [(0, Pp - len(aff)), (0, 0)])
        wt = np.stack(weight_rows)
        self._wp_hwm = Wp = max(pow2_bucket(len(wt), lo=2), self._wp_hwm)
        if Wp > len(wt):
            wt = np.pad(wt, [(0, Wp - len(wt)), (0, 0)])
        return BindingBatch(
            keys=keys,
            uids=uids,
            replicas=replicas,
            unknown_request=unknown_request,
            gvk=gvk,
            strategy=strategy,
            fresh=fresh,
            tol_tables=self._tol_table(),
            tol_idx=tol_idx,
            aff_masks=aff,
            aff_idx=aff_idx,
            weight_tables=wt,
            weight_idx=weight_idx,
            prev_idx=prev_idx,
            prev_rep=prev_rep,
            evict_idx=evict_idx,
            seeds=seeds,
            n_clusters=C,
            req_unique=self._req_table(),
            req_idx=req_idx_arr,
        )


def _reschedule_required(spec, status) -> bool:
    """util.RescheduleRequired: a WorkloadRebalancer stamped
    spec.rescheduleTriggeredAt after the last successful schedule
    (assignment.go:110-115 → Fresh mode)."""
    if spec.reschedule_triggered_at is None:
        return False
    if status.last_scheduled_time is None:
        return True
    return spec.reschedule_triggered_at > status.last_scheduled_time

"""Per-round binding batch encoding: dirty ResourceBindings → dense arrays.

The reference schedules one binding at a time (scheduler.go:375-443); the TPU
build gathers all dirty bindings of a round into one [B,...] batch. String
work (affinity/label selectors, static-weight rule matching) happens here on
host with per-policy dedup; the device sees only ids, masks and integers.

Strategy codes mirror newAssignState's dispatch (core/assignment.go:89-117):
  0 NON_WORKLOAD (spec.replicas <= 0 → all candidates, no counts,
    core/common.go:68-75)
  1 DUPLICATED
  2 STATIC_WEIGHT
  3 DYNAMIC_WEIGHT
  4 AGGREGATED
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from ..api.policy import (
    DIVISION_PREFERENCE_AGGREGATED,
    DIVISION_PREFERENCE_WEIGHTED,
    Placement,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
)
from ..api.work import ResourceBinding
from ..sched.affinity import AffinityMaskCache, affinity_key
from .fleet import EFFECT_CODES, FleetArrays, FleetEncoder, to_int_units
from ..ops.filters import TOL_OP_EQUAL, TOL_OP_EXISTS

NON_WORKLOAD = 0
DUPLICATED = 1
STATIC_WEIGHT = 2
DYNAMIC_WEIGHT = 3
AGGREGATED = 4


def strategy_code(placement: Optional[Placement], replicas: int) -> int:
    if replicas <= 0:
        return NON_WORKLOAD
    if placement is None or placement.replica_scheduling is None:
        return DUPLICATED
    rs = placement.replica_scheduling
    if rs.replica_scheduling_type == REPLICA_SCHEDULING_DUPLICATED:
        return DUPLICATED
    if rs.replica_scheduling_type == REPLICA_SCHEDULING_DIVIDED:
        if rs.replica_division_preference == DIVISION_PREFERENCE_AGGREGATED:
            return AGGREGATED
        if rs.replica_division_preference == DIVISION_PREFERENCE_WEIGHTED:
            if rs.weight_preference is not None and rs.weight_preference.dynamic_weight:
                return DYNAMIC_WEIGHT
            return STATIC_WEIGHT
    return DUPLICATED


def uid_seed(uid: str) -> np.uint64:
    return np.frombuffer(hashlib.blake2b(uid.encode(), digest_size=8).digest(), np.uint64)[0]


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — stateless deterministic tie-break randomness."""
    with np.errstate(over="ignore"):
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def tie_matrix(uids: Sequence[str], n_clusters: int) -> np.ndarray:
    """Deterministic replacement for the crypto-rand tie-break
    (binding.go:74-79): per-(binding,cluster) pseudo-random i32 derived from
    the binding UID, independent of batch composition."""
    seeds = np.array([uid_seed(u) for u in uids], np.uint64)[:, None]
    idx = np.arange(1, n_clusters + 1, dtype=np.uint64)[None, :]
    return (_mix64(seeds ^ idx) >> np.uint64(33)).astype(np.int32)


@dataclass
class BindingBatch:
    keys: list[str]  # namespace/name per row
    uids: list[str]
    # core tensors
    replicas: np.ndarray  # i32[B]
    request: np.ndarray  # i64[B,R] integer units (cpu milli)
    unknown_request: np.ndarray  # bool[B] request names outside the resource
    #   vocabulary ⇒ estimators must report 0 (missing allocatable key → 0,
    #   general.go:166-169)
    gvk: np.ndarray  # i32[B]
    strategy: np.ndarray  # i32[B]
    fresh: np.ndarray  # bool[B]
    # tolerations
    tol_key: np.ndarray  # i32[B,K]
    tol_value: np.ndarray
    tol_effect: np.ndarray
    tol_op: np.ndarray
    # host-evaluated masks / weights
    affinity_ok: np.ndarray  # bool[B,C]
    eviction_ok: np.ndarray  # bool[B,C]
    static_weight: np.ndarray  # i64[B,C]
    prev_member: np.ndarray  # bool[B,C]
    prev_replicas: np.ndarray  # i32[B,C]
    tie: np.ndarray  # i32[B,C]

    @property
    def size(self) -> int:
        return len(self.keys)


class BatchEncoder:
    """Encodes bindings against one fleet encoding. Create a new instance
    when the fleet changes (affinity masks depend on cluster labels)."""

    def __init__(self, encoder: FleetEncoder, fleet: FleetArrays, clusters, max_tolerations: int = 6):
        self.encoder = encoder
        self.fleet = fleet
        self.clusters = list(clusters)
        self.max_tolerations = max_tolerations
        self.affinity_cache = AffinityMaskCache(self.clusters)
        self._weight_cache: dict[str, np.ndarray] = {}
        self._cluster_index = {c.name: i for i, c in enumerate(self.clusters)}

    def _static_weights(self, placement: Optional[Placement]) -> np.ndarray:
        """weight[c] = max over matching rules (division_algorithm.go:40-55);
        0 where no rule matches. The all-zero → all-ones fallback happens on
        device against the *candidate* set."""
        C = len(self.clusters)
        if (
            placement is None
            or placement.replica_scheduling is None
            or placement.replica_scheduling.weight_preference is None
            or not placement.replica_scheduling.weight_preference.static_weight_list
        ):
            return np.zeros(C, np.int64)
        rules = placement.replica_scheduling.weight_preference.static_weight_list
        key = "&".join(f"{affinity_key(r.target_cluster)}#{r.weight}" for r in rules)
        w = self._weight_cache.get(key)
        if w is None:
            w = np.zeros(C, np.int64)
            for r in rules:
                m = self.affinity_cache.mask(r.target_cluster)
                w = np.where(m, np.maximum(w, r.weight), w)
            self._weight_cache[key] = w
        return w

    def active_affinity(self, rb: ResourceBinding, term_index: int = -1):
        """Single affinity, or the term_index-th ordered affinity term
        (scheduler.go:562-625 failover loop)."""
        p = rb.spec.placement
        if p is None:
            return None
        if p.cluster_affinities:
            i = max(term_index, 0)
            return p.cluster_affinities[i].affinity
        return p.cluster_affinity

    def encode(
        self,
        bindings: Sequence[ResourceBinding],
        term_indices: Optional[Sequence[int]] = None,
    ) -> BindingBatch:
        B = len(bindings)
        C = len(self.clusters)
        R = len(self.encoder.resources)
        # Toleration axis sized to the batch maximum (bucketed) — capping
        # would wrongly reject bindings whose matching toleration is dropped.
        widest = max(
            (
                len(b.spec.placement.cluster_tolerations)
                for b in bindings
                if b.spec.placement is not None
            ),
            default=0,
        )
        K = self.max_tolerations
        while K < widest:
            K *= 2

        keys, uids = [], []
        replicas = np.zeros(B, np.int32)
        request = np.zeros((B, R), np.int64)
        unknown_request = np.zeros(B, bool)
        gvk = np.zeros(B, np.int32)
        strategy = np.zeros(B, np.int32)
        fresh = np.zeros(B, bool)
        tol_key = np.zeros((B, K), np.int32)
        tol_value = np.zeros((B, K), np.int32)
        tol_effect = np.zeros((B, K), np.int32)
        tol_op = np.zeros((B, K), np.int32)
        affinity_ok = np.ones((B, C), bool)
        eviction_ok = np.ones((B, C), bool)
        static_weight = np.zeros((B, C), np.int64)
        prev_member = np.zeros((B, C), bool)
        prev_replicas = np.zeros((B, C), np.int32)

        for b, rb in enumerate(bindings):
            keys.append(rb.metadata.key())
            uids.append(rb.metadata.uid or rb.metadata.key())
            spec = rb.spec
            replicas[b] = spec.replicas
            gvk[b] = self.encoder.gvk_id(spec.resource.api_version, spec.resource.kind)
            strategy[b] = strategy_code(spec.placement, spec.replicas)
            fresh[b] = _reschedule_required(spec, rb.status)
            if spec.replica_requirements is not None:
                known = set(self.encoder.resources)
                for rname, val in spec.replica_requirements.resource_request.items():
                    if rname not in known and to_int_units(rname, val) > 0:
                        unknown_request[b] = True
                for r, rname in enumerate(self.encoder.resources):
                    request[b, r] = to_int_units(
                        rname, spec.replica_requirements.resource_request.get(rname, 0.0)
                    )

            placement = spec.placement or Placement()
            for k, tol in enumerate(placement.cluster_tolerations):
                tol_key[b, k] = self.encoder.strings.id(tol.key)
                tol_value[b, k] = self.encoder.strings.id(tol.value)
                tol_effect[b, k] = EFFECT_CODES.get(tol.effect, 0)
                tol_op[b, k] = TOL_OP_EXISTS if tol.operator == "Exists" else TOL_OP_EQUAL

            term = -1 if term_indices is None else term_indices[b]
            affinity_ok[b] = self.affinity_cache.mask(self.active_affinity(rb, term))
            static_weight[b] = self._static_weights(placement)

            for tc in spec.clusters:
                i = self._cluster_index.get(tc.name)
                if i is not None:
                    prev_member[b, i] = True
                    prev_replicas[b, i] = tc.replicas
            for task in spec.graceful_eviction_tasks:
                i = self._cluster_index.get(task.from_cluster)
                if i is not None:
                    eviction_ok[b, i] = False

        return BindingBatch(
            keys=keys,
            uids=uids,
            replicas=replicas,
            request=request,
            unknown_request=unknown_request,
            gvk=gvk,
            strategy=strategy,
            fresh=fresh,
            tol_key=tol_key,
            tol_value=tol_value,
            tol_effect=tol_effect,
            tol_op=tol_op,
            affinity_ok=affinity_ok,
            eviction_ok=eviction_ok,
            static_weight=static_weight,
            prev_member=prev_member,
            prev_replicas=prev_replicas,
            tie=tie_matrix(uids, C),
        )


def _reschedule_required(spec, status) -> bool:
    """util.RescheduleRequired: a WorkloadRebalancer stamped
    spec.rescheduleTriggeredAt after the last successful schedule
    (assignment.go:110-115 → Fresh mode)."""
    if spec.reschedule_triggered_at is None:
        return False
    if status.last_scheduled_time is None:
        return True
    return spec.reschedule_triggered_at > status.last_scheduled_time

"""Node fleet encoding for the accurate estimator.

Counterpart of the estimator server's NodeInfo snapshot
(pkg/util/lifted/scheduler NodeInfo/snapshot, fed by node/pod informers in
server.go:92-193): nodes become dense arrays; pods fold into per-node
requested totals. Node affinity (strings) is evaluated host-side with
per-claim dedup, exactly like cluster affinity masks.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..api.cluster import Taint
from ..api.meta import LabelSelector, LabelSelectorRequirement, Resources
from ..api.policy import Toleration
from ..api.work import NodeClaim
from .fleet import EFFECT_CODES, to_int_units
from ..utils.interner import Interner

NODE_RESOURCES = ("cpu", "memory", "ephemeral-storage", "nvidia.com/gpu")


@dataclass
class NodeSpec:
    name: str
    labels: dict[str, str] = field(default_factory=dict)
    taints: list[Taint] = field(default_factory=list)
    allocatable: Resources = field(default_factory=dict)
    allowed_pods: int = 110


@dataclass
class NodeArrays:
    names: list[str]
    alloc: np.ndarray  # i64[N,R]
    requested: np.ndarray  # i64[N,R] (mutable: pod placement updates it)
    pod_count: np.ndarray  # i64[N]
    allowed_pods: np.ndarray  # i64[N]
    taint_key: np.ndarray  # i32[N,T]
    taint_value: np.ndarray
    taint_effect: np.ndarray
    labels: list[dict[str, str]]

    @property
    def n_nodes(self) -> int:
        return len(self.names)


class NodeEncoder:
    def __init__(self, resources: Sequence[str] = NODE_RESOURCES, strings: Optional[Interner] = None):
        self.resources = list(resources)
        self.strings = strings or Interner()

    def encode(self, nodes: Sequence[NodeSpec], max_taints: int = 2) -> NodeArrays:
        N, R = len(nodes), len(self.resources)
        widest = max((len(n.taints) for n in nodes), default=0)
        T = max_taints
        while T < widest:
            T *= 2
        alloc = np.zeros((N, R), np.int64)
        taint_key = np.zeros((N, T), np.int32)
        taint_value = np.zeros((N, T), np.int32)
        taint_effect = np.zeros((N, T), np.int32)
        allowed = np.zeros(N, np.int64)
        for i, n in enumerate(nodes):
            for r, rname in enumerate(self.resources):
                alloc[i, r] = to_int_units(rname, n.allocatable.get(rname, 0.0))
            allowed[i] = n.allowed_pods
            for t, taint in enumerate(n.taints):
                taint_key[i, t] = self.strings.id(taint.key)
                taint_value[i, t] = self.strings.id(taint.value)
                taint_effect[i, t] = EFFECT_CODES.get(taint.effect, 1)
        return NodeArrays(
            names=[n.name for n in nodes],
            alloc=alloc,
            requested=np.zeros((N, R), np.int64),
            pod_count=np.zeros(N, np.int64),
            allowed_pods=allowed,
            taint_key=taint_key,
            taint_value=taint_value,
            taint_effect=taint_effect,
            labels=[dict(n.labels) for n in nodes],
        )

    def request_vector(self, request: Resources) -> np.ndarray:
        return np.array(
            [to_int_units(r, request.get(r, 0.0)) for r in self.resources], np.int64
        )


def node_claim_matches(claim: Optional[NodeClaim], labels: dict[str, str]) -> bool:
    """NodeSelector + required NodeAffinity label matching
    (nodeutil.IsNodeAffinityMatched in estimate.go:90-92)."""
    if claim is None:
        return True
    for k, v in claim.node_selector.items():
        if labels.get(k) != v:
            return False
    affinity = claim.hard_node_affinity
    if affinity:
        # affinity: list of terms (OR), each a list of match_expressions (AND)
        terms = affinity if isinstance(affinity, list) else [affinity]
        ok = False
        for term in terms:
            sel = LabelSelector(
                match_expressions=[
                    LabelSelectorRequirement(
                        key=e.get("key", ""),
                        operator=e.get("operator", "In"),
                        values=list(e.get("values", [])),
                    )
                    for e in term.get("matchExpressions", [])
                ]
            )
            if sel.matches(labels):
                ok = True
                break
        if not ok:
            return False
    return True


def tolerations_cover_node_taints(
    tolerations: Sequence, taints: Sequence[Taint]
) -> bool:
    """IsTolerationMatched (estimate.go:90-92): NoSchedule/NoExecute node
    taints must be tolerated."""
    tols = [
        t if isinstance(t, Toleration) else Toleration(
            key=t.get("key", ""),
            operator=t.get("operator", "Equal"),
            value=t.get("value", ""),
            effect=t.get("effect", ""),
        )
        for t in tolerations
    ]
    for taint in taints:
        if taint.effect not in ("NoSchedule", "NoExecute"):
            continue
        if not any(t.tolerates(taint) for t in tols):
            return False
    return True

"""In-process PKI for the register/agent bootstrap flow.

Parity surface:
- bootstrap tokens in the kubeadm "<id>.<secret>" format with TTL and
  CA-cert-hash pinning (ref pkg/karmadactl/register/register.go:70-74,
  304-308: token required, CACertHashes verified unless explicitly skipped;
  pkg/karmadactl/cmdinit + util/bootstraptoken issue them);
- CSR signing for the pull-mode agent identity
  ("system:node:<cluster>"-style subject, ref register.go generates a
  karmada-agent cert with O=system:nodes);
- certificate rotation bookkeeping for the agent cert
  (ref pkg/controllers/certificate/cert_rotation_controller.go:56-82 —
  rotate when remaining/total lifetime <= threshold).

EC P-256 keys keep issuance sub-millisecond; certificates are real x509
(cryptography lib) so hashes/expiries behave like production artifacts. The
clock is injectable: token TTL and cert rotation are tested deterministically.
"""
from __future__ import annotations

import datetime
import hashlib
import time
import secrets
import string
from dataclasses import dataclass
from typing import Callable, Optional

_EPOCH = datetime.datetime(1970, 1, 1)

AGENT_ORGANIZATION = "system:nodes"
SIGNER_NAME = "kubernetes.io/kube-apiserver-client-kubelet"  # cert_rotation_controller.go:57


def _now_dt(now_s: float) -> datetime.datetime:
    return _EPOCH + datetime.timedelta(seconds=now_s)


@dataclass
class IssuedCertificate:
    cert_pem: bytes
    key_pem: bytes
    common_name: str
    not_before: float  # seconds (injectable-clock domain)
    not_after: float

    def remaining_ratio(self, now_s: float) -> float:
        total = self.not_after - self.not_before
        if total <= 0:
            return 0.0
        return max(self.not_after - now_s, 0.0) / total


class CertificateAuthority:
    """The control plane's cluster CA (cmdinit generates one; agents trust
    it via the discovery token CA hash)."""

    def __init__(self, common_name: str = "karmada-ca",
                 clock: Optional[Callable[[], float]] = None):
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID

        # default to the wall clock: certificates must satisfy REAL TLS
        # validity checks (the HTTPS hook servers verify against this CA);
        # tests inject a fixed clock for determinism
        self._clock = clock or time.time
        self._key = ec.generate_private_key(ec.SECP256R1())
        now = _now_dt(self._clock())
        name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
        self._cert = (
            x509.CertificateBuilder()
            .subject_name(name)
            .issuer_name(name)
            .public_key(self._key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=3650))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
            .sign(self._key, hashes.SHA256())
        )
        self.ca_pem = self._cert.public_bytes(serialization.Encoding.PEM)

    def cert_hash(self) -> str:
        """kubeadm-style discovery hash: sha256 over the CA's SPKI DER
        ("sha256:<hex>") — what --discovery-token-ca-cert-hash pins."""
        from cryptography.hazmat.primitives import serialization

        spki = self._cert.public_key().public_bytes(
            serialization.Encoding.DER,
            serialization.PublicFormat.SubjectPublicKeyInfo,
        )
        return "sha256:" + hashlib.sha256(spki).hexdigest()

    def sign(
        self,
        common_name: str,
        organizations: tuple[str, ...] = (),
        ttl_seconds: float = 365 * 86400.0,
        dns_names: tuple[str, ...] = (),
    ) -> IssuedCertificate:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID

        key = ec.generate_private_key(ec.SECP256R1())
        now_s = self._clock()
        now = _now_dt(now_s)
        attrs = [x509.NameAttribute(NameOID.COMMON_NAME, common_name)]
        attrs.extend(
            x509.NameAttribute(NameOID.ORGANIZATION_NAME, o) for o in organizations
        )
        builder = (
            x509.CertificateBuilder()
            .subject_name(x509.Name(attrs))
            .issuer_name(self._cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(seconds=ttl_seconds))
        )
        if dns_names:
            # server certs: modern TLS hostname verification requires SANs;
            # IP-literal names must be iPAddress entries (OpenSSL refuses to
            # match an IP peer against a DNSName SAN)
            import ipaddress

            sans = []
            for n in dns_names:
                try:
                    sans.append(x509.IPAddress(ipaddress.ip_address(n)))
                except ValueError:
                    sans.append(x509.DNSName(n))
            builder = builder.add_extension(
                x509.SubjectAlternativeName(sans), critical=False,
            )
        cert = builder.sign(self._key, hashes.SHA256())
        return IssuedCertificate(
            cert_pem=cert.public_bytes(serialization.Encoding.PEM),
            key_pem=key.private_bytes(
                serialization.Encoding.PEM,
                serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption(),
            ),
            common_name=common_name,
            not_before=now_s,
            not_after=now_s + ttl_seconds,
        )


class InvalidToken(Exception):
    pass


_TOKEN_CHARS = string.ascii_lowercase + string.digits


def _rand(n: int) -> str:
    return "".join(secrets.choice(_TOKEN_CHARS) for _ in range(n))


@dataclass
class BootstrapToken:
    token_id: str  # 6 chars, public
    secret: str  # 16 chars
    expires_at: float
    description: str = ""

    @property
    def token(self) -> str:
        return f"{self.token_id}.{self.secret}"


class BootstrapTokens:
    """kubeadm-format bootstrap tokens with TTL (util/bootstraptoken)."""

    DEFAULT_TTL_S = 24 * 3600.0  # cmdinit default: 24h

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.time
        self._tokens: dict[str, BootstrapToken] = {}

    def create(self, ttl_seconds: float = DEFAULT_TTL_S,
               description: str = "") -> BootstrapToken:
        t = BootstrapToken(
            token_id=_rand(6),
            secret=_rand(16),
            expires_at=self._clock() + ttl_seconds,
            description=description,
        )
        self._tokens[t.token_id] = t
        return t

    def list(self) -> list[BootstrapToken]:
        now = self._clock()
        return [t for t in self._tokens.values() if t.expires_at > now]

    def delete(self, token_id: str) -> bool:
        return self._tokens.pop(token_id, None) is not None

    def validate(self, token: str) -> BootstrapToken:
        """Raises InvalidToken on malformed/unknown/expired tokens
        (register.go:304: token is required and must validate)."""
        tid, sep, secret = token.partition(".")
        if not sep or len(tid) != 6 or len(secret) != 16:
            raise InvalidToken("token must be of the form <6 chars>.<16 chars>")
        t = self._tokens.get(tid)
        if t is None or not secrets.compare_digest(t.secret, secret):
            raise InvalidToken("unknown or mismatched bootstrap token")
        if t.expires_at <= self._clock():
            raise InvalidToken("bootstrap token expired")
        return t

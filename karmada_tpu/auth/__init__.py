from .pki import (
    AGENT_ORGANIZATION,
    BootstrapToken,
    BootstrapTokens,
    CertificateAuthority,
    InvalidToken,
    IssuedCertificate,
    SIGNER_NAME,
)

__all__ = [
    "AGENT_ORGANIZATION",
    "BootstrapToken",
    "BootstrapTokens",
    "CertificateAuthority",
    "InvalidToken",
    "IssuedCertificate",
    "SIGNER_NAME",
]

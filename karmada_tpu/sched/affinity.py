"""Host-side cluster affinity evaluation (string programs).

Equivalent of util.ClusterMatches as used by the ClusterAffinity plugin
(cluster_affinity.go:51-80) and static-weight rule matching
(division_algorithm.go getStaticWeightInfoList → util.ClusterMatches):
exclude list, clusterNames, labelSelector, fieldSelector (provider/region/zone
In/NotIn). Affinity masks are evaluated once per *unique* affinity per round
(policies are shared by many bindings) and handed to the device pipeline as
bool[B,C] — strings never reach the device.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..api.cluster import Cluster
from ..api.policy import ClusterAffinity, FieldSelector


def field_selector_matches(fs: Optional[FieldSelector], cluster: Cluster) -> bool:
    if fs is None:
        return True
    fields = {
        "provider": cluster.spec.provider,
        "region": cluster.spec.region,
        "zone": cluster.spec.zone,
    }
    for req in fs.match_expressions:
        val = fields.get(req.key, "")
        if req.operator == "In":
            if val not in req.values:
                return False
        elif req.operator == "NotIn":
            if val in req.values:
                return False
        else:
            raise ValueError(f"unsupported field selector operator {req.operator!r}")
    return True


def cluster_matches(cluster: Cluster, affinity: Optional[ClusterAffinity]) -> bool:
    """util.ClusterMatches: exclude wins; then clusterNames (if set), label
    selector, field selector must all hold."""
    if affinity is None:
        return True
    if cluster.name in affinity.exclude:
        return False
    if affinity.cluster_names and cluster.name not in affinity.cluster_names:
        return False
    if affinity.label_selector is not None and not affinity.label_selector.matches(
        cluster.metadata.labels
    ):
        return False
    if not field_selector_matches(affinity.field_selector, cluster):
        return False
    return True


def affinity_key(affinity: Optional[ClusterAffinity]) -> str:
    """Canonical dedup key: bindings sharing a policy share the mask."""
    if affinity is None:
        return "<all>"
    parts = [
        ",".join(sorted(affinity.cluster_names)),
        ",".join(sorted(affinity.exclude)),
    ]
    if affinity.label_selector is not None:
        ls = affinity.label_selector
        parts.append(";".join(f"{k}={v}" for k, v in sorted(ls.match_labels.items())))
        parts.append(
            ";".join(
                f"{r.key} {r.operator} [{','.join(sorted(r.values))}]"
                for r in ls.match_expressions
            )
        )
    if affinity.field_selector is not None:
        parts.append(
            ";".join(
                f"{r.key} {r.operator} [{','.join(sorted(r.values))}]"
                for r in affinity.field_selector.match_expressions
            )
        )
    return "|".join(parts)


class AffinityMaskCache:
    """Evaluates affinity → bool[C] masks with dedup across bindings.
    Invalidate on any cluster change (encoder re-encode)."""

    def __init__(self, clusters: Sequence[Cluster]):
        self.clusters = list(clusters)
        self._cache: dict[str, np.ndarray] = {}

    def mask(self, affinity: Optional[ClusterAffinity]) -> np.ndarray:
        key = affinity_key(affinity)
        m = self._cache.get(key)
        if m is None:
            m = np.array([cluster_matches(c, affinity) for c in self.clusters], bool)
            self._cache[key] = m
        return m

"""Sharded scheduler plane: N concurrent streaming leaders over disjoint
binding slices, with cross-shard gang commit (docs/SCHEDULING.md).

- shardmap: deterministic rendezvous hash of binding ns/uid onto shard
  slots — bounded movement on resize, no assignment state to replicate.
- daemon: ShardedDaemon (a SchedulerDaemon that owns only its slice) and
  ShardPlane (the in-process host running one leader stack per shard).
- gangs: the cross-shard all-or-nothing commit protocol over
  ShardGangProposal objects.
- fairness: the shared per-cluster estimator concurrency budget.
"""
from .shardmap import ShardMap, shard_of, shard_of_binding, shard_of_gang  # noqa: F401
from .daemon import ShardedDaemon, ShardPlane  # noqa: F401
from .fairness import ClusterFairnessBudget  # noqa: F401

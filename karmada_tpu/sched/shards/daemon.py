"""ShardedDaemon + ShardPlane: N concurrent streaming leaders, one slice each.

`ShardedDaemon` is a SchedulerDaemon whose ownership predicate is the
rendezvous shard map: it admits (and therefore solves and patches) only
the bindings whose ns/uid hashes to its slot. Everything else — the solve,
the prewarm lattice, the micro-batch pipeline, the patch path — is the
parent's machinery untouched; sharding changes WHICH keys admit, never how
they schedule. Gang cohorts route through the cross-shard commit protocol
(gangs.py) instead of the local coordinator whenever more than one shard
exists.

Handoff discipline (the exactly-once story, pinned by tests/test_shards.py):

- The shard map swap is atomic (plain attribute assignment); from that
  instant the admission gate — which `_patch_result` re-checks under the
  store's serialization — answers with the NEW map. A losing shard's
  in-flight decision that reaches the writer after the swap re-gates to
  "drop" and vetoes; one that committed before the swap is a normal
  placement the gaining shard observes as clean. There is no interleaving
  in which two shards both patch the same binding for one admission epoch.
- The losing side additionally FENCES the moving keyspace (admission
  epoch bump per moved key — any decision still mid-pipeline discards at
  the epoch check) and forgets the keys' queue bookkeeping; the gaining
  side re-admits level-triggered from a store re-list.
- Across processes the same argument holds with the lease fencing token
  in place of the in-process gate: a deposed shard leader's batch writes
  bounce on the store's fence (PR-10), and its successor re-lists.

`ShardPlane` hosts one full leader stack per shard in a single process —
daemon + StreamingScheduler + per-shard elector on the
`karmada-sched-shard-<i>` lease — which is the bench/test topology and a
legitimate single-box deployment (the per-process topology runs one
`python -m karmada_tpu.sched --scheduler-shards N --shard-index i` per
slot instead). The plane owns the shared cross-shard estimator fairness
budget and the shard status objects `karmadactl get shards` renders.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ...api.meta import ObjectMeta
from ...api.sharding import (
    KIND_SCHEDULER_SHARD,
    SHARD_NAMESPACE,
    SchedulerShard,
    ShardStatus,
    shard_lease_name,
    shard_object_name,
)
from ...metrics import shard_bindings, shard_handoffs, shard_queue_depth
from ...store.store import DELETED, MODIFIED, ConflictError
from ..scheduler import SchedulerDaemon
from .fairness import ClusterFairnessBudget
from .gangs import CrossShardGangs
from .shardmap import ShardMap, shard_of_binding

log = logging.getLogger(__name__)

# shard-status publish throttle: transitions publish immediately; the
# steady-state refresh rides the serve loop's idle tick at most this often
_STATUS_INTERVAL = 0.5


class ShardedDaemon(SchedulerDaemon):
    """A SchedulerDaemon that owns one rendezvous shard of the binding
    keyspace. Construct with the slot coordinates; every other argument
    passes through to SchedulerDaemon."""

    def __init__(self, store, runtime, shard_index: int, shards_total: int,
                 **kwargs) -> None:
        # the map must exist BEFORE super().__init__: the parent's watch
        # subscription replays the store through _on_binding, which gates
        # on _owns immediately
        self.shards = ShardMap(shard_index, shards_total)
        # owned-keyspace index (key -> True), maintained by _on_binding:
        # the O(1) source for the shard_bindings gauge and the status view
        self._owned: dict[str, bool] = {}
        super().__init__(store, runtime, **kwargs)
        self.shard_id = str(shard_index)
        self.xshards = CrossShardGangs(self)
        self._status_stamp = 0.0
        self._handoff_state = ""
        self._last_solve_time = 0.0

    # -- ownership ---------------------------------------------------------

    def _owns(self, rb) -> bool:
        return self.shards.mine(rb)

    def _gang_holds(self, rb) -> str:
        # cross-shard cohorts cannot assemble in one queue: members admit
        # as solo rows; gangs.py supplies the all-or-nothing commit
        if self.shards.total > 1:
            return ""
        return self._gang_of(rb)

    def _patch_gang(self, gname: str, items):
        if self.shards.total <= 1:
            return super()._patch_gang(gname, items)
        # publish this shard's solved members; the coordinator commits.
        # False = "not patched here": streaming keeps the admission
        # stretch pending until the coordinator's outcome lands
        self.xshards.publish(gname, items)
        return [False] * len(items)

    def _patch_results(self, items, gang_sink=None):
        self._last_solve_time = self.clock.now()
        return super()._patch_results(items, gang_sink=gang_sink)

    def _on_binding(self, event: str, rb) -> None:
        key = rb.metadata.key()
        if event == DELETED or rb.metadata.deletion_timestamp is not None:
            self._owned.pop(key, None)
        elif self._owns(rb):
            self._owned[key] = True
        else:
            self._owned.pop(key, None)
        super()._on_binding(event, rb)

    def owned_count(self) -> int:
        return len(self._owned)

    # -- handoff -----------------------------------------------------------

    def _list_bindings_retried(self):
        """The resize/relist sweep over the wire: a map-resize or leader
        takeover that died on ONE transient store error would strand its
        slice of the keyspace un-readmitted (the soak's shard-kill wave
        hits exactly this against a faulted apiserver). Transport errors
        retry under full jitter; anything else is terminal and escapes
        immediately."""
        from ...faults.policy import RetryPolicy

        def transient(e: Exception) -> bool:
            from ...server.remote import RemoteError
            from ...store.store import ConflictError, NotFoundError

            return isinstance(e, RemoteError) and not isinstance(
                e, (ConflictError, NotFoundError))

        policy = RetryPolicy(base_delay=0.1, max_delay=2.0,
                             max_attempts=6, deadline=20.0)
        return policy.run(
            lambda: self.store.list("ResourceBinding"), transient)

    def set_total(self, new_total: int, reason: str = "resize") -> int:
        """Resize the shard map in place. The swap is atomic; the moved
        keyspace is fenced off the losing side (epoch bump + queue forget)
        and re-admitted level-triggered on the gaining side. Returns the
        number of bindings that moved relative to this slot."""
        old = self.shards
        if new_total == old.total:
            return 0
        if old.index >= new_total:
            raise ValueError(
                f"shard {old.index} does not exist at total={new_total}; "
                f"retire the stack instead of resizing it")
        new = ShardMap(old.index, new_total)
        self._handoff_state = "draining" if new_total < old.total \
            else "absorbing"
        self.shards = new  # the gate answers with the new map from here on
        moved = 0
        try:
            for rb in self._list_bindings_retried():
                was = shard_of_binding(rb, old.total) == old.index
                now = new.mine(rb)
                if was == now:
                    continue
                moved += 1
                key = rb.metadata.key()
                if was:
                    # losing: fence any in-flight decision (epoch bump) and
                    # drop the queue's per-key bookkeeping; the gaining
                    # shard owns the key's future
                    self._owned.pop(key, None)
                    if self.admission.enabled:
                        self.admission.invalidate(key)
                    self.controller.queue.forget(key)
                else:
                    # gaining: level-triggered re-admission through the
                    # ordinary event path (notes the epoch, enqueues)
                    self._on_binding(MODIFIED, rb)
        finally:
            # even a retry-exhausted sweep must not leave the daemon
            # claiming a handoff is still in flight
            self._handoff_state = ""
        if moved:
            shard_handoffs.inc(float(moved), reason=reason)
        return moved

    def relist(self) -> int:
        """Leader-takeover re-admission: enqueue every owned binding
        level-triggered, so work the deposed leader had in flight (whose
        patches the fence bounced) re-places under this leader. Counted
        as a takeover handoff."""
        n = 0
        for rb in self._list_bindings_retried():
            if rb.metadata.deletion_timestamp is None and self._owns(rb):
                self._on_binding(MODIFIED, rb)
                n += 1
        if n:
            shard_handoffs.inc(float(n), reason="takeover")
        return n

    # -- status surface ----------------------------------------------------

    def publish_status(self, leader: str = "", token: int = 0,
                       force: bool = False) -> None:
        """Write (or refresh) this shard's SchedulerShard object — the
        `karmadactl get shards` row — and its gauge series. Throttled;
        transitions pass force=True."""
        now = time.monotonic()
        if not force and now - self._status_stamp < _STATUS_INTERVAL:
            return
        self._status_stamp = now
        depth = len(self.controller.queue)
        owned = self.owned_count()
        shard_bindings.set(float(owned), shard=self.shard_id)
        shard_queue_depth.set(float(depth), shard=self.shard_id)
        status = ShardStatus(
            leader=leader,
            fencing_token=token,
            epoch=self.admission.last_epoch(),
            queue_depth=depth,
            bindings=owned,
            last_solve_time=getattr(self, "_last_solve_time", 0.0),
            handoff=self._handoff_state,
            shards_total=self.shards.total,
        )
        name = shard_object_name(self.shards.index)
        try:
            cur = self.store.try_get(KIND_SCHEDULER_SHARD, name,
                                     SHARD_NAMESPACE)
            if cur is None:
                self.store.create(SchedulerShard(
                    metadata=ObjectMeta(name=name, namespace=SHARD_NAMESPACE),
                    status=status,
                ))
            else:
                cur.status = status
                self.store.update(cur)
        except ConflictError:
            pass  # a sibling published concurrently; next tick wins
        except Exception:  # noqa: BLE001 - status is best-effort
            log.exception("shard %s status publish", self.shard_id)

    def retire_status(self) -> None:
        """Remove the shard's gauge rows and status object (a retired
        shard must not leave stale series behind)."""
        shard_bindings.remove(shard=self.shard_id)
        shard_queue_depth.remove(shard=self.shard_id)
        try:
            self.store.delete(KIND_SCHEDULER_SHARD,
                              shard_object_name(self.shards.index),
                              SHARD_NAMESPACE)
        except Exception:  # noqa: BLE001 - already gone is fine
            pass

    def detach(self) -> None:
        """Unsubscribe the daemon's watches and stop the cross-shard
        worker (plane shutdown / stack retirement)."""
        self.xshards.detach()
        try:
            self.store.unwatch("ResourceBinding", self._on_binding)
            self.store.unwatch("Cluster", self._on_cluster)
        except Exception:  # noqa: BLE001 - double-detach is fine
            pass


class _ShardStack:
    """One shard's full leader stack inside a ShardPlane: daemon +
    streaming service + elector + serve thread."""

    def __init__(self, plane: "ShardPlane", index: int) -> None:
        from ...coordination.elector import Elector
        from ...coordination.lease import LeaseCoordinator
        from ...runtime.controller import Runtime

        self.plane = plane
        self.index = index
        self.runtime = Runtime(plane.clock)
        self.daemon = ShardedDaemon(
            plane.store, self.runtime, index, plane.total,
            scheduler_name=plane.scheduler_name,
            estimator_registry=plane.registry_factory(index)
            if plane.registry_factory else None,
            gates=plane.gates,
            gang_wait_seconds=plane.gang_wait_seconds,
            aot_prewarm=plane.aot_prewarm,
        )
        reg = self.daemon.estimator_registry
        if reg is not None:
            # the shared budget: every shard's per-cluster estimator legs
            # draw from ONE pool per member cluster
            for est in getattr(reg, "replica_estimators", {}).values():
                if hasattr(est, "fairness"):
                    est.fairness = plane.fairness
        self.service = self.daemon.streaming(**plane.streaming_kwargs)
        self.leading = threading.Event()
        self.stop_evt = threading.Event()
        self.token = 0
        self.elector: Optional[object] = None
        if plane.elect:
            from ...coordination.elector import LocalLeaseClient

            if hasattr(plane.store, "acquire_lease"):
                # the store already speaks the lease-client protocol
                # (RemoteStore in the daemon deployment shape): elections go
                # through the apiserver's lease routes, same as sched
                # __main__ — NOT raw object CAS against a remote store
                lease_client = plane.store
            else:
                coordinator = LeaseCoordinator(plane.store, clock=plane.clock)
                lease_client = LocalLeaseClient(coordinator)
            self.elector = Elector(
                lease_client,
                shard_lease_name(index),
                f"{plane.identity}-s{index}",
                lease_duration=plane.lease_duration,
                on_started_leading=self._started,
                on_stopped_leading=self._stopped,
            )
        self.thread = threading.Thread(
            target=self._run, name=f"shard-serve-{index}", daemon=True
        )

    def _started(self, token: int) -> None:
        self.token = token
        self.daemon.abandon_prewarm()
        self.daemon.xshards.start()
        self.daemon.relist()
        self.leading.set()
        self.daemon.publish_status(
            leader=self.elector.identity if self.elector else "local",
            token=token, force=True,
        )

    def _stopped(self, reason: str) -> None:
        self.leading.clear()
        self.token = 0
        self.daemon.xshards.stop()
        self.daemon.publish_status(force=True)

    def start(self) -> None:
        if self.elector is not None:
            self.elector.step()
            self.elector.run()
        else:
            self._started(0)
        self.thread.start()

    def _run(self) -> None:
        while not self.stop_evt.is_set():
            if self.leading.is_set():
                try:
                    self.service.serve(
                        should_stop=lambda: (
                            not self.leading.is_set()
                            or self.stop_evt.is_set()
                        ),
                        idle=self._idle,
                    )
                except Exception:  # noqa: BLE001 - survive transients
                    log.exception("shard %d streaming service", self.index)
                    self.stop_evt.wait(0.2)
            else:
                self.stop_evt.wait(0.05)

    def _idle(self) -> None:
        self.daemon.publish_status(
            leader=self.elector.identity if self.elector else "local",
            token=self.token,
        )

    def stop(self, retire: bool = False) -> None:
        self.stop_evt.set()
        self.leading.clear()
        self.service.stop()
        if self.elector is not None:
            self.elector.stop(release=True)
        self.thread.join(timeout=10.0)
        self.daemon.xshards.stop()
        if retire:
            self.daemon.retire_status()
        self.daemon.detach()


class ShardPlane:
    """The in-process host: one _ShardStack per shard slot over a shared
    store. `resize()` re-maps the keyspace through the handoff fence;
    shrinking retires the dropped slots (status objects deleted, gauge
    rows removed)."""

    def __init__(
        self,
        store,
        total: int,
        *,
        clock=None,
        scheduler_name: str = "default-scheduler",
        registry_factory=None,  # index -> EstimatorRegistry (per shard)
        gates=None,
        gang_wait_seconds: Optional[float] = None,
        aot_prewarm: bool = False,
        elect: bool = True,
        lease_duration: float = 5.0,
        identity: str = "shardplane",
        fairness_limit: int = 4,
        **streaming_kwargs,
    ) -> None:
        if total < 1:
            raise ValueError("shard total must be >= 1")
        from ...runtime.controller import Clock

        self.store = store
        self.total = total
        self.clock = clock or Clock()
        self.scheduler_name = scheduler_name
        self.registry_factory = registry_factory
        self.gates = gates
        self.gang_wait_seconds = gang_wait_seconds
        self.aot_prewarm = aot_prewarm
        self.elect = elect
        self.lease_duration = lease_duration
        self.identity = identity
        self.fairness = ClusterFairnessBudget(fairness_limit)
        self.streaming_kwargs = streaming_kwargs
        self.stacks: list[_ShardStack] = [
            _ShardStack(self, i) for i in range(total)
        ]

    def start(self) -> None:
        for s in self.stacks:
            s.start()

    def wait_leading(self, timeout: float = 10.0) -> bool:
        """Block until every shard has a leader (bench/test setup)."""
        deadline = time.monotonic() + timeout
        for s in self.stacks:
            if not s.leading.wait(max(0.0, deadline - time.monotonic())):
                return False
        return True

    def resize(self, new_total: int) -> int:
        """Change the shard count in place. Every surviving stack swaps
        its map (fencing + re-admitting its side of the moved keyspace);
        new slots spin up cold and retired slots drain out. Returns total
        keyspace movement observed across surviving shards."""
        if new_total < 1:
            raise ValueError("shard total must be >= 1")
        old_total = self.total
        if new_total == old_total:
            return 0
        moved = 0
        if new_total < old_total:
            # retiring slots first: their keys re-admit on the survivors
            # (whose maps still cover them) only after the swap below, so
            # stop the leaders before any survivor claims the keyspace
            for s in self.stacks[new_total:]:
                s.stop(retire=True)
            self.stacks = self.stacks[:new_total]
        self.total = new_total
        for s in self.stacks:
            moved += s.daemon.set_total(new_total)
        if new_total > old_total:
            for i in range(old_total, new_total):
                stack = _ShardStack(self, i)
                self.stacks.append(stack)
                stack.start()
        return moved

    def quiesce(self, timeout: float = 60.0) -> bool:
        """Wait until every shard's queue is empty and nothing is
        mid-pipeline (the bench's drain barrier). Also drives the
        cross-shard gang coordinators so cohorts resolve."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            busy = False
            for s in self.stacks:
                q = s.daemon.controller.queue
                snap = s.service.stats_snapshot()
                if len(q) or snap["formed"] != snap["batches"]:
                    busy = True
            if not busy:
                return True
            time.sleep(0.01)
        return False

    def stats(self) -> dict:
        out = {}
        for s in self.stacks:
            out[s.index] = s.service.stats_snapshot()
        return out

    def close(self) -> None:
        for s in self.stacks:
            s.stop(retire=True)

"""Cross-shard gang commit: PR-13 all-or-nothing semantics across shards.

A gang's members hash to shards independently (the shard map keys on
ns/uid), so a cohort generally spans several shards and no single shard's
local GangCoordinator can assemble it. The sharded daemon therefore holds
NOTHING locally (`_gang_holds` returns ""): gang rows admit and solve like
solo rows, and instead of committing, each member shard PUBLISHES its
solved members as entries on its own `ShardGangProposal` object — one
object per (gang, shard), so entry writes never contend across shards.

The gang's deterministic COORDINATOR shard (shardmap.shard_of_gang)
assembles entries until the cohort is complete, then commits every member
in ONE rv-checked `update_batch`:

- every member is re-read fresh; a missing member, or a member whose
  resource_version moved past the entry's `solved_rv`, VETOES the whole
  gang (outcome `aborted`) — the spec a shard solved against is no longer
  the spec in the store. The rv fence subsumes the per-shard epoch fence
  here: an epoch bump is always a store write, and a store write always
  moves the rv.
- a member that solved infeasible (or short of its full replica count)
  makes the gang jointly infeasible (outcome `rejected`): Scheduled=False
  conditions, exactly the local `_reject_gang` disposition.
- a cohort that never completes within the gang wait window times out
  (outcome `timeout`).

The coordinator stamps the outcome on every shard's proposal object;
member shards react to that watch event — re-admit their members UNCHARGED
on abort (queue `readd`: no retry charge, cached priority), settle on the
terminal outcomes — and the coordinator then deletes the proposals. The
binding store never holds a partial gang: nothing writes placements except
the coordinator's single fenced batch.
"""
from __future__ import annotations

import logging
import threading
from typing import Optional

from ...api.meta import ObjectMeta
from ...api.sharding import (
    KIND_SHARD_GANG_PROPOSAL,
    SHARD_NAMESPACE,
    GangMemberEntry,
    GangProposalSpec,
    GangProposalStatus,
    ShardGangProposal,
    gang_proposal_name,
)
from ...api.work import (
    CONDITION_SCHEDULED,
    REASON_GANG_TIMEOUT,
    REASON_GANG_UNSCHEDULABLE,
)
from ...metrics import xshard_gang_commits
from ...store.store import BatchError, ConflictError, DELETED
from ...tracing import tracer
from ..core import ScheduleDecision
from ..queue import PrioritySchedulingQueue

log = logging.getLogger(__name__)

_CAS_ATTEMPTS = 16


class CrossShardGangs:
    """Both halves of the protocol for one shard's daemon: the member-side
    publisher (`publish`, called from the daemon's `_patch_gang` override
    on the writer thread) and the coordinator-side assembler (a worker
    thread driven level-triggered by proposal watch events + a periodic
    expiry tick). The worker only acts on gangs this shard coordinates."""

    def __init__(self, daemon, interval: float = 0.2) -> None:
        self.daemon = daemon
        self.interval = interval
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._dirty = True  # level-triggered: scan on every wake
        daemon.store.watch(KIND_SHARD_GANG_PROPOSAL, self._on_proposal)

    # -- member side -------------------------------------------------------

    def publish(self, gname: str, items) -> None:
        """Merge this micro-batch's solved members of gang `gname` into
        the shard's proposal object. `items` = [(rb, decision), ...] with
        rb the drain-time snapshot each decision solved against."""
        daemon = self.daemon
        shard = daemon.shards.index
        gang_ns = items[0][0].metadata.namespace
        entries = []
        for rb, dec in items:
            entries.append(GangMemberEntry(
                key=rb.metadata.key(),
                uid=rb.metadata.uid,
                solved_rv=rb.metadata.resource_version,
                targets=[[t.name, t.replicas] for t in (dec.targets or [])],
                affinity_name=dec.affinity_name,
                error=dec.error,
                feasible=daemon._gang_full(rb, dec),
            ))
        size = max(max((rb.spec.gang_size or 0) for rb, _ in items), 1)
        name = gang_proposal_name(gang_ns, gname, shard)
        for _ in range(_CAS_ATTEMPTS):
            cur = daemon.store.try_get(KIND_SHARD_GANG_PROPOSAL, name,
                                       SHARD_NAMESPACE)
            try:
                if cur is None:
                    daemon.store.create(ShardGangProposal(
                        metadata=ObjectMeta(name=name,
                                            namespace=SHARD_NAMESPACE),
                        spec=GangProposalSpec(
                            gang_name=gname, gang_ns=gang_ns,
                            gang_size=size, shard=shard,
                            coordinator=daemon.shards.coordinator(
                                gang_ns, gname),
                            entries=entries,
                            created_at=daemon.clock.now(),
                        ),
                    ))
                    return
                if cur.status.outcome:
                    # terminal proposal racing deletion: the members just
                    # re-solved — re-admit them; the next drain republishes
                    # onto a fresh object
                    self._member_dispose(cur.status.outcome, entries)
                    return
                merged = {e.key: e for e in cur.spec.entries}
                for e in entries:
                    merged[e.key] = e  # a re-solve supersedes its old entry
                cur.spec.entries = list(merged.values())
                daemon.store.update(cur, check_rv=True)
                return
            except ConflictError:
                continue
        log.error("gang %s shard %d: proposal CAS contention", gname, shard)

    def _member_dispose(self, outcome: str, entries) -> None:
        """Member-shard disposition of its entries once the coordinator
        stamped a terminal outcome."""
        daemon = self.daemon
        q = daemon.controller.queue
        for e in entries:
            key = e.key
            if outcome == "aborted":
                # a veto re-admits the whole gang UNCHARGED: readd keeps
                # the cached priority and burns no retry budget
                readd = getattr(q, "readd", None) or q.add
                readd(key)
                continue
            if daemon.admission.enabled:
                if outcome == "committed":
                    lat = daemon.admission.observe_patch(
                        key, daemon.clock.now())
                    tracer.finish_placement(key, lat)
                else:
                    daemon.admission.settle(key)
            if outcome in ("rejected", "timeout") and isinstance(
                    q, PrioritySchedulingQueue):
                q.push_unschedulable(key)

    # -- watch + worker ----------------------------------------------------

    def _on_proposal(self, event: str, prop: ShardGangProposal) -> None:
        if (event != DELETED and prop.status.outcome
                and prop.spec.shard == self.daemon.shards.index):
            # our shard's entries reached a terminal outcome: dispose on
            # the dispatch thread (queue/admission ops are thread-safe)
            self._member_dispose(prop.status.outcome, prop.spec.entries)
        with self._cond:
            self._dirty = True
            self._cond.notify_all()

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        with self._cond:
            self._dirty = True  # takeover: scan proposals already pending
        self._thread = threading.Thread(
            target=self._run,
            name=f"xshard-gangs-{self.daemon.shards.index}", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        """Stop the coordinator worker (leadership loss). The proposal
        WATCH stays attached: the member-side disposition must keep
        running — a standby's members still need their re-admit/settle
        when some other shard's coordinator resolves their gang."""
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def detach(self) -> None:
        """Full teardown: stop the worker AND unsubscribe the watch."""
        self.stop()
        try:
            self.daemon.store.unwatch(KIND_SHARD_GANG_PROPOSAL,
                                      self._on_proposal)
        except Exception:  # noqa: BLE001 - double-detach is fine
            pass

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._cond:
                if not self._dirty:
                    self._cond.wait(timeout=self.interval)
                self._dirty = False
            if self._stop.is_set():
                return
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the worker must survive
                log.exception("cross-shard gang coordinator tick")

    # -- coordinator side --------------------------------------------------

    def tick(self) -> int:
        """One coordinator pass: assemble / commit / expire every gang this
        shard coordinates. Returns the number of gangs resolved (any
        terminal outcome). Also the test/bench drive — deterministic."""
        daemon = self.daemon
        gangs: dict[tuple[str, str], list] = {}
        for prop in daemon.store.list(KIND_SHARD_GANG_PROPOSAL,
                                      SHARD_NAMESPACE):
            if prop.status.outcome:
                continue
            gkey = (prop.spec.gang_ns, prop.spec.gang_name)
            if daemon.shards.coordinator(*gkey) != daemon.shards.index:
                continue
            gangs.setdefault(gkey, []).append(prop)
        resolved = 0
        for (gang_ns, gname), props in gangs.items():
            outcome = self._resolve(gang_ns, gname, props)
            if outcome:
                resolved += 1
                self._finish(props, outcome)
        return resolved

    def _resolve(self, gang_ns: str, gname: str, props: list) -> str:
        """Decide one gang: "" = keep waiting; else the terminal outcome
        (the commit, condition writes, and metrics happen here)."""
        daemon = self.daemon
        # dedupe entries by key (a resize can move a key between shards
        # mid-gang, leaving entries on both sides): the freshest solve wins
        by_key: dict[str, GangMemberEntry] = {}
        size = 1
        for prop in props:
            size = max(size, prop.spec.gang_size)
            for e in prop.spec.entries:
                old = by_key.get(e.key)
                if old is None or e.solved_rv >= old.solved_rv:
                    by_key[e.key] = e
        entries = list(by_key.values())
        if len(entries) < size:
            oldest = min(p.spec.created_at for p in props)
            if daemon.clock.now() - oldest > daemon.gangs.wait_seconds:
                self._write_conditions(
                    entries, REASON_GANG_TIMEOUT,
                    f"gang {gname} timed out waiting for members "
                    f"across shards")
                xshard_gang_commits.inc(outcome="timeout")
                return "timeout"
            return ""
        if not all(e.feasible and not e.error for e in entries):
            self._write_conditions(
                entries, REASON_GANG_UNSCHEDULABLE,
                f"gang {gname}: cohort did not place all {size} "
                f"members fully")
            xshard_gang_commits.inc(outcome="rejected")
            return "rejected"
        # the fenced commit: fresh batch read, rv fence per member, ONE
        # rv-checked batch write — nothing partial can reach the store
        pairs = []
        for e in entries:
            ns, _, name = e.key.partition("/")
            pairs.append((name, ns))
        fresh_list = daemon.store.get_batch("ResourceBinding", pairs)
        sink: list = []
        for e, fresh in zip(entries, fresh_list):
            if fresh is None or fresh.metadata.resource_version != e.solved_rv:
                xshard_gang_commits.inc(outcome="aborted")
                return "aborted"
            from ...api.work import TargetCluster

            dec = ScheduleDecision(
                e.key,
                targets=[TargetCluster(name=n, replicas=r)
                         for n, r in e.targets],
                affinity_name=e.affinity_name,
            )
            if not daemon._patch_result(fresh, dec, fresh=fresh, sink=sink,
                                        any_shard=True):
                xshard_gang_commits.inc(outcome="aborted")
                return "aborted"
        try:
            objs = [obj for obj, _ in sink]
            if objs:
                daemon.store.update_batch(objs, check_rv=True)
        except BatchError:
            xshard_gang_commits.inc(outcome="aborted")
            return "aborted"
        for obj, dec in sink:
            if dec is not None:
                daemon._record_event(obj, dec)
        xshard_gang_commits.inc(outcome="committed")
        return "committed"

    def _write_conditions(self, entries, reason: str, message: str) -> None:
        """Terminal rejection: Scheduled=False on every member we have an
        entry for (idempotent — the event fixpoint terminates)."""
        from ...api.meta import Condition, set_condition

        daemon = self.daemon
        for e in entries:
            ns, _, name = e.key.partition("/")
            fresh = daemon.store.try_get("ResourceBinding", name, ns)
            if fresh is None or fresh.metadata.deletion_timestamp is not None:
                continue
            if set_condition(
                fresh.status.conditions,
                Condition(type=CONDITION_SCHEDULED, status="False",
                          reason=reason, message=message),
            ):
                daemon.store.update(fresh)

    def _finish(self, props: list, outcome: str) -> None:
        """Stamp every shard's proposal with the outcome (the member
        shards' disposition trigger), then delete them."""
        daemon = self.daemon
        for prop in props:
            for _ in range(_CAS_ATTEMPTS):
                cur = daemon.store.try_get(
                    KIND_SHARD_GANG_PROPOSAL, prop.name, SHARD_NAMESPACE)
                if cur is None:
                    break
                cur.status = GangProposalStatus(outcome=outcome)
                try:
                    daemon.store.update(cur, check_rv=True)
                    break
                except ConflictError:
                    continue
            try:
                daemon.store.delete(KIND_SHARD_GANG_PROPOSAL, prop.name,
                                    SHARD_NAMESPACE)
            except Exception:  # noqa: BLE001 - already gone is fine
                pass

"""Deterministic shard map: rendezvous hashing of bindings onto slots.

Every participant — N shard leaders, their standbys, the CLI — must agree
on which shard owns a binding WITHOUT a coordination round, and a resize
from N to N+1 shards must move only ~1/(N+1) of the keyspace (a modulo
ring would reshuffle nearly everything). Rendezvous (highest-random-weight)
hashing gives both for free: each key scores every slot with a keyed hash
and the argmax owns it. Adding a slot moves exactly the keys whose new
slot's score beats their old argmax — in expectation 1/(N+1) of them —
and removing a slot moves only the removed slot's keys. No state, no
bounded-movement bookkeeping to replicate or persist.

Keys hash on `namespace/uid`, not name: a delete→recreate of the same
ns/name mints a new uid and may land on a different shard, which is safe
(the tombstone and the recreate are distinct keys to the admission log
too) — while a stable binding never migrates except at resize.
"""
from __future__ import annotations

import hashlib


def _score(slot: int, key: str) -> int:
    """The (slot, key) rendezvous weight: 8 bytes of blake2b, keyed by the
    slot index. Stable across processes and Python versions (never use
    hash() here — PYTHONHASHSEED would split the fleet's view)."""
    h = hashlib.blake2b(
        f"{slot}:{key}".encode("utf-8", "surrogatepass"), digest_size=8
    )
    return int.from_bytes(h.digest(), "big")


def shard_of(key: str, total: int) -> int:
    """The owning shard slot for `key` among `total` slots."""
    if total <= 1:
        return 0
    return max(range(total), key=lambda s: _score(s, key))


def shard_of_binding(rb, total: int) -> int:
    """Owner slot for a ResourceBinding: hashes namespace/uid (falls back
    to the ns/name key for objects minted without a uid, e.g. bare test
    fixtures — still deterministic, just resize-coupled to the name)."""
    ns = rb.metadata.namespace
    ident = rb.metadata.uid or rb.metadata.name
    return shard_of(f"{ns}/{ident}", total)


def shard_of_gang(gang_ns: str, gang_name: str, total: int) -> int:
    """The gang's COORDINATOR slot: the shard that assembles and commits a
    cross-shard cohort. Hashed on the gang identity (not any member's uid)
    so every member shard independently names the same coordinator."""
    return shard_of(f"gang:{gang_ns}/{gang_name}", total)


class ShardMap:
    """A (total, index) view of the rendezvous map: `mine(rb)` is the
    ownership predicate a ShardedDaemon gates admission on. `total` and
    `index` are plain attributes — a resize swaps them atomically under
    the GIL and the next gate evaluation sees the new map (the handoff
    protocol in daemon.py drives the re-admit/invalidate around that
    swap)."""

    def __init__(self, index: int, total: int) -> None:
        if total < 1:
            raise ValueError(f"shard total must be >= 1, got {total}")
        if not 0 <= index < total:
            raise ValueError(f"shard index {index} out of range for "
                             f"{total} slots")
        self.index = index
        self.total = total

    def mine(self, rb) -> bool:
        return shard_of_binding(rb, self.total) == self.index

    def owner(self, rb) -> int:
        return shard_of_binding(rb, self.total)

    def coordinator(self, gang_ns: str, gang_name: str) -> int:
        return shard_of_gang(gang_ns, gang_name, self.total)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ShardMap(index={self.index}, total={self.total})"

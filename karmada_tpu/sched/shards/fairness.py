"""Cross-shard estimator fairness: a shared per-cluster concurrency budget.

With N shard leaders fanning estimator calls out independently, one hot
shard's sweep can occupy every connection a member cluster's estimator
will serve and starve the other shards' sweeps — the per-shard pools
(`MemberEstimators._pool_for`) bound each SHARD's concurrency, not the
cluster's aggregate. `ClusterFairnessBudget` is the aggregate bound: one
process-wide BoundedSemaphore per member cluster, acquired around each
per-cluster estimator leg (`MemberEstimators._guarded` consults the hook
when installed). Shards contend on the semaphore FIFO-ish (threading
semaphores wake waiters roughly in arrival order), so a burst from one
shard queues behind, not instead of, its siblings' in-flight legs.

The budget is deliberately per-process: in the one-process-per-shard
deployment each process talks to the member's estimator over its own
connections and the member's own server enforces its aggregate; the
shared-process ShardPlane (bench, tests, single-box deployments) is where
unfair interleaving actually manifests and where this budget binds.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

DEFAULT_PER_CLUSTER = 4


class ClusterFairnessBudget:
    """`limit` concurrent estimator legs per member cluster, fleet-wide
    across every shard that shares the budget object."""

    def __init__(self, limit: int = DEFAULT_PER_CLUSTER) -> None:
        self.limit = max(1, int(limit))
        self._lock = threading.Lock()
        self._sems: dict[str, threading.BoundedSemaphore] = {}
        # contention visibility: legs that had to WAIT on the budget
        self.waits = 0

    def _sem(self, cluster: str) -> threading.BoundedSemaphore:
        with self._lock:
            sem = self._sems.get(cluster)
            if sem is None:
                sem = self._sems[cluster] = threading.BoundedSemaphore(
                    self.limit
                )
            return sem

    @contextmanager
    def leg(self, cluster: str):
        """Hold one of `cluster`'s estimator-call slots for the duration
        of a per-cluster estimator leg."""
        sem = self._sem(cluster)
        if not sem.acquire(blocking=False):
            with self._lock:
                self.waits += 1
            sem.acquire()
        try:
            yield
        finally:
            sem.release()

    def forget(self, cluster: str) -> None:
        """Drop a retired member's semaphore so the map stays bounded."""
        with self._lock:
            self._sems.pop(cluster, None)

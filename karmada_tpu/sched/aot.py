"""AOT prewarm lattice: compile the round kernels before the first round.

The standby scheduler (docs/HA.md) used to prime the jit cache with one
tiny dry solve — which warms exactly one program shape, while a real
takeover round dispatches chunked kernels at the shape_bucket lattice
points the chunk planner produces (sched/pipeline.py `plan_chunk_rows`).
On a cold fleet epoch every one of those shapes paid a fresh XLA compile
(67–157 s per shape on TPU, BENCH_tpu_latest.json) in the middle of the
first round after takeover.

This module walks the bucket lattice REACHABLE from the current fleet
width and AOT-compiles the partitioned round kernels for each point with
`jit(...).lower(...).compile()` — tracing plus XLA compilation, no device
execution, no decisions. With the persistent compilation cache enabled
(sched/compilecache.py) the compiled programs land on disk, so:

- the standby's background prewarm thread absorbs the compile cost while
  it is NOT leading, and takeover-to-first-placement stays inside the
  lease TTL from a genuinely cold process;
- any later process (restart, failover, bench rerun) re-uses them — the
  lower().compile() path and the live jit dispatch path share the same
  cache key, so a prewarmed shape costs a disk read, not an XLA run.

Shape fidelity: the kernels' table axes (affinity masks [P,C], toleration
tables, deduped requests) depend on the batch CONTENT, so prewarming with
a made-up batch would compile programs no real round dispatches. The
entry point therefore takes the daemon's real binding snapshot when one
exists (the standby has live watches — the takeover round's rows are
already known) and encodes the round's first chunk through the live
`BatchEncoder` (which also warms its row cache); a synthetic mixed-
strategy template stands in only before any bindings exist. Arg shapes
come from `ArrayScheduler.filter_kernel_args` — the same builder live
rounds use — so prewarmed shapes cannot drift from dispatched ones.
"""
from __future__ import annotations

import logging
import time
from typing import Optional, Sequence

import numpy as np

from ..models.batch import pow2_bucket, shape_bucket
from .compilecache import compile_counts, compile_delta

log = logging.getLogger(__name__)

# compile-budget guard: prewarm is a background nicety, never a boot hog —
# at most this many row buckets compile per pass (the persistent cache
# makes later passes incremental anyway)
MAX_PREWARM_SHAPES = 8

# the bottom of the shape_bucket lattice, where streaming micro-batches
# live: the admission loop drains ~arrival_rate x solve_time rows per
# micro-batch, so steady state walks these buckets as load breathes —
# prewarming them is what makes "zero XLA compiles at steady state" hold
# from the FIRST admitted micro-batch (docs/PERF.md streaming scheduler)
MICROBATCH_LADDER = (8, 12, 16, 24, 32, 48, 64, 96, 128, 192, 256)


def row_buckets_for(sched, n_hint: Optional[int] = None,
                    max_shapes: int = MAX_PREWARM_SHAPES,
                    stream: bool = False) -> list[int]:
    """Padded row buckets a round on this scheduler can reach, most
    valuable first: the equalized chunk schedule of the current working set
    (`n_hint` bindings — the shape the takeover round will actually
    dispatch), then — with `stream` — the micro-batch ladder a streaming
    admission loop breathes through, then a small-round ladder every boot
    passes through, capped at the per-launch HBM row cap."""
    C = len(sched.fleet.names)
    if C == 0:
        return []
    serial_cap = sched._max_rows_per_round(C)
    chunk_cap = min(serial_cap, sched.pipeline_chunk_rows(C))
    from .pipeline import chunk_spans, plan_chunk_rows

    pts: list[int] = []
    if n_hint:
        rows = plan_chunk_rows(n_hint, sched.round_chunk_rows(n_hint))
        for s, e in chunk_spans(n_hint, rows):
            pts.append(shape_bucket(e - s))
    if stream:
        pts += list(MICROBATCH_LADDER)
    pts += [8, 256, 1024]
    if n_hint and n_hint > chunk_cap:
        # the chunk cap is only a REACHABLE shape when the working set
        # actually chunks — at a small fleet the cap is millions of rows
        # (budget // C) and compiling it would be pure waste (and, on a
        # real chip, minutes of XLA for a program no round dispatches)
        pts.append(chunk_cap)
    out: list[int] = []
    for p in pts:
        p = min(p, serial_cap)
        if p not in out:
            out.append(p)
        if len(out) >= max_shapes:
            break
    return out


def _synthetic_bindings(sched) -> list:
    """One binding per strategy class (duplicated / static-weight / dynamic
    / aggregated) — the template when the store holds no bindings yet. The
    encoded tables then carry one row per class, which is also what the
    daemon's dry prewarm round encodes."""
    from ..api.meta import ObjectMeta
    from ..api.policy import (
        ClusterAffinity,
        ClusterPreferences,
        DIVISION_PREFERENCE_AGGREGATED,
        DIVISION_PREFERENCE_WEIGHTED,
        DYNAMIC_WEIGHT_AVAILABLE_REPLICAS,
        Placement,
        REPLICA_SCHEDULING_DIVIDED,
        REPLICA_SCHEDULING_DUPLICATED,
        ReplicaSchedulingStrategy,
        StaticClusterWeight,
    )
    from ..api.work import BindingSpec, ObjectReference, ResourceBinding

    affinity = ClusterAffinity(cluster_names=[])
    placements = [
        Placement(
            cluster_affinity=affinity,
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED
            ),
        ),
        Placement(
            cluster_affinity=affinity,
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=DIVISION_PREFERENCE_WEIGHTED,
                weight_preference=ClusterPreferences(static_weight_list=[
                    StaticClusterWeight(
                        target_cluster=ClusterAffinity(
                            cluster_names=[sched.fleet.names[0]]
                        ),
                        weight=1,
                    ),
                ]),
            ),
        ),
        Placement(
            cluster_affinity=affinity,
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=DIVISION_PREFERENCE_WEIGHTED,
                weight_preference=ClusterPreferences(
                    dynamic_weight=DYNAMIC_WEIGHT_AVAILABLE_REPLICAS
                ),
            ),
        ),
        Placement(
            cluster_affinity=affinity,
            replica_scheduling=ReplicaSchedulingStrategy(
                replica_scheduling_type=REPLICA_SCHEDULING_DIVIDED,
                replica_division_preference=DIVISION_PREFERENCE_AGGREGATED,
            ),
        ),
    ]
    return [
        ResourceBinding(
            metadata=ObjectMeta(name=f"__aot-prewarm-{i}",
                                uid=f"aot-prewarm-{i}"),
            spec=BindingSpec(
                resource=ObjectReference(
                    api_version="apps/v1", kind="Deployment",
                    namespace="default", name=f"__aot-prewarm-{i}",
                ),
                replicas=2,
                placement=p,
            ),
        )
        for i, p in enumerate(placements)
    ]


def prewarm_schedule(
    sched,
    bindings: Optional[Sequence] = None,
    with_extra: bool = False,
    max_shapes: int = MAX_PREWARM_SHAPES,
    stream: bool = False,
    stop=None,
) -> dict:
    """AOT-lower+compile the partitioned round kernels over the reachable
    row-bucket lattice at the current fleet width. `bindings`: the live
    working set (shape hint AND encode template); `with_extra`: also
    compile the dense estimator-answer variant (registered estimators make
    rounds carry an i32[B,C] extra matrix, a different program shape);
    `stream`: the daemon runs the streaming admission loop — include the
    micro-batch ladder (and widen the shape budget to fit it; the ladder's
    shapes are the lattice bottom, seconds not minutes of XLA each);
    `stop`: optional threading.Event checked between shapes so a standby
    promoted mid-prewarm abandons the pass immediately. Returns a stats
    dict (shapes compiled, compile seconds, persistent-cache hits)."""
    import jax

    from .core import _filter_kernel_compact, _tail_kernel, pad_batch

    t0 = time.perf_counter()
    bindings = list(bindings or [])
    if stream and max_shapes == MAX_PREWARM_SHAPES:
        max_shapes = MAX_PREWARM_SHAPES + len(MICROBATCH_LADDER)
    buckets = row_buckets_for(sched, len(bindings) or None, max_shapes,
                              stream=stream)
    snap = compile_counts()
    stats = {"row_buckets": [], "aot_seconds": 0.0, **compile_delta(snap)}
    if not buckets:
        return stats
    if not bindings:
        bindings = _synthetic_bindings(sched)
    C = len(sched.fleet.names)
    for b in buckets:
        if stop is not None and stop.is_set():
            break
        rows = list(bindings[:b])  # the table shapes a real b-row chunk of
        #   this working set would encode (matching the live encode exactly)
        with sched._encode_lock:
            raw = sched.batch_encoder.encode(rows)
        batch = pad_batch(raw, lambda n, _b=b: _b)
        # per-SLICE, exactly as _launch_once_partitioned derives it for the
        # chunk it dispatches — a whole-set bound could compile tail
        # programs no live chunk uses. (This and the class-split/topk
        # derivation below intentionally mirror the launch half; keep them
        # in sync with core._launch_once_partitioned.)
        narrow16 = C < 2**15 and int(raw.replicas.max(initial=0)) < 2**15
        extra = np.full((b, C), -1, np.int32) if with_extra else None
        args = sched.filter_kernel_args(batch, extra)
        _filter_kernel_compact.lower(
            *args, plugin_bits=sched._plugin_bits
        ).compile()
        # top-K candidate sparsification (sched/candidates.py): fleets wider
        # than the bucketed window dispatch the candidate prepass instead of
        # the dense filter on every non-policy round — prewarm it at the
        # same lattice points (the dense lowering above stays: policy
        # opt-out rounds and spread wide-row fallbacks still dispatch it)
        from . import candidates as cand_mod

        compact = cand_mod.compact_width_ok(sched)
        if compact:
            cand_k = cand_mod.effective_k(sched, raw, C)
            cand_mod._candidate_select_kernel.lower(
                *args, k=cand_k, plugin_bits=sched._plugin_bits
            ).compile()
            stats["candidate_k"] = cand_k
        stats["row_buckets"].append(b)
        if sched._host_sorts:
            # cpu backend: the division tails run as the numpy host twins —
            # there is no tail program to compile
            continue
        # division-tail shapes: gathered row subsets bucket by class count;
        # compute the template's class split exactly as the launch half does
        pre_b, _pre_cfg, pre_fb = sched._classify_spread(rows)
        spread_set = set(pre_b) | set(pre_fb)
        cls = [
            sched._row_class(rb, i in spread_set) for i, rb in enumerate(rows)
        ]
        shapes = jax.eval_shape(
            lambda *a: _filter_kernel_compact(
                *a, plugin_bits=sched._plugin_bits
            ),
            *args,
        )
        sd_feas, _sd_score, sd_avail, sd_prev, sd_tie, _sd_fc = shapes
        for want_cls, has_agg in ((1, False), (2, True)):
            n_cls = sum(1 for c in cls if c == want_cls)
            if not n_cls:
                continue
            sp = sched._bucket(n_cls)
            max_repl = max(
                (rb.spec.replicas for i, rb in enumerate(rows)
                 if cls[i] == want_cls),
                default=1,
            )
            from .core import TOPK_TARGETS

            topk = min(
                pow2_bucket(min(max(max_repl, 1), TOPK_TARGETS), lo=8),
                TOPK_TARGETS,
            )
            row2d = lambda sd, n: jax.ShapeDtypeStruct((n, C), sd.dtype)
            _, narrow, _ = sched._batch_flags(batch)
            _tail_kernel.lower(
                row2d(sd_feas, sp), row2d(sd_avail, sp),
                row2d(sd_prev, sp), row2d(sd_tie, sp),
                batch.weight_tables,
                jax.ShapeDtypeStruct((sp,), batch.weight_idx.dtype),
                jax.ShapeDtypeStruct((sp,), batch.strategy.dtype),
                jax.ShapeDtypeStruct((sp,), batch.replicas.dtype),
                jax.ShapeDtypeStruct((sp,), batch.fresh.dtype),
                topk=topk, narrow=narrow, has_agg=has_agg,
                narrow16=narrow16,
            ).compile()
            if compact:
                # the compact division tail live rounds dispatch at this
                # class split: [rows, K] windows + the global candidate
                # index ([rows, K] i32)
                win = lambda dt, n: jax.ShapeDtypeStruct(
                    (n, cand_k), np.dtype(dt)
                )
                cand_mod._candidate_tail_kernel.lower(
                    win(np.bool_, sp), win(np.int32, sp), win(np.int32, sp),
                    win(np.int32, sp), win(np.int32, sp),
                    batch.weight_tables,
                    jax.ShapeDtypeStruct((sp,), batch.weight_idx.dtype),
                    jax.ShapeDtypeStruct((sp,), batch.strategy.dtype),
                    jax.ShapeDtypeStruct((sp,), batch.replicas.dtype),
                    jax.ShapeDtypeStruct((sp,), batch.fresh.dtype),
                    topk=topk, narrow=narrow, has_agg=has_agg,
                    narrow16=narrow16,
                ).compile()
    stats.update(compile_delta(snap))
    stats["aot_seconds"] = round(time.perf_counter() - t0, 3)
    return stats

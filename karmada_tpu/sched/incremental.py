"""Cross-round incremental scheduling state: per-binding decision replay.

The solve is row-independent — every binding's placement is a pure function
of (its own spec/status inputs, the fleet snapshot, its estimator answers).
So a binding whose inputs did not change since the round that last solved it
can skip the device solve entirely and replay the cached ScheduleDecision.
This is the per-row memo that turns a steady-state churn round (≤5% of
bindings dirty) into a solve over only the dirty rows.

`DecisionEntry` captures EVERYTHING `ArrayScheduler._schedule_once` reads
from a binding:

  - metadata.generation + the identities of placement / replica_requirements
    / resource (the store contract: managed updates replace these objects
    and bump generation — the entry holds strong refs, so `is` can never
    false-positive on a recycled id; same contract as BatchEncoder's row
    cache),
  - spec.replicas,
  - previous placements and graceful-eviction entries by VALUE (they are
    status-driven and mutate between rounds),
  - the Fresh-reschedule bit (rescheduleTriggeredAt vs lastScheduledTime),
  - status.scheduler_observed_affinity_name (the ordered-affinity retry
    loop's starting term),
  - a digest of the binding's registered-estimator answer row, and
  - the scheduler's fleet epoch (any cluster change bumps it, so a fleet
    delta re-solves every row — cheap insurance that replay can never serve
    a decision computed against a stale fleet).

The tie-break is seeded from the binding UID (models/batch.py tie_matrix),
so a replayed decision is bit-identical to what a cold re-solve would have
produced — the incremental-vs-cold parity suite pins this.
"""
from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np

from ..models.batch import _reschedule_required


def extra_digest(row: Optional[np.ndarray]) -> Optional[bytes]:
    """Fixed-size digest of one binding's estimator-answer row (storing the
    raw row would pin O(B·C) host memory in the cache)."""
    if row is None:
        return None
    return hashlib.blake2b(np.ascontiguousarray(row).tobytes(),
                           digest_size=8).digest()


class DecisionEntry:
    __slots__ = (
        "epoch", "key", "generation", "replicas",
        "placement", "requirements", "resource",
        "prev", "evict", "fresh", "observed_affinity", "extra",
        "decision",
    )

    def __init__(self, rb, epoch: int, extra: Optional[bytes], decision):
        spec = rb.spec
        self.epoch = epoch
        self.key = rb.metadata.key()
        self.generation = rb.metadata.generation
        self.replicas = spec.replicas
        self.placement = spec.placement
        self.requirements = spec.replica_requirements
        self.resource = spec.resource
        self.prev = tuple(
            (tc.name, tc.replicas) for tc in (spec.clusters or ())
        )
        self.evict = tuple(
            t.from_cluster for t in (spec.graceful_eviction_tasks or ())
        )
        self.fresh = _reschedule_required(spec, rb.status)
        self.observed_affinity = rb.status.scheduler_observed_affinity_name
        self.extra = extra
        self.decision = decision

    def matches(self, rb, epoch: int, extra: Optional[bytes]) -> bool:
        spec = rb.spec
        return (
            self.epoch == epoch
            and self.generation == rb.metadata.generation
            and self.replicas == spec.replicas
            and self.placement is spec.placement
            and self.requirements is spec.replica_requirements
            and self.resource is spec.resource
            and self.extra == extra
            and self.key == rb.metadata.key()
            and self.fresh == _reschedule_required(spec, rb.status)
            and self.observed_affinity
            == rb.status.scheduler_observed_affinity_name
            and self.prev
            == tuple((tc.name, tc.replicas) for tc in (spec.clusters or ()))
            and self.evict
            == tuple(t.from_cluster for t in (spec.graceful_eviction_tasks or ()))
        )

"""Cross-round incremental scheduling state: per-binding decision replay.

The solve is row-independent — every binding's placement is a pure function
of (its own spec/status inputs, the fleet snapshot, its estimator answers).
So a binding whose inputs did not change since the round that last solved it
can skip the device solve entirely and replay the cached ScheduleDecision.
This is the per-row memo that turns a steady-state churn round (≤5% of
bindings dirty) into a solve over only the dirty rows.

`DecisionEntry` captures EVERYTHING `ArrayScheduler._schedule_once` reads
from a binding:

  - metadata.generation + placement / replica_requirements / resource
    compared by VALUE with an object-identity fast path (the in-process
    store contract — managed updates replace these objects and bump
    generation — makes `is` a sufficient check there, but the daemon path
    re-fetches bindings through the store's deepcopy / the wire codec, so
    out-of-process every fetch hands back NEW objects and an identity-only
    compare would defeat replay entirely; dataclass `==` restores it),
  - spec.replicas,
  - previous placements and graceful-eviction entries by VALUE (they are
    status-driven and mutate between rounds),
  - the Fresh-reschedule bit (rescheduleTriggeredAt vs lastScheduledTime),
  - status.scheduler_observed_affinity_name (the ordered-affinity retry
    loop's starting term),
  - a digest of the binding's registered-estimator answer row, and
  - the scheduler's fleet epoch (any cluster change bumps it, so a fleet
    delta re-solves every row — cheap insurance that replay can never serve
    a decision computed against a stale fleet).

The tie-break is seeded from the binding UID (models/batch.py tie_matrix),
so a replayed decision is bit-identical to what a cold re-solve would have
produced — the incremental-vs-cold parity suite pins this.
"""
from __future__ import annotations

import hashlib
from typing import Optional

import numpy as np

from ..models.batch import _reschedule_required


def extra_digest(row: Optional[np.ndarray]) -> Optional[bytes]:
    """Fixed-size digest of one binding's estimator-answer row (storing the
    raw row would pin O(B·C) host memory in the cache)."""
    if row is None:
        return None
    return hashlib.blake2b(np.ascontiguousarray(row).tobytes(),
                           digest_size=8).digest()


class DecisionEntry:
    __slots__ = (
        "epoch", "key", "generation", "replicas",
        "placement", "requirements", "resource",
        "prev", "evict", "fresh", "observed_affinity", "extra",
        "decision",
    )

    def __init__(self, rb, epoch: int, extra: Optional[bytes], decision):
        spec = rb.spec
        self.epoch = epoch
        self.key = rb.metadata.key()
        self.generation = rb.metadata.generation
        self.replicas = spec.replicas
        self.placement = spec.placement
        self.requirements = spec.replica_requirements
        self.resource = spec.resource
        self.prev = tuple(
            (tc.name, tc.replicas) for tc in (spec.clusters or ())
        )
        self.evict = tuple(
            t.from_cluster for t in (spec.graceful_eviction_tasks or ())
        )
        self.fresh = _reschedule_required(spec, rb.status)
        self.observed_affinity = rb.status.scheduler_observed_affinity_name
        self.extra = extra
        self.decision = decision

    @staticmethod
    def _same(a, b) -> bool:
        """Identity fast path (in-process callers hand back the very same
        policy objects), value compare otherwise (the daemon path re-fetches
        through the store's deepcopy / wire codec, where identity never
        holds but dataclass equality does)."""
        return a is b or a == b

    def matches(self, rb, epoch: int, extra: Optional[bytes]) -> bool:
        spec = rb.spec
        return (
            self.epoch == epoch
            and self.generation == rb.metadata.generation
            and self.replicas == spec.replicas
            and self.extra == extra
            and self.key == rb.metadata.key()
            and self.fresh == _reschedule_required(spec, rb.status)
            and self.observed_affinity
            == rb.status.scheduler_observed_affinity_name
            and self.prev
            == tuple((tc.name, tc.replicas) for tc in (spec.clusters or ()))
            and self.evict
            == tuple(t.from_cluster for t in (spec.graceful_eviction_tasks or ()))
            and self._same(self.placement, spec.placement)
            and self._same(self.requirements, spec.replica_requirements)
            and self._same(self.resource, spec.resource)
        )

"""Batched SpreadConstraint selection: the device/vectorized fast path.

The reference resolves spread constraints one binding at a time: build
ClusterDetail objects, group by region, score each group with a sorted
prefix walk, then DFS over group combinations
(pkg/scheduler/core/spreadconstraint/{group_clusters,select_groups}.go).
Round 2 ported that shape to per-row numpy and still measured 7.2 s for 5k
spread rows — the per-row lexsort + Python DFS dominate.

TPU reframing (SURVEY §7 "beam/masked relaxation" hard part):

- REGION IS A FLEET PROPERTY: the cluster→region map does not vary per
  binding, so a static column permutation groups each region into a
  contiguous column slice. Group scoring then runs per-region slice sorts
  ([S, w_r] instead of [S, C]) + cumsums — one jitted program scores EVERY
  (row, region) pair at once (group_clusters.go:143-330 semantics).
- The group-combination search becomes a masked tensor program on host:
  enumerate candidate combinations ONCE per constraint config, compute all
  row×combination weight/value sums as one matmul against the combination
  one-hot matrix, and select the winner per row lexicographically
  (select_groups.go:100-230). Rows whose winner TIES on (weight, value) —
  where the reference's DFS discovery order decides — fall back to the
  exact per-row DFS, so placements stay bit-identical.
- Selected-cluster masks are bit-packed on device (u8 [S, C/8]) so a row
  spanning hundreds of clusters ships in C/8 bytes and decodes lazily.

Only region-spread rows without a cluster MaxGroups cap ride this path;
cluster-only constraints and capped rows use the per-row exact path
(sched/spread.py), which stays the semantic spec either way.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from itertools import combinations
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api.policy import (
    Placement,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_REGION,
)
from .spread import (
    SpreadError,
    WEIGHT_UNIT,
    _constraint_map,
    should_ignore_available_resource,
)

# combination-enumeration guards: beyond these the exact per-row DFS is no
# better, but the batched matmul would burn memory — fall back per row.
MAX_REGIONS = 64
MAX_PATH_LEN = 6
# Combination-count : row-count ratio above which a (deduped) batch takes
# the class-collapsed DFS instead of the [S, n_combo] table passes — the
# table's per-call fixed cost scales with the enumeration while the DFS
# scales with rows (measured ~5x on the skewed bench: 51 config groups ×
# ~27 representative rows × C(31, 4..6) combos).
CLASS_DFS_COMBO_RATIO = 64
MAX_COMBOS = 40000


@dataclass(frozen=True)
class SpreadConfig:
    """The per-placement knobs that shape group scoring + selection."""

    rmin: int  # region MinGroups
    rmax: int  # region MaxGroups (0 = unbounded)
    cmin: int  # cluster MinGroups (the DFS coverage target)
    cmax: int  # cluster MaxGroups (0 = unbounded; >0 forces fallback)
    duplicated: bool  # availability ignored per-cluster (select_clusters.go:79-88)

    @property
    def need(self) -> int:
        return max(self.cmin, max(self.rmin, 1))


def config_of(placement: Placement) -> Optional[SpreadConfig]:
    """Classify a placement for the batched path; None = not eligible
    (no region constraint, zone/provider fields, or a cluster cap)."""
    cmap = _constraint_map(placement.spread_constraints)
    if SPREAD_BY_FIELD_REGION not in cmap:
        return None
    if any(f not in (SPREAD_BY_FIELD_REGION, SPREAD_BY_FIELD_CLUSTER) for f in cmap):
        return None
    rc = cmap[SPREAD_BY_FIELD_REGION]
    cc = cmap.get(SPREAD_BY_FIELD_CLUSTER)
    cmin = cc.min_groups if cc else 0
    cmax = cc.max_groups if cc else 0
    if cmax > 0:
        return None  # phase-C truncation: exact path
    return SpreadConfig(
        rmin=rc.min_groups,
        rmax=rc.max_groups,
        cmin=cmin,
        cmax=cmax,
        duplicated=should_ignore_available_resource(placement),
    )


class RegionLayout:
    """Static fleet-side spread encoding: the region-grouping column
    permutation and its contiguous slices. Built once per cluster set."""

    def __init__(self, region_id: np.ndarray, region_names: Sequence[str],
                 name_rank: np.ndarray):
        self.n_regions = len(region_names)
        self.region_names = list(region_names)
        C = len(region_id)
        # clusters without a region sort to the tail and never join a group
        order = np.lexsort((np.arange(C), np.where(region_id < 0, self.n_regions, region_id)))
        self.perm = order.astype(np.int32)  # permuted -> original column
        rid_p = region_id[order]
        self.slices: list[tuple[int, int]] = []
        for r in range(self.n_regions):
            pos = np.nonzero(rid_p == r)[0]
            self.slices.append((int(pos[0]), int(pos[-1]) + 1) if len(pos) else (0, 0))
        self.name_rank_p = name_rank[order].astype(np.int32)
        # padded [R, W] grid of original column ids (W = widest region):
        # lets group scoring run as ONE [S, R, W] sort instead of R unrolled
        # per-slice sorts — R distinct sort shapes made the jitted program
        # pathologically slow to compile (612 s at R=16, C=5000)
        self.grid_width = max(
            (e - s for s, e in self.slices), default=0
        )
        # skew guard: the padded grid holds R x W elements vs the C the
        # per-slice form touched — one giant region among many tiny ones
        # would multiply group-scoring memory ~R-fold. Such fleets score via
        # group_score_kernel_segmented instead, so the grid arrays build
        # LAZILY (an unbalanced fleet never pays the R x W allocation).
        self.grid_balanced = (
            self.n_regions * max(self.grid_width, 1) <= max(4 * C, 1024)
        )
        self._name_rank = name_rank
        self._grid = None
        # segmented layout (skew-proof twin of the grid): the permuted
        # columns whose region is real are contiguous per region, so group
        # reductions are prefix-sum differences at STATIC offsets — memory
        # O(C) regardless of how unbalanced the region sizes are
        self.seg_cp = int((region_id >= 0).sum())
        self.seg_id_p = rid_p[: self.seg_cp].astype(np.int32)
        self.seg_start = np.array(
            [s for s, _ in self.slices], np.int32
        ) if self.slices else np.zeros(0, np.int32)
        self.seg_end = np.array(
            [e for _, e in self.slices], np.int32
        ) if self.slices else np.zeros(0, np.int32)
        # original-column-order region ids, shifted by one (0 = regionless —
        # such clusters never join a region selection)
        self.rid_orig = np.where(region_id < 0, 0, region_id + 1).astype(np.int32)
        # region-name ascending ranks (group order + path-sort tie-breaks)
        names_idx = sorted(range(self.n_regions), key=lambda r: self.region_names[r])
        self.rname_rank = np.empty(self.n_regions, np.int64)
        self.rname_rank[names_idx] = np.arange(self.n_regions)

    def _build_grid(self):
        if self._grid is None:
            grid_idx = np.zeros(
                (self.n_regions, max(self.grid_width, 1)), np.int32
            )
            grid_valid = np.zeros_like(grid_idx, dtype=bool)
            for r, (s, e) in enumerate(self.slices):
                w = e - s
                grid_idx[r, :w] = self.perm[s:e]
                grid_valid[r, :w] = True
            grid_name_rank = np.where(
                grid_valid, self._name_rank[grid_idx], np.iinfo(np.int32).max
            ).astype(np.int32)
            self._grid = (grid_idx, grid_valid, grid_name_rank)
        return self._grid

    @property
    def grid_idx(self) -> np.ndarray:
        return self._build_grid()[0]

    @property
    def grid_valid(self) -> np.ndarray:
        return self._build_grid()[1]

    @property
    def grid_name_rank(self) -> np.ndarray:
        return self._build_grid()[2]

@partial(jax.jit, static_argnames=("layout",))
def group_score_kernel(
    feasible,  # bool[S,C] (original column order)
    score,  # i32[S,C]
    avail,  # i32[S,C] estimator answer (post min-merge)
    prev_replicas,  # i32[S,C]
    replicas,  # i64[S] spec.replicas
    need,  # i64[S] max(cluster MinGroups, region MinGroups, 1)
    target,  # i64[S] ceil(replicas / max(region MinGroups, 1))
    duplicated,  # bool[S]
    layout: RegionLayout,
):
    """Score every (row, region) group in one program.

    The fleet's regions are laid out as a static padded grid [R, W]
    (W = widest region), so scoring is ONE [S, R, W] sort along the member
    axis — rows by (infeasible, score desc, available desc, name), the
    sortClusters order (util.go:43-57) with infeasible/pad members pushed
    to the tail — followed by prefix cumsums, exactly like calcGroupScore
    (group_clusters.go:143-330). Returns (weight i64[S,R], value i32[S,R],
    avail_sum i64[S,R], feas_count i32[S] — the unrestricted fit count for
    FitError checks)."""
    S = feasible.shape[0]
    grid = jnp.asarray(layout.grid_idx)  # [R, W] original column ids
    valid = jnp.asarray(layout.grid_valid)  # [R, W]
    R, W = grid.shape

    f3 = feasible[:, grid] & valid  # [S, R, W]
    av3 = jnp.where(
        f3,
        avail[:, grid].astype(jnp.int64) + prev_replicas[:, grid].astype(jnp.int64),
        0,
    )
    sc3 = jnp.where(f3, score[:, grid].astype(jnp.int64), 0)

    infeas = (~f3).astype(jnp.int32)
    nrank = jnp.broadcast_to(layout.grid_name_rank, (S, R, W))
    # pad slots carry an INT32_MAX rank sentinel; they are forced
    # infeasible (zero payloads), so their rank never affects results —
    # mask them out of both the guard and the packed key
    rank_masked = np.where(
        np.asarray(layout.grid_valid), np.asarray(layout.grid_name_rank), 0
    )
    max_rank = int(rank_masked.max(initial=0))
    if (
        avail.dtype == jnp.int32
        and prev_replicas.dtype == jnp.int32
        and score.dtype == jnp.int32
        and max_rank < (1 << 24)
    ):
        # the same order-preserving bit-pack as the segmented kernel
        # (see there for the exactness argument): 2 sort operands
        # instead of 6, payloads reconstructed from the sorted keys
        one = jnp.int64(1)
        key1 = (infeas.astype(jnp.int64) << 33) | ((one << 32) - sc3)
        key2 = (
            ((one << 34) - av3) << 24
        ) | jnp.broadcast_to(jnp.asarray(rank_masked), (S, R, W)).astype(jnp.int64)
        key1_s, key2_s = jax.lax.sort((key1, key2), dimension=-1, num_keys=2)
        sc_s = (one << 32) - (key1_s & ((one << 33) - 1))
        av_s = (one << 34) - (key2_s >> 24)
    else:
        # fallback keeps the score key i64: negating an i32 key wraps at
        # INT32_MIN (scores span the full int32 domain via plugin terms)
        nscore = -sc3
        nav = -av3
        _, _, _, _, av_s, sc_s = jax.lax.sort(
            (infeas, nscore, nav, nrank, av3, sc3), dimension=-1, num_keys=4
        )
    cum_av = jnp.cumsum(av_s, axis=-1)
    cum_sc = jnp.cumsum(sc_s, axis=-1)
    value = f3.sum(-1).astype(jnp.int32)  # [S, R] feasible member count
    value64 = value.astype(jnp.int64)
    av_sum = cum_av[..., -1]
    sc_sum = cum_sc[..., -1]
    idx = jax.lax.broadcasted_iota(jnp.int64, (S, R, W), 2)
    # divided branch: first k with (count >= need) & (cum_av >= target),
    # restricted to real members (group_clusters.go:217-330)
    cond = (
        (idx + 1 >= need[:, None, None])
        & (cum_av >= target[:, None, None])
        & (idx < value64[..., None])
    )
    big = jnp.int64(1 << 40)
    k = jnp.min(jnp.where(cond, idx, big), axis=-1)  # [S, R]
    met = k < big
    k_eff = jnp.clip(jnp.where(met, k, value64 - 1), 0, W - 1)
    sc_at_k = jnp.take_along_axis(
        cum_sc, k_eff[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    denom = jnp.maximum(jnp.where(met, k_eff + 1, value64), 1)
    tgt = target[:, None]
    w_div = jnp.where(
        av_sum < tgt,
        av_sum * WEIGHT_UNIT + sc_sum // jnp.maximum(value64, 1),
        tgt * WEIGHT_UNIT + sc_at_k // denom,
    )
    # duplicated branch (group_clusters.go:143-215): order-free
    dup_ok = f3 & (av3 >= replicas[:, None, None])
    cnt = dup_ok.sum(-1).astype(jnp.int64)
    sc_dup = jnp.where(dup_ok, sc3, 0).sum(-1)
    w_dup = jnp.where(cnt > 0, cnt * WEIGHT_UNIT + sc_dup // jnp.maximum(cnt, 1), 0)

    weight = jnp.where(duplicated[:, None], w_dup, w_div)
    weight = jnp.where(value > 0, weight, 0)
    return weight, value, av_sum, feasible.sum(-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("layout",))
def group_score_kernel_segmented(
    feasible, score, avail, prev_replicas,
    replicas, need, target, duplicated,
    layout: RegionLayout,
):
    """Skew-proof twin of group_score_kernel: identical outputs, O(S·C)
    memory for ANY region-size distribution.

    The grid form pads every region to the widest one ([S,R,W] — one giant
    region among many tiny ones multiplies memory ~R-fold, the
    `grid_balanced` guard). Here the member sort runs over the PERMUTED
    columns with the region id as the leading sort key, so each region's
    members land in their static contiguous slice already ordered by the
    sortClusters order (util.go:43-57); every per-region aggregate is then
    an exclusive-prefix-sum difference at static offsets, and the
    calcGroupScore first-k (group_clusters.go:217-330) falls out of a
    monotone fail-count per segment — no scatters, no padding."""
    S = feasible.shape[0]
    Cp = layout.seg_cp
    perm = jnp.asarray(layout.perm[:Cp])
    seg = jnp.asarray(layout.seg_id_p)  # i32[Cp]
    seg_start = jnp.asarray(layout.seg_start)  # i32[R]
    seg_end = jnp.asarray(layout.seg_end)  # i32[R]

    f = feasible[:, perm]
    av = jnp.where(
        f,
        avail[:, perm].astype(jnp.int64) + prev_replicas[:, perm].astype(jnp.int64),
        0,
    )
    sc = jnp.where(f, score[:, perm].astype(jnp.int64), 0)
    infeas = (~f).astype(jnp.int32)
    nav = -av
    nrank = jnp.broadcast_to(jnp.asarray(layout.name_rank_p[:Cp]), (S, Cp))
    segb = jnp.broadcast_to(seg, (S, Cp))
    max_rank = int(layout.name_rank_p[:Cp].max(initial=0)) if Cp else 0
    if (
        avail.dtype == jnp.int32
        and prev_replicas.dtype == jnp.int32
        and score.dtype == jnp.int32
        and max_rank < (1 << 24)
    ):
        # Order-preserving bit-pack: lex(seg, infeas, -score, -av, rank)
        # == lex(key1, key2), exact by dtype alone — |score| < 2^31
        # (bias 2^32, 33 bits), av = i32 + i32 ∈ (-2^33, 2^33) (bias
        # 2^34, 35 bits), rank < 2^24 (ranks span the FULL fleet, so the
        # guard checks the max rank actually packed, not Cp). One
        # 2-operand 2-key sort replaces the 8-operand 5-key form and
        # every payload reconstructs from the sorted keys.
        one = jnp.int64(1)
        key1 = (
            (segb.astype(jnp.int64) << 34)
            | (infeas.astype(jnp.int64) << 33)
            | ((one << 32) - sc)
        )
        key2 = (((one << 34) - av) << 24) | nrank.astype(jnp.int64)
        key1_s, key2_s = jax.lax.sort((key1, key2), dimension=-1, num_keys=2)
        f_s = (1 - ((key1_s >> 33) & 1)).astype(jnp.int32)
        sc_s = (one << 32) - (key1_s & ((one << 33) - 1))
        av_s = (one << 34) - (key2_s >> 24)
    else:
        # fallback keeps the score key i64: negating an i32 key wraps at
        # INT32_MIN (scores span the full int32 domain via plugin terms)
        _, _, _, _, _, f_s, av_s, sc_s = jax.lax.sort(
            (segb, infeas, -sc, nav, nrank,
             f.astype(jnp.int32), av, sc),
            dimension=-1, num_keys=5,
        )

    def excl(x):  # P[j] = sum of first j entries, [S, Cp+1]
        return jnp.concatenate(
            [jnp.zeros((S, 1), x.dtype), jnp.cumsum(x, axis=-1)], axis=-1
        )

    Pf = excl(f_s.astype(jnp.int64))
    Pav = excl(av_s)
    Psc = excl(sc_s)

    def segsum(P):  # [S, R]
        return P[:, seg_end] - P[:, seg_start]

    value64 = segsum(Pf)  # feasible member count per region
    value = value64.astype(jnp.int32)
    av_sum = segsum(Pav)
    sc_sum = segsum(Psc)

    iota = jax.lax.broadcasted_iota(jnp.int32, (S, Cp), 1)
    idx_rel = (iota - seg_start[seg][None, :]).astype(jnp.int64)
    cum_av_rel = Pav[:, 1:] - jnp.take(Pav, seg_start[seg], axis=1)
    value_at = jnp.take_along_axis(
        value64, jnp.broadcast_to(seg, (S, Cp)).astype(jnp.int32), axis=1
    )
    condA = idx_rel + 1 >= need[:, None]
    condB = cum_av_rel >= target[:, None]
    condC = idx_rel < value_at
    # within the feasible prefix, A∧B flips once and stays true (cum_av is
    # nondecreasing), so the failing positions are a prefix and the first
    # satisfying index equals their count
    fail = (condC & ~(condA & condB)).astype(jnp.int64)
    k_count = segsum(excl(fail))  # [S, R]
    met = k_count < value64
    k_eff = jnp.clip(jnp.where(met, k_count, value64 - 1), 0, max(Cp - 1, 0))
    at = seg_start[None, :] + k_eff.astype(jnp.int32) + 1
    sc_at_k = jnp.take_along_axis(Psc, at, axis=1) - jnp.take(
        Psc, seg_start, axis=1
    )
    denom = jnp.maximum(jnp.where(met, k_eff + 1, value64), 1)
    tgt = target[:, None]
    w_div = jnp.where(
        av_sum < tgt,
        av_sum * WEIGHT_UNIT + sc_sum // jnp.maximum(value64, 1),
        tgt * WEIGHT_UNIT + sc_at_k // denom,
    )
    dup_ok = f & (av >= replicas[:, None])
    Pdup = excl(dup_ok.astype(jnp.int64))
    # dup aggregates are order-free — sum over the UNSORTED segmented
    # columns works because segments are contiguous pre-sort too
    cnt = segsum(Pdup)
    Pscd = excl(jnp.where(dup_ok, sc, 0))
    sc_dup = segsum(Pscd)
    w_dup = jnp.where(cnt > 0, cnt * WEIGHT_UNIT + sc_dup // jnp.maximum(cnt, 1), 0)

    weight = jnp.where(duplicated[:, None], w_dup, w_div)
    weight = jnp.where(value > 0, weight, 0)
    return weight, value, av_sum, feasible.sum(-1).astype(jnp.int32)


def _apply_chosen(feasible, chosen, layout: RegionLayout):
    """sel[s,c] = feasible & (cluster c's region chosen for row s)."""
    rid = jnp.asarray(layout.rid_orig)
    chosen_pad = jnp.concatenate(
        [jnp.zeros((chosen.shape[0], 1), bool), chosen], axis=1
    )
    return feasible & chosen_pad[:, rid]


def _pack_bits(sel):
    # jit-safe bit-packing shared with the candidate prepass
    # (sched/candidates.py ships complete feasible masks through it for
    # duplicated / non-workload rows — their target sets never truncate)
    C = sel.shape[1]
    pad = (-C) % 8
    if pad:
        sel = jnp.pad(sel, ((0, 0), (0, pad)))
    bits = sel.reshape(sel.shape[0], -1, 8).astype(jnp.uint8)
    weightsv = jnp.asarray([1, 2, 4, 8, 16, 32, 64, 128], jnp.uint8)
    return (bits * weightsv).sum(-1).astype(jnp.uint8)


@partial(jax.jit, static_argnames=("layout",))
def packed_selection_kernel(feasible, chosen, layout: RegionLayout):
    """Bit-packed selection masks, u8 [S, ceil(C/8)]: a row spanning
    hundreds of clusters ships in C/8 bytes and decodes lazily on host."""
    return _pack_bits(_apply_chosen(feasible, chosen, layout))


@partial(jax.jit, static_argnames=("layout", "topk", "narrow", "has_agg"))
def spread_tail_kernel(
    feasible,  # bool[S,C] unrestricted feasible rows (device)
    avail,  # i32[S,C] post-merge estimator answers (device)
    prev_replicas,  # i32[S,C]
    tie,  # i32[S,C]
    chosen,  # bool[S,R] selected regions per row
    strategy,  # i32[S]
    replicas,  # i32[S]
    fresh,  # bool[S]
    layout: RegionLayout,
    topk: int,
    narrow: bool,
    has_agg: bool,
):
    """Replica division re-run over the spread-selected cluster set for the
    DIVIDED spread rows (the reference re-enters assignReplicas with the
    SelectClusters result; duplicated rows need no division — their targets
    are the packed mask × spec.replicas). Skips the filter/estimate phase
    entirely: restricting candidates cannot change per-cluster feasibility
    or estimates, only the feasible mask."""
    from .core import assignment_tail, compact_outputs

    sel = _apply_chosen(feasible, chosen, layout)
    zero_w = jnp.zeros((1, 1), jnp.int64)
    result, unsched, avail_sum = assignment_tail(
        sel, strategy, jnp.broadcast_to(zero_w, sel.shape), avail,
        prev_replicas, tie, replicas, fresh, narrow=narrow, has_agg=has_agg,
    )
    feas_count, nnz, top_idx, top_val = compact_outputs(
        sel, result, min(sel.shape[1], topk)
    )
    # result rides FIRST and stays device-resident (callers fetch [1:]);
    # rows whose nnz overflows the top-K window fetch their dense row from
    # it instead of silently truncating (same contract as _tail_kernel)
    return result, unsched, avail_sum, feas_count, nnz, top_idx, top_val


def unpack_row(packed_row: np.ndarray, n_cols: int) -> np.ndarray:
    """Host-side lazy inverse of packed_selection_kernel for one row."""
    bits = np.unpackbits(packed_row, bitorder="little")[:n_cols]
    return np.nonzero(bits)[0]


# -- host combination search -------------------------------------------------


class _ComboTable:
    """All candidate region subsets for one (R, kmin..kmax) shape, with the
    one-hot matrix for the batched weight/value sums."""

    def __init__(self, n_regions: int, kmin: int, kmax: int):
        self.members: list[tuple[int, ...]] = []
        for k in range(kmin, kmax + 1):
            self.members.extend(combinations(range(n_regions), k))
        self.onehot = np.zeros((len(self.members), n_regions), np.int64)
        for i, m in enumerate(self.members):
            self.onehot[i, list(m)] = 1
        self.sizes = self.onehot.sum(1)
        self.max_len = max((len(m) for m in self.members), default=1)
        self.onehot_f_t = self.onehot.astype(np.float64).T  # cached for BLAS
        self.members_pad = np.full((max(len(self.members), 1), self.max_len),
                                   -1, np.int64)
        for i, m in enumerate(self.members):
            self.members_pad[i, : len(m)] = m


_combo_cache: dict[tuple[int, int, int], _ComboTable] = {}


def _combos(n_regions: int, kmin: int, kmax: int) -> Optional[_ComboTable]:
    total = 0
    for k in range(kmin, kmax + 1):
        total += math.comb(n_regions, k)
        if total > MAX_COMBOS:
            return None
    key = (n_regions, kmin, kmax)
    t = _combo_cache.get(key)
    if t is None:
        t = _combo_cache[key] = _ComboTable(n_regions, kmin, kmax)
    return t


@dataclass
class ComboResult:
    chosen: np.ndarray  # bool[S,R] selected regions (False rows: see below)
    errors: dict[int, str]  # row -> SpreadError message
    fallback: list[int]  # rows needing the exact per-row path (ties etc.)


_CLASS_DFS_BUDGET = 200_000  # recursion-step bound per row


def _class_dfs_rows_native(weight, value, cfg, layout, kmax_row, rows,
                           chosen, errors) -> set:
    """Run the class-collapsed DFS for many rows through the native batch
    kernel. Classification (group order → contiguous (value, weight)
    classes) is vectorized across rows; winners decode through the shared
    subpath walk. Returns the set of rows fully handled here (winner or
    error); rows needing the Python twin (no native library, budget hit,
    or the full-set special case mismatch) are left out."""
    from .. import native

    if not rows or not native.native_available():
        return set()
    kmin = max(cfg.rmin, 1)
    cmin = cfg.cmin
    rr = layout.rname_rank
    rows_a = np.asarray(rows)
    Wl = weight[rows_a]
    Vl = value[rows_a]
    S, R = Wl.shape
    present = Vl > 0
    n_present = present.sum(1)
    # group order (value asc, weight desc, name asc), absent regions last
    order = np.lexsort(
        (np.broadcast_to(rr, Wl.shape), -Wl, Vl, ~present), axis=-1
    )
    Vs = np.take_along_axis(Vl, order, 1)
    Ws = np.take_along_axis(Wl, order, 1)
    Ps = np.take_along_axis(present, order, 1)  # prefix mask per row
    new_cls = np.ones_like(Ps)
    new_cls[:, 1:] = (Vs[:, 1:] != Vs[:, :-1]) | (Ws[:, 1:] != Ws[:, :-1])
    new_cls &= Ps
    r_idx, c_idx = np.nonzero(new_cls)
    per_row = np.bincount(r_idx, minlength=S)
    row_off = np.concatenate([[0], np.cumsum(per_row)]).astype(np.int64)
    cls_v = Vs[r_idx, c_idx].astype(np.int64)
    cls_w = Ws[r_idx, c_idx].astype(np.int64)
    # class end = next class start within the row, else the row's n_present
    ends = np.empty(len(c_idx), np.int64)
    if len(c_idx):
        ends[:-1] = c_idx[1:]
        ends[-1] = 0
        last_of_row = row_off[1:][per_row > 0] - 1
        ends[last_of_row] = n_present[per_row > 0]
    cls_m = ends - c_idx

    kmax_l = np.minimum(np.asarray(kmax_row)[rows_a], n_present).astype(np.int64)
    handled: set = set()

    # special cases the Python twin handles before its DFS; their kernel
    # slots run with kmax 0 so the DFS short-circuits (no wasted work)
    full_set = n_present == kmin
    too_few_regions = n_present < kmin
    bad_kmax = (kmax_l < kmin) & ~too_few_regions
    skip = full_set | too_few_regions | bad_kmax

    out = native.class_dfs_batch(
        cls_v, cls_w, cls_m, row_off, np.where(skip, 0, kmax_l),
        kmin, cmin, _CLASS_DFS_BUDGET,
    )
    if out is None:
        return set()
    counts, status = out
    err_msg = (
        "the number of clusters is less than the cluster "
        "spreadConstraint.MinGroups"
    )
    for i, s in enumerate(rows):
        if too_few_regions[i]:
            # same text as the Python twin's n_present < kmin branch
            errors[s] = (
                "the number of feasible region is less than "
                "spreadConstraint.MinGroups"
            )
            handled.add(s)
            continue
        if bad_kmax[i]:
            errors[s] = err_msg
            handled.add(s)
            continue
        lo, hi = int(row_off[i]), int(row_off[i + 1])
        if full_set[i]:
            # `len(groups) == minConstraint` (select_groups.go:181-183):
            # the DFS takes exactly the full set
            if int(Vs[i, : n_present[i]].sum()) < cmin:
                errors[s] = err_msg
            else:
                cnts = cls_m[lo:hi]
                regs = _decode_class_winner(
                    order[i], c_idx[lo:hi], cnts, cls_v[lo:hi], cls_w[lo:hi],
                    rr, kmin, cmin,
                )
                chosen[s, regs] = True
            handled.add(s)
            continue
        st = int(status[i])
        if st == -1:
            continue  # budget: Python twin decides (it will fall back too)
        if st == 0:
            errors[s] = err_msg
            handled.add(s)
            continue
        regs = _decode_class_winner(
            order[i], c_idx[lo:hi], counts[lo:hi], cls_v[lo:hi], cls_w[lo:hi],
            rr, kmin, cmin,
        )
        chosen[s, regs] = True
        handled.add(s)
    return handled


def _decode_class_winner(order_row, starts, counts, cls_v, cls_w, rr,
                         kmin: int, cmin: int) -> np.ndarray:
    """Winner counts → concrete regions: class members are contiguous in
    the row's group order and name-ascending within a class, so the
    canonical representative is the first `count` entries of each run; the
    shared subpath walk finishes the selection."""
    members: list[int] = []
    mem_v: list[int] = []
    mem_w: list[int] = []
    mem_pos: list[int] = []
    for k in range(len(starts)):
        j = int(counts[k])
        for i in range(j):
            pos = int(starts[k]) + i
            members.append(int(order_row[pos]))
            mem_v.append(int(cls_v[k]))
            mem_w.append(int(cls_w[k]))
            mem_pos.append(pos)
    return _finish_row_members(members, mem_v, mem_w, mem_pos, rr, kmin, cmin)


def _select_row_class_dfs(weight: np.ndarray, value: np.ndarray,
                          cfg: SpreadConfig, layout: RegionLayout,
                          kmax: int):
    """Exact region selection for ONE row by collapsing identical regions.

    When the combination table would be too large to enumerate
    (C(R, kmin..kmax) > MAX_COMBOS), the skewed-fleet structure that causes
    it — many interchangeable tiny regions — also defeats it: regions with
    identical (weight, value) are indistinguishable to the DFS except for
    name order, so recorded paths collapse to CLASS MULTISETS. Subsets
    realizing one multiset share (Σw, Σv) and recorded-ness, and the
    discovery-order representative is the canonical first-members-per-class
    subset (lex-min position sequence); the reference's winner rule
    (weight desc, value desc, id asc; select_groups.go:200-213) therefore
    reduces to a DFS over class counts — tiny wherever the subset
    enumeration explodes.

    Returns (region_index_array) on success, an error string for the
    too-few-groups cases, or None when the class DFS itself exceeds its
    budget (caller falls back to the per-row subset path)."""
    kmin = max(cfg.rmin, 1)
    cmin = cfg.cmin
    present = np.nonzero(value > 0)[0]
    if len(present) < kmin:
        return (
            "the number of feasible region is less than "
            "spreadConstraint.MinGroups"
        )
    # group order (value asc, weight desc, name asc)
    rr = layout.rname_rank
    order = sorted(
        present, key=lambda r: (value[r], -weight[r], rr[r])
    )
    # contiguous classes over (value, weight)
    cls_v: list[int] = []
    cls_w: list[int] = []
    cls_members: list[list[int]] = []
    cls_start: list[int] = []
    for pos, r in enumerate(order):
        if cls_v and value[r] == cls_v[-1] and weight[r] == cls_w[-1]:
            cls_members[-1].append(r)
        else:
            cls_v.append(int(value[r]))
            cls_w.append(int(weight[r]))
            cls_members.append([r])
            cls_start.append(pos)
    K = len(cls_v)
    n_present = len(present)
    kmax = min(kmax, n_present)
    if kmax < kmin:
        return (
            "the number of clusters is less than the cluster "
            "spreadConstraint.MinGroups"
        )

    # `if len(groups) == minConstraint: break` (select_groups.go:181-183):
    # the DFS takes exactly the full set
    if n_present == kmin:
        sv = int(value[present].sum())
        if sv < cmin:
            return (
                "the number of clusters is less than the cluster "
                "spreadConstraint.MinGroups"
            )
        counts = [len(m) for m in cls_members]
        return _class_counts_to_regions(
            counts, cls_members, cls_v, cls_w, cls_start, rr, kmin, cmin
        )

    recorded: list[tuple[int, int, tuple[int, ...]]] = []  # (Σw, Σv, counts)
    counts = [0] * K
    budget = [_CLASS_DFS_BUDGET]

    def rec(k: int, size: int, sv: int, sw: int) -> None:
        budget[0] -= 1
        if budget[0] <= 0:
            raise _Budget()
        if k == K:
            return
        # j = 0 (skip this class)
        rec(k + 1, size, sv, sw)
        m = len(cls_members[k])
        vk, wk = cls_v[k], cls_w[k]
        for j in range(1, min(m, kmax - size) + 1):
            size_j = size + j
            sv_j = sv + j * vk
            sw_j = sw + j * wk
            if sv_j >= cmin and size_j >= kmin:
                # the subset DFS records here and RETURNS — deeper members
                # of this class or later classes would have a satisfied
                # prefix and never be enumerated
                counts[k] = j
                recorded.append((sw_j, sv_j, tuple(counts)))
                counts[k] = 0
                break
            counts[k] = j
            rec(k + 1, size_j, sv_j, sw_j)
            counts[k] = 0

    class _Budget(Exception):
        pass

    try:
        rec(0, 0, 0, 0)
    except _Budget:
        return None
    if not recorded:
        return (
            "the number of clusters is less than the cluster "
            "spreadConstraint.MinGroups"
        )

    def canonical_key(cv: tuple[int, ...]) -> tuple[int, ...]:
        key: list[int] = []
        for k, j in enumerate(cv):
            key.extend(range(cls_start[k], cls_start[k] + j))
        return tuple(key)

    # two-stage winner: (Σw, Σv) max with cheap tuple compares first; the
    # discovery-order canonical key is built ONLY for the tied maxima (the
    # single-pass min() built it for every recorded multiset — the dominant
    # cost of the whole combination search at 5k rows)
    best_w, best_v = max((t[0], t[1]) for t in recorded)
    tied = [t for t in recorded if t[0] == best_w and t[1] == best_v]
    best = (
        tied[0]
        if len(tied) == 1
        else min(tied, key=lambda t: canonical_key(t[2]))
    )
    return _class_counts_to_regions(
        list(best[2]), cls_members, cls_v, cls_w, cls_start, rr, kmin, cmin
    )


def _class_counts_to_regions(counts, cls_members, cls_v, cls_w, cls_start,
                             rr, kmin: int, cmin: int) -> np.ndarray:
    """Counts → concrete regions (first members per class, name-ascending —
    the canonical representative) + the subpath preference."""
    members: list[int] = []  # winner's concrete regions
    mem_v: list[int] = []
    mem_w: list[int] = []
    mem_pos: list[int] = []
    for k, j in enumerate(counts):
        ordered = sorted(cls_members[k], key=lambda r: rr[r])
        for i in range(j):
            members.append(ordered[i])
            mem_v.append(cls_v[k])
            mem_w.append(cls_w[k])
            mem_pos.append(cls_start[k] + i)
    return _finish_row_members(members, mem_v, mem_w, mem_pos, rr, kmin, cmin)


def _finish_row_members(members, mem_v, mem_w, mem_pos, rr,
                        kmin: int, cmin: int) -> np.ndarray:
    """The subpath preference (select_groups.go:210-230): the SHORTEST
    (weight desc, name asc)-ordered prefix of the winner that is itself a
    recorded feasible path."""
    worder = sorted(range(len(members)),
                    key=lambda i: (-mem_w[i], rr[members[i]]))
    n = len(members)
    cut = n
    for L in range(max(kmin, 1), n):
        prefix = worder[:L]
        sv = sum(mem_v[i] for i in prefix)
        if sv < cmin:
            continue
        if L > kmin:
            # recorded-ness: drop the prefix's group-order-last member
            last = max(prefix, key=lambda i: mem_pos[i])
            if sv - mem_v[last] >= cmin:
                continue
        cut = L
        break
    return np.asarray(sorted(members[i] for i in worder[:cut]), np.int64)


# device winner-selection guard: the [S,K,L] gathers must fit comfortably
SPREAD_COMBO_DEVICE_BYTES = 1 << 30


@partial(jax.jit, static_argnames=("table", "cmin", "kmin"))
def _combo_select_kernel(weight, value, kmax_row, rname, table, cmin: int,
                         kmin: int):
    """Device twin of the winner-selection block of select_regions_batch:
    per-combination sums via [S,K,L] gathers (int-exact, no f64 dance),
    DFS recorded-path pruning via the group-order positional gather, the
    (Σweight, Σvalue) lexicographic winner, and the discovery-order tie
    resolution (see _discovery_keys). Returns (first_idx i32[S],
    n_ties i32[S], none_feasible bool[S]); n_ties stays >1 only when the
    path length defeats the packed discovery key."""
    S, R = weight.shape
    v64 = value.astype(jnp.int64)
    mp = jnp.asarray(table.members_pad)  # [K, L]
    valid = mp >= 0
    mpc = jnp.where(valid, mp, 0)
    w_g = jnp.where(valid[None, :, :], weight[:, mpc], 0)  # [S,K,L]
    v_g = jnp.where(valid[None, :, :], v64[:, mpc], 0)
    present_g = jnp.where(valid[None, :, :], value[:, mpc] > 0, True)
    sum_w = w_g.sum(-1)  # [S,K] i64
    sum_v = v_g.sum(-1)
    sizes = jnp.asarray(table.sizes)
    feasible = (
        present_g.all(-1)
        & (sum_v >= cmin)
        & (sizes[None, :] <= kmax_row[:, None].astype(jnp.int64))
    )
    # recorded-path pruning: group-order (value asc, weight desc, name asc)
    order_g = jnp.lexsort(
        (jnp.broadcast_to(rname, (S, R)), -weight, v64), axis=-1
    )
    pos = jnp.zeros((S, R), jnp.int32).at[
        jnp.arange(S)[:, None], order_g
    ].set(jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32), (S, R)))
    pos_g = jnp.where(valid[None, :, :], pos[:, mpc], -1)
    am = pos_g.argmax(-1)  # [S,K]
    last_region = jnp.take_along_axis(
        jnp.broadcast_to(mpc[None, :, :], pos_g.shape), am[:, :, None], axis=2
    )[:, :, 0]
    v_last = jnp.take_along_axis(v64, last_region, axis=1)
    recorded = (sizes[None, :] - 1 < kmin) | (sum_v - v_last < cmin)
    feasible = feasible & recorded

    NEG = jnp.int64(-(1 << 62))
    w_m = jnp.where(feasible, sum_w, NEG)
    best_w = w_m.max(1)
    none_feasible = best_w == NEG
    cand = feasible & (w_m == best_w[:, None])
    v_m = jnp.where(cand, sum_v, NEG)
    best_v = v_m.max(1)
    cand2 = cand & (sum_v == best_v[:, None])
    L = mp.shape[1]
    if 7 * L <= 62:  # 7 bits/slot: positions reach 63 at R == MAX_REGIONS,
        # so the pad sentinel must be a distinct 127
        seq = jnp.sort(
            jnp.where(pos_g < 0, 127, pos_g).astype(jnp.int64), axis=2
        )
        shifts = 7 * jnp.arange(L - 1, -1, -1, dtype=jnp.int64)
        disc = (seq << shifts).sum(axis=2)
        disc_m = jnp.where(cand2, disc, jnp.int64(1) << 62)
        first_idx = jnp.argmin(disc_m, axis=1).astype(jnp.int32)
        n_ties = jnp.minimum(cand2.sum(1), 1).astype(jnp.int32)
    else:
        first_idx = jnp.argmax(cand2, axis=1).astype(jnp.int32)
        n_ties = cand2.sum(1).astype(jnp.int32)
    return first_idx, n_ties, none_feasible


def select_regions_batch(
    weight: np.ndarray,  # i64[S,R]
    value: np.ndarray,  # i32[S,R]
    cfg: SpreadConfig,
    layout: RegionLayout,
    device: "bool | None" = None,  # None = auto (accelerator + size gate)
) -> ComboResult:
    """Vectorized selectGroups (select_groups.go:100-230) for rows sharing
    one constraint config. Winner per row = feasible combination maximizing
    (Σweight, Σvalue); the reference's discovery-order tie-break only
    matters on exact (Σw, Σv) ties, which are detected and sent to the
    per-row DFS. Subpath preference (prefer the shortest weight-ordered
    prefix of the winner that still covers the target) is applied exactly."""
    S, R = weight.shape

    # Dedup identical (weight, value) rows first: bindings sharing a
    # placement + request (the common case — thousands of rows over a few
    # policies) produce identical group matrices, and the winner depends
    # only on the row content. The search then runs once per DISTINCT row
    # and results scatter back — at the 5k-row bench this collapses ~5000
    # rows to a few hundred and moves the whole block off the hot path.
    key = np.concatenate([weight, value.astype(np.int64)], axis=1)
    uniq_first, inverse = np.unique(
        key, axis=0, return_index=True, return_inverse=True
    )[1:]
    if len(uniq_first) < S:
        res_u = select_regions_batch(
            weight[uniq_first], value[uniq_first], cfg, layout, device
        )
        err_u = res_u.errors
        fb_u = set(res_u.fallback)
        errors: dict[int, str] = {}
        fallback: list[int] = []
        for s in range(S):
            u = int(inverse[s])
            if u in err_u:
                errors[s] = err_u[u]
            elif u in fb_u:
                fallback.append(s)
        return ComboResult(res_u.chosen[inverse], errors, fallback)

    present = value > 0
    n_present = present.sum(1)
    errors: dict[int, str] = {}
    fallback: list[int] = []
    chosen = np.zeros((S, R), bool)

    kmin = max(cfg.rmin, 1)
    too_few = n_present < cfg.rmin
    for s in np.nonzero(too_few)[0]:
        errors[int(s)] = (
            "the number of feasible region is less than spreadConstraint.MinGroups"
        )

    # per-row max path length: MaxGroups, else the row's present-region
    # count; never below kmin (the DFS clamps max_constraint =
    # max(max_constraint, min_constraint), select_groups.go:102-107)
    kmax_row = np.maximum(
        np.where(cfg.rmax > 0, cfg.rmax, n_present), kmin
    ).astype(np.int64)
    kmax_enum = int(min(R, kmax_row.max(initial=0), MAX_PATH_LEN if cfg.rmax <= 0 else cfg.rmax))
    if kmax_enum < kmin:
        kmax_enum = kmin
    if int(np.abs(weight).max(initial=0)) >= (1 << 48):
        # pathological magnitudes would lose exactness in the f64 host rank
        # compares AND overflow the native DFS's int64 weight sums — route
        # such fleets to the per-row exact DFS everywhere (checked BEFORE
        # the class-DFS branch so it covers that path too)
        live = np.nonzero(~too_few)[0]
        fallback.extend(int(s) for s in live)
        return ComboResult(chosen, errors, fallback)

    def run_class_dfs() -> ComboResult:
        # the class-collapsed exact DFS (skewed fleets: many
        # interchangeable regions ⇒ few classes). The batch runs through
        # the native kernel when available (the per-row Python recursion
        # cost ~0.5 ms × thousands of rows); rows the native path cannot
        # take (or budget blowouts) use the Python twin.
        live = [int(s) for s in np.nonzero(~too_few)[0]]
        handled = _class_dfs_rows_native(
            weight, value, cfg, layout, kmax_row, live, chosen, errors
        )
        for s in live:
            if s in handled:
                continue
            out = _select_row_class_dfs(
                weight[s], value[s], cfg, layout, int(kmax_row[s])
            )
            if out is None:
                fallback.append(s)
            elif isinstance(out, str):
                errors[s] = out
            else:
                chosen[s, out] = True
        return ComboResult(chosen, errors, fallback)

    if device is None and R <= MAX_REGIONS:
        # auto mode only: an explicit device= pin (tests A/B the table
        # paths) must still reach the enumeration below. Without the
        # native kernel every row pays the ~0.5 ms Python DFS twin, which
        # loses to the table pass — only divert when native is loaded.
        from .. import native

        n_enum = sum(math.comb(R, k) for k in range(kmin, min(kmax_enum, R) + 1))
        if n_enum > S * CLASS_DFS_COMBO_RATIO and native.native_available():
            # small batch over a rich enumeration: per-row DFS beats the
            # table passes (and skips building the table entirely)
            return run_class_dfs()
    table = _combos(R, kmin, min(kmax_enum, R))
    if R > MAX_REGIONS:
        live = np.nonzero(~too_few)[0]
        fallback.extend(int(s) for s in live)
        return ComboResult(chosen, errors, fallback)
    if table is None:
        return run_class_dfs()  # enumeration too large even to build
    if not table.members:  # kmin > R: no combination can exist
        for s in np.nonzero(~too_few)[0]:
            errors[int(s)] = (
                "the number of clusters is less than the cluster "
                "spreadConstraint.MinGroups"
            )
        return ComboResult(chosen, errors, fallback)
    # rows whose own kmax exceeds what we enumerated (unbounded MaxGroups
    # with many regions) cannot be proven optimal here
    overflow = (~too_few) & (kmax_row > kmax_enum) & (n_present > kmax_enum)

    v64 = value.astype(np.int64)

    if device is None:
        # the device win only materializes once the (deduped) row count is
        # large — below that the dispatch+sync round-trip (~70 ms on the
        # tunnel) dwarfs the host BLAS pass
        device = (
            jax.default_backend() != "cpu"
            and S >= 4096
            and S * len(table.members) * table.max_len * 8
            <= SPREAD_COMBO_DEVICE_BYTES
        )
    if device:
        # the winner-selection block as ONE jitted program (int-exact)
        fi, nt, nf = jax.device_get(_combo_select_kernel(
            jnp.asarray(weight), jnp.asarray(value),
            jnp.asarray(kmax_row.astype(np.int32)),
            jnp.asarray(layout.rname_rank.astype(np.int32)),
            table=table, cmin=int(cfg.cmin), kmin=int(kmin),
        ))
        first_idx = np.asarray(fi)
        n_ties = np.asarray(nt)
        none_feasible = np.asarray(nf)
        return _finish_selection(
            weight, v64, cfg, layout, table, kmin, chosen, errors,
            fallback, overflow, first_idx, n_ties, none_feasible,
        )

    # host path (also the spec the device kernel is tested against)
    # int64 matmul has no BLAS path in numpy (it cost ~0.5 s at 5k rows x
    # 680 combos); float64 is exact while |weight| * path-length < 2^53,
    # which holds for every sane score (weight <= target*1000 + avg score).
    # The [S,K] aggregates STAY f64/i32 — halving the bandwidth of the
    # dozen masked passes below.
    onehot_f = table.onehot_f_t
    sum_w = weight.astype(np.float64) @ onehot_f  # exact below 2^48
    # values are i32 per region; a path of several huge regions can pass
    # 2^31, so the summed form stays i64 (f64 is exact: counts << 2^53)
    sum_v = (v64.astype(np.float64) @ onehot_f).astype(np.int64)
    members_present = (
        (present.astype(np.float64) @ onehot_f).astype(np.int32)
        == table.sizes[None, :]
    )
    feasible_combo = (
        members_present
        & (sum_v >= cfg.cmin)
        & (table.sizes[None, :] <= kmax_row[:, None])
    )

    # RECORDED-path pruning: the reference DFS returns at the FIRST
    # satisfied prefix (select_groups.go dfs), so a subset is enumerated
    # iff removing its LAST member in the group order (value asc, weight
    # desc, name asc) leaves an UNsatisfied prefix. Each row ranks its
    # regions in that order ONCE (pos, int8 — R <= 64), then every combo's
    # last member falls out of one [S, K, Lmax] positional gather.
    rr = layout.rname_rank
    order_g = np.lexsort(
        (np.broadcast_to(rr, (S, R)), -weight, v64), axis=-1
    )  # ascending group order; last position = the DFS path's last member
    pos = np.empty((S, R), np.int8)
    np.put_along_axis(pos, order_g, np.arange(R, dtype=np.int8)[None, :], -1)
    mp = table.members_pad  # [K, Lmax], -1 = pad
    mpc = np.where(mp >= 0, mp, 0)
    pos_g = pos[:, mpc]  # [S, K, Lmax] int8
    pos_g = np.where(mp[None, :, :] >= 0, pos_g, np.int8(-1))
    am = pos_g.argmax(axis=2)  # [S, K]
    last_region = mpc[np.arange(mpc.shape[0])[None, :], am]  # [S, K]
    v_last = np.take_along_axis(value, last_region, axis=1)  # i32
    recorded = (table.sizes[None, :] - 1 < kmin) | (sum_v - v_last < cfg.cmin)
    feasible_combo &= recorded

    w_masked = np.where(feasible_combo, sum_w, -np.inf)
    best_w = w_masked.max(1)
    none_feasible = np.isneginf(best_w)
    cand = w_masked == best_w[:, None]
    v_masked = np.where(cand, sum_v, np.int64(-(1 << 62)))
    best_v = v_masked.max(1)
    cand2 = cand & (sum_v == best_v[:, None]) & feasible_combo
    n_ties = cand2.sum(1)

    first_idx = np.argmax(cand2, axis=1)
    if n_ties.max(initial=0) > 1 and 7 * table.max_len <= 62:
        # (Σw, Σv) ties resolve by DFS DISCOVERY ORDER (prioritizePaths
        # sorts (weight desc, value desc, id asc), select_groups.go:207-213;
        # id = append order of the DFS, which emits recorded paths in
        # lexicographic order of their group-order position sequences, and
        # no recorded path is a prefix of another — the DFS returns at the
        # first satisfied prefix). Pack each combo's sorted positions into
        # one integer (7 bits/slot — positions reach 63 at R == MAX_REGIONS,
        # so the pad sentinel is a distinct 127) and take the min — skewed
        # fleets produce MANY exact ties (identical tiny regions), and this
        # keeps them off the per-row fallback entirely.
        tied = np.nonzero(n_ties > 1)[0]
        seq = np.where(pos_g[tied] < 0, 127, pos_g[tied]).astype(np.int64)
        seq.sort(axis=2)
        shifts = 7 * np.arange(table.max_len - 1, -1, -1, dtype=np.int64)
        disc = (seq << shifts).sum(axis=2)
        disc = np.where(cand2[tied], disc, np.int64(1) << 62)
        first_idx[tied] = disc.argmin(axis=1)
        n_ties[tied] = 1
    return _finish_selection(
        weight, v64, cfg, layout, table, kmin, chosen, errors,
        fallback, overflow, first_idx, n_ties, none_feasible,
    )


def _finish_selection(
    weight, v64, cfg, layout, table, kmin, chosen, errors, fallback,
    overflow, first_idx, n_ties, none_feasible,
) -> ComboResult:
    """Shared tail of select_regions_batch: error/fallback routing + the
    vectorized subpath preference, fed by either the host or the device
    winner selection."""
    S = weight.shape[0]
    rr = layout.rname_rank

    # rows that need a decision here (everything else errors or falls back)
    live = np.ones(S, bool)
    for s in np.nonzero(none_feasible)[0]:
        if int(s) not in errors:
            errors[int(s)] = (
                "the number of clusters is less than the cluster "
                "spreadConstraint.MinGroups"
            )
    live &= ~none_feasible
    for s in errors:
        live[s] = False
    fb_mask = live & (overflow | (n_ties > 1))
    fallback.extend(int(s) for s in np.nonzero(fb_mask)[0])
    live &= ~fb_mask
    rows = np.nonzero(live)[0]
    if not len(rows):
        return ComboResult(chosen, errors, fallback)

    # ---- vectorized subpath preference (select_groups.go:210-230): order
    # each winner's members by (weight desc, name asc), then take the
    # SHORTEST prefix that is itself a RECORDED feasible path ----
    Lmax = table.max_len
    mem = table.members_pad[first_idx[rows]]  # [N, Lmax] region ids, -1 = pad
    valid_m = mem >= 0
    midx = np.where(valid_m, mem, 0)
    mw = np.where(valid_m, weight[rows[:, None], midx], np.int64(-1) << 62)
    mv = np.where(valid_m, v64[rows[:, None], midx], 0)
    mn = np.where(valid_m, rr[midx], np.int64(1) << 40)
    # row-wise sort by (weight desc, name asc): stable argsort name, then -w
    o1 = np.argsort(mn, axis=1, kind="stable")
    mw1 = np.take_along_axis(mw, o1, 1)
    o2 = np.argsort(-mw1, axis=1, kind="stable")
    order = np.take_along_axis(o1, o2, 1)
    ms = np.take_along_axis(mem, order, 1)  # sorted member ids
    vs = np.take_along_axis(mv, order, 1)
    ws = np.take_along_axis(mw, order, 1)
    ns = np.take_along_axis(mn, order, 1)
    sizes_r = valid_m.sum(1)
    cum_v = np.cumsum(vs, axis=1)

    cut = sizes_r.copy()
    decided = np.zeros(len(rows), bool)
    for L in range(max(kmin, 1), Lmax):
        cand_rows = (~decided) & (sizes_r > L)
        if not cand_rows.any():
            break
        ok = cum_v[:, L - 1] >= cfg.cmin
        if L - 1 >= kmin:
            # recorded-ness: drop the prefix's value-order last member
            # ((value asc, weight desc, name asc) max) — tournament over L
            bv = vs[:, 0].copy()
            bw = ws[:, 0].copy()
            bn = ns[:, 0].copy()
            for j in range(1, L):
                after = (vs[:, j] > bv) | (
                    (vs[:, j] == bv)
                    & ((ws[:, j] < bw) | ((ws[:, j] == bw) & (ns[:, j] > bn)))
                )
                bv = np.where(after, vs[:, j], bv)
                bw = np.where(after, ws[:, j], bw)
                bn = np.where(after, ns[:, j], bn)
            ok = ok & (cum_v[:, L - 1] - bv < cfg.cmin)
        hit = cand_rows & ok
        cut[hit] = L
        decided |= hit

    # scatter the chosen prefixes: position < cut (over the sorted order)
    keep = np.arange(Lmax)[None, :] < cut[:, None]
    sel_rows = np.repeat(rows, Lmax)[keep.ravel()]
    sel_regions = ms.ravel()[keep.ravel()]
    chosen[sel_rows, sel_regions] = True
    return ComboResult(chosen, errors, fallback)


def host_group_score(feasible, score, avail, prev_replicas,
                     replicas, need, target, duplicated,
                     layout: RegionLayout):
    """group_score_kernel's numpy twin for the cpu backend (identical
    outputs; same segmented math as group_score_kernel_segmented, with the
    5-key lax.sort replaced by a packed single-key np.argsort when the
    per-batch value ranges fit an int64, else a stable np.lexsort).
    XLA:CPU's comparator-loop sort costs ~20 s at 6k rows x 5k clusters;
    this lands the same (weight, value, avail_sum, feas_count) in a couple
    of seconds. Parity is guarded by TestHostSpreadScoreParity."""
    feasible = np.asarray(feasible)
    score = np.asarray(score)
    avail = np.asarray(avail)
    prev_replicas = np.asarray(prev_replicas)
    S = feasible.shape[0]
    Cp = layout.seg_cp
    perm = layout.perm[:Cp]
    seg = layout.seg_id_p.astype(np.int64)
    seg_start = layout.seg_start
    seg_end = layout.seg_end

    f = feasible[:, perm]
    av = np.where(
        f,
        avail[:, perm].astype(np.int64) + prev_replicas[:, perm].astype(np.int64),
        0,
    )
    sc = np.where(f, score[:, perm].astype(np.int64), 0)
    rank = layout.name_rank_p[:Cp].astype(np.int64)
    infeas = ~f

    # member order per region: (infeasible, score desc, avail desc, name) —
    # the seg id leads so each region's members land contiguous
    sb = max(int(sc.max(initial=0)).bit_length(), 1)
    ab = max(int(av.max(initial=0)).bit_length(), 1)
    # ranks range over the FULL fleet (regionless clusters hold ranks too)
    rb = max(int(rank.max(initial=0)).bit_length(), 1)
    gb = max(int(max(layout.n_regions - 1, 1)).bit_length(), 1)
    # negative values (out-of-tree plugin scores) break the offset-binary
    # packing; signed inputs take the lexsort path
    signed = int(sc.min(initial=0)) < 0 or int(av.min(initial=0)) < 0
    if not signed and gb + 1 + sb + ab + rb <= 63:
        packed = (
            (seg[None, :] << (1 + sb + ab + rb))
            | (infeas.astype(np.int64) << (sb + ab + rb))
            | ((int(sc.max(initial=0)) - sc) << (ab + rb))
            | ((int(av.max(initial=0)) - av) << rb)
            | rank[None, :]
        )
        order = np.argsort(packed, axis=-1, kind="stable")
    else:  # values too wide to pack: stable lexsort, last key primary
        order = np.lexsort((
            np.broadcast_to(rank, (S, Cp)), -av, -sc,
            infeas.astype(np.int64), np.broadcast_to(seg, (S, Cp)),
        ), axis=-1)
    f_s = np.take_along_axis(f, order, axis=-1)
    av_s = np.take_along_axis(av, order, axis=-1)
    sc_s = np.take_along_axis(sc, order, axis=-1)

    def excl(x):  # P[j] = sum of first j entries, [S, Cp+1]
        return np.concatenate(
            [np.zeros((S, 1), x.dtype), np.cumsum(x, axis=-1)], axis=1
        )

    def segsum(P):  # [S, R]
        return P[:, seg_end] - P[:, seg_start]

    Pf = excl(f_s.astype(np.int64))
    Pav = excl(av_s)
    Psc = excl(sc_s)
    value64 = segsum(Pf)
    value = value64.astype(np.int32)
    av_sum = segsum(Pav)
    sc_sum = segsum(Psc)

    iota = np.arange(Cp, dtype=np.int64)[None, :]
    seg32 = seg.astype(np.int64)
    idx_rel = iota - seg_start[seg32][None, :]
    cum_av_rel = Pav[:, 1:] - Pav[:, seg_start[seg32]]
    value_at = value64[:, seg32]
    condA = idx_rel + 1 >= need[:, None]
    condB = cum_av_rel >= target[:, None]
    condC = idx_rel < value_at
    fail = (condC & ~(condA & condB)).astype(np.int64)
    k_count = segsum(excl(fail))
    met = k_count < value64
    k_eff = np.clip(np.where(met, k_count, value64 - 1), 0, max(Cp - 1, 0))
    at = seg_start[None, :] + k_eff.astype(np.int32) + 1
    sc_at_k = np.take_along_axis(Psc, at, axis=1) - Psc[:, seg_start]
    denom = np.maximum(np.where(met, k_eff + 1, value64), 1)
    tgt = target[:, None]
    w_div = np.where(
        av_sum < tgt,
        av_sum * WEIGHT_UNIT + sc_sum // np.maximum(value64, 1),
        tgt * WEIGHT_UNIT + sc_at_k // denom,
    )
    dup_ok = f & (av >= replicas[:, None])
    cnt = segsum(excl(dup_ok.astype(np.int64)))
    sc_dup = segsum(excl(np.where(dup_ok, sc, 0)))
    w_dup = np.where(
        cnt > 0, cnt * WEIGHT_UNIT + sc_dup // np.maximum(cnt, 1), 0
    )

    weight = np.where(duplicated[:, None], w_dup, w_div)
    weight = np.where(value > 0, weight, 0)
    return weight, value, av_sum, feasible.sum(-1).astype(np.int32)

"""Priority scheduling queue (SCH3).

Parity with pkg/scheduler/internal/queue/scheduling_queue.go:43-57 under the
PriorityBasedScheduling feature gate: an activeQ (max-heap by binding priority,
FIFO among equals), a backoffQ with exponential per-key backoff (1s initial →
10s max), and an unschedulable pool whose items re-enter activeQ after at most
5 minutes. The heap mirrors internal/heap/heap.go; priority comes from
`spec.SchedulePriorityValue()` (event_handler.go:122-137) — here the binding's
`schedule_priority` (None ⇒ 0).

Starvation control: the reference pops strictly by priority, so a sustained
flood of high-priority bindings can park priority-0 keys in activeQ forever —
under the streaming scheduler (sched/streaming.py), where admission never
pauses, that is a real livelock, not a transient. This queue AGES instead:
a key's effective priority grows by one for every `aging_step` seconds it
waits in activeQ, so any binding eventually out-ranks a flood of fresh
arrivals while short-term ordering stays exactly priority-then-FIFO. Aging
uses the injectable clock (deterministic in fake-clock tests); 0 disables it.

Implements the same queue interface the controller runtime drains
(add/pop/drain/retry/forget/len), so it can be dropped into a
BatchingController in place of the FIFO WorkQueue. Time is injectable (Clock)
so backoff and aging windows are deterministic in tests.
"""
from __future__ import annotations

import heapq
import itertools
import math
import threading
from typing import Callable, Optional

DEFAULT_BACKOFF_INITIAL = 1.0  # scheduling_queue.go:43-51
DEFAULT_BACKOFF_MAX = 10.0
DEFAULT_UNSCHEDULABLE_MAX_STAY = 300.0  # 5 min
DEFAULT_AGING_STEP = 60.0  # +1 effective priority per minute of queue age

DEFAULT_GANG_WAIT = 30.0  # partial gangs reject after this hold window


class GangCoordinator:
    """The queue-side half of gang scheduling (sched/preemption.py): holds
    partial gangs — bindings sharing `spec.gang_name`, expecting
    `spec.gang_size` members — until the cohort completes (all K offered)
    or a timeout rejects it. A drained gang member parks HERE instead of
    entering a micro-batch; the offer that completes the gang releases
    every held member into the CURRENT batch formation, so the cohort
    always solves (and commits) together.

    Held entries keep the binding snapshot + admission epoch captured at
    offer time: a member whose spec changes while held re-offers through
    its own watch event and REPLACES the stale entry (key-based), and the
    epoch fence discards any decision computed on a replaced snapshot.
    Thread-safe — the streaming admission loop and the batch daemon's
    drain both offer."""

    def __init__(self, clock, wait_seconds: float = DEFAULT_GANG_WAIT):
        self.clock = clock
        self.wait_seconds = wait_seconds
        self._lock = threading.Lock()
        # gang -> key -> (binding snapshot, epoch)
        self._held: dict[str, dict[str, tuple]] = {}
        self._deadline: dict[str, float] = {}
        self._size: dict[str, int] = {}

    def offer(self, key: str, rb, epoch: int = 0) -> list[tuple]:
        """Offer one gang member. Returns the full cohort [(key, binding,
        epoch), ...] when this offer completes the gang (the coordinator
        forgets it — the cohort is the caller's now), else [] (held)."""
        gname = rb.spec.gang_name
        with self._lock:
            g = self._held.setdefault(gname, {})
            if not g:
                self._deadline[gname] = self.clock.now() + self.wait_seconds
            g[key] = (rb, epoch)
            # misdeclared sizes: the largest declared K wins (a gang can
            # only complete when every declared expectation is met)
            self._size[gname] = max(
                self._size.get(gname, 0), int(rb.spec.gang_size or 0)
            )
            if len(g) >= max(self._size[gname], 1):
                self._forget_locked(gname)
                return [(k, r, e) for k, (r, e) in g.items()]
            return []

    def discard(self, key: str, gang_name: str) -> None:
        """Drop one held member (tombstone / re-target / suspension): the
        remainder keeps waiting and times out if never completed."""
        with self._lock:
            g = self._held.get(gang_name)
            if g is not None:
                g.pop(key, None)
                if not g:
                    self._forget_locked(gang_name)

    def expire(self, now: float) -> list[tuple[str, list[tuple]]]:
        """Pop every gang whose hold window elapsed incomplete:
        [(gang_name, [(key, binding, epoch), ...]), ...]."""
        out = []
        with self._lock:
            for gname in [g for g, d in self._deadline.items() if now >= d]:
                members = self._held.get(gname, {})
                out.append(
                    (gname, [(k, r, e) for k, (r, e) in members.items()])
                )
                self._forget_locked(gname)
        return out

    def held_count(self) -> int:
        with self._lock:
            return sum(len(g) for g in self._held.values())

    def _forget_locked(self, gname: str) -> None:
        self._held.pop(gname, None)
        self._deadline.pop(gname, None)
        self._size.pop(gname, None)


class PrioritySchedulingQueue:
    """activeQ + backoffQ + unschedulable pool.

    `priority_fn(key) -> int` resolves a binding key to its current priority at
    enqueue time (the reference reads spec.SchedulePriorityValue at event time).
    """

    def __init__(
        self,
        clock,
        priority_fn: Optional[Callable[[str], int]] = None,
        backoff_initial: float = DEFAULT_BACKOFF_INITIAL,
        backoff_max: float = DEFAULT_BACKOFF_MAX,
        unschedulable_max_stay: float = DEFAULT_UNSCHEDULABLE_MAX_STAY,
        max_retries: int = 16,
        aging_step: float = DEFAULT_AGING_STEP,
    ):
        self.clock = clock
        self.priority_fn = priority_fn or (lambda _key: 0)
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self.unschedulable_max_stay = unschedulable_max_stay
        self.max_retries = max_retries
        self.aging_step = aging_step
        # enqueue wakeup hook — same contract as WorkQueue.on_add (called
        # outside any internal state mutation): the streaming scheduler's
        # condition variable notifies on it
        self.on_add: Optional[Callable[[], None]] = None
        # under the streaming scheduler this queue is shared across threads
        # (watch handlers add, the admission loop drains, the writer
        # forgets/retries) — the same cross-goroutine seam WorkQueue locks
        self._lock = threading.RLock()

        self._seq = itertools.count()  # FIFO tie-break among equal priorities
        # base priority per known key, read at add() time OUTSIDE the lock
        # (see add()); lifecycle matches _attempts (cleared by forget)
        self._base_prio: dict[str, int] = {}
        self._active: list[tuple[int, int, str]] = []  # (-eff_prio, seq, key)
        # key -> (base priority, seq, activeQ-entry time): the live entry
        # set the aging re-heap rebuilds from (heap entries are immutable,
        # so aging periodically re-keys the whole heap from this map)
        self._active_meta: dict[str, tuple[int, int, float]] = {}
        self._aged_at: float = clock.now()
        self._backoff: list[tuple[float, int, str]] = []  # (due, seq, key)
        self._in_backoff: set[str] = set()
        self._unschedulable: dict[str, float] = {}  # key -> entered-at
        # earliest entered-at still (possibly) parked: _flush skips the
        # full-map expiry scan until this key's stay can have elapsed —
        # the streaming loop calls _flush several times per admission, and
        # an O(parked) comprehension per call is the kind of lock-held work
        # watch handlers contend on. Removals may leave this stale-early
        # (one wasted scan recomputes it); it is never stale-late.
        self._unsched_earliest: float = math.inf
        self._attempts: dict[str, int] = {}

    # -- queue interface (WorkQueue-compatible) ---------------------------

    def add(self, key: str) -> None:
        """Add/move to activeQ. An add always wins over backoff/unschedulable
        (a fresh event means new information — moveToActiveQ semantics).

        `priority_fn` runs BEFORE the lock, never under it: it typically
        reads the store (SchedulerDaemon._priority_of), and watch handlers
        calling add() can run WITH the store lock held (Store.apply) — a
        priority read under the queue lock would complete an ABBA cycle
        with that path. The base priority is cached per key (cleared by
        forget()) so backoff/unschedulable re-activation inside _flush —
        which does run under the lock — never needs the callback; a fresh
        add() re-reads it. Duplicate events for an already-active key
        return before the priority read at all — under sustained watch
        floods that store get would otherwise run per event."""
        with self._lock:
            if key in self._active_meta:
                # active keys are never simultaneously parked (backoff /
                # unschedulable pushes refuse active keys), so this is a
                # complete no-op re-event
                return
        prio = self.priority_fn(key)
        with self._lock:
            self._base_prio[key] = prio
            self._in_backoff.discard(key)
            self._unschedulable.pop(key, None)
            if key in self._active_meta:
                return
            self._push_active(key)
        if self.on_add is not None:
            self.on_add()

    def pop(self) -> Optional[str]:
        with self._lock:
            self._flush()
            while self._active:
                _, _, key = heapq.heappop(self._active)
                if key in self._active_meta:
                    del self._active_meta[key]
                    return key
            return None

    def drain(self, limit: Optional[int] = None) -> list[str]:
        """Pop up to `limit` due keys (all, when None) in priority order —
        the streaming micro-batch former's quota drain, under ONE lock
        hold and ONE backoff/unschedulable flush (a pop-per-item loop
        would rescan the unschedulable map per key). Aging keeps a bounded
        drain fair: a starved key's effective priority eventually rises
        into every quota."""
        out: list[str] = []
        with self._lock:
            self._flush()
            while self._active and (limit is None or len(out) < limit):
                _, _, key = heapq.heappop(self._active)
                if key in self._active_meta:
                    del self._active_meta[key]
                    out.append(key)
        return out

    def readd(self, key: str) -> None:
        """Return a previously drained key to activeQ WITHOUT consulting
        `priority_fn`: the cached base priority (which a drain leaves in
        place — only forget() clears it) is used as-is. The streaming
        scheduler's error-recovery paths re-admit drained keys with this:
        `priority_fn` typically reads the store, and those paths run
        exactly when the store is erroring — a raise mid-loop would lose
        every key after it."""
        with self._lock:
            self._in_backoff.discard(key)
            self._unschedulable.pop(key, None)
            if key in self._active_meta:
                return
            self._push_active(key)
        if self.on_add is not None:
            self.on_add()

    def retry(self, key: str) -> bool:
        """Failed attempt → backoffQ with exponential delay."""
        with self._lock:
            n = self._attempts.get(key, 0) + 1
            self._attempts[key] = n
            if n > self.max_retries:
                return False
            delay = min(
                self.backoff_initial * (2 ** (n - 1)), self.backoff_max
            )
            self._push_backoff(key, delay)
            return True

    def forget(self, key: str) -> None:
        with self._lock:
            self._attempts.pop(key, None)
            # keep the cached priority while the key is PARKED: the patch
            # path forgets right after _patch_result may have pushed the
            # key unschedulable, and its later _flush re-activation must
            # re-enqueue at the real priority, not 0
            if (key not in self._in_backoff
                    and key not in self._unschedulable
                    and key not in self._active_meta):
                self._base_prio.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            self._flush()
            return (len(self._active_meta) + len(self._in_backoff)
                    + len(self._unschedulable))

    # -- scheduler-facing extras ------------------------------------------

    def push_unschedulable(self, key: str) -> None:
        """Park a binding that found no feasible cluster; it re-enters activeQ
        after at most `unschedulable_max_stay` (or earlier via add())."""
        with self._lock:
            if key in self._active_meta or key in self._in_backoff:
                return
            self._unschedulable.setdefault(key, self.clock.now())
            self._unsched_earliest = min(
                self._unsched_earliest, self._unschedulable[key]
            )

    def active_len(self) -> int:
        with self._lock:
            self._flush()
            return len(self._active_meta)

    # -- internals --------------------------------------------------------

    def _effective(self, prio: int, entered: float, now: float) -> int:
        """Effective priority: base + one per aging_step seconds of activeQ
        age — the anti-starvation ramp (0 disables aging)."""
        if self.aging_step <= 0 or now <= entered:
            return prio
        return prio + int((now - entered) / self.aging_step)

    def _push_active(self, key: str, now: Optional[float] = None) -> None:
        if now is None:
            now = self.clock.now()
        prio = self._base_prio.get(key, 0)
        seq = next(self._seq)
        self._active_meta[key] = (prio, seq, now)
        heapq.heappush(self._active, (-prio, seq, key))

    def _push_backoff(self, key: str, delay: float) -> None:
        if key in self._active_meta or key in self._in_backoff:
            return
        heapq.heappush(self._backoff, (self.clock.now() + delay, next(self._seq), key))
        self._in_backoff.add(key)

    def _flush(self) -> None:
        """Move due backoff items and expired unschedulable items to activeQ
        (the reference's flushBackoffQCompleted / flushUnschedulableLeftover),
        then re-age the heap once per aging_step."""
        now = self.clock.now()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, key = heapq.heappop(self._backoff)
            if key in self._in_backoff:
                self._in_backoff.discard(key)
                if key not in self._active_meta:
                    self._push_active(key, now)
        if (self._unschedulable
                and now - self._unsched_earliest
                >= self.unschedulable_max_stay):
            expired = [
                k
                for k, entered in self._unschedulable.items()
                if now - entered >= self.unschedulable_max_stay
            ]
            for key in expired:
                self._unschedulable.pop(key, None)
                if key not in self._active_meta:
                    self._push_active(key, now)
            self._unsched_earliest = min(
                self._unschedulable.values(), default=math.inf
            )
        if self.aging_step > 0 and now - self._aged_at >= self.aging_step:
            # re-key the heap with aged effective priorities; rebuilding
            # from the meta map also sweeps lazily-deleted stale entries
            self._aged_at = now
            self._active = [
                (-self._effective(p, entered, now), seq, k)
                for k, (p, seq, entered) in self._active_meta.items()
            ]
            heapq.heapify(self._active)

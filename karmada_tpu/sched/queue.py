"""Priority scheduling queue (SCH3).

Parity with pkg/scheduler/internal/queue/scheduling_queue.go:43-57 under the
PriorityBasedScheduling feature gate: an activeQ (max-heap by binding priority,
FIFO among equals), a backoffQ with exponential per-key backoff (1s initial →
10s max), and an unschedulable pool whose items re-enter activeQ after at most
5 minutes. The heap mirrors internal/heap/heap.go; priority comes from
`spec.SchedulePriorityValue()` (event_handler.go:122-137) — here the binding's
`schedule_priority` (None ⇒ 0).

Implements the same queue interface the controller runtime drains
(add/pop/retry/forget/len), so it can be dropped into a BatchingController in
place of the FIFO WorkQueue. Time is injectable (Clock) so backoff windows are
deterministic in tests.
"""
from __future__ import annotations

import heapq
import itertools
from typing import Callable, Optional

DEFAULT_BACKOFF_INITIAL = 1.0  # scheduling_queue.go:43-51
DEFAULT_BACKOFF_MAX = 10.0
DEFAULT_UNSCHEDULABLE_MAX_STAY = 300.0  # 5 min


class PrioritySchedulingQueue:
    """activeQ + backoffQ + unschedulable pool.

    `priority_fn(key) -> int` resolves a binding key to its current priority at
    enqueue time (the reference reads spec.SchedulePriorityValue at event time).
    """

    def __init__(
        self,
        clock,
        priority_fn: Optional[Callable[[str], int]] = None,
        backoff_initial: float = DEFAULT_BACKOFF_INITIAL,
        backoff_max: float = DEFAULT_BACKOFF_MAX,
        unschedulable_max_stay: float = DEFAULT_UNSCHEDULABLE_MAX_STAY,
        max_retries: int = 16,
    ):
        self.clock = clock
        self.priority_fn = priority_fn or (lambda _key: 0)
        self.backoff_initial = backoff_initial
        self.backoff_max = backoff_max
        self.unschedulable_max_stay = unschedulable_max_stay
        self.max_retries = max_retries

        self._seq = itertools.count()  # FIFO tie-break among equal priorities
        self._active: list[tuple[int, int, str]] = []  # (-priority, seq, key)
        self._in_active: set[str] = set()
        self._backoff: list[tuple[float, int, str]] = []  # (due, seq, key)
        self._in_backoff: set[str] = set()
        self._unschedulable: dict[str, float] = {}  # key -> entered-at
        self._attempts: dict[str, int] = {}

    # -- queue interface (WorkQueue-compatible) ---------------------------

    def add(self, key: str) -> None:
        """Add/move to activeQ. An add always wins over backoff/unschedulable
        (a fresh event means new information — moveToActiveQ semantics)."""
        self._in_backoff.discard(key)
        self._unschedulable.pop(key, None)
        if key in self._in_active:
            return
        prio = self.priority_fn(key)
        heapq.heappush(self._active, (-prio, next(self._seq), key))
        self._in_active.add(key)

    def pop(self) -> Optional[str]:
        self._flush()
        while self._active:
            _, _, key = heapq.heappop(self._active)
            if key in self._in_active:
                self._in_active.discard(key)
                return key
        return None

    def retry(self, key: str) -> bool:
        """Failed attempt → backoffQ with exponential delay."""
        n = self._attempts.get(key, 0) + 1
        self._attempts[key] = n
        if n > self.max_retries:
            return False
        delay = min(self.backoff_initial * (2 ** (n - 1)), self.backoff_max)
        self._push_backoff(key, delay)
        return True

    def forget(self, key: str) -> None:
        self._attempts.pop(key, None)

    def __len__(self) -> int:
        self._flush()
        return len(self._in_active) + len(self._in_backoff) + len(self._unschedulable)

    # -- scheduler-facing extras ------------------------------------------

    def push_unschedulable(self, key: str) -> None:
        """Park a binding that found no feasible cluster; it re-enters activeQ
        after at most `unschedulable_max_stay` (or earlier via add())."""
        if key in self._in_active or key in self._in_backoff:
            return
        self._unschedulable.setdefault(key, self.clock.now())

    def active_len(self) -> int:
        self._flush()
        return len(self._in_active)

    # -- internals --------------------------------------------------------

    def _push_backoff(self, key: str, delay: float) -> None:
        if key in self._in_active or key in self._in_backoff:
            return
        heapq.heappush(self._backoff, (self.clock.now() + delay, next(self._seq), key))
        self._in_backoff.add(key)

    def _flush(self) -> None:
        """Move due backoff items and expired unschedulable items to activeQ
        (the reference's flushBackoffQCompleted / flushUnschedulableLeftover)."""
        now = self.clock.now()
        while self._backoff and self._backoff[0][0] <= now:
            _, _, key = heapq.heappop(self._backoff)
            if key in self._in_backoff:
                self._in_backoff.discard(key)
                if key not in self._in_active:
                    prio = self.priority_fn(key)
                    heapq.heappush(self._active, (-prio, next(self._seq), key))
                    self._in_active.add(key)
        expired = [
            k
            for k, entered in self._unschedulable.items()
            if now - entered >= self.unschedulable_max_stay
        ]
        for key in expired:
            self._unschedulable.pop(key, None)
            if key not in self._in_active:
                prio = self.priority_fn(key)
                heapq.heappush(self._active, (-prio, next(self._seq), key))
                self._in_active.add(key)

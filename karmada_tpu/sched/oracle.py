"""Sequential oracle: a direct, obviously-correct transcription of the
reference scheduler's per-binding semantics, used ONLY in tests to validate
the batched device path (the "sequential-equivalence mode for parity testing"
from SURVEY §7). One binding at a time, plain Python ints — mirrors
pkg/scheduler/core/{generic_scheduler,assignment,division_algorithm}.go and
pkg/util/helper/binding.go behavior, with the crypto-rand tie-break replaced
by the same deterministic `tie` values the device uses.
"""
from __future__ import annotations

from typing import Optional, Sequence

from ..api.cluster import Cluster, cluster_api_enabled, cluster_ready
from ..api.cluster import EFFECT_NO_EXECUTE, EFFECT_NO_SCHEDULE
from ..api.policy import Placement
from ..api.work import ResourceBinding, TargetCluster
from ..models.batch import (
    AGGREGATED,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    NON_WORKLOAD,
    STATIC_WEIGHT,
    strategy_code,
    _reschedule_required,
)
from .affinity import cluster_matches

MAX_INT32 = 2**31 - 1


class Unschedulable(Exception):
    pass


def tolerates_all_taints(placement: Optional[Placement], cluster: Cluster) -> bool:
    tolerations = placement.cluster_tolerations if placement else []
    for taint in cluster.spec.taints:
        if taint.effect not in (EFFECT_NO_SCHEDULE, EFFECT_NO_EXECUTE):
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            return False
    return True


def feasible_clusters(rb: ResourceBinding, clusters: Sequence[Cluster]) -> list[Cluster]:
    spec = rb.spec
    placement = spec.placement
    affinity = None
    if placement is not None:
        affinity = placement.cluster_affinity
        if placement.cluster_affinities:
            affinity = placement.cluster_affinities[0].affinity
    evicted = {t.from_cluster for t in spec.graceful_eviction_tasks}
    out = []
    for c in clusters:
        if not cluster_ready(c):
            continue
        if not cluster_api_enabled(c, spec.resource.api_version, spec.resource.kind):
            continue
        if not cluster_matches(c, affinity):
            continue
        if not tolerates_all_taints(placement, c):
            continue
        if c.name in evicted:
            continue
        out.append(c)
    return out


def general_estimate_one(cluster: Cluster, request: dict[str, float], replicas: int) -> int:
    from ..models.fleet import to_int_units

    rs = cluster.status.resource_summary
    if rs is None:
        return 0
    positive = {k: v for k, v in request.items() if to_int_units(k, v) > 0}
    if not positive:
        return replicas  # MaxInt32 → clamp (core/util.go:94-100)
    best = MAX_INT32
    for k, v in positive.items():
        if k not in rs.allocatable:
            return 0
        a = (
            to_int_units(k, rs.allocatable.get(k, 0.0))
            - to_int_units(k, rs.allocated.get(k, 0.0))
            - to_int_units(k, rs.allocating.get(k, 0.0))
        )
        if a <= 0:
            return 0
        best = min(best, a // to_int_units(k, v))
    return replicas if best >= MAX_INT32 else best


def take_by_weight(
    entries: list[tuple[str, int, int, int]],  # (name, weight, last, tie)
    target: int,
    init: dict[str, int],
) -> tuple[dict[str, int], int]:
    """Dispenser.TakeByWeight (binding.go:112-144)."""
    result = dict(init)
    total = sum(w for _, w, _, _ in entries)
    if total == 0:
        return result, target
    ordered = sorted(entries, key=lambda e: (-e[1], -e[2], e[3]))
    remain = target
    quotas = []
    for name, w, _, _ in ordered:
        q = w * target // total
        quotas.append([name, q])
        remain -= q
    for q in quotas:
        if remain == 0:
            break
        q[1] += 1
        remain -= 1
    for name, q in quotas:
        result[name] = result.get(name, 0) + q
    return result, remain


def schedule_one(
    rb: ResourceBinding,
    clusters: Sequence[Cluster],
    tie: dict[str, int],
) -> list[TargetCluster]:
    spec = rb.spec
    candidates = feasible_clusters(rb, clusters)
    if not candidates:
        raise Unschedulable(f"0/{len(clusters)} clusters are available")
    code = strategy_code(spec.placement, spec.replicas)

    if code == NON_WORKLOAD:
        return [TargetCluster(name=c.name, replicas=0) for c in candidates]
    if code == DUPLICATED:
        return [TargetCluster(name=c.name, replicas=spec.replicas) for c in candidates]

    prev = {tc.name: tc.replicas for tc in spec.clusters}
    if code == STATIC_WEIGHT:
        weights = []
        rules = (
            spec.placement.replica_scheduling.weight_preference.static_weight_list
            if spec.placement.replica_scheduling.weight_preference
            else []
        )
        for c in candidates:
            w = 0
            for r in rules:
                if cluster_matches(c, r.target_cluster):
                    w = max(w, r.weight)
            if w > 0:
                weights.append((c.name, w, prev.get(c.name, 0), tie[c.name]))
        if not weights:
            weights = [(c.name, 1, prev.get(c.name, 0), tie[c.name]) for c in candidates]
        result, _ = take_by_weight(weights, spec.replicas, {})
        return [TargetCluster(name=n, replicas=r) for n, r in result.items() if r > 0]

    # dynamic strategies
    req = spec.replica_requirements.resource_request if spec.replica_requirements else {}
    avail = {c.name: general_estimate_one(c, req, spec.replicas) for c in candidates}
    scheduled = [(n, prev[n]) for n in (c.name for c in candidates) if n in prev]
    assigned = sum(r for _, r in scheduled)
    fresh = _reschedule_required(spec, rb.status)
    aggregated = code == AGGREGATED

    if fresh:
        target = spec.replicas
        weight_list = [(n, avail[n] + prev.get(n, 0)) for n in avail]
        init: dict[str, int] = {}
        last: dict[str, int] = {}
    elif assigned > spec.replicas:  # scale down
        target = spec.replicas
        weight_list = list(scheduled)
        init, last = {}, {}
    elif assigned < spec.replicas:  # scale up / first schedule
        target = spec.replicas - assigned
        weight_list = [(n, avail[n]) for n in avail]
        init = dict(scheduled)
        last = dict(scheduled)
    else:
        return [TargetCluster(name=n, replicas=r) for n, r in scheduled if r > 0]

    if sum(w for _, w in weight_list) < target:
        raise Unschedulable(
            f"Clusters available replicas {sum(w for _, w in weight_list)} are not enough to schedule."
        )

    if aggregated:
        prior = {n for n, r in (init.items() if init else []) if r > 0}
        order = sorted(
            weight_list,
            key=lambda e: (0 if e[0] in prior else 1, -e[1], _index_of(candidates, e[0])),
        )
        cum, kept = 0, []
        for n, w in order:
            kept.append((n, w))
            cum += w
            if cum >= target:
                break
        weight_list = kept

    entries = [(n, w, last.get(n, 0), tie[n]) for n, w in weight_list]
    result, _ = take_by_weight(entries, target, init)
    return [TargetCluster(name=n, replicas=r) for n, r in result.items() if r > 0]


def _index_of(candidates, name):
    for i, c in enumerate(candidates):
        if c.name == name:
            return i
    return len(candidates)

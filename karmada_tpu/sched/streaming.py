"""Streaming scheduler: kill the round boundary.

The batch daemon wakes on a fixed tick, drains every dirty binding into one
round, and sleeps — a binding arriving right after a drain waits the whole
tick PLUS the whole next round before its placement patches, and the only
latency anyone can state is a round p99. This module replaces the tick with
an always-on admission service, the continuous-batching shape LLM inference
serving proved out (Orca/vLLM-style in-flight batching): admit new work into
the gaps of an already-running pipeline instead of waiting for the next
round.

Mechanics:

- **Event-driven wakeup.** Watch events enqueue keys and notify a condition
  variable (`WorkQueue.on_add` / `PrioritySchedulingQueue.on_add`); the
  admission loop sleeps until work exists, with the old `--interval` kept
  only as a max-sleep fallback so an idle leader still runs its idle hook
  (election renew piggyback, prewarm re-checks). The 0.2 s idle latency
  floor is gone.
- **Micro-batch admission.** When work arrives the loop optionally waits
  `batch_delay` (the `--batch-delay-ms` knob: trade a latency floor for
  batch efficiency — applied only to trickle arrivals; a backlog admits
  immediately), then drains a quota of keys and launches them as ONE
  micro-batch through the open-ended StreamPipeline. The launch returns as
  soon as the kernels dispatch; the loop goes straight back to
  accumulating, so the NEXT micro-batch forms while this one solves on
  device and the previous one patches on the writer. Micro-batch size is
  self-pacing: it grows toward arrival_rate × solve_time under load and
  shrinks to single bindings when traffic trickles.
- **Epoch-tagged staleness.** Every watch event bumps the binding's
  admission epoch (scheduler.AdmissionLog). A micro-batch snapshots each
  binding's epoch BEFORE reading its spec; if the epoch moved by the time
  the writer patches — the binding dirtied mid-flight — the in-flight
  decision is DISCARDED and the binding re-admits with its fresh spec (the
  bumping event already re-enqueued the key).
- **Parity.** Decisions for any stable snapshot are bit-identical to the
  equivalent one-shot batch round: micro-batches ride the same replay-aware
  `launch_chunk`/`materialize_chunk` rows-independent solve, and the
  tie-break is UID-seeded — batch composition cannot leak into placements
  (pinned by tests/test_streaming.py).
- **Zero steady-state compiles.** Micro-batch rows pad to the shape_bucket
  lattice like every other round, the drain quota is FLOORED to a lattice
  point (a deep queue drains exactly a bucket's worth and leaves the
  remainder for the immediately-following batch, instead of padding up),
  and the AOT prewarm ladder includes the micro-batch buckets
  (sched/aot.py MICROBATCH_LADDER) — so admission-driven batch-size drift
  inside a bucket changes tensor values, never program shapes.

Streaming admission is leader-only (docs/HA.md): the daemon runs `serve()`
only while it holds the scheduler lease, and a standby's queue keeps
accumulating from its own watches — takeover resumes the queue, losing
nothing but the deposed leader's un-patched in-flight decisions (whose
patches would bounce on the fencing token anyway).
"""
from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..metrics import (
    degraded_rounds,
    e2e_scheduling_duration,
    microbatch_size,
    placement_latency,
    sched_queue_depth,
    schedule_attempts,
)
from ..models.batch import shape_floor
from ..tracing import tracer
from .pipeline import DEFAULT_DEPTH, StageTimer, StreamPipeline

log = logging.getLogger(__name__)

# trickle threshold: the batching delay only applies while fewer than one
# minimal row bucket is ready — under backlog, delaying admission buys no
# batching (the batch is already big) and only adds latency
MIN_ACCUMULATE = 8

# drain-quota ceiling when the caller sets none: one shape_bucket lattice
# run's worth of rows — micro-batches above this split across consecutive
# admissions (each still under the pipeline's per-chunk HBM cap)
DEFAULT_MAX_BATCH = 4096


@dataclass
class _MicroBatch:
    """One admitted micro-batch: the bindings with their pre-read epoch
    snapshots (the staleness fence) and the per-batch accounting the patch
    stage publishes."""

    bindings: list
    keys: list[str]
    epochs: list[int]
    compile_snap: dict
    t0: float  # perf_counter at formation (e2e histogram)
    swept_open: tuple = ()
    replayed: int = 0
    solved: int = 0
    stats: dict = field(default_factory=dict)
    # tracing: (launch id, wall start, wall dispatch-end) of the shared
    # device launch — ONE span fanned out to the batch's member traces
    launch_wall: tuple = ()


class StreamingScheduler:
    """The admission service around a SchedulerDaemon.

    `serve()` runs the admission loop on the calling thread (the daemon
    main thread while it leads); `stop()` — or `should_stop` returning
    True — makes it return after draining in-flight work. `batch_delay`,
    `interval`, `max_batch`, `depth` are the tuning surface; everything
    else (what needs scheduling, how it solves, how results patch) is the
    daemon's existing machinery."""

    def __init__(
        self,
        daemon,
        batch_delay: float = 0.005,
        interval: float = 0.2,
        max_batch: int = 0,
        depth: int = DEFAULT_DEPTH,
    ) -> None:
        self.daemon = daemon
        self.batch_delay = batch_delay
        self.interval = interval
        self.max_batch = max_batch
        self.depth = depth
        self._cond = threading.Condition()
        self._stop_evt = threading.Event()
        self._serving = False
        self._array = None
        self._timer: Optional[StageTimer] = None
        self._stop_check: Callable[[], bool] = lambda: False
        self._n_batches = 0
        self._stats_lock = threading.Lock()
        # keys from a failed multi-key micro-batch: the culprit is unknown,
        # so each suspect re-admits as a SINGLETON batch (the
        # BatchingController.step isolation discipline) — the next failure
        # charges exactly the poison binding's retry budget, and its
        # healthy cohort neighbors keep theirs. Plain-set membership ops
        # only (atomic under the GIL); touched by admission + writer.
        self._suspects: set[str] = set()
        from collections import deque

        # exact recent placement latencies (admission → patch), next to the
        # bucketed histogram: the stream bench reports honest percentiles
        self._latencies: deque = deque(maxlen=100_000)
        self.stats = {
            "batches": 0, "formed": 0, "admitted": 0, "placed": 0,
            "failed": 0, "stale_discarded": 0, "clean": 0, "jit_compiles": 0,
        }
        # attach: admission/epoch bookkeeping on, condvar wakeup on
        # enqueue, micro-batch buckets into the AOT prewarm walk
        daemon.admission.enabled = True
        daemon.stream_prewarm = True
        daemon.controller.queue.on_add = self._wake

    # -- wakeup ------------------------------------------------------------

    def _wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def stop(self) -> None:
        """Make a serve() on another thread return (clean shutdown and the
        leadership-loss path both land here)."""
        self._stop_evt.set()
        self._wake()

    def _ready(self) -> int:
        q = self.daemon.controller.queue
        return q.active_len() if hasattr(q, "active_len") else len(q)

    def _wait_for_work(self) -> bool:
        """Sleep until a key is enqueued (condition-variable wakeup) or the
        `interval` max-sleep fallback elapses; True iff work is ready."""
        with self._cond:
            if self._ready():
                return True
            self._cond.wait(timeout=self.interval)
            return bool(self._ready())

    # -- the admission loop ------------------------------------------------

    def serve(
        self,
        should_stop: Optional[Callable[[], bool]] = None,
        idle: Optional[Callable[[], None]] = None,
        quiescent: bool = False,
        max_batches: int = 0,
    ) -> int:
        """Run the admission loop until `should_stop()`/`stop()` (the
        daemon deployment), the queue goes quiescent (`quiescent=True` —
        the test/bench drive: returns once no work is ready and every
        in-flight micro-batch has retired, including the fixpoint events
        the patches themselves generate), or `max_batches` micro-batches
        admitted. Returns the number of micro-batches admitted. `idle`
        runs on every max-sleep fallback wakeup that found no work."""
        if self._serving:
            raise RuntimeError("serve() is not reentrant")
        self._serving = True
        self._stop_evt.clear()
        daemon = self.daemon
        stop_fn = should_stop or (lambda: False)
        # visible to _submit's bounded-slot poll (leadership loss must
        # interrupt a slot wait, not just the condvar sleep)
        self._stop_check = stop_fn
        n0 = self._n_batches
        stream = None
        try:
            # inside the try: _ensure_fleet reads the store and can raise
            # transiently — the finally must still reset _serving, or every
            # retry serve() is rejected as reentrant and the leader never
            # schedules again
            array = self._array = daemon._ensure_fleet()
            timer = self._timer = StageTimer()
            with array.pipeline_context(timer, overlap=True):
                stream = self._open_stream(array, timer)
                while not (stop_fn() or self._stop_evt.is_set()):
                    if max_batches and self._n_batches - n0 >= max_batches:
                        break
                    if stream.aborted:
                        # eager writer-death detection: with an EMPTY queue
                        # the failed micro-batch's bindings would otherwise
                        # stay un-readmitted (and unplaced) until some
                        # unrelated watch event woke the loop — recycle
                        # now; _shutdown_stream re-admits the unretired
                        # work, which re-fills the queue
                        stream = self._recycle(stream, timer, array)
                        continue
                    if not self._ready():
                        if quiescent:
                            # in-flight patches can re-enqueue (fixpoint
                            # events); only an empty queue AFTER a full
                            # drain is genuinely quiescent
                            stream.drain()
                            if not self._ready():
                                break
                            continue
                        if not self._wait_for_work():
                            if idle is not None:
                                idle()
                            continue
                    if stop_fn() or self._stop_evt.is_set():
                        break
                    if self.batch_delay > 0 and self._ready() < MIN_ACCUMULATE:
                        # the batching-delay knob: let a trickle coalesce
                        # into one micro-batch; a backlog admits at once
                        self._stop_evt.wait(self.batch_delay)
                    try:
                        if daemon._fleet_dirty:
                            # a fleet re-encode must not race in-flight
                            # chunks (the writer's retry sub-rounds encode
                            # against the live fleet): drain, then swap.
                            # Bounded wait — a writer wedged in a hung
                            # patch must not pin serve() past a stop
                            # request (the same wedge _shutdown_stream
                            # bounds); on timeout loop back so stop is
                            # re-checked and the stale fleet keeps serving
                            if not stream.drain(timeout=min(self.interval, 1.0)):
                                continue
                            array = self._array = daemon._ensure_fleet()
                        mb = self._form_batch(array)
                    except Exception:
                        # transient-error survival, the streaming analogue
                        # of the batch loop's `except Exception: log;
                        # continue` around settle(): a store blip (remote
                        # error, fencing 409 on the clean-path write) must
                        # not kill the admission service — _form_batch
                        # re-admitted its drained keys before re-raising,
                        # so nothing is lost; back off one interval
                        log.exception("streaming admission iteration")
                        self._stop_evt.wait(min(self.interval, 1.0))
                        continue
                    if mb is None:
                        continue
                    try:
                        ok = self._submit(stream, array, mb)
                    except Exception:
                        # per-batch error isolation: the failed batch
                        # re-admits with poison isolation; the service
                        # keeps serving
                        log.exception("streaming micro-batch admission")
                        self._readmit_failed(mb)
                        continue
                    if not ok:
                        if stop_fn() or self._stop_evt.is_set():
                            # the writer died because we are being deposed
                            # (fencing 409 from the new leader's store is
                            # the usual shape) — not a scheduling failure:
                            # re-admit uncharged/unmarked and let the
                            # finally shut the stream down clean
                            self._readmit_clean(mb)
                            break
                        # the writer aborted (materialize/patch failure) or
                        # a wedged writer timed the slot wait out: recover
                        # its unretired work and re-open the stream. THIS
                        # batch never entered the pipeline (a failed launch
                        # raises instead of returning False) — it is
                        # innocent of whatever killed the writer, so it
                        # re-admits clean; the culprit batch is among the
                        # unretired chunks _recycle charges
                        stream = self._recycle(stream, timer, array)
                        self._readmit_clean(mb)
                        continue
                    self._n_batches += 1
        finally:
            if stream is not None:
                # leftovers at a REQUESTED stop (shutdown/leadership loss)
                # are undone work, not failures: re-admit them without
                # suspect-marking or retry charges so the next leadership
                # resumes full-width micro-batches at full retry budget
                self._shutdown_stream(
                    stream, clean=stop_fn() or self._stop_evt.is_set()
                )
            self._array = None
            self._timer = None
            self._stop_check = lambda: False
            self._serving = False
        return self._n_batches - n0

    # -- micro-batch formation ---------------------------------------------

    def _quota(self, array) -> int:
        """Drain quota for this admission: bounded by the pipeline's
        per-chunk HBM cap and `max_batch`, and FLOORED to the shape_bucket
        lattice when the queue runs deep — a full drain then dispatches
        exactly one bucket's rows (zero pad waste) and the remainder
        admits immediately after."""
        ready = self._ready()
        cap = self.max_batch or DEFAULT_MAX_BATCH
        if array.fleet.names:
            cap = min(cap, array.pipeline_chunk_rows(len(array.fleet.names)))
        quota = min(ready, cap)
        if quota > MIN_ACCUMULATE:
            quota = max(MIN_ACCUMULATE, min(shape_floor(quota), cap))
        return quota

    def _form_batch(self, array) -> Optional[_MicroBatch]:
        daemon = self.daemon
        q = daemon.controller.queue
        keys = q.drain(self._quota(array))
        sched_queue_depth.set(float(len(q)))
        if daemon.shard_id:
            from ..metrics import shard_queue_depth

            shard_queue_depth.set(float(len(q)), shard=daemon.shard_id)
        if not keys:
            return None
        if self._suspects:
            sus = [k for k in keys if k in self._suspects]
            if sus and len(keys) > 1:
                # poison isolation: a suspect admits ALONE so a repeat
                # failure implicates exactly it; the rest of the drain
                # re-queues (readd: store-free, cached priority) and
                # admits right after
                keep = sus[0]
                for k in keys:
                    if k != keep:
                        q.readd(k)
                keys = [keep]
        from .compilecache import compile_counts

        # gang hold windows expire on the admission clock: reject cohorts
        # that never completed before forming the next batch
        daemon.gang_tick()
        bindings, out_keys, epochs = [], [], []
        try:
            clean = self._form_keys(daemon, keys, bindings, out_keys, epochs)
        except Exception:
            # a store read/write failed mid-drain: give EVERY drained key
            # back to the queue (the already-collected ones simply re-read
            # next time) so a transient error loses no bindings, then let
            # serve()'s survival wrap log and back off. readd, NOT add:
            # add's priority_fn reads the store — during the very outage
            # this path recovers from, a raise mid-loop would lose every
            # key after it
            for key in keys:
                q.readd(key)
            raise
        if clean:
            with self._stats_lock:
                self.stats["clean"] += clean
        if not bindings:
            return None
        with self._stats_lock:
            # formed-vs-retired ("batches") is the in-flight gauge an
            # external quiesce check needs: equal counts + empty queue
            # means nothing is mid-pipeline
            self.stats["formed"] += 1
        microbatch_size.observe(float(len(bindings)))
        return _MicroBatch(
            bindings=bindings, keys=out_keys, epochs=epochs,
            compile_snap=compile_counts(), t0=time.perf_counter(),
        )

    def _form_keys(self, daemon, keys, bindings, out_keys, epochs) -> int:
        """The store-facing half of batch formation (split out so the
        caller can re-admit `keys` wholesale when a read/write here hits a
        transient error). Returns the count of clean (needed-no-schedule)
        keys."""
        clean = 0
        observed: list = []
        # per-shard span attribution: queue_wait records WHICH shard's
        # queue held the key (empty for the unsharded singleton)
        shard_attr = {"shard": daemon.shard_id} if daemon.shard_id else {}
        for key in keys:
            # epoch BEFORE the spec read: an event landing in between
            # discards a decision that was in fact computed on the fresh
            # spec (one cheap re-solve via the replay cache) — the safe
            # direction; the reverse order could patch a stale decision
            epoch = daemon.admission.epoch(key)
            ns, _, name = key.partition("/")
            rb = daemon.store.try_get("ResourceBinding", name, ns)
            # the gate itself is SHARED with the batch round's
            # _schedule_batch (decision-parity contract); only the
            # admission/queue bookkeeping around it is streaming's
            gate = daemon._admission_gate(rb)
            if gate == "drop":
                # tombstone or re-targeted to another scheduler: this
                # drain is the last time we see the key — clear the
                # queue's per-key bookkeeping (cached priority, retry
                # budget) and any suspect mark too, or sustained
                # create/delete churn grows them without bound
                daemon.admission.forget(key)
                daemon.controller.queue.forget(key)
                self._suspects.discard(key)
                if rb is not None and daemon._gang_of(rb):
                    daemon.gangs.discard(key, rb.spec.gang_name)
            elif gate == "suspended":
                daemon.admission.settle(key)
                if daemon._gang_of(rb):
                    daemon.gangs.discard(key, rb.spec.gang_name)
            elif gate == "schedule":
                aging = getattr(daemon.controller.queue, "aging_step", 0.0)
                if daemon._gang_holds(rb):
                    # gang member: park in the coordinator until the whole
                    # cohort is here; the completing offer releases every
                    # held member into THIS micro-batch, so a gang always
                    # solves (and commits) as one cohort. (The sharded
                    # daemon holds nothing here — _gang_holds returns ""
                    # and members ride the cross-shard commit instead.)
                    cohort = daemon.gangs.offer(key, rb, epoch)
                    if not cohort:
                        # held: the gang_hold span stays open until the
                        # completing offer (or a timeout drops the trace)
                        tracer.mark(key, "gang_hold")
                    for k2, rb2, e2 in cohort:
                        tracer.unmark(k2, "gang_hold",
                                      gang=rb.spec.gang_name)
                        tracer.drained(k2, aging, **shard_attr)
                        bindings.append(rb2)
                        out_keys.append(k2)
                        epochs.append(e2)
                    continue
                tracer.drained(key, aging, **shard_attr)
                bindings.append(rb)
                out_keys.append(key)
                epochs.append(epoch)
            else:  # clean
                daemon._record_observed(rb, sink=observed)
                daemon.admission.settle(key)
                self._suspects.discard(key)
                clean += 1
        # one batch write for the drain's observed-generation bookkeeping
        # (a raise here rides the caller's re-admit-everything contract)
        daemon._flush_observed(observed)
        return clean

    # -- launch / patch (StreamPipeline callbacks) -------------------------

    def _open_stream(self, array, timer: StageTimer) -> StreamPipeline:
        # out-of-tree plugins' stateful host hooks must never run on two
        # threads (the same guard the chunked executor applies): depth 1
        # serializes admission behind the writer
        depth = 1 if array._oot_plugins else self.depth
        return StreamPipeline(
            launch=self._launch,
            materialize=array.materialize_chunk,
            patch=self._patch,
            depth=depth, timer=timer,
            # materialize_chunk times its own finer spans
            time_materialize=False,
            # the stream lives for the whole leadership: per-chunk results
            # must not accumulate
            keep_results=False,
        )

    def _submit(self, stream: StreamPipeline, array, mb: _MicroBatch) -> bool:
        daemon = self.daemon
        reg = daemon.estimator_registry
        extra = None
        if reg is not None:
            # each micro-batch is one logical round for the staleness
            # cache: snapshots merge within it, decay advances once
            with self._timer.stage("estimate"), reg.sweep_round():
                extra = reg.batch_estimates(mb.bindings, array.fleet.names)
            mb.swept_open = tuple(reg.last_sweep_open)
            if mb.swept_open:
                degraded_rounds.inc()
        # autoshard contract parity with the batch round; micro-batches are
        # bounded under the HBM budget, so this is a no-op check in practice
        array._maybe_autoshard(len(mb.bindings))
        # bounded-slot submit: a writer wedged in a hung patch holds every
        # depth slot, and an unbounded acquire here would pin the admission
        # loop — and a deposed leader — forever (the one wedge the
        # drain/close timeouts didn't cover). Poll so stop() and leadership
        # loss are honored mid-wait; a full minute of full slots is the
        # wedge itself — return False and let serve() recycle the stream
        # (whose close(timeout=) abandons the stuck writer)
        deadline = time.monotonic() + 60.0
        while True:
            if stream.submit(mb, extra, timeout=0.5) is not None:
                return True
            if (stream.aborted or self._stop_evt.is_set()
                    or self._stop_check()):
                return False
            if time.monotonic() >= deadline:
                return False

    def _launch(self, i: int, mb: _MicroBatch, extra):
        # routed: a mixed-priority micro-batch solves as ONE segmented
        # tiered launch (sched/preemption.py); uniform batches ride the
        # ordinary replay-aware path — identical call shape either way
        t0 = time.time()
        pending = self.daemon._launch_routed(
            self._array, mb.bindings, extra, round_rows=len(mb.bindings)
        )
        if tracer.enabled:
            # one shared launch span per micro-batch, fanned out to the
            # member traces at the patch stage (dispatch end here; the
            # device+materialize tail closes when the writer picks it up)
            mb.launch_wall = (f"launch-{i}", t0, time.time())
        mb.replayed = pending["replayed"]
        mb.solved = pending["solved"]
        return pending

    def _patch(self, i: int, mb: _MicroBatch, decisions) -> None:
        """Writer-thread patch stage: epoch-check every decision, patch the
        still-current ones, publish per-batch stats."""
        from .compilecache import compile_delta

        daemon = self.daemon
        q = daemon.controller.queue
        admission = daemon.admission
        placed = failed = stale = 0
        cohort = []
        stale_keys = {
            key for key, epoch0 in zip(mb.keys, mb.epochs)
            if admission.epoch(key) != epoch0
        }
        # gang stale fencing: ONE stale member vetoes its WHOLE gang — the
        # cohort must commit all K against current specs or not at all, so
        # the healthy members re-admit uncharged and the coordinator
        # reassembles the gang once the stale member's event re-offers it
        vetoed_rows: set[int] = set()
        if stale_keys:
            gang_rows: dict[str, list[int]] = {}
            for idx, rb in enumerate(mb.bindings):
                g = daemon._gang_of(rb)
                if g:
                    gang_rows.setdefault(g, []).append(idx)
            for g, idxs in gang_rows.items():
                if any(mb.keys[i] in stale_keys for i in idxs):
                    vetoed_rows.update(idxs)
                    for i in idxs:
                        # readd is a no-op for the stale member (its event
                        # already re-enqueued it) and uncharged for the rest
                        q.readd(mb.keys[i])
        for idx, (key, epoch0, rb, dec) in enumerate(
            zip(mb.keys, mb.epochs, mb.bindings, decisions)
        ):
            if idx in vetoed_rows:
                if key in stale_keys:
                    stale += 1
                continue
            if key in stale_keys:
                # dirtied mid-flight: the decision is stale — discard it;
                # the bumping event already re-enqueued the key, so the
                # binding re-admits with its fresh spec
                stale += 1
                continue
            schedule_attempts.inc(result="scheduled" if dec.ok else "error")
            cohort.append((key, rb, dec))
        # tracing: the shared solve span (launch dispatch -> writer pickup,
        # covering device compute + materialize) fans out to every row of
        # the cohort, split into dispatch vs device time; the rv-checked
        # commit below becomes each row's commit span
        t_solved = time.time()
        # coalesced patch (docs/PERF.md "Write path at fleet scale"): one
        # batch read + ONE transactional batch write for the whole cohort —
        # the micro-batch's B decisions were 2·B store round-trips
        outcomes = daemon._patch_results([(rb, dec) for _, rb, dec in cohort])
        t_committed = time.time()
        if tracer.enabled and mb.launch_wall and cohort:
            lid, l0, l1 = mb.launch_wall
            shard_attr = (
                {"shard": daemon.shard_id} if daemon.shard_id else {}
            )
            for (key, _rb, _dec), ok in zip(cohort, outcomes):
                if not ok:
                    continue
                tracer.record(key, "solve", l0, t_solved, launch=lid,
                              rows=len(mb.bindings), replayed=mb.replayed,
                              solved=mb.solved,
                              dispatch_ms=round((l1 - l0) * 1e3, 3),
                              device_ms=round((t_solved - l1) * 1e3, 3),
                              **shard_attr)
                tracer.record(key, "commit", t_solved, t_committed,
                              cohort=len(cohort), **shard_attr)
        for (key, rb, dec), ok in zip(cohort, outcomes):
            if not ok:
                # last-moment veto under the store's serialization: a
                # deletion/suspension/re-target landed AFTER the epoch
                # check above — the epoch fence is check-then-act, and
                # this closes the window. Same disposition as stale: the
                # vetoing event's own handling (tombstone drain, settle,
                # or fade-out) owns the key from here
                stale += 1
                continue
            q.forget(key)
            self._suspects.discard(key)  # a clean patch clears suspicion
            if not dec.ok:
                # unschedulable/failed: _patch_result wrote the condition
                # (and parked the key on a priority queue). The SLO
                # histogram measures time-to-PLACEMENT only — the pending
                # stretch resolves unmeasured, like the clean-drain path
                admission.settle(key)
                failed += 1
                continue
            lat = admission.observe_patch(key, daemon.clock.now())
            if lat is not None:
                # retention decision: head-sampled or SLO-breaching traces
                # survive; the retained trace id rides the SLO histogram
                # as the bucket exemplar (worst trace per bucket)
                tid = tracer.finish_placement(key, lat)
                placement_latency.observe(lat, exemplar=tid)
                with self._stats_lock:
                    self._latencies.append(lat)
            else:
                tracer.finish_placement(key, None)
            placed += 1
        e2e_scheduling_duration.observe(time.perf_counter() - mb.t0)
        # per-batch stats (the streaming analogue of the round stats).
        # Compile attribution is process-global and micro-batches overlap
        # (this batch's delta can carry a neighbor's launch compiles), but
        # the steady-state invariant — EVERY batch at zero — is exact.
        mb.stats = {
            "streaming": True,
            "replayed": mb.replayed, "solved": mb.solved,
            "batch_rows": len(mb.bindings),
            "placed": placed, "failed": failed, "stale_discarded": stale,
            "queue_depth": int(self._ready()),
            **compile_delta(mb.compile_snap),
            # candidate sparsification (sched/candidates.py): the last
            # compact round's effective K and truncation count — empty on
            # dense-solved micro-batches
            **self._array.last_candidate_stats,
        }
        self._array.last_round_stats = mb.stats
        with self._stats_lock:
            self.stats["batches"] += 1
            self.stats["admitted"] += len(mb.bindings)
            self.stats["placed"] += placed
            self.stats["failed"] += failed
            self.stats["stale_discarded"] += stale
            self.stats["jit_compiles"] += int(mb.stats.get("jit_compiles", 0))

    # -- failure recovery / shutdown ---------------------------------------

    def _readmit_failed(self, mb: _MicroBatch) -> None:
        """A formed micro-batch that will never reach the patch stage:
        retire it from the formed-vs-patched in-flight gauge and re-admit
        its keys with poison isolation. A multi-key batch re-adds its keys
        UNCHARGED but marked suspect — the culprit is unknown, and burning
        every neighbor's retry budget per failure would silently drop
        healthy bindings; each suspect then re-admits as a singleton, so a
        repeat failure charges exactly the poison binding. A singleton
        failure charges its own retry/backoff budget, and a binding that
        exhausts it is dropped LOUDLY (until its next watch event)."""
        with self._stats_lock:
            self.stats["formed"] -= 1
        q = self.daemon.controller.queue
        if len(mb.keys) > 1:
            self._suspects.update(mb.keys)
            for key in mb.keys:
                q.readd(key)
            return
        for key in mb.keys:
            if not q.retry(key):
                log.error(
                    "binding %s dropped after exhausting its scheduling "
                    "retry budget (repeated micro-batch failures); it "
                    "re-admits on its next watch event", key,
                )
                self._suspects.discard(key)
                self.daemon.admission.forget(key)

    def _readmit_clean(self, mb: _MicroBatch) -> None:
        """Undone in-flight work at a requested stop (shutdown or
        leadership loss): NOT a scheduling failure — the keys re-add
        uncharged and unmarked (no suspect isolation, no retry budget), so
        a lease flap costs nothing but the re-solve. readd is store-free:
        a deposed leader's priority_fn may be mid-outage too."""
        with self._stats_lock:
            self.stats["formed"] -= 1
        q = self.daemon.controller.queue
        for key in mb.keys:
            q.readd(key)

    def _recycle(self, stream: StreamPipeline, timer: StageTimer,
                 array) -> StreamPipeline:
        leftovers = self._shutdown_stream(stream)
        log.error("streaming writer failed; re-opened stream "
                  "(re-admitted %d micro-batches)", leftovers)
        return self._open_stream(array, timer)

    def _shutdown_stream(self, stream: StreamPipeline,
                         clean: bool = False) -> int:
        """Drain + close; re-admit any unretired work (abort leftovers) so
        a failure or shutdown loses no bindings. `clean` (a requested
        stop) re-admits without failure semantics. On a writer FAILURE the
        poison-isolation discipline (_readmit_failed) is charged to the
        FIRST unretired chunk only: the writer retires strictly in
        submission order, so that is the chunk it was processing when it
        died — the trailing chunks drained un-executed and are innocent
        (suspect-marking them would force hundreds of healthy bindings
        through singleton re-admission over one store blip)."""
        stream.drain(timeout=60.0)
        # bounded close: a writer wedged in a hung patch must not pin
        # serve() forever — a deposed leader has to get back to standby
        stream.close(raise_failure=False, timeout=10.0)
        if stream.failure is not None:
            log.error("streaming stream failure: %r", stream.failure)
        leftovers = stream.unretired_chunks()
        for j, mb in enumerate(leftovers):
            if clean or j > 0:
                self._readmit_clean(mb)
            else:
                self._readmit_failed(mb)
        return len(leftovers)

    # -- introspection -----------------------------------------------------

    def latencies(self) -> list[float]:
        """Recent exact placement latencies (admission → patch), oldest
        first — the stream bench's percentile source."""
        with self._stats_lock:
            return list(self._latencies)

    def stats_snapshot(self) -> dict:
        with self._stats_lock:
            return dict(self.stats)

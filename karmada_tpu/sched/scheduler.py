"""Scheduler daemon: the host loop around the batched device solve.

Parity with pkg/scheduler/scheduler.go: watches ResourceBindings + Clusters
(event_handler.go:46,94-120 filters: schedulerName, scheduling suspension),
decides WHETHER each binding needs scheduling (doScheduleBinding:375-443 — the
four triggers: applied-placement changed :401, replicas changed :408,
reschedule triggered :415, Duplicated refresh :422), then — unlike the
reference's one-at-a-time loop — drains every dirty binding into ONE
ArrayScheduler batch (BatchingController), and patches results + conditions
(patchScheduleResultForResourceBinding:627-651, condition updates :913-961).

Cluster add/update/delete re-encodes the device fleet and re-enqueues all
bindings (reconcileCluster/enqueueAffectedBindings event_handler.go:313-368);
idempotent no-op writes make the fixpoint terminate.
"""
from __future__ import annotations

import json
from dataclasses import asdict
from typing import Optional

from ..api.meta import Condition, set_condition
from ..api.policy import DEFAULT_SCHEDULER_NAME, REPLICA_SCHEDULING_DUPLICATED
from ..api.work import (
    CONDITION_SCHEDULED,
    EVICTION_PRODUCER_PREEMPTION,
    EVICTION_REASON_PREEMPTED,
    GracefulEvictionTask,
    POLICY_PLACEMENT_ANNOTATION,
    REASON_BINDING_SCHEDULED,
    REASON_GANG_TIMEOUT,
    REASON_GANG_UNSCHEDULABLE,
    REASON_SCHEDULE_FAILED,
    REASON_UNSCHEDULABLE,
    ResourceBinding,
)
from ..features import FeatureGates, PRIORITY_BASED_SCHEDULING
from ..metrics import (
    degraded_rounds,
    e2e_scheduling_duration,
    gang_admissions,
    preemption_victims,
    preemptions_total,
    queue_incoming_bindings,
    schedule_attempts,
    scheduling_algorithm_duration,
    timed,
)
from ..runtime.controller import BatchingController, Runtime
from ..store.store import DELETED, MODIFIED, Store
from ..tracing import tracer
from .core import ArrayScheduler, ScheduleDecision
from .queue import GangCoordinator, PrioritySchedulingQueue


def placement_json(placement) -> str:
    if placement is None:
        return ""
    return json.dumps(asdict(placement), sort_keys=True, default=str)


# sentinel: "no batch-read snapshot — do the per-binding try_get"
_UNREAD = object()


class AdmissionLog:
    """Per-binding admission bookkeeping for the streaming scheduler
    (sched/streaming.py). Two facts per key, both bumped by the watch
    handlers the moment an event enqueues the binding:

    - a monotonically increasing dirty EPOCH — the staleness fence: a
      micro-batch snapshots each binding's epoch BEFORE reading its spec,
      and the patch stage discards any in-flight decision whose binding
      moved past the snapshot (it dirtied mid-flight; the bumping event
      already re-enqueued the key, so the binding re-admits with the
      fresh spec);
    - the ADMITTED-AT timestamp the placement-latency histogram measures
      from: the FIRST event of the current pending stretch (coalesced
      re-events while the key waits do not reset the clock — the binding
      has been dirty since the first one).

    Disabled (`enabled=False`) outside streaming mode so the batch daemon
    pays no bookkeeping and the maps cannot grow in a mode that never
    clears them."""

    def __init__(self) -> None:
        import itertools
        import threading

        self.enabled = False
        self._lock = threading.Lock()
        # epochs come from ONE process-global counter, not per-key counts:
        # forget() may drop a key's entry while a snapshot of it is still
        # in flight (delete→recreate of the same ns/name), and a per-key
        # count restarting at 1 could collide with that old snapshot and
        # let a stale decision patch the recreated binding
        self._gen = itertools.count(1)
        self._epoch: dict[str, int] = {}
        self._admitted: dict[str, float] = {}

    def note(self, key: str, now: float, uid: str = "") -> None:
        with self._lock:
            epoch = next(self._gen)
            self._epoch[key] = epoch
            self._admitted.setdefault(key, now)
        # distributed tracing (tracing/spans.py): the admission IS the
        # trace's (uid, epoch) key — setdefault semantics inside admit()
        # mirror _admitted, so coalesced re-events share one trace
        tracer.admit(key, uid or key, epoch)

    def invalidate(self, key: str) -> None:
        """Fence off any in-flight decision for `key` WITHOUT starting a
        new pending stretch: events that stop scheduling rather than
        request it (suspension, scheduler_name re-target, deletion) must
        still move the epoch — the in-flight decision was computed on the
        pre-event spec — but there is nothing to measure a placement
        latency against."""
        with self._lock:
            self._epoch[key] = next(self._gen)
            self._admitted.pop(key, None)
        tracer.settle(key)

    def epoch(self, key: str) -> int:
        with self._lock:
            return self._epoch.get(key, 0)

    def last_epoch(self) -> int:
        """Highest admission epoch currently tracked — the shard status
        view's EPOCH column (observational; forgotten keys drop out)."""
        with self._lock:
            return max(self._epoch.values(), default=0)

    def observe_patch(self, key: str, now: float) -> Optional[float]:
        """Latency of the patch that just landed (admission → patch);
        clears the pending stretch. None when nothing was pending. The
        daemon's own patch re-notes the key (its store write is a watch
        event) BEFORE this pop runs on the same thread, and setdefault
        keeps the original timestamp — so the pop both measures from the
        true first admission and retires the self-inflicted note."""
        with self._lock:
            t0 = self._admitted.pop(key, None)
        return None if t0 is None else max(0.0, now - t0)

    def settle(self, key: str) -> None:
        """A drained key needed no scheduling: the pending stretch (if
        any — e.g. the daemon's own patch event) resolves un-measured."""
        with self._lock:
            self._admitted.pop(key, None)
        tracer.settle(key)

    def forget(self, key: str) -> None:
        with self._lock:
            self._epoch.pop(key, None)
            self._admitted.pop(key, None)
        tracer.forget(key)


class SchedulerDaemon:
    def __init__(
        self,
        store: Store,
        runtime: Runtime,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        # estimator.client.SchedulerEstimatorRegistry: the typed contract —
        # batch_estimates + the last_sweep_open degraded-sweep attribute
        estimator_registry=None,
        gates: Optional[FeatureGates] = None,
        event_recorder=None,
        plugins=None,  # the --plugins list: "*" / "foo" / "-foo"
        plugin_registry=None,  # out-of-tree plugins (WithOutOfTreeRegistry)
        pipeline=None,  # pipelined round executor (None = KARMADA_TPU_PIPELINE)
        aot_prewarm=None,  # AOT bucket-lattice prewarm on the standby
        #   (sched/aot.py); None = KARMADA_TPU_AOT_PREWARM env (default on)
        gang_wait_seconds: Optional[float] = None,  # partial-gang hold
        #   window before a timeout rejects the cohort (sched/queue.py)
        preemption: bool = True,  # the PreemptLowerPriority second solve
        #   pass (sched/preemption.py); bindings still opt in per policy
    ) -> None:
        self.store = store
        self.clock = runtime.clock
        self.scheduler_name = scheduler_name
        if estimator_registry is not None:
            # contract check ONCE, loudly, at boot: the pipelined round
            # reads these every chunk, and a registry from the old
            # batch_estimates-only duck contract would otherwise fail every
            # round with a bare AttributeError deep in the pipeline
            missing = [
                a for a in ("batch_estimates", "last_sweep_open",
                            "sweep_round")
                if not hasattr(estimator_registry, a)
            ]
            if missing:
                raise TypeError(
                    "estimator_registry must satisfy "
                    "estimator.client.SchedulerEstimatorRegistry; missing: "
                    + ", ".join(missing)
                )
        self.estimator_registry = estimator_registry
        self.event_recorder = event_recorder
        self.plugins = plugins
        self.plugin_registry = plugin_registry
        self.pipeline = pipeline
        self._array: Optional[ArrayScheduler] = None
        self._fleet_dirty = True
        self._prewarmed_epoch = -1
        if aot_prewarm is None:
            import os

            aot_prewarm = os.environ.get("KARMADA_TPU_AOT_PREWARM", "") not in (
                "0", "off", "false",
            )
        self.aot_prewarm = bool(aot_prewarm)
        import threading as _threading

        self._aot_epoch = -1  # fleet epoch the last AOT pass covered
        self._aot_thread = None
        self._aot_stop = None  # threading.Event: promotion abandons the pass
        # start/abandon run on different threads (standby idle loop vs the
        # elector callback); the lock makes stop-event assignment and the
        # suspension flag atomic with thread start, so a promotion landing
        # mid-_start_aot can never race a fresh pass into a leading daemon
        self._aot_lock = _threading.Lock()
        self._prewarm_suspended = False
        self.last_prewarm_stats: dict = {}
        # streaming admission state (sched/streaming.py): epoch + latency
        # bookkeeping (inert until a StreamingScheduler attaches) and the
        # AOT hint that micro-batch row buckets belong in the prewarm walk
        self.admission = AdmissionLog()
        self.stream_prewarm = False
        # sharded plane (sched/shards/): the shard this daemon serves, as a
        # span/metric label ("" = the unsharded singleton). Ownership and
        # gang-hold routing go through the _owns/_gang_holds seams below so
        # the sharded subclass changes WHICH keys admit, never HOW they
        # solve or patch.
        self.shard_id = ""
        # workload-class scheduling (sched/preemption.py, docs/SCHEDULING.md):
        # the gang coordinator holds partial all-or-nothing cohorts at the
        # queue seam, and `preemption_enabled` arms the second solve pass
        # for PreemptLowerPriority bindings that place short
        from .queue import DEFAULT_GANG_WAIT

        self.gangs = GangCoordinator(
            self.clock,
            DEFAULT_GANG_WAIT if gang_wait_seconds is None
            else gang_wait_seconds,
        )
        self.preemption_enabled = bool(preemption)
        # placed-bindings index for the preemption planner: maintained by
        # the binding watch (replay seeds it at subscription), so a plan
        # snapshot is a dict scan instead of a full store.list deep-copy
        # per preemption. Eventually consistent only — the atomic commit
        # re-reads every victim fresh and rv-checks, so a stale snapshot
        # can only abort a plan, never mis-commit one.
        self._placed: dict[str, ResourceBinding] = {}
        # names of clusters MODIFIED since the last fleet encode; None means
        # the membership changed (add/delete) and the next encode must be a
        # full rebuild instead of the dirty-column scatter
        self._dirty_clusters: Optional[set[str]] = None
        self.controller = runtime.register(
            BatchingController(
                name="scheduler", reconcile=None, reconcile_batch=self._schedule_batch
            )
        )
        if gates is not None and gates.enabled(PRIORITY_BASED_SCHEDULING):
            # swap the FIFO for the activeQ/backoffQ/unschedulable-pool queue
            # (scheduling_queue.go:43-57 under the PriorityBasedScheduling gate)
            self.controller.queue = PrioritySchedulingQueue(
                self.clock, priority_fn=self._priority_of
            )
        store.watch("ResourceBinding", self._on_binding)
        store.watch("Cluster", self._on_cluster)

    # -- event handlers (event_handler.go:94-120) -------------------------

    def _on_binding(self, event: str, rb: ResourceBinding) -> None:
        # placed index upkeep FIRST — before any gating below returns (the
        # handler's rb is this subscriber's own copy, safe to retain)
        if (event == DELETED or rb.metadata.deletion_timestamp is not None
                or not rb.spec.clusters):
            self._placed.pop(rb.metadata.key(), None)
        else:
            self._placed[rb.metadata.key()] = rb
        if event == DELETED:
            if self.admission.enabled:
                # fence + drain: the bump discards any in-flight decision,
                # and enqueueing lets _form_keys see the tombstone and
                # forget the key, keeping the admission maps bounded
                self.admission.invalidate(rb.metadata.key())
                self.controller.enqueue(rb.metadata.key())
            return
        if rb.spec.scheduler_name and rb.spec.scheduler_name != self.scheduler_name:
            # re-targeted to another scheduler: any in-flight decision of
            # ours was computed on the pre-retarget spec — fence it off
            # (no enqueue: the binding is not ours to schedule)
            if self.admission.enabled:
                self.admission.invalidate(rb.metadata.key())
            return
        if not self._owns(rb):
            # sharded plane: another shard's key. Fence any in-flight
            # decision of ours (a resize may have just moved the key off
            # this shard mid-solve) but do not enqueue — the owning
            # shard's own watch admits it.
            if self.admission.enabled:
                self.admission.invalidate(rb.metadata.key())
            return
        if rb.spec.scheduling_suspended():
            # suspension must also move the epoch: an in-flight decision
            # passing the writer's fence would place a binding the user
            # explicitly told the scheduler to leave alone. Enqueue so the
            # drain settles the pending stretch (un-measured).
            if self.admission.enabled:
                self.admission.invalidate(rb.metadata.key())
                self.controller.enqueue(rb.metadata.key())
            return
        queue_incoming_bindings.inc(event=event)
        key = rb.metadata.key()
        if self.admission.enabled:
            # note BEFORE enqueue: the enqueue hook wakes the streaming
            # admission loop, whose epoch snapshot must already see this
            # event's bump; the uid keys the binding's placement trace
            self.admission.note(key, self.clock.now(), uid=rb.metadata.uid)
        self.controller.enqueue(key)

    def _priority_of(self, key: str) -> int:
        ns, _, name = key.partition("/")
        rb = self.store.try_get("ResourceBinding", name, ns)
        if rb is None or rb.spec.schedule_priority is None:
            return 0
        return rb.spec.schedule_priority

    def _on_cluster(self, event: str, cluster) -> None:
        # record the delta FIRST, then mark dirty unconditionally — there is
        # no check-then-act window in which a concurrent _ensure_fleet swap
        # could absorb the flag without the event. Racing with the swap can
        # at worst add the name to the retired set (the fresh set is then
        # empty ⇒ the re-marked round does a full rebuild): a lost NAME
        # degrades to a full re-encode, a lost FLAG would mean scheduling
        # against a stale fleet.
        if event == MODIFIED:
            d = self._dirty_clusters
            if d is not None:
                d.add(cluster.name)
        else:
            self._dirty_clusters = None  # membership changed: full rebuild
        self._fleet_dirty = True
        for rb in self.store.list("ResourceBinding"):
            self._on_binding("MODIFIED", rb)

    # -- trigger decision (doScheduleBinding:375-443) ---------------------

    def _needs_schedule(self, rb: ResourceBinding) -> bool:
        applied = rb.metadata.annotations.get(POLICY_PLACEMENT_ANNOTATION, "")
        current = placement_json(rb.spec.placement)
        if applied != current:
            return True  # placement changed (:401) or never scheduled
        if rb.spec.reschedule_triggered_at is not None and (
            rb.status.last_scheduled_time is None
            or rb.spec.reschedule_triggered_at > rb.status.last_scheduled_time
        ):
            return True  # reschedule triggered (:415)
        if rb.spec.replicas > 0:
            placement = rb.spec.placement
            if placement is not None and placement.replica_scheduling_type() == REPLICA_SCHEDULING_DUPLICATED:
                # Duplicated: replicas synced whenever any target drifts (:422);
                # cluster-set changes also re-run (cluster events enqueue us).
                return True
            if rb.spec.assigned_replicas() != rb.spec.replicas:
                return True  # replicas changed → scale schedule (:408)
        return False

    def _admission_gate(self, rb: Optional[ResourceBinding],
                        any_shard: bool = False) -> str:
        """Per-key admission decision, shared by BOTH drain paths (the
        batch round's _schedule_batch and streaming's _form_keys) so the
        skip conditions cannot drift apart and silently break the
        streaming-vs-batch decision-parity contract. 'drop': tombstone or
        re-targeted to another scheduler (not ours — the key's bookkeeping
        should be forgotten); 'suspended': the user told us to leave it
        alone; 'schedule': solve it; 'clean': current, just record the
        observed generation."""
        if rb is None or rb.metadata.deletion_timestamp is not None:
            return "drop"
        if (rb.spec.scheduler_name
                and rb.spec.scheduler_name != self.scheduler_name):
            # re-targeted while queued: the event handler declines
            # re-target events, but this key was enqueued BEFORE
            return "drop"
        if not any_shard and not self._owns(rb):
            # sharded plane: the key moved (or never belonged) to another
            # shard. Dropping here — and in the _patch_result re-check,
            # which runs under the store's serialization — is the handoff
            # fence: a losing shard's in-flight decision can never patch a
            # binding the gaining shard now owns. `any_shard` is the one
            # sanctioned bypass: the cross-shard gang COORDINATOR commits
            # members it does not own (safety comes from the rv fence on
            # its batch commit, not from ownership).
            return "drop"
        if rb.spec.scheduling_suspended():
            return "suspended"
        return "schedule" if self._needs_schedule(rb) else "clean"

    def _owns(self, rb: ResourceBinding) -> bool:
        """Shard-ownership predicate (sched/shards/): the unsharded daemon
        owns everything; ShardedDaemon overrides with the rendezvous map."""
        return True

    def _record_observed(self, rb: ResourceBinding, sink=None) -> None:
        """No scheduling required: still record that the current spec was
        observed (scheduler.go:437-441) — graceful eviction assessment
        gates on this. With `sink`, the write is collected for one batch
        flush (_flush_observed) instead of its own round-trip — the drain
        loops call this once per clean key."""
        if rb.status.scheduler_observed_generation != rb.metadata.generation:
            rb.status.scheduler_observed_generation = rb.metadata.generation
            if sink is not None:
                sink.append(rb)
            else:
                self.store.update(rb)

    def _flush_observed(self, objs: list) -> None:
        """Commit a drain's observed-generation bookkeeping as ONE batch
        write — rv-checked with per-slot skip: these are full-object
        snapshots read at drain start, and a drain can run long, so a user
        write landing mid-drain must WIN (the skipped binding's own change
        event re-drains it; a binding deleted since its read just drops)."""
        if not objs:
            return
        from ..store.batching import update_all

        update_all(self.store, objs, path="sched_observed",
                   skip_missing=True, skip_stale=True)

    # -- the batch solve --------------------------------------------------

    def _ensure_fleet(self) -> ArrayScheduler:
        if self._array is None or self._fleet_dirty:
            # swap the dirty state out FIRST: a cluster event landing while
            # we encode re-marks the fleet dirty for the next round instead
            # of being silently absorbed into this one
            self._fleet_dirty = False
            dirty = self._dirty_clusters
            self._dirty_clusters = set()
            clusters = self.store.list("Cluster")
            clusters.sort(key=lambda c: c.name)
            if self._array is None:
                self._array = ArrayScheduler(
                    clusters,
                    plugins=self.plugins,
                    plugin_registry=self.plugin_registry,
                    pipeline=self.pipeline,
                )
            else:
                # MODIFIED-only churn rides the dirty-column scatter (the
                # batch encoder and its row cache survive); membership
                # changes rebuild everything as before
                self._array.set_clusters(clusters, dirty_names=dirty)
        return self._array

    def prewarm(self, wait_aot: bool = False) -> None:
        """Hot-standby warmth (coordination plane): build the fleet encoders
        and prime the solve's jit cache with a throwaway dry round, so a
        standby promoted on leader death takes over within the lease TTL
        instead of paying encoder + compile cold-start. Idempotent per fleet
        epoch — cheap to call from the standby's idle loop; cluster churn
        (which bumps the epoch via the watch handlers) re-warms.

        Beyond the dry solve, a background thread AOT-compiles the round
        kernels over the bucket lattice reachable from the current fleet
        width (sched/aot.py), using the store's LIVE binding snapshot as
        the shape template — the takeover round's chunks then hit compiled
        (and, with the persistent cache, disk-resident) programs instead of
        paying 67–157 s of XLA mid-round. `wait_aot` blocks until the pass
        finishes (tests and explicit boot warming); the idle loop never
        waits. `abandon_prewarm()` stops a pass on promotion; calling
        prewarm again (the standby loop after losing leadership) lifts the
        suspension."""
        with self._aot_lock:
            self._prewarm_suspended = False
        try:
            array = self._ensure_fleet()
            if not array.fleet.names:
                return  # nothing to encode against yet
            if self._prewarmed_epoch == array.fleet_epoch:
                # dry solve already warm for this epoch — but the AOT pass
                # has its own lifecycle (it may have been abandoned on
                # promotion, or still cover a stale epoch) and must get its
                # chance every standby tick
                self._start_aot(array, wait=wait_aot)
                return
            self._prewarmed_epoch = array.fleet_epoch
            from ..api.meta import ObjectMeta
            from ..api.policy import (
                ClusterAffinity,
                Placement,
                ReplicaSchedulingStrategy,
            )
            from ..api.work import BindingSpec, ResourceBinding

            dry = ResourceBinding(
                metadata=ObjectMeta(name="__prewarm__"),
                spec=BindingSpec(
                    replicas=0,
                    placement=Placement(
                        cluster_affinity=ClusterAffinity(cluster_names=[]),
                        replica_scheduling=ReplicaSchedulingStrategy(
                            replica_scheduling_type=REPLICA_SCHEDULING_DUPLICATED
                        ),
                    ),
                ),
            )
            # plain schedule(), NOT schedule_incremental: the dry decision
            # must never enter the replay cache
            array.schedule([dry])
            self._start_aot(array, wait=wait_aot)
        except Exception:  # noqa: BLE001 - warmth is best-effort
            import logging

            logging.getLogger(__name__).exception("standby prewarm")

    def _start_aot(self, array: ArrayScheduler, wait: bool = False) -> None:
        """Kick (or join) the AOT bucket-lattice pass for the current fleet
        epoch. One pass per epoch; runs on a daemon thread so the standby's
        idle loop keeps renewing its election heartbeat while XLA works."""
        if not self.aot_prewarm:
            return
        import threading

        with self._aot_lock:
            t = self._aot_thread
            if t is not None and t.is_alive():
                if (self._aot_epoch != array.fleet_epoch
                        and self._aot_stop is not None):
                    # the running pass covers a stale fleet epoch: wind it
                    # down; the NEXT prewarm tick starts the fresh-epoch pass
                    # (cheap — the persistent cache makes re-walked shapes
                    # disk hits)
                    self._aot_stop.set()
            else:
                t = None
        if t is not None:
            if not wait:
                return
            t.join()
        # snapshot the live working set NOW (watches keep it current): the
        # takeover round's rows — and therefore its encoded table shapes —
        # are exactly these
        bindings = [
            rb for rb in self.store.list("ResourceBinding")
            if rb.metadata.deletion_timestamp is None
            and not rb.spec.scheduling_suspended()
        ]
        with self._aot_lock:
            if self._prewarm_suspended:
                return  # promoted while we were snapshotting: do not start
            if self._aot_thread is not None and self._aot_thread.is_alive():
                return
            if self._aot_epoch == array.fleet_epoch:
                return
            self._aot_epoch = array.fleet_epoch
            epoch = array.fleet_epoch
            stop = threading.Event()
            self._aot_stop = stop

        def run() -> None:
            import logging

            from .aot import prewarm_schedule

            try:
                stats = prewarm_schedule(
                    array, bindings,
                    with_extra=self.estimator_registry is not None,
                    stream=self.stream_prewarm,
                    stop=stop,
                )
                self.last_prewarm_stats = {"epoch": epoch, **stats}
                # loud by design (docs/HA.md): whether takeover rides warm
                # programs is the first thing to check when it looks slow
                logging.getLogger(__name__).warning(
                    "aot prewarm: epoch %d row buckets %s — %d XLA compiles "
                    "(%.1fs), %d persistent-cache hits",
                    epoch, stats.get("row_buckets"),
                    stats.get("jit_compiles", 0),
                    stats.get("jit_compile_seconds", 0.0),
                    stats.get("jit_persistent_cache_hits", 0),
                )
            except Exception:  # noqa: BLE001 - warmth is best-effort
                logging.getLogger(__name__).exception("aot prewarm")

        t = threading.Thread(target=run, name="sched-aot-prewarm", daemon=True)
        self._aot_thread = t
        t.start()
        if wait:
            t.join()

    def abandon_prewarm(self) -> None:
        """Promotion hook: stop an in-flight AOT pass — the new leader's
        first round must not share the backend with a background compile
        walk (the pass resumes, persistent-cache-incremental, next time the
        process stands by). The stop is polled between shapes: a single
        in-flight XLA compile cannot be aborted mid-program, so at most one
        shape's compile drains after promotion (and its compile-counter
        delta can then leak into the first leader round's process-global
        attribution — see _schedule_batch)."""
        with self._aot_lock:
            self._prewarm_suspended = True
            if self._aot_stop is not None:
                self._aot_stop.set()
            self._aot_epoch = -1  # re-arm for the next standby period

    def streaming(self, **kwargs):
        """Attach the streaming admission service (sched/streaming.py):
        kills the round boundary — watch events wake an always-on admission
        loop that accumulates micro-batches while the previous one solves
        on device. Enables admission/epoch bookkeeping and adds the
        micro-batch row buckets to the AOT prewarm walk. kwargs pass
        through to StreamingScheduler (batch_delay, interval, max_batch,
        depth)."""
        from .streaming import StreamingScheduler

        return StreamingScheduler(self, **kwargs)

    def _gang_of(self, rb: ResourceBinding) -> str:
        from .preemption import gang_of

        return gang_of(rb)

    def _gang_holds(self, rb: ResourceBinding) -> str:
        """The gang identity for QUEUE-HOLD purposes: non-empty parks the
        member in the local GangCoordinator until its cohort assembles.
        The sharded daemon (N>1) returns "" — members hash to different
        shards, so no single queue can assemble the cohort; gang rows
        admit like solo rows and the cross-shard proposal protocol
        (sched/shards/gangs.py) supplies the all-or-nothing commit."""
        return self._gang_of(rb)

    def gang_tick(self) -> int:
        """Reject gangs whose hold window elapsed incomplete (ControlPlane
        .tick drives this for the batch daemon; the streaming loop checks
        on every admission). Returns the number of gangs rejected."""
        expired = self.gangs.expire(self.clock.now())
        for gname, members in expired:
            self._reject_gang(
                gname, members, REASON_GANG_TIMEOUT,
                "gang %s timed out waiting for members" % gname,
                outcome="timeout",
            )
        return len(expired)

    def _reject_gang(self, gname: str, members, reason: str, message: str,
                     outcome: str) -> None:
        """Terminal gang disposition (timeout / joint infeasibility):
        write the Scheduled=False condition on every member (idempotent —
        a repeat writes nothing, so the event fixpoint terminates), park
        priority-queue keys unschedulable, settle admission bookkeeping."""
        gang_admissions.inc(outcome=outcome)
        q = self.controller.queue
        for key, rb, _epoch in members:
            fresh = self.store.try_get("ResourceBinding", rb.name,
                                       rb.namespace)
            if self._admission_gate(fresh) in ("drop", "suspended"):
                continue
            if self.admission.enabled:
                self.admission.settle(key)
            if isinstance(q, PrioritySchedulingQueue):
                q.push_unschedulable(key)
            if set_condition(
                fresh.status.conditions,
                Condition(type=CONDITION_SCHEDULED, status="False",
                          reason=reason, message=message),
            ):
                self.store.update(fresh)

    def _admit_gangs(self, bindings: list) -> list:
        """Gang admission at the drain seam: gang members park in the
        coordinator until their cohort completes; the completing member
        releases the whole gang into THIS batch (so the cohort always
        solves together). Non-gang rows pass through untouched."""
        ready: list = []
        for rb in bindings:
            if self._gang_holds(rb):
                released = self.gangs.offer(rb.metadata.key(), rb, 0)
                ready.extend(rb2 for _k, rb2, _e in released)
            else:
                ready.append(rb)
        return ready

    def _launch_routed(self, array: ArrayScheduler, chunk: list,
                       extra, round_rows: int) -> dict:
        """Launch one chunk, routing workload-class batches — mixed
        priorities (segmented tiered solve) or preemption-armed rows
        (speculative victim-augmented pass) — through ONE
        sched/preemption.py launch, and plain batches through the ordinary
        replay-aware path."""
        from .preemption import (
            armed_for_preemption, launch_tiered, wants_workload_solve,
        )

        if wants_workload_solve(array, chunk,
                                preemption=self.preemption_enabled):
            # the O(placed) snapshot copy is only paid when a row will
            # actually read it (speculative second pass) — a plain
            # mixed-priority stream must not tax every micro-batch with it
            placed = None
            if self.preemption_enabled and any(
                armed_for_preemption(rb) for rb in chunk
            ):
                placed = list(self._placed.values())
            return launch_tiered(array, chunk, extra, placed=placed)
        return array.launch_chunk(chunk, extra, round_rows=round_rows)

    def _schedule_batch(self, keys: list[str]) -> list[str]:
        bindings = []
        observed: list = []
        for key in keys:
            ns, _, name = key.partition("/")
            rb = self.store.try_get("ResourceBinding", name, ns)
            gate = self._admission_gate(rb)
            if gate == "schedule":
                bindings.append(rb)
            elif gate == "clean":
                self._record_observed(rb, sink=observed)
            if rb is not None and gate in ("drop", "suspended"):
                g = self._gang_of(rb)
                if g:
                    self.gangs.discard(key, g)
        self._flush_observed(observed)
        bindings = self._admit_gangs(bindings)
        self.gang_tick()
        if not bindings:
            return []
        from ..tracing import Trace
        from .compilecache import compile_counts, compile_delta
        from .pipeline import (
            ChunkPipeline, StageTimer, chunk_spans, plan_chunk_rows,
        )

        trace = Trace("Scheduling", {"bindings": len(bindings)})
        compile_snap = compile_counts()
        with timed(e2e_scheduling_duration):
            array = self._ensure_fleet()
            trace.step("Fleet snapshot ready")
            names = array.fleet.names
            reg = self.estimator_registry
            # Pipelined round (sched/pipeline.py): the round is cut into row
            # chunks and the five stages overlap across them — chunk k+1's
            # estimator sweep prefetches on a worker thread and its rows
            # encode/dispatch on this thread while chunk k solves on device
            # and chunk k−1 materializes + patches on the bounded writer.
            # Decisions are bit-identical to the serial executor (rows are
            # independent; tie-breaks UID-seeded) and the writer patches
            # chunks strictly in order, so per-binding store-write ordering
            # is exactly the serial sequence. Autoshard routes on the WHOLE
            # round first — chunked launches must see the same backend the
            # serial executor would.
            array._maybe_autoshard(len(bindings))
            # equalized chunk-size schedule: lattice-snapped equal chunks —
            # never more program shapes than the greedy split, usually one
            rows = plan_chunk_rows(
                len(bindings), array.round_chunk_rows(len(bindings))
            )
            chunks = [
                bindings[s:e] for s, e in chunk_spans(len(bindings), rows)
            ]
            # same guard as ArrayScheduler._schedule_chunked: out-of-tree
            # plugins' stateful host hooks must never run on two threads,
            # so their (HBM-chunked) rounds execute serially
            pipelined = (
                array.pipeline_enabled
                and not array._oot_plugins
                and len(chunks) > 1
            )
            timer = StageTimer()
            open_members: set[str] = set()
            totals = {"replayed": 0, "solved": 0}

            def estimate(chunk):
                # chunk-shard estimator fan-out: each sweep covers only this
                # chunk's bindings, so the next chunk's answers prefetch
                # while the current one solves. Snapshot the degraded set
                # per sweep — breaker-open members' stale columns merged
                # into THIS chunk's matrix exactly as a serial sweep would.
                extra = reg.batch_estimates(chunk, names)
                return extra, tuple(reg.last_sweep_open)

            def launch(i, chunk, est):
                extra = None
                if est is not None:
                    extra, swept_open = est
                    open_members.update(swept_open)
                pending = self._launch_routed(array, chunk, extra,
                                              round_rows=len(bindings))
                totals["replayed"] += pending["replayed"]
                totals["solved"] += pending["solved"]
                return pending

            # gang cohorts commit at ROUND scope, not chunk scope: the
            # equalized chunk split can land a gang's members in different
            # chunks, and the all-or-nothing commit must see the whole
            # cohort (the streaming path never splits — the coordinator
            # releases a gang into one micro-batch)
            gang_buffer: list = []

            def patch(i, chunk, decisions):
                for decision in decisions:
                    schedule_attempts.inc(
                        result="scheduled" if decision.ok else "error"
                    )
                # coalesced: one batch read + one transactional batch write
                # per chunk instead of 2 store round-trips per binding
                self._patch_results(list(zip(chunk, decisions)),
                                    gang_sink=gang_buffer)

            from contextlib import nullcontext

            # the round's chunk-shard sweeps count as ONE sweep for the
            # staleness cache (snapshots merge, epochs advance once/round)
            sweep_scope = (
                reg.sweep_round() if reg is not None else nullcontext()
            )
            with array.pipeline_context(timer, overlap=pipelined), sweep_scope:
                pipe = ChunkPipeline(
                    launch=launch,
                    materialize=array.materialize_chunk,
                    estimate=estimate if reg is not None else None,
                    patch=patch,
                    pipelined=pipelined,
                    timer=timer,
                    # materialize_chunk times its own finer span
                    time_materialize=False,
                )
                pipe.run(chunks)
                if gang_buffer:
                    self._flush_gang_sink(gang_buffer)
            # the algorithm metric keeps its solve-only reference semantics
            # (estimate RPC time and store patching stay OUTSIDE it, as they
            # were before the pipeline): observe the round's algorithm-stage
            # busy time — stages overlap, so wall-clock would under-count
            scheduling_algorithm_duration.observe(sum(
                timer.totals.get(s, 0.0)
                for s in ("encode", "solve", "materialize")
            ))
            if open_members:
                # degraded mode: at least one member's breaker was open
                # during this round's sweeps — its stale (penalized) rows
                # stayed in the matrix and every chunk still completed as a
                # batched launch
                degraded_rounds.inc()
            stats = pipe.stats()
            stats["chunks"] = len(chunks)
            stats["chunk_rows"] = rows
            # compile economics: a steady-state round on the bucket lattice
            # shows jit_compiles == 0; anything else here is a shape the
            # prewarm lattice (or the persistent cache) should have covered.
            # Attribution is PROCESS-global (the jax.monitoring hook cannot
            # see threads): a concurrent compile — e.g. an abandoned AOT
            # pass draining its last uninterruptible shape right after
            # takeover, or a second in-process scheduler — can leak into
            # one round's delta; treat a lone nonzero round next to a
            # takeover as that, a RECURRING nonzero as a real bucket miss
            stats.update(compile_delta(compile_snap))
            array.last_round_stats = {**totals, **stats}
            trace.step("Pipelined round done (estimate/encode/solve/"
                       "materialize/patch)")
        # slow-round span (the scheduler-side analogue of estimate.go:37-38)
        trace.log_if_long(1.0)
        return []

    def _patch_results(self, items, gang_sink: Optional[list] = None
                       ) -> list[bool]:
        """Coalesced decision patching with workload-class routing: gang
        cohorts split off to the all-or-nothing `_patch_gang` commit (or,
        with `gang_sink`, defer to the caller's round-end `_flush_gang_sink`
        — the batch round's chunk split can separate a gang's members, and
        the atomic commit must see the whole cohort), everything else rides
        the coalesced solo path, and failed PreemptLowerPriority rows take
        the preemption second pass afterwards."""
        if not items:
            return []
        gang_groups: dict[str, list[int]] = {}
        for j, (rb, _dec) in enumerate(items):
            g = self._gang_of(rb)
            if g:
                gang_groups.setdefault(g, []).append(j)
        if not gang_groups:
            return self._patch_solo(items)
        outcomes: list[bool] = [False] * len(items)
        in_gang = {j for js in gang_groups.values() for j in js}
        solo_js = [j for j in range(len(items)) if j not in in_gang]
        if solo_js:
            for j, ok in zip(solo_js,
                             self._patch_solo([items[j] for j in solo_js])):
                outcomes[j] = ok
        for gname, js in gang_groups.items():
            group = [items[j] for j in js]
            if gang_sink is not None:
                # deferred: the round-end flush owns the cohort
                gang_sink.append((gname, group))
                continue
            for j, ok in zip(js, self._patch_gang(gname, group)):
                outcomes[j] = ok
        return outcomes

    def _flush_gang_sink(self, gang_buffer: list) -> None:
        """Round-end gang commit for the batch daemon: chunks deferred
        their gang items here, so a gang split across chunk boundaries
        still commits as ONE cohort."""
        merged: dict[str, list] = {}
        for gname, group in gang_buffer:
            merged.setdefault(gname, []).extend(group)
        for gname, group in merged.items():
            self._patch_gang(gname, group)

    def _gang_full(self, rb: ResourceBinding, dec: ScheduleDecision) -> bool:
        """Joint-feasibility term for one gang member: the solve succeeded
        AND a divided workload placed its FULL replica count (a gang's
        all-or-nothing contract covers partial placements too)."""
        if not dec.ok:
            return False
        if rb.spec.replicas > 0 and rb.spec.placement is not None and (
            rb.spec.placement.replica_scheduling_type()
            != REPLICA_SCHEDULING_DUPLICATED
        ):
            return sum(t.replicas for t in (dec.targets or [])) \
                >= rb.spec.replicas
        return True

    def _patch_gang(self, gname: str, items) -> list[bool]:
        """All-or-nothing gang commit: the whole cohort passes the joint
        feasibility check, prepares against fresh snapshots, and commits in
        ONE rv-checked `update_batch` — a mid-cohort veto (stale rv,
        vanished member, last-moment gate flip) re-admits the WHOLE gang
        uncharged; nothing partial ever reaches the store (pinned by
        tests/test_preemption.py)."""
        from ..store.store import BatchError

        size = max(max((rb.spec.gang_size or 0) for rb, _ in items), 1)
        if len(items) < size or not all(
            self._gang_full(rb, dec) for rb, dec in items
        ):
            self._reject_gang(
                gname,
                [(rb.metadata.key(), rb, 0) for rb, _ in items],
                REASON_GANG_UNSCHEDULABLE,
                f"gang {gname}: cohort did not place all "
                f"{size} members fully",
                outcome="rejected",
            )
            return [False] * len(items)
        get_batch = getattr(self.store, "get_batch", None)
        if get_batch is not None:
            fresh_list = get_batch(
                "ResourceBinding",
                [(rb.name, rb.namespace) for rb, _ in items],
            )
        else:
            fresh_list = [
                self.store.try_get("ResourceBinding", rb.name, rb.namespace)
                for rb, _ in items
            ]
        sink: list = []
        for (rb, dec), fresh in zip(items, fresh_list):
            if fresh is None:
                return self._readmit_gang(items)
            if not self._patch_result(rb, dec, fresh=fresh, sink=sink):
                return self._readmit_gang(items)
        objs = [obj for obj, _ in sink]
        try:
            if objs:
                batch = getattr(self.store, "update_batch", None)
                if batch is not None:
                    batch(objs, check_rv=True)
                else:
                    for obj in objs:
                        self.store.update(obj)
        except BatchError:
            return self._readmit_gang(items)
        gang_admissions.inc(outcome="placed")
        for obj, dec in sink:
            if dec is not None:
                self._record_event(obj, dec)
        return [True] * len(items)

    def _readmit_gang(self, items) -> list[bool]:
        """Mid-cohort veto: something moved under one member and nothing
        committed — the whole gang re-admits uncharged (readd keeps cached
        priorities and burns no retry budget; the coordinator reassembles
        the cohort on the next drain)."""
        q = self.controller.queue
        readd = getattr(q, "readd", None) or q.add
        for rb, _dec in items:
            readd(rb.metadata.key())
        return [False] * len(items)

    def _patch_solo(self, items) -> list[bool]:
        """The coalesced non-gang patch path: per-binding prepare/veto
        against a batch-read fresh snapshot, then ONE transactional batch
        write for the whole cohort — a micro-batch of B decisions costs ≤1
        batch read + 1 batch write instead of 2·B store round-trips, with
        store bytes and event stream bit-identical to the per-object path
        (same objects, same order, contiguous rvs; under concurrent writers
        the cohort write is rv-checked, so a mid-window rewrite skips its
        slot instead of being clobbered). Event recording runs AFTER the
        commit and only for slots that landed. Returns the per-item outcome
        (False = vetoed/skipped, as _patch_result)."""
        if not items:
            return []
        fresh_list = None
        get_batch = getattr(self.store, "get_batch", None)
        if get_batch is not None and len(items) > 1:
            fresh_list = get_batch(
                "ResourceBinding",
                [(rb.name, rb.namespace) for rb, _ in items],
            )
        from ..api.policy import PREEMPT_LOWER_PRIORITY

        sink: list = []
        outcomes = []
        spans = []
        preempt_later: list[int] = []
        for j, (rb, decision) in enumerate(items):
            if (self.preemption_enabled and not decision.ok
                    and rb.spec.preemption_policy == PREEMPT_LOWER_PRIORITY
                    and not self._gang_of(rb)):
                # short-placed preemptor: defer — the preemption pass runs
                # after this cohort commits, and only an infeasible or
                # aborted plan writes the Unschedulable condition (a
                # committed plan would immediately overwrite it, costing a
                # wasted store round-trip per preemption on the hot path)
                preempt_later.append(j)
                outcomes.append(True)  # resolved by _preempt_pass below
                spans.append((len(sink), len(sink)))
                continue
            fresh = fresh_list[j] if fresh_list is not None else _UNREAD
            n0 = len(sink)
            outcomes.append(
                self._patch_result(rb, decision, fresh=fresh, sink=sink)
            )
            spans.append((n0, len(sink)))
        if sink:
            from ..store.batching import update_all

            # rv-checked with per-slot skip: batching widens the
            # read→commit window from per-binding to per-cohort, so a
            # binding rewritten (or deleted) in that window SKIPS — never
            # clobbered by the stale snapshot — and reports a veto below;
            # its own change event re-admits the key
            committed = update_all(self.store, [obj for obj, _ in sink],
                                   path="sched_patch",
                                   skip_missing=True, skip_stale=True)
            for j, (n0, n1) in enumerate(spans):
                if any(committed[k] is None for k in range(n0, n1)):
                    outcomes[j] = False
            # events record post-commit, and only for writes that LANDED —
            # a skipped slot must not log "scheduled successfully"
            for (obj, decision), done in zip(sink, committed):
                if decision is not None and done is not None:
                    self._record_event(obj, decision)
        if preempt_later:
            self._preempt_pass(items, preempt_later, outcomes)
        return outcomes

    # -- preemption second pass (sched/preemption.py) ----------------------

    def _preempt_pass(self, items, idxs, outcomes) -> None:
        """Short-placed PreemptLowerPriority bindings take the second solve
        pass: plan over a victim-augmented capacity matrix (one launch per
        distinct preemptor priority), then commit victim replica reductions
        + preemptor placements as ONE rv-checked batch cohort. A committed
        plan rewrites the in-flight decision to its placement so the
        streaming writer observes the preemptor's placement latency on the
        same SLO histogram as ordinary admissions; anything else falls back
        to the ordinary unschedulable patch."""
        cands = [(j, *items[j]) for j in idxs]
        plans_by_key: dict = {}
        if self._array is not None:
            import numpy as np

            from .preemption import (
                PlanLedger, plan_from_speculative, plan_preemption,
            )

            placed = [
                b for b in list(self._placed.values())
                if b.spec.clusters and b.metadata.deletion_timestamp is None
            ]
            # rows whose victim-augmented decision already rode the
            # admission launch (speculative second pass) plan with ZERO
            # extra solves; the rest (batch-round fallbacks, std-path
            # rows) pay the standalone planner's launch. ONE ledger spans
            # both paths: every plan in this pass sees the free capacity
            # and victim replicas earlier plans already claimed, so the
            # joint commit cannot double-count either.
            ledger = PlanLedger(
                np.asarray(self._array.fleet.capacity, np.int64)
            )
            spec_pairs = [(rb, dec.speculative) for _j, rb, dec in cands
                          if dec.speculative is not None]
            solve_rbs = [rb for _j, rb, dec in cands
                         if dec.speculative is None]
            plans = []
            if spec_pairs:
                plans += plan_from_speculative(self._array, placed,
                                               spec_pairs, ledger=ledger)
            if solve_rbs:
                plans += plan_preemption(self._array, placed, solve_rbs,
                                         ledger=ledger)
            plans_by_key = {p.key: p for p in plans}
        feasible = []
        feasible_js = []
        fallback = []
        for j, rb, dec in cands:
            plan = plans_by_key.get(rb.metadata.key())
            if plan is None or not plan.feasible:
                preemptions_total.inc(outcome="infeasible")
                fallback.append(j)
                continue
            feasible.append((rb, dec, plan))
            feasible_js.append(j)
        if feasible and not self._commit_preemption(feasible):
            fallback.extend(feasible_js)
        for j in fallback:
            rb, dec = items[j]
            outcomes[j] = self._patch_result(rb, dec)

    def _commit_preemption(self, feasible) -> bool:
        """The atomic half: victim cuts (merged per victim binding, flowing
        through graceful-eviction tasks) and every preemptor's placement in
        ONE `update_batch(check_rv=True)` — a concurrent write to any
        member aborts the whole plan (outcome=aborted; the preemptor stays
        unschedulable and retries on its next event)."""
        from ..store.store import BatchError

        # merge victim cuts per (binding, cluster): plans within one
        # priority group SHARE one victims list (id-identical — the joint
        # selection), counted once; DISTINCT groups' cuts SUM — the plan
        # ledger already guaranteed they claim disjoint replicas, so the
        # sum is exactly the combined eviction the pass decided on
        cuts: dict[tuple[str, str], int] = {}
        seen_lists: set[int] = set()
        for _rb, _dec, plan in feasible:
            if id(plan.victims) in seen_lists:
                continue
            seen_lists.add(id(plan.victims))
            for v in plan.victims:
                k = (v.key, v.cluster)
                cuts[k] = cuts.get(k, 0) + v.replicas
        victim_keys = sorted({k for k, _c in cuts})
        now = self.clock.now()
        objs: list = []
        # fresh reads coalesced: one batch read for the victims + one for
        # the preemptors instead of a try_get (lock hold + deep copy) each
        get_batch = getattr(self.store, "get_batch", None)
        if get_batch is not None:
            pre_keys = [rb.metadata.key() for rb, _d, _p in feasible]
            pairs = [(k.partition("/")[2], k.partition("/")[0])
                     for k in victim_keys + pre_keys]
            fresh_all = get_batch("ResourceBinding", pairs)
            victims_fresh = dict(zip(victim_keys, fresh_all))
            preemptors_fresh = dict(zip(pre_keys,
                                        fresh_all[len(victim_keys):]))
        else:
            victims_fresh = preemptors_fresh = None
        for vkey in victim_keys:
            ns, _, name = vkey.partition("/")
            if victims_fresh is not None:
                victim = victims_fresh[vkey]
            else:
                victim = self.store.try_get("ResourceBinding", name, ns)
            if victim is None or victim.metadata.deletion_timestamp is not None:
                self._abort_preemption(feasible, "victim vanished mid-plan")
                return False
            for (k2, cluster), cut in sorted(cuts.items()):
                if k2 != vkey:
                    continue
                entry = next(
                    (tc for tc in victim.spec.clusters
                     if tc.name == cluster), None,
                )
                if entry is None or entry.replicas < cut:
                    self._abort_preemption(
                        feasible, "victim placement changed mid-plan"
                    )
                    return False
                entry.replicas -= cut
                if entry.replicas == 0:
                    victim.spec.clusters = [
                        tc for tc in victim.spec.clusters
                        if tc.name != cluster
                    ]
                victim.spec.graceful_eviction_tasks.append(
                    GracefulEvictionTask(
                        from_cluster=cluster,
                        replicas=cut,
                        reason=EVICTION_REASON_PREEMPTED,
                        message=("preempted by higher-priority binding(s): "
                                 + ", ".join(p.key for _r, _d, p in feasible
                                             )[:200]),
                        producer=EVICTION_PRODUCER_PREEMPTION,
                        creation_timestamp=now,
                    )
                )
            objs.append(victim)
        # the preemptor's placement write goes through _patch_result — THE
        # one placement-write implementation (annotation, condition,
        # observed generation/affinity, reschedule handling) — so the
        # preemption path cannot drift from the ordinary patch path, and
        # committed placements record the same binding Event
        sink: list = []
        for rb, dec, plan in feasible:
            if preemptors_fresh is not None:
                fresh = preemptors_fresh[rb.metadata.key()]
            else:
                fresh = self.store.try_get("ResourceBinding", rb.name,
                                           rb.namespace)
            if fresh is None:
                self._abort_preemption(feasible, "preemptor vanished")
                return False
            placed_dec = ScheduleDecision(dec.key,
                                          targets=list(plan.targets))
            if not self._patch_result(rb, placed_dec, fresh=fresh,
                                      sink=sink):
                self._abort_preemption(feasible, "preemptor gate flipped")
                return False
        objs.extend(obj for obj, _dec in sink)
        try:
            batch = getattr(self.store, "update_batch", None)
            if batch is not None:
                batch(objs, check_rv=True)
            else:
                for obj in objs:
                    self.store.update(obj)
        except BatchError:
            self._abort_preemption(feasible, "atomic commit lost a race")
            return False
        for obj, dec in sink:
            if dec is not None:
                self._record_event(obj, dec)
        for rb, dec, plan in feasible:
            preemptions_total.inc(outcome="committed")
            preemption_victims.observe(float(len(plan.victim_keys())))
            # rewrite the in-flight decision: the preemptor IS placed now,
            # so the streaming writer's SLO accounting sees a placement
            dec.error = ""
            dec.targets = list(plan.targets)
        return True

    def _abort_preemption(self, feasible, why: str) -> None:
        import logging

        logging.getLogger(__name__).warning("preemption aborted: %s", why)
        for _rb, _dec, _plan in feasible:
            preemptions_total.inc(outcome="aborted")

    def _patch_result(self, rb: ResourceBinding, decision: ScheduleDecision,
                      *, fresh=None, sink=None,
                      any_shard: bool = False) -> bool:
        """Write a decision back to the store. Returns False when the write
        is VETOED by a last-moment spec change: the streaming writer's epoch
        fence is check-then-act, so a deletion/suspension/re-target event
        landing between the epoch comparison and this write must still stop
        the patch — re-checked here against the freshest spec, under the
        store's serialization (which orders this read after that event's
        write).

        Coalescing seams (used by _patch_results): `fresh` supplies a
        batch-read snapshot instead of the per-binding try_get; `sink`
        collects (obj, decision-to-record|None) instead of writing — the
        caller commits the whole cohort as one batch and records events
        post-commit."""
        if fresh is _UNREAD or (fresh is None and sink is None):
            fresh = self.store.try_get("ResourceBinding", rb.name, rb.namespace)
        if self._admission_gate(fresh, any_shard=any_shard) in (
                "drop", "suspended"):
            return False
        if decision.ok:
            placement = placement_json(fresh.spec.placement)
            trigger_active = fresh.spec.reschedule_triggered_at is not None and (
                fresh.status.last_scheduled_time is None
                or fresh.spec.reschedule_triggered_at > fresh.status.last_scheduled_time
            )
            changed = (
                _targets_fingerprint(fresh.spec.clusters)
                != _targets_fingerprint(decision.targets)
                or fresh.metadata.annotations.get(POLICY_PLACEMENT_ANNOTATION) != placement
                or trigger_active
            )
            fresh.spec.clusters = decision.targets
            fresh.metadata.annotations[POLICY_PLACEMENT_ANNOTATION] = placement
            cond_changed = set_condition(
                fresh.status.conditions,
                Condition(
                    type=CONDITION_SCHEDULED,
                    status="True",
                    reason=REASON_BINDING_SCHEDULED,
                    message="Binding has been scheduled successfully.",
                ),
            )
            if not changed and not cond_changed:
                if fresh.status.scheduler_observed_generation != fresh.metadata.generation:
                    fresh.status.scheduler_observed_generation = fresh.metadata.generation
                    self._commit_patch(fresh, None, sink)
                return True  # idempotent no-op: the event fixpoint terminates here
            fresh.status.scheduler_observed_generation = fresh.metadata.generation
            fresh.status.scheduler_observed_affinity_name = decision.affinity_name
            fresh.status.last_scheduled_time = self.clock.now()
        else:
            reason = (
                REASON_UNSCHEDULABLE
                if "not enough" in decision.error or "available" in decision.error
                else REASON_SCHEDULE_FAILED
            )
            if isinstance(self.controller.queue, PrioritySchedulingQueue):
                # park until new information arrives (≤5 min max stay)
                self.controller.queue.push_unschedulable(fresh.metadata.key())
            if not set_condition(
                fresh.status.conditions,
                Condition(
                    type=CONDITION_SCHEDULED,
                    status="False",
                    reason=reason,
                    message=decision.error,
                ),
            ):
                return True
        self._commit_patch(fresh, decision, sink)
        return True

    def _commit_patch(self, fresh: ResourceBinding,
                      decision: Optional[ScheduleDecision], sink) -> None:
        """The write point of a patch: straight to the store (per-object
        path), or into the caller's sink for one transactional batch write
        (decision=None marks a bookkeeping-only write with no event)."""
        if sink is not None:
            sink.append((fresh, decision))
            return
        self.store.update(fresh)
        if decision is not None:
            self._record_event(fresh, decision)

    def _record_event(self, fresh: ResourceBinding,
                      decision: ScheduleDecision) -> None:
        if self.event_recorder is None:
            return
        # recorded on the binding (scheduler.go:964-1010); the binding
        # status controller mirrors template-side visibility
        from ..events import (
            REASON_SCHEDULE_BINDING_FAILED,
            REASON_SCHEDULE_BINDING_SUCCEED,
            TYPE_NORMAL,
            TYPE_WARNING,
        )

        if decision.ok:
            self.event_recorder.event(
                fresh, TYPE_NORMAL, REASON_SCHEDULE_BINDING_SUCCEED,
                "Binding has been scheduled successfully.",
            )
        else:
            self.event_recorder.event(
                fresh, TYPE_WARNING, REASON_SCHEDULE_BINDING_FAILED, decision.error
            )


def _targets_fingerprint(targets) -> tuple:
    return tuple(sorted((t.name, t.replicas) for t in (targets or [])))

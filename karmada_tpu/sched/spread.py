"""SpreadConstraint selection: multi-dimensional HA group choice.

Parity with pkg/scheduler/core/spreadconstraint (SCH8): group scoring
(group_clusters.go:138-330), cluster-only selection with the
availability-swap repair (select_clusters_by_cluster.go:46-99), region
selection via the exact DFS over group combinations with pruning and
weight>value>id path ranking + subpath preference (select_groups.go:100-230,
select_clusters_by_region.go:28-119). Only cluster and region constraints
are implemented — matching the reference, which errors on provider/zone-only
combinations (select_clusters.go:59).

Two implementations of the same semantics:
- the ClusterDetail list functions below are the readable spec (and what the
  parity tests exercise directly);
- `select_by_spread_arrays` is the hot path the scheduler core calls: group
  membership, availability sums and group scores are numpy array ops over the
  kernel's score/avail rows (one lexsort + cumsums per row) — no per-cluster
  Python object is ever built, which is what makes 5k spread rows × 5k
  clusters per round viable. Only the group-combination DFS stays
  combinatorial (SURVEY §7 hard parts; group counts are small).
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from ..api.policy import (
    DIVISION_PREFERENCE_WEIGHTED,
    Placement,
    REPLICA_SCHEDULING_DIVIDED,
    REPLICA_SCHEDULING_DUPLICATED,
    SPREAD_BY_FIELD_CLUSTER,
    SPREAD_BY_FIELD_REGION,
    SpreadConstraint,
)

INVALID_REPLICAS = -1
WEIGHT_UNIT = 1000


class SpreadError(Exception):
    pass


@dataclass
class ClusterDetail:
    name: str
    index: int  # position in the fleet arrays (deterministic tie-break)
    score: int
    available: int  # estimator avail + own assigned replicas
    region: str = ""
    zone: str = ""
    provider: str = ""


def should_ignore_spread_constraint(placement: Placement) -> bool:
    """Static-weighted division ignores spread constraints
    (select_clusters.go:63-77)."""
    rs = placement.replica_scheduling
    if (
        rs is not None
        and rs.replica_scheduling_type == REPLICA_SCHEDULING_DIVIDED
        and rs.replica_division_preference == DIVISION_PREFERENCE_WEIGHTED
        and (
            rs.weight_preference is None
            or (rs.weight_preference.static_weight_list and not rs.weight_preference.dynamic_weight)
        )
    ):
        return True
    return False


def should_ignore_available_resource(placement: Placement) -> bool:
    """Duplicated ignores availability during selection (select_clusters.go:79-88)."""
    rs = placement.replica_scheduling
    return rs is None or rs.replica_scheduling_type == REPLICA_SCHEDULING_DUPLICATED


def sort_details(details: list[ClusterDetail], avail_desc: bool = True) -> list[ClusterDetail]:
    """sortClusters (util.go:43-57): score desc, then avail desc, then name."""
    if avail_desc:
        return sorted(details, key=lambda d: (-d.score, -d.available, d.name))
    return sorted(details, key=lambda d: (-d.score, d.name))


def calc_group_score_duplicated(clusters: list[ClusterDetail], replicas: int) -> int:
    """calcGroupScoreForDuplicate (group_clusters.go:143-215):
    validClusters*1000 + avg(valid scores)."""
    valid = [c for c in clusters if c.available >= replicas]
    if not valid:
        return 0
    return len(valid) * WEIGHT_UNIT + sum(c.score for c in valid) // len(valid)


def calc_group_score_divided(
    clusters: list[ClusterDetail],
    replicas: int,
    min_groups: int,
    cluster_min_groups: int,
) -> int:
    """calcGroupScore divided branch (group_clusters.go:217-330)."""
    target = math.ceil(replicas / max(min_groups, 1))
    need = max(cluster_min_groups, min_groups)
    sum_avail = sum_score = valid = 0
    for c in clusters:  # clusters already sorted score desc, avail desc
        sum_avail += c.available
        sum_score += c.score
        valid += 1
        if valid >= need and sum_avail >= target:
            break
    if sum_avail < target:
        return sum_avail * WEIGHT_UNIT + sum_score // len(clusters)
    return target * WEIGHT_UNIT + sum_score // valid


def _constraint_map(constraints: Sequence[SpreadConstraint]) -> dict[str, SpreadConstraint]:
    return {c.spread_by_field: c for c in constraints}


def select_clusters_by_spread(
    details: list[ClusterDetail],
    placement: Placement,
    replicas: int,
) -> list[ClusterDetail]:
    """SelectBestClusters (select_clusters.go:29-60). `details` must be the
    feasible clusters with device-computed score/avail. Raises SpreadError
    when constraints cannot be met."""
    constraints = placement.spread_constraints
    details = sort_details(details)
    if not constraints or should_ignore_spread_constraint(placement):
        return details

    cmap = _constraint_map(constraints)
    if SPREAD_BY_FIELD_REGION in cmap:
        return _select_by_region(cmap, details, placement, replicas)
    if SPREAD_BY_FIELD_CLUSTER in cmap:
        need_replicas = (
            INVALID_REPLICAS if should_ignore_available_resource(placement) else replicas
        )
        return _select_by_cluster(cmap[SPREAD_BY_FIELD_CLUSTER], details, need_replicas)
    raise SpreadError("just support cluster and region spread constraint")


# -- cluster-only (select_clusters_by_cluster.go) ---------------------------


def _select_by_cluster(
    constraint: SpreadConstraint,
    details: list[ClusterDetail],
    need_replicas: int,
) -> list[ClusterDetail]:
    total = len(details)
    if total < constraint.min_groups:
        raise SpreadError(
            "the number of feasible clusters is less than spreadConstraint.MinGroups"
        )
    need_cnt = constraint.max_groups if constraint.max_groups > 0 else total
    need_cnt = min(need_cnt, total)
    if need_replicas == INVALID_REPLICAS:
        return details[:need_cnt]
    selected = _select_by_available_resource(details, need_cnt, need_replicas)
    if not selected:
        raise SpreadError(f"no enough resource when selecting {need_cnt} clusters")
    return selected


def _select_by_available_resource(
    candidates: list[ClusterDetail], need_cnt: int, need_replicas: int
) -> list[ClusterDetail]:
    """selectClustersByAvailableResource (select_clusters_by_cluster.go:66-88):
    start from the top-scored prefix; while capacity is short, replace the
    lowest-scored kept cluster with the biggest-capacity rest cluster."""
    ret = list(candidates[:need_cnt])
    rest = list(candidates[need_cnt:])
    update_idx = len(ret) - 1
    while sum(c.available for c in ret) < need_replicas and update_idx >= 0:
        best = None
        for i, c in enumerate(rest):
            if c.available > ret[update_idx].available and (
                best is None or c.available > rest[best].available
            ):
                best = i
        if best is None:
            update_idx -= 1
            continue
        ret[update_idx], rest[best] = rest[best], ret[update_idx]
        update_idx -= 1
    if sum(c.available for c in ret) < need_replicas:
        return []
    return ret


# -- region (select_clusters_by_region.go + select_groups.go) ---------------


@dataclass
class _Group:
    name: str
    value: int  # number of clusters
    weight: int  # group score
    clusters: list[ClusterDetail] = field(default_factory=list)
    available: int = 0


def _select_by_region(
    cmap: dict[str, SpreadConstraint],
    details: list[ClusterDetail],
    placement: Placement,
    replicas: int,
) -> list[ClusterDetail]:
    region_constraint = cmap[SPREAD_BY_FIELD_REGION]
    cluster_constraint = cmap.get(SPREAD_BY_FIELD_CLUSTER, SpreadConstraint(min_groups=0))

    regions: dict[str, _Group] = {}
    for c in details:  # details sorted; region cluster lists inherit order
        if not c.region:
            continue
        g = regions.setdefault(c.region, _Group(name=c.region, value=0, weight=0))
        g.clusters.append(c)
        g.value += 1
        g.available += c.available

    if len(regions) < region_constraint.min_groups:
        raise SpreadError("the number of feasible region is less than spreadConstraint.MinGroups")

    duplicated = should_ignore_available_resource(placement)
    for g in regions.values():
        if duplicated:
            g.weight = calc_group_score_duplicated(g.clusters, replicas)
        else:
            g.weight = calc_group_score_divided(
                g.clusters,
                replicas,
                max(region_constraint.min_groups, 1),
                cluster_constraint.min_groups,
            )

    chosen = _select_groups(
        list(regions.values()),
        region_constraint.min_groups,
        region_constraint.max_groups if region_constraint.max_groups > 0 else len(regions),
        cluster_constraint.min_groups,
    )
    if not chosen:
        raise SpreadError("the number of clusters is less than the cluster spreadConstraint.MinGroups")

    # best cluster per selected region, then fill by score (avail tie-break)
    selected = [g.clusters[0] for g in chosen]
    candidates: list[ClusterDetail] = []
    for g in chosen:
        candidates.extend(g.clusters[1:])
    need_cnt = len(selected) + len(candidates)
    if cluster_constraint.max_groups > 0:
        need_cnt = min(need_cnt, cluster_constraint.max_groups)
    rest_cnt = need_cnt - len(selected)
    if rest_cnt > 0:
        selected.extend(sort_details(candidates)[:rest_cnt])
    return selected


# -- array fast path (scheduler core) ---------------------------------------


@dataclass
class _ArrayGroup:
    """Region group over positions into the row's sorted feasible arrays.
    Duck-types _Group for the shared DFS (value/weight/name)."""

    name: str
    value: int
    weight: int
    positions: np.ndarray = None
    available: int = 0


def select_by_spread_arrays(
    feas_idx: np.ndarray,  # i64[N] fleet indices of the row's feasible clusters
    score: np.ndarray,  # i32[N] kernel score row
    available: np.ndarray,  # i64[N] kernel avail + own previous replicas
    name_rank: np.ndarray,  # i32[N] cluster-name ascending rank (tie-break)
    region_id: np.ndarray,  # i32[N] region id, -1 = none
    region_names: Sequence[str],  # id → region name (group-id tie-break)
    placement: Placement,
    replicas: int,
) -> np.ndarray:
    """Array equivalent of select_clusters_by_spread: returns the SELECTED
    fleet indices. Semantics identical to the ClusterDetail path (parity
    tested); no per-cluster objects are built.

    Callers feed per-FEASIBLE-cluster arrays; the indices only name the
    clusters, so the compact candidate round (sched/candidates.py) passes
    its window-gathered slices directly — the selection is well-defined
    over ANY subset that contains the row's whole feasible set, and rows
    whose feasible set outruns the candidate window must re-solve dense
    before calling here (the loud spread_constraint fallback)."""
    available = available.astype(np.int64)
    # sortClusters (util.go:43-57): score desc, avail desc, name asc
    order = np.lexsort((name_rank, -available, -score))
    feas_idx = feas_idx[order]
    score = score[order]
    available = available[order]
    region_id = region_id[order]

    constraints = placement.spread_constraints
    if not constraints or should_ignore_spread_constraint(placement):
        return feas_idx

    cmap = _constraint_map(constraints)
    if SPREAD_BY_FIELD_REGION in cmap:
        return _region_arrays(
            cmap, feas_idx, score, available, region_id, region_names,
            placement, replicas,
        )
    if SPREAD_BY_FIELD_CLUSTER in cmap:
        need_replicas = (
            INVALID_REPLICAS if should_ignore_available_resource(placement) else replicas
        )
        return _cluster_arrays(
            cmap[SPREAD_BY_FIELD_CLUSTER], feas_idx, available, need_replicas
        )
    raise SpreadError("just support cluster and region spread constraint")


def _cluster_arrays(
    constraint: SpreadConstraint,
    feas_idx: np.ndarray,  # sorted
    available: np.ndarray,
    need_replicas: int,
) -> np.ndarray:
    """_select_by_cluster + the availability-swap repair
    (select_clusters_by_cluster.go:46-99) over arrays."""
    total = len(feas_idx)
    if total < constraint.min_groups:
        raise SpreadError(
            "the number of feasible clusters is less than spreadConstraint.MinGroups"
        )
    need_cnt = constraint.max_groups if constraint.max_groups > 0 else total
    need_cnt = min(need_cnt, total)
    if need_replicas == INVALID_REPLICAS:
        return feas_idx[:need_cnt]

    ret_pos = np.arange(need_cnt)
    rest_pos = np.arange(need_cnt, total)
    ret_av = available[:need_cnt].copy()
    rest_av = available[need_cnt:].copy()
    update = need_cnt - 1
    while ret_av.sum() < need_replicas and update >= 0:
        # reference picks the max-availability rest cluster strictly better
        # than the one being replaced; argmax's first-max == its choice
        if rest_av.size:
            best = int(np.argmax(rest_av))
            if rest_av[best] > ret_av[update]:
                ret_pos[update], rest_pos[best] = rest_pos[best], ret_pos[update]
                ret_av[update], rest_av[best] = rest_av[best], ret_av[update]
        update -= 1
    if ret_av.sum() < need_replicas:
        raise SpreadError(f"no enough resource when selecting {need_cnt} clusters")
    return feas_idx[ret_pos]


def _region_arrays(
    cmap: dict[str, SpreadConstraint],
    feas_idx: np.ndarray,  # all sorted by (score desc, avail desc, name asc)
    score: np.ndarray,
    available: np.ndarray,
    region_id: np.ndarray,
    region_names: Sequence[str],
    placement: Placement,
    replicas: int,
) -> np.ndarray:
    """_select_by_region over arrays: per-region membership/sums/scores via
    cumsums on the sorted row; DFS unchanged."""
    region_constraint = cmap[SPREAD_BY_FIELD_REGION]
    cluster_constraint = cmap.get(SPREAD_BY_FIELD_CLUSTER, SpreadConstraint(min_groups=0))

    has_region = region_id >= 0
    rids = region_id[has_region]
    positions = np.nonzero(has_region)[0]
    unique_rids = np.unique(rids)
    if len(unique_rids) < region_constraint.min_groups:
        raise SpreadError(
            "the number of feasible region is less than spreadConstraint.MinGroups"
        )

    duplicated = should_ignore_available_resource(placement)
    min_groups = max(region_constraint.min_groups, 1)
    need = max(cluster_constraint.min_groups, min_groups)
    target = math.ceil(replicas / min_groups)

    groups: list[_ArrayGroup] = []
    for rid in unique_rids:
        pos = positions[rids == int(rid)]  # ascending = global sorted order
        av = available[pos]
        sc = score[pos].astype(np.int64)
        n = len(pos)
        if duplicated:
            # calcGroupScoreForDuplicate (group_clusters.go:143-215)
            valid = av >= replicas
            cnt = int(valid.sum())
            weight = cnt * WEIGHT_UNIT + int(sc[valid].sum()) // cnt if cnt else 0
        else:
            # calcGroupScore divided branch (group_clusters.go:217-330):
            # prefix accumulation in sorted order with early stop
            cum_av = np.cumsum(av)
            cum_sc = np.cumsum(sc)
            cond = (np.arange(1, n + 1) >= need) & (cum_av >= target)
            if cond.any():
                k = int(np.argmax(cond))
                weight = target * WEIGHT_UNIT + int(cum_sc[k]) // (k + 1)
            elif int(cum_av[-1]) < target:
                weight = int(cum_av[-1]) * WEIGHT_UNIT + int(cum_sc[-1]) // n
            else:
                weight = target * WEIGHT_UNIT + int(cum_sc[-1]) // n
        groups.append(
            _ArrayGroup(
                name=region_names[int(rid)],
                value=n,
                weight=weight,
                positions=pos,
                available=int(av.sum()),
            )
        )

    chosen = _select_groups(
        groups,
        region_constraint.min_groups,
        region_constraint.max_groups if region_constraint.max_groups > 0 else len(groups),
        cluster_constraint.min_groups,
    )
    if not chosen:
        raise SpreadError(
            "the number of clusters is less than the cluster spreadConstraint.MinGroups"
        )

    # best cluster per selected region, then fill by score — candidate
    # positions ascending reproduce sort_details order exactly
    selected = [int(g.positions[0]) for g in chosen]
    candidates = np.sort(np.concatenate([g.positions[1:] for g in chosen]))
    need_cnt = len(selected) + len(candidates)
    if cluster_constraint.max_groups > 0:
        need_cnt = min(need_cnt, cluster_constraint.max_groups)
    rest_cnt = need_cnt - len(selected)
    if rest_cnt > 0:
        selected.extend(int(p) for p in candidates[:rest_cnt])
    return feas_idx[selected]


def _select_groups(
    groups: list[_Group], min_constraint: int, max_constraint: int, target: int
) -> list[_Group]:
    """selectGroups/findFeasiblePaths/prioritizePaths (select_groups.go:100-230):
    exact DFS over group combinations whose total cluster count covers
    `target`, path length within [min,max]; rank weight desc > value desc >
    id asc; prefer subpaths of the winner."""
    if not groups:
        return []
    groups = sorted(groups, key=lambda g: (g.value, -g.weight, g.name))
    min_constraint = max(min_constraint, 1)
    max_constraint = max(max_constraint, min_constraint)

    paths: list[tuple[int, list[_Group]]] = []
    path: list[_Group] = []
    counter = [0]

    def dfs(total: int, begin: int) -> None:
        if total >= target and min_constraint <= len(path) <= max_constraint:
            counter[0] += 1
            # groups within a recorded path sort by weight desc, name asc
            # (dfsPath.sortGroups) — subpath preference compares this order
            paths.append((counter[0], sorted(path, key=lambda g: (-g.weight, g.name))))
            return
        if len(path) >= max_constraint:
            return
        for i in range(begin, len(groups)):
            path.append(groups[i])
            dfs(total + groups[i].value, i + 1)
            if len(groups) == min_constraint:
                break
            path.pop()

    dfs(0, 0)
    if not paths:
        return []

    def rank(entry):
        pid, gs = entry
        return (-sum(g.weight for g in gs), -sum(g.value for g in gs), pid)

    paths.sort(key=rank)
    final = paths[0][1]
    for _, gs in paths[1:]:
        names = [g.name for g in gs]
        final_names = [g.name for g in final]
        if len(names) < len(final_names) and final_names[: len(names)] == names:
            final = gs
    return final

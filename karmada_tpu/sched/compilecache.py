"""Persistent XLA compilation cache + compile observability.

BENCH_tpu_latest.json shows compile time dominating real deployments: every
new (B, C) shape pays 67–157 s of warm time before its first round, so the
"takeover within one lease TTL" claim (docs/HA.md) only held once XLA was
warm. Three mechanisms make compilation a boot-time, cached, shape-stable
cost instead of a per-fleet-epoch one:

1. **Shape bucketing** (models/batch.py `shape_bucket`) bounds the set of
   program shapes a deployment can reach — see sched/core.py.
2. **The persistent compilation cache** (this module): JAX's disk cache,
   keyed under the daemon's data dir, so a cold PROCESS re-uses every
   program any previous process compiled. `enable_persistent_cache` wires
   `jax_compilation_cache_dir` with thresholds dropped to zero (the round
   kernels are exactly the programs worth persisting) and reports the
   entry count loudly at boot.
3. **AOT prewarm** (sched/aot.py) walks the reachable bucket lattice at
   boot/standby time and `lower(...).compile()`s the round kernels, so the
   disk cache is populated BEFORE the first real round.

Observability: `install_compile_listeners()` hooks `jax.monitoring` —
every XLA backend compile observes `karmada_jit_compile_seconds` and
increments `karmada_jit_cache_misses_total`; compiles served from the disk
cache increment `karmada_jit_persistent_cache_hits_total`. All three ride
`/metrics`, and the scheduler daemon folds the per-round deltas into
`ArrayScheduler.last_round_stats`.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from ..metrics import (
    jit_cache_misses,
    jit_compile_seconds,
    jit_persistent_cache_hits,
)

log = logging.getLogger(__name__)

ENV_COMPILE_CACHE = "KARMADA_TPU_COMPILE_CACHE"

# jax.monitoring event names (stable across the 0.4.x line this image
# bakes): the duration event fires once per actual XLA backend compile —
# not on executable-cache or persistent-cache hits — and the hit event
# fires when the persistent cache served a program from disk.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_COMPILE_EVENT_LEGACY = "/jax/core/compile/backend_compile_time"
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"

_install_lock = threading.Lock()
_installed = False


def install_compile_listeners() -> None:
    """Register the jax.monitoring listeners feeding the compile metrics.
    Idempotent and cheap — ArrayScheduler installs it at construction so
    every entry point (daemons, tests, bench) gets compile observability
    without its own wiring."""
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True
    from jax._src import monitoring

    def _on_duration(event: str, duration: float, **_kw) -> None:
        if event == _COMPILE_EVENT or event == _COMPILE_EVENT_LEGACY:
            jit_compile_seconds.observe(duration)
            jit_cache_misses.inc()

    def _on_event(event: str, **_kw) -> None:
        if event == _CACHE_HIT_EVENT:
            jit_persistent_cache_hits.inc()

    monitoring.register_event_duration_secs_listener(_on_duration)
    monitoring.register_event_listener(_on_event)


def compile_counts() -> dict:
    """Snapshot of the compile counters — callers diff two snapshots to
    attribute compiles/seconds/disk-hits to one round or prewarm pass."""
    return {
        "jit_compiles": int(jit_cache_misses.total()),
        "jit_compile_seconds": round(jit_compile_seconds.sum(), 6),
        "jit_persistent_cache_hits": int(jit_persistent_cache_hits.total()),
    }


def compile_delta(before: dict, after: Optional[dict] = None) -> dict:
    if after is None:
        after = compile_counts()
    return {
        k: round(after[k] - before[k], 6) if isinstance(after[k], float)
        else after[k] - before[k]
        for k in before
    }


def resolve_cache_dir(
    flag: str = "", data_dir: str = "", env: Optional[dict] = None
) -> str:
    """Cache-location precedence shared by every daemon: explicit
    --compile-cache-dir flag > KARMADA_TPU_COMPILE_CACHE env > a
    `compile-cache/` subdir of --data-dir when one is configured > disabled
    (empty string). `off`/`none`/`0`/`false` as the flag or env disables
    even when a data dir exists (`false` included so the token every
    sibling KARMADA_TPU_* switch accepts cannot create a cache directory
    literally named ./false)."""
    env = os.environ if env is None else env
    for val in (flag, env.get(ENV_COMPILE_CACHE, "")):
        if val in ("off", "none", "0", "false"):
            return ""
        if val:
            return val
    if data_dir:
        return os.path.join(data_dir, "compile-cache")
    return ""


def enable_persistent_cache(path: str) -> int:
    """Point JAX's persistent compilation cache at `path` (created if
    missing) and return the number of cached programs already there. The
    size/time thresholds drop to zero: the schedule-round kernels are
    exactly the programs worth persisting, and the sub-millisecond helper
    jits around them are noise either way. Also installs the compile
    listeners so the boot log's hit/miss claim is backed by counters."""
    import jax

    install_compile_listeners()
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    # the cache object initializes lazily on first compile and then pins its
    # decision — a process that already compiled something (tests, a late
    # enable) must drop that state or the new dir is silently ignored
    from jax._src import compilation_cache

    compilation_cache.reset_cache()
    n = cache_entries(path)
    # loud by design: whether a boot is riding warm programs is the first
    # thing to check when takeover latency looks wrong. describe_cache is
    # the SINGLE wording source — the daemons print the same line to stdout.
    log.warning("%s", describe_cache(path, n))
    return n


def describe_cache(path: str, n: int) -> str:
    """The canonical one-line boot report for a cache dir with n cached
    programs — shared by the library log and every daemon's stdout print so
    the wording cannot drift."""
    state = (
        "warm boot, compiles hit disk" if n
        else "cold boot, this process compiles"
    )
    return f"compile cache: {path} ({n} cached programs — {state})"


def disable_persistent_cache() -> None:
    """Detach the persistent cache (tests restore global state with this)."""
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    from jax._src import compilation_cache

    compilation_cache.reset_cache()


def cache_entries(path: str) -> int:
    """Number of cached programs under a cache dir (best-effort)."""
    try:
        names = os.listdir(path)
    except OSError:
        return 0
    cached = [f for f in names if f.endswith("-cache")]
    if cached:  # this jax line writes <key>-cache + <key>-atime pairs
        return len(cached)
    return sum(1 for f in names if not f.endswith("-atime"))

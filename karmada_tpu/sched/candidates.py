"""Top-K candidate sparsification: compact [B, K] solves with pinned
exact-dense parity (ROADMAP item 3(i), docs/PERF.md "Candidate
sparsification").

The dense round solves every binding against every cluster column — [B, C]
— and no bucketing saves that product at 1M x 50k. This module inserts a
cheap fully-vectorized PREPASS (one device launch, elementwise masks +
static score only) that picks each row's top-K candidate clusters via
`jax.lax.top_k`, then compacts everything the expensive phases consume —
estimator answers, previous placements, tie values, static weights,
override masks — into [B, K] via gathers along the candidate index.
Decisions scatter back to fleet indices on decode. Solve cost becomes
O(B·K) after one O(B·C) elementwise pass.

Correctness contract (tests/test_candidates.py):

- **Feasibility-aware selection.** The top-K key is
  `(feasible << 33) + score`, so EVERY feasible cluster outranks every
  infeasible one — a row whose only feasible cluster scores below the
  K-th raw static score still places. Whenever a row's feasible count fits
  in K, its candidate window is a superset of its feasible set and the
  compact solve is bit-identical to dense (infeasible filler candidates
  are inert: zero weight, zero quota, bonus gated on weight > 0).
- **Ascending candidate order.** Candidate windows are sorted ascending by
  global cluster index, so every local-order tie-break (column iota in the
  dispenser, Aggregated truncation keep-order) sees the same relative
  order as the dense solve; splitmix64 tie VALUES are computed from global
  indices (`_tie_at`, ops/assign.py `col_ids`).
- **Exact-dense fallback.** Fleets where C <= shape_bucket(K) solve dense
  (compaction would be a reorder, not a reduction), as do rounds whose
  bindings carry the `karmada-tpu.io/dense-solve` annotation and spread
  rows whose feasible set outruns the window (full-fleet visibility) —
  each fallback is counted (`karmada_candidate_fallback_total{reason}`).
- **Truncation is observable.** Rows solved through the window with
  feasible count > K lose candidates — the dropped count feeds
  `karmada_candidate_truncations_total` (the decision-quality
  early-warning signal). Duplicated / non-workload rows never truncate:
  their target set is the feasible set, decoded from complete packed
  masks exactly as in the dense round.

K is resolved once per scheduler (`candidate_k` ctor arg, else
KARMADA_TPU_CANDIDATE_K, else 128; 0 disables) and bucketed per round on
the `shape_bucket` lattice (`effective_k`), so content-derived K drift —
e.g. the affinity-popcount shrink — never triggers fresh XLA compiles
(the PR-13 recompile class; pinned in tests/test_candidates.py).
"""
from __future__ import annotations

import logging
import os
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.batch import (
    AGGREGATED,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    NON_WORKLOAD,
    STATIC_WEIGHT,
    pow2_bucket,
    shape_bucket,
)
from ..ops import assign as assign_ops
from . import plugins as plugin_mod
from . import core as core_mod
from .core import (
    TOPK_TARGETS,
    ScheduleDecision,
    _gather_rows_kernel,
    _pad_extra_avail,
    _pad_rows_idx,
    _sorted_pairs,
    assignment_tail,
    compact_outputs,
    fetch_rows,
    filter_phase,
)
from .pipeline import stage_span

log = logging.getLogger(__name__)

# default candidate window: covers every row whose feasible set fits 128
# clusters exactly; wider feasible sets solve over their 128 best-scored
# feasible candidates (truncation-counted)
CANDIDATE_K_DEFAULT = 128

# per-policy opt-out: bindings carrying this annotation (value 1/true/yes/on)
# pin their whole round to the exact dense solve
DENSE_SOLVE_ANNOTATION = "karmada-tpu.io/dense-solve"

_TRUTHY = ("1", "true", "yes", "on")


def resolve_candidate_k(override: Optional[int] = None) -> int:
    """THE candidate-window size: explicit override, else
    KARMADA_TPU_CANDIDATE_K, else CANDIDATE_K_DEFAULT; 0 disables the
    compact path entirely. Malformed env fails loudly (same contract as
    resolve_max_bc_elems)."""
    if override is not None:
        val, src = int(override), "candidate_k override"
    else:
        env = os.environ.get("KARMADA_TPU_CANDIDATE_K", "")
        if not env:
            return CANDIDATE_K_DEFAULT
        try:
            val = int(env)
        except ValueError:
            raise ValueError(
                f"KARMADA_TPU_CANDIDATE_K={env!r}: must be an integer"
            ) from None
        src = f"KARMADA_TPU_CANDIDATE_K={env!r}"
    if val < 0:
        raise ValueError(f"{src}: must be >= 0 (0 disables)")
    return val


def compact_width_ok(array) -> bool:
    """Binding-free half of the gate (the AOT prewarm pass uses it): the
    compact path only pays off when the bucketed window is strictly
    narrower than the fleet."""
    k = getattr(array, "candidate_k", 0)
    return k > 0 and len(array.fleet.names) > shape_bucket(max(k, 8))


def dense_reason(array, bindings) -> Optional[str]:
    """Why this round must solve dense — None when the compact path
    engages. "disabled" is configuration, not a fallback (no counter)."""
    if getattr(array, "candidate_k", 0) <= 0:
        return "disabled"
    if not compact_width_ok(array):
        return "small_fleet"
    for rb in bindings:
        md = getattr(rb, "metadata", None)
        ann = getattr(md, "annotations", None)
        if ann and ann.get(DENSE_SOLVE_ANNOTATION, "").lower() in _TRUTHY:
            return "policy"
    return None


def note_fallback(reason: str, n: int = 1) -> None:
    if reason == "disabled":
        return  # configuration, not a fallback
    from ..metrics import candidate_fallback

    candidate_fallback.inc(n, reason=reason)


def effective_k(array, raw, n_cols: int) -> int:
    """Per-round effective window, ON THE shape_bucket LATTICE (K drift
    inside a bucket never compiles a fresh program — the PR-13 recompile
    class). With the ClusterAffinity plugin enabled, feasible ⊆ affinity
    mask, so the batch's max affinity popcount is a lossless shrink."""
    k = array.candidate_k
    if (array._plugin_bits & plugin_mod.BIT_AFFINITY) and raw.aff_masks.size:
        pc = raw.aff_masks.sum(axis=1)
        bound = int(pc[raw.aff_idx].max(initial=0))
        if 0 < bound < k:
            k = bound
    return min(shape_bucket(max(k, 8)), n_cols)


def _tie_at(seeds, cand_idx):
    """splitmix64 tie values AT the candidate positions — the same
    per-(binding, global cluster) stream as core.tie_from_index, evaluated
    elementwise over [B, K] instead of gathered from a [B, C] matrix."""
    x = seeds[:, None] ^ (cand_idx.astype(jnp.uint64) + jnp.uint64(1))
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x >> jnp.uint64(33)).astype(jnp.int32)


def _compact_estimate(
    capacity, has_summary, req_unique, req_idx, replicas, unknown_request,
    cand_idx, c_extra,
):
    """GeneralEstimator answers AT the candidate positions: the [U, C]
    unique-request solve stays dense (U is the distinct-policy count, tiny),
    rows double-gather [B, K]; the per-row clamps replicate
    general_estimate_apply in the same order — bit-exact with the dense
    form at every surviving position. c_extra is the registered-estimator
    override already gathered to [B, K] (None skips the min-merge — the
    speculative preemption pass models victim-freed capacity the
    registered estimators cannot see)."""
    est_u, any_u = assign_ops.general_estimate_unique(
        capacity, has_summary, req_unique
    )
    est = est_u[req_idx[:, None], cand_idx]  # i64[B,K]
    any_req = any_u[req_idx]
    replicas64 = replicas.astype(jnp.int64)[:, None]
    est = jnp.where(any_req[:, None], est, replicas64)
    est = jnp.where(has_summary[cand_idx], est, 0)
    est = jnp.where(
        est >= assign_ops.I32_MAX.astype(jnp.int64), replicas64, est
    )
    c_avail = est.astype(jnp.int32)
    c_avail = jnp.where(unknown_request[:, None], 0, c_avail)
    if c_extra is not None:
        c_avail = jnp.where(
            c_extra >= 0, jnp.minimum(c_avail, c_extra), c_avail
        )
    return c_avail


@partial(jax.jit, static_argnames=("k", "plugin_bits"))
def _candidate_select_kernel(
    # fleet (device-resident) — the signature tracks _filter_kernel_compact
    # so ArrayScheduler.filter_kernel_args builds the args for both
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    replicas, unknown_request, gvk,
    tol_tables, tol_idx,
    aff_masks, aff_idx, prev_idx, prev_rep, evict_idx, seeds,
    req_unique, req_idx,
    extra_avail,
    extra_mask, extra_score,
    k: int = CANDIDATE_K_DEFAULT,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
):
    """Phase 1 of the compact round: ONE elementwise [B, C] pass (filters +
    static score, no estimator, no sorts beyond top_k), then everything the
    later phases consume gathers to [B, K].

    Selection key `(feasible << 33) + score`: feasible columns ALWAYS
    outrank infeasible ones (score is i32, so the feasibility bit clears
    any score), making the window a superset of the feasible set whenever
    that set fits in K. Candidate indices are sorted ASCENDING per row —
    the local-order tie-breaks downstream then match the dense solve.

    Returns (cand_idx i32[B,K], c_feas, c_score, c_avail, c_prev, c_tie,
    feas_count i32[B], packed u8[B,ceil(C/8)]); feas_count is the EXACT
    dense count (FitError diagnosis and truncation accounting), packed is
    the complete feasible bitmask (duplicated / non-workload rows decode
    from it, windowless)."""
    from . import spread_batch

    B = replicas.shape[0]
    C = alive.shape[0]
    rows = jnp.arange(B)[:, None]
    tol = tol_tables[tol_idx]  # [B,4,K]
    affinity_ok = aff_masks[aff_idx]
    p = jnp.where((prev_idx >= 0) & (prev_idx < C), prev_idx, C)
    prev_member = jnp.zeros((B, C), bool).at[rows, p].set(True, mode="drop")
    prev_replicas = (
        jnp.zeros((B, C), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    e = jnp.where((evict_idx >= 0) & (evict_idx < C), evict_idx, C)
    eviction_ok = jnp.ones((B, C), bool).at[rows, e].set(False, mode="drop")
    feasible, score = filter_phase(
        alive, taint_key, taint_value, taint_effect, api_ok, gvk,
        tol[:, 0], tol[:, 1], tol[:, 2], tol[:, 3],
        affinity_ok, eviction_ok, prev_member,
        plugin_bits=plugin_bits,
        extra_mask=extra_mask, extra_score=extra_score,
    )
    key = (feasible.astype(jnp.int64) << 33) + score.astype(jnp.int64)
    _, ti = jax.lax.top_k(key, k)
    cand_idx = jnp.sort(ti, axis=-1).astype(jnp.int32)

    def take(a):
        return jnp.take_along_axis(a, cand_idx, axis=-1)

    extra = jnp.broadcast_to(extra_avail, (B, C))
    c_avail = _compact_estimate(
        capacity, has_summary, req_unique, req_idx, replicas,
        unknown_request, cand_idx, take(extra),
    )
    return (
        cand_idx, take(feasible), take(score), c_avail,
        take(prev_replicas), _tie_at(seeds, cand_idx),
        feasible.sum(-1).astype(jnp.int32),
        spread_batch._pack_bits(feasible),
    )


@partial(jax.jit, static_argnames=("topk", "narrow", "has_agg", "narrow16"))
def _candidate_tail_kernel(
    c_feas, c_avail, c_prev, c_tie, cand_idx,  # gathered [rows, K] windows
    weight_tables, weight_idx, strategy, replicas, fresh,
    topk: int, narrow: bool, has_agg: bool, narrow16: bool = False,
):
    """Division tail over compact candidate windows — _tail_kernel with the
    column axis narrowed from C to K. Static weights gather directly to
    [rows, K] (never materializing [rows, C]); the compact output window's
    indices map back to GLOBAL cluster ids through cand_idx, so decode is
    identical to the dense tail's."""
    static_weight = weight_tables[weight_idx[:, None], cand_idx]
    result, unschedulable, avail_sum = assignment_tail(
        c_feas, strategy, static_weight, c_avail, c_prev, c_tie,
        replicas, fresh, narrow=narrow, has_agg=has_agg,
    )
    K = c_feas.shape[1]
    _, nnz, l_idx, top_val = compact_outputs(c_feas, result, min(K, topk))
    top_idx = jnp.take_along_axis(cand_idx, l_idx, axis=-1)
    if narrow16:
        top_idx = top_idx.astype(jnp.int16)
        top_val = top_val.astype(jnp.int16)
    return result, unschedulable, avail_sum, nnz, top_idx, top_val


def _host_tail_compact(batch, rows_idx, nr, h_feas, h_avail, h_prev, h_cand,
                       topk: int):
    """The cpu-backend host-tail twin over compact windows: ops/assign.py
    host_tail with `col_ids` carrying the global candidate indices (tie
    parity), static weights fancy-gathered to [rows, K]. Returns the
    device-tail tuple shape with top_idx already GLOBAL."""
    rsub = np.asarray(rows_idx, np.int64)[:nr]
    h_feas = np.asarray(h_feas)[:nr]
    h_avail = np.asarray(h_avail)[:nr]
    h_prev = np.asarray(h_prev)[:nr]
    h_cand = np.asarray(h_cand)[:nr].astype(np.int64)
    wt = np.asarray(batch.weight_tables)
    widx = np.asarray(batch.weight_idx)[rsub]
    w_compact = wt[widx[:, None], h_cand]
    result, unsched, avail_sum, nnz, l_idx, top_val = assign_ops.host_tail(
        h_feas, h_avail, h_prev, np.asarray(batch.seeds)[rsub], w_compact,
        np.asarray(batch.strategy)[rsub], np.asarray(batch.replicas)[rsub],
        np.asarray(batch.fresh)[rsub],
        (STATIC_WEIGHT, DYNAMIC_WEIGHT, AGGREGATED),
        topk=topk, col_ids=h_cand,
    )
    top_idx = np.take_along_axis(
        h_cand, l_idx.astype(np.int64), axis=1
    ).astype(np.int32)
    return result, h_cand, (unsched, avail_sum, nnz, top_idx, top_val)


# --------------------------------------------------------------------------
# the compact round (launch / materialize pair — same seam as the dense
# partitioned round, so the pipeline and daemon drive it unchanged)
# --------------------------------------------------------------------------


def launch_candidates(array, bindings: Sequence, extra_avail=None,
                      term_indices=None) -> dict:
    """LAUNCH half of the compact round — the gather/scatter analogue of
    ArrayScheduler._launch_once_partitioned: classify + permute rows by
    class, encode, run the candidate prepass (ONE [B,C] elementwise
    launch), then dispatch every phase-2 consumer over [B, K] windows. No
    device sync here."""
    n_real = len(bindings)
    if n_real == 0:
        return {"candidates": True, "n_real": 0}
    names = array.fleet.names
    C = len(names)
    timer = array.stage_timer

    with stage_span("encode", timer):
        pre_b, _pre_cfg, pre_f = array._classify_spread(bindings)
        spread_set = set(pre_b) | set(pre_f)
        cls = np.asarray(
            [array._row_class(rb, b in spread_set)
             for b, rb in enumerate(bindings)],
            np.int8,
        )
        order = np.argsort(cls, kind="stable")
        bindings = [bindings[i] for i in order]
        cls = cls[order]
        if term_indices is not None:
            term_indices = [term_indices[i] for i in order]
        if extra_avail is not None:
            extra_avail = extra_avail[order]
        # re-derive spread rows in permuted space (placement-only, cheap)
        perm_b, _cfg, perm_f = array._classify_spread(bindings)
        spread_rows = sorted(set(perm_b) | set(perm_f))

        with array._encode_lock:
            raw = array.batch_encoder.encode(bindings, term_indices=term_indices)
        batch = array._pad(raw)
        if extra_avail is not None:
            extra_avail = _pad_extra_avail(extra_avail, C, len(batch.replicas))
        extra_mask, extra_score = array._plugin_terms(
            bindings, len(batch.replicas)
        )
        _, narrow, _ = array._batch_flags(batch)
        narrow16 = C < 2**15 and int(raw.replicas.max(initial=0)) < 2**15
        k = effective_k(array, raw, C)

    with stage_span("solve", timer):
        sel = _candidate_select_kernel(
            *array.filter_kernel_args(batch, extra_avail, extra_mask,
                                      extra_score),
            k=k, plugin_bits=array._plugin_bits,
        )
        (cand_idx, c_feas, c_score, c_avail, c_prev, c_tie, dev_fc,
         dev_packed) = sel

        from ..metrics import candidate_k as candidate_k_gauge

        candidate_k_gauge.set(float(k), bucket=str(k))

        # ---- phase 2: division tails per sub-class over [rows, K] ----
        tails = []
        for want_cls, has_agg in ((1, False), (2, True)):
            rows = [b for b in range(n_real) if cls[b] == want_cls]
            if not rows:
                continue
            idx_pad, nr = _pad_rows_idx(rows, array._bucket)
            rsel = idx_pad.astype(np.int64)
            t_feas = _gather_rows_kernel(c_feas, idx_pad)
            t_avail = _gather_rows_kernel(c_avail, idx_pad)
            t_prev = _gather_rows_kernel(c_prev, idx_pad)
            t_cand = _gather_rows_kernel(cand_idx, idx_pad)
            max_repl = int(raw.replicas[rows].max(initial=0))
            topk = min(
                pow2_bucket(min(max_repl, TOPK_TARGETS), lo=8), TOPK_TARGETS
            )
            # the host-twin gate keys on the DENSE volume the dense round
            # would have sorted — compact and dense rounds then route the
            # same sub-batches to the same twin, keeping the parity
            # surfaces aligned (the twin itself runs over [rows, K])
            if array._host_sorts and (
                len(rows) * C >= core_mod.HOST_TAIL_MIN_ELEMS
                or array._overlap_active
            ):
                tails.append({
                    "kind": "host", "rows": rows, "idx_pad": idx_pad,
                    "nr": nr, "t_feas": t_feas, "t_avail": t_avail,
                    "t_prev": t_prev, "t_cand": t_cand, "topk": topk,
                })
            else:
                t_tie = _gather_rows_kernel(c_tie, idx_pad)
                t_out = _candidate_tail_kernel(
                    t_feas, t_avail, t_prev, t_tie, t_cand,
                    batch.weight_tables, batch.weight_idx[rsel],
                    batch.strategy[rsel], batch.replicas[rsel],
                    batch.fresh[rsel],
                    topk=topk, narrow=narrow, has_agg=has_agg,
                    narrow16=narrow16,
                )
                tails.append({
                    "kind": "dev", "rows": rows, "t_out": t_out,
                    "t_cand": t_cand,
                })

        # ---- phase 2: duplicated / non-workload packed feasible masks ----
        spread_perm = set(spread_rows)
        mask_rows = [
            b for b in range(n_real)
            if cls[b] == 0 and b not in spread_perm
        ]
        mask_pack = None
        nm = 0
        if mask_rows:
            mask_idx, nm = _pad_rows_idx(mask_rows, array._bucket)
            mask_pack = _gather_rows_kernel(dev_packed, mask_idx)

        # ---- phase 2: spread rows' candidate windows (selection runs on
        # host at materialize over these compact gathers) ----
        spread_fetch = None
        ns = 0
        if spread_rows:
            s_idx, ns = _pad_rows_idx(spread_rows, array._bucket)
            spread_fetch = tuple(
                _gather_rows_kernel(a, s_idx)
                for a in (cand_idx, c_feas, c_score, c_avail, c_prev, c_tie)
            )

    return {
        "candidates": True, "bindings": bindings, "raw": raw, "batch": batch,
        "cls": cls, "order": order, "n_real": n_real,
        "extra_avail": extra_avail, "term_indices": term_indices,
        "narrow": narrow, "narrow16": narrow16, "k": k,
        "dev_fc": dev_fc, "tails": tails,
        "mask_rows": mask_rows, "mask_pack": mask_pack, "nm": nm,
        "spread_rows": spread_rows, "spread_fetch": spread_fetch, "ns": ns,
    }


def materialize_candidates(array, p: dict) -> list[ScheduleDecision]:
    """MATERIALIZE half: ONE device→host sync, deferred host-sort twins,
    candidate-set spread selection, decode, unpermute."""
    if p["n_real"] == 0:
        return []
    with stage_span("materialize", array.stage_timer):
        return _materialize_inner(array, p)


def _materialize_inner(array, p: dict) -> list[ScheduleDecision]:
    bindings, raw, batch = p["bindings"], p["raw"], p["batch"]
    cls, order, n_real = p["cls"], p["order"], p["n_real"]
    k, narrow = p["k"], p["narrow"]
    tails, mask_rows, spread_rows = p["tails"], p["mask_rows"], p["spread_rows"]
    names = array.fleet.names
    C = len(names)

    unsched = np.zeros(n_real, bool)
    avail_sum = np.zeros(n_real, np.int64)
    feas_count_ovr: dict[int, int] = {}
    row_err: dict[int, str] = {}
    row_target_src: dict[int, tuple] = {}
    row_feas_src: dict[int, tuple] = {}
    wide_dec: dict[int, ScheduleDecision] = {}

    # ---- THE sync ----
    host = jax.device_get((
        p["dev_fc"],
        [t["t_out"][1:] for t in tails if t["kind"] == "dev"],
        p["mask_pack"],
        p["spread_fetch"],
        [(t["t_feas"], t["t_avail"], t["t_prev"], t["t_cand"])
         for t in tails if t["kind"] == "host"],
    ))
    feas_count = np.asarray(host[0])[:n_real].astype(np.int64)

    # truncation accounting: only rows solved THROUGH the window can drop
    # feasible candidates (divided rows; spread rows wider than the window
    # fall back dense instead and are fallback-counted)
    div_rows = cls > 0
    trunc = int(np.maximum(feas_count[div_rows] - k, 0).sum()) if (
        div_rows.any()
    ) else 0
    if trunc:
        from ..metrics import candidate_truncations

        candidate_truncations.inc(trunc)
    array.last_candidate_stats = {
        "candidate_k": k, "candidate_truncations": trunc,
    }

    # ---- division tails (device outputs + deferred host twins) ----
    dev_vals = iter(host[1])
    host_inputs = iter(host[4])
    decoded_tails = []  # (rows, result_src, cand_src, vals)
    for t in tails:
        if t["kind"] == "dev":
            decoded_tails.append(
                (t["rows"], t["t_out"][0], t["t_cand"], next(dev_vals))
            )
            continue
        h_feas, h_avail, h_prev, h_cand = next(host_inputs)
        result, h_cand64, vals = _host_tail_compact(
            batch, t["idx_pad"], t["nr"], h_feas, h_avail, h_prev, h_cand,
            t["topk"],
        )
        decoded_tails.append((t["rows"], result, h_cand64, vals))

    for rows, res_src, cand_src, vals in decoded_tails:
        t_unsched, t_asum, t_nnz, t_ti, t_tv = vals  # t_ti is GLOBAL
        tis, tvs = _sorted_pairs(np.asarray(t_ti), np.asarray(t_tv))
        overflow = []
        for j, b in enumerate(rows):
            unsched[b] = bool(t_unsched[j])
            avail_sum[b] = int(t_asum[j])
            n = int(t_nnz[j])
            if n > t_ti.shape[1]:
                overflow.append((j, b))
                continue
            row_target_src[b] = ("pairs", names, tis[j, :n], tvs[j, :n])
        if overflow:
            ks = [j for j, _ in overflow]
            if isinstance(res_src, np.ndarray):  # host twin: no fetch
                o_res, o_cand = res_src[ks], np.asarray(cand_src)[ks]
            else:
                o_res = fetch_rows(res_src, ks, array._bucket)
                o_cand = fetch_rows(cand_src, ks, array._bucket)
            for m, (_, b) in enumerate(overflow):
                pos = np.nonzero(o_res[m] > 0)[0]
                row_target_src[b] = (
                    "pairs", names, o_cand[m, pos].astype(np.int64),
                    o_res[m, pos].astype(np.int64),
                )

    # ---- duplicated / non-workload rows: complete packed masks ----
    if mask_rows:
        packed_h = np.asarray(host[2])[: p["nm"]]
        for j, b in enumerate(mask_rows):
            if feas_count[b] <= 0:
                continue  # FitError branch
            strat = int(raw.strategy[b])
            reps = (
                0 if strat == NON_WORKLOAD
                else int(bindings[b].spec.replicas)
            )
            row_feas_src[b] = ("mask", names, packed_h[j], C)
            row_target_src[b] = ("mask", names, packed_h[j], C, reps)

    # ---- spread rows: exact per-row selection over the candidate set ----
    if spread_rows:
        self_dec = _spread_over_candidates(
            array, p, bindings, raw, batch, spread_rows, host[3], feas_count,
            unsched, avail_sum, feas_count_ovr, row_err, row_target_src,
            row_feas_src,
        )
        wide_dec.update(self_dec)

    # ---- build decisions, then unpermute ----
    dec_p: list[ScheduleDecision] = []
    for b, key in enumerate(raw.keys):
        if b in wide_dec:
            dec_p.append(wide_dec[b])
            continue
        dec = ScheduleDecision(key=key)
        fc = feas_count_ovr.get(b, int(feas_count[b]))
        if b in row_feas_src:
            dec._feasible_src = row_feas_src[b]
        if b in row_err:
            dec.error = row_err[b]
        elif fc == 0:
            dec.error = f"0/{array.n_real_clusters} clusters are available"
        elif unsched[b]:
            dec.error = (
                f"Clusters available replicas {int(avail_sum[b])} are not "
                "enough to schedule."
            )
        elif b in row_target_src:
            dec._targets_src = row_target_src[b]
        else:
            raise AssertionError(
                "compact schedule round produced no decode source for live "
                f"row {key!r} (class {int(cls[b])}, strategy "
                f"{int(raw.strategy[b])})"
            )
        dec_p.append(dec)
    out: list[Optional[ScheduleDecision]] = [None] * n_real
    for j, dec in enumerate(dec_p):
        out[int(order[j])] = dec
    return out


def _spread_over_candidates(
    array, p, bindings, raw, batch, spread_rows, fetch, feas_count,
    unsched, avail_sum, feas_count_ovr, row_err, row_target_src,
    row_feas_src,
) -> dict[int, ScheduleDecision]:
    """Spread constraints evaluated over CANDIDATE sets: whenever a row's
    feasible set fits the window, the window holds every feasible cluster
    and the per-row exact selection (sched/spread.py, the semantic spec)
    runs on the compact arrays — same inputs the dense fallback would pass,
    gathered instead of fetched dense. Rows whose feasible set outruns the
    window need full-fleet visibility: they re-solve through the dense
    partitioned round (LOUD — log.warning + fallback counter) and their
    finished decisions merge in by position."""
    from . import spread as spread_mod

    names = array.fleet.names
    C = len(names)
    k = p["k"]
    ns = p["ns"]
    s_cand, s_feas, s_score, s_avail, s_prev, s_tie = (
        np.asarray(a)[:ns] for a in fetch
    )
    wide: list[int] = []
    live_div: list[tuple[int, int, np.ndarray]] = []  # (fetch row, round row, sel)
    for j, b in enumerate(spread_rows):
        if feas_count[b] == 0:
            continue  # FitError branch
        if feas_count[b] > k:
            wide.append(b)
            continue
        f = np.flatnonzero(s_feas[j])
        gidx = s_cand[j, f].astype(np.int64)
        rb = bindings[b]
        try:
            selected_idx = spread_mod.select_by_spread_arrays(
                gidx,
                s_score[j, f],
                s_avail[j, f].astype(np.int64) + s_prev[j, f],
                array._name_rank[gidx],
                array._region_id[gidx],
                array._region_names,
                rb.spec.placement,
                rb.spec.replicas,
            )
        except spread_mod.SpreadError as e:
            row_err[b] = str(e)
            continue
        sel_sorted = np.sort(np.asarray(selected_idx, np.int64))
        # the dense fallback re-runs the kernel with the selection folded
        # into the feasibility mask, so its feasible set IS the selection —
        # mirror that exactly
        row_feas_src[b] = ("idx", names, sel_sorted)
        feas_count_ovr[b] = len(sel_sorted)
        strat = int(raw.strategy[b])
        if strat == NON_WORKLOAD:
            row_target_src[b] = (
                "pairs", names, sel_sorted,
                np.zeros(len(sel_sorted), np.int64),
            )
        elif strat == DUPLICATED:
            row_target_src[b] = (
                "pairs", names, sel_sorted,
                np.full(len(sel_sorted), int(rb.spec.replicas), np.int64),
            )
        else:
            live_div.append((j, b, np.isin(s_cand[j], sel_sorted)))

    if live_div:
        d_rows = [b for _, b, _ in live_div]
        jks = [j for j, _, _ in live_div]
        idx_pad, _nd = _pad_rows_idx(jks, array._bucket)
        # pad by repeating the first live row (same contract as
        # _pad_rows_idx): build the selection-restricted feasibility for
        # the padded fetch-row subset
        sel_rows = {j: sel for j, _, sel in live_div}
        sel_stack = np.stack([sel_rows.get(int(j), live_div[0][2])
                              for j in idx_pad])
        d_feas = s_feas[idx_pad] & sel_stack
        rows_pad, _ = _pad_rows_idx(d_rows, array._bucket)
        rsel = rows_pad.astype(np.int64)
        max_repl = int(raw.replicas[d_rows].max(initial=0))
        topk = min(
            pow2_bucket(min(max_repl, TOPK_TARGETS), lo=8), TOPK_TARGETS
        )
        has_agg = bool((raw.strategy[d_rows] == AGGREGATED).any())
        t_out = _candidate_tail_kernel(
            d_feas, s_avail[idx_pad], s_prev[idx_pad], s_tie[idx_pad],
            s_cand[idx_pad],
            batch.weight_tables, batch.weight_idx[rsel],
            batch.strategy[rsel], batch.replicas[rsel], batch.fresh[rsel],
            topk=topk, narrow=narrow_of(p), has_agg=has_agg, narrow16=False,
        )
        d_res, d_unsched, d_asum, d_nnz, d_ti, d_tv = (
            np.asarray(a) for a in jax.device_get(t_out)
        )
        tis, tvs = _sorted_pairs(d_ti, d_tv)
        d_cand = s_cand[idx_pad]
        for m, (j, b, sel) in enumerate(live_div):
            unsched[b] = bool(d_unsched[m])
            avail_sum[b] = int(d_asum[m])
            feas_count_ovr[b] = int(d_feas[m].sum())
            n = int(d_nnz[m])
            if n > d_ti.shape[1]:
                pos = np.nonzero(d_res[m] > 0)[0]
                row_target_src[b] = (
                    "pairs", names, d_cand[m, pos].astype(np.int64),
                    d_res[m, pos].astype(np.int64),
                )
            else:
                row_target_src[b] = ("pairs", names, tis[m, :n], tvs[m, :n])

    out: dict[int, ScheduleDecision] = {}
    if wide:
        log.warning(
            "candidate window k=%d too narrow for %d spread row(s) "
            "(feasible set needs full-fleet visibility) — re-solving them "
            "through the exact dense round", k, len(wide),
        )
        note_fallback("spread_constraint", len(wide))
        extra_avail = p["extra_avail"]
        term_indices = p["term_indices"]
        sub_extra = (
            None if extra_avail is None else np.asarray(extra_avail)[wide]
        )
        sub_terms = (
            None if term_indices is None else [term_indices[b] for b in wide]
        )
        sub_dec = array._schedule_once_partitioned(
            [bindings[b] for b in wide], sub_extra, sub_terms
        )
        for b, dec in zip(wide, sub_dec):
            out[b] = dec
    return out


def narrow_of(p: dict) -> bool:
    return bool(p["narrow"])


# --------------------------------------------------------------------------
# the compact tiered kernel (sched/preemption.py routes here)
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_tiers", "k", "topk", "has_agg",
                                   "plugin_bits", "speculate"))
def _tiered_candidate_kernel(
    # fleet (capacity may be a victim-augmented override)
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    tier_of,
    replicas, unknown_request, gvk, strategy, fresh,
    tol_tables, tol_idx, aff_masks, aff_idx, weight_tables, weight_idx,
    prev_idx, prev_rep, evict_idx, seeds, req_unique, req_idx,
    extra_avail, request_dense, reclaim,
    n_tiers: int = 1,
    k: int = CANDIDATE_K_DEFAULT,
    topk: int = TOPK_TARGETS,
    has_agg: bool = True,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
    speculate: bool = False,
):
    """preemption._tiered_kernel over compact candidate windows. Candidates
    select ONCE (feasibility and static score are capacity-independent, so
    they are tier-invariant); each tier re-runs only the estimator — the
    [U, C] unique solve over the residual capacity, double-gathered through
    the SAME candidate index (reclaimed capacity flows the same way on the
    speculative pass) — and the [B, K] division tail. Tier consumption
    scatter-adds compact placements back to the dense [C, R] capacity
    matrix through cand_idx, so the residual each later tier sees is
    bit-identical to the dense kernel's whenever every row's feasible set
    fits the window. Duplicated rows are routed dense by the caller (their
    target set must never truncate)."""
    B = replicas.shape[0]
    C = alive.shape[0]
    rows = jnp.arange(B)[:, None]
    tol = tol_tables[tol_idx]
    affinity_ok = aff_masks[aff_idx]
    p = jnp.where((prev_idx >= 0) & (prev_idx < C), prev_idx, C)
    prev_member = jnp.zeros((B, C), bool).at[rows, p].set(True, mode="drop")
    prev_replicas = (
        jnp.zeros((B, C), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    e = jnp.where((evict_idx >= 0) & (evict_idx < C), evict_idx, C)
    eviction_ok = jnp.ones((B, C), bool).at[rows, e].set(False, mode="drop")
    feasible, score = filter_phase(
        alive, taint_key, taint_value, taint_effect, api_ok, gvk,
        tol[:, 0], tol[:, 1], tol[:, 2], tol[:, 3],
        affinity_ok, eviction_ok, prev_member,
        plugin_bits=plugin_bits,
    )
    key = (feasible.astype(jnp.int64) << 33) + score.astype(jnp.int64)
    _, ti = jax.lax.top_k(key, k)
    cand_idx = jnp.sort(ti, axis=-1).astype(jnp.int32)

    def take(a):
        return jnp.take_along_axis(a, cand_idx, axis=-1)

    c_feas = take(feasible)
    c_prev = take(prev_replicas)
    c_tie = _tie_at(seeds, cand_idx)
    c_weight = weight_tables[weight_idx[:, None], cand_idx]
    c_extra = take(jnp.broadcast_to(extra_avail, (B, C)))

    def body(cap_t, use_extra: bool):
        c_avail = _compact_estimate(
            cap_t, has_summary, req_unique, req_idx, replicas,
            unknown_request, cand_idx, c_extra if use_extra else None,
        )
        return assignment_tail(
            c_feas, strategy, c_weight, c_avail, c_prev, c_tie,
            replicas, fresh, narrow=False, has_agg=has_agg,
        )

    cap = capacity
    out_result = out_unsched = out_asum = None
    aug_result = aug_unsched = aug_asum = None
    for t in range(n_tiers):
        res_t, unsch_t, asum_t = body(cap, True)
        m = tier_of == t
        placed = jnp.where((m & ~unsch_t)[:, None], res_t, 0)
        if out_result is None:
            out_result = placed
            out_unsched = m & unsch_t
            out_asum = jnp.where(m, asum_t, 0)
        else:
            out_result = jnp.where(m[:, None], res_t, out_result)
            out_unsched = jnp.where(m, unsch_t, out_unsched)
            out_asum = jnp.where(m, asum_t, out_asum)
        if speculate:
            ares_t, aunsch_t, aasum_t = body(cap + reclaim[t], False)
            if aug_result is None:
                aug_result = jnp.where(m[:, None], ares_t, 0)
                aug_unsched = m & aunsch_t
                aug_asum = jnp.where(m, aasum_t, 0)
            else:
                aug_result = jnp.where(m[:, None], ares_t, aug_result)
                aug_unsched = jnp.where(m, aunsch_t, aug_unsched)
                aug_asum = jnp.where(m, aasum_t, aug_asum)
        if t + 1 < n_tiers:
            cons = jnp.zeros((C, request_dense.shape[1]), jnp.int64).at[
                cand_idx
            ].add(
                placed.astype(jnp.int64)[:, :, None]
                * request_dense[:, None, :]
            )
            cap = jnp.maximum(cap - cons, 0)
    feas_count = feasible.sum(-1).astype(jnp.int32)
    window = min(k, topk)
    _, nnz, l_idx, top_val = compact_outputs(c_feas, out_result, window)
    top_idx = jnp.take_along_axis(cand_idx, l_idx, axis=-1)
    out = (out_unsched, out_asum, feas_count, nnz, top_idx, top_val,
           out_result)
    if speculate:
        _, a_nnz, a_l, a_val = compact_outputs(c_feas, aug_result, window)
        a_idx = jnp.take_along_axis(cand_idx, a_l, axis=-1)
        out += (aug_unsched, aug_asum, a_nnz, a_idx, a_val, aug_result)
    return out + (cand_idx,)


def tiered_k(array, raw, n_cols: int) -> int:
    """Effective window for a tiered/speculative batch, or 0 for dense:
    the width gate plus a duplicated-row exclusion — a duplicated row's
    target set IS its feasible set, which a window would truncate
    silently (the main round decodes those rows from complete packed
    masks; the tiered kernel has no such side channel)."""
    if not compact_width_ok(array):
        return 0
    if bool((np.asarray(raw.strategy) == DUPLICATED).any()):
        return 0
    return effective_k(array, raw, n_cols)

"""The batched scheduling core: one jitted [B,C] program per round.

TPU reframing of pkg/scheduler/core/generic_scheduler.go:70-115
(Schedule = snapshot → findClustersThatFit → prioritizeClusters →
SelectClusters → AssignReplicas): the per-binding sequential loop becomes a
single fused device program over all dirty bindings. The fleet snapshot is the
persistent device encoding (models/fleet.py) instead of an O(N) deep copy per
attempt (cache/cache.go:62-77).

Spread-constraint selection is layered on in sched/spread.py; without spread
constraints SelectClusters returns every feasible cluster (common.go:32-39
with empty constraints).
"""
from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api.work import TargetCluster
from ..models.batch import (
    AGGREGATED,
    pow2_bucket,
    shape_bucket,
    shape_floor,
    BatchEncoder,
    BindingBatch,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    NON_WORKLOAD,
    STATIC_WEIGHT,
)
from ..models.fleet import FleetArrays, FleetEncoder
from ..ops import assign as assign_ops
from ..ops import filters as filter_ops
from . import plugins as plugin_mod
from .pipeline import (
    ChunkPipeline,
    StageTimer,
    chunk_spans,
    plan_chunk_rows,
    resolve_pipeline,
    stage_span,
)

# below this [tail rows x C] volume the numpy host tail loses to the jit
# kernel (per-row Python overhead); tests pin it to 0 to force the host path
HOST_TAIL_MIN_ELEMS = 2_000_000

# compact-output width: covers every row whose target count is <= this
# (divided rows are bounded by spec.replicas; wider duplicated rows fetch
# their dense result row as a fallback)
TOPK_TARGETS = 128

# pipelined-round chunking policy (sched/pipeline.py): a daemon round is cut
# into ~PIPELINE_CHUNKS chunks so the estimate/encode/solve/materialize/patch
# stages overlap across them, but never below PIPELINE_MIN_ROWS rows per
# chunk — tiny launches pay more in dispatch than overlap buys back
PIPELINE_MIN_ROWS = 256
PIPELINE_CHUNKS = 8


class ScheduleDecision:
    """Outcome for one binding.

    Target/feasible lists materialize LAZILY from array-backed sources (SoA
    decode): a region-HA selection can span hundreds of clusters per row, and
    building those TargetCluster objects eagerly for 5k rows costs seconds of
    host time before anything consumes them. Consumers see plain lists via
    the `targets`/`feasible` properties; assigning a list works too."""

    __slots__ = ("key", "error", "affinity_name", "score", "speculative",
                 "_targets", "_targets_src", "_feasible", "_feasible_src")

    def __init__(self, key: str, targets=None, error: str = "",
                 feasible=None, score=None, affinity_name: str = ""):
        self.key = key
        self.error = error  # non-empty ⇒ unschedulable / fit error
        self.affinity_name = affinity_name  # applied ordered-affinity term
        self.score = score
        # speculative victim-augmented decision (sched/preemption.py): the
        # same launch solved this row a second time over reclaimable
        # capacity; a short placement's preemption plan reads it instead
        # of paying a second launch
        self.speculative: "Optional[ScheduleDecision]" = None
        self._targets = targets
        self._targets_src = None
        self._feasible = feasible
        self._feasible_src = None

    @property
    def ok(self) -> bool:
        return not self.error

    @property
    def targets(self) -> Optional[list[TargetCluster]]:
        if self._targets is None and self._targets_src is not None:
            src = self._targets_src
            if src[0] == "pairs":  # pre-sorted (cluster idx, replicas) arrays
                _, names, idxs, reps = src
                self._targets = [
                    TargetCluster(name=names[int(i)], replicas=int(r))
                    for i, r in zip(idxs, reps)
                ]
            else:  # ("mask", names, packed_bits, n_cols, replicas_per_cluster)
                from . import spread_batch

                _, names, packed, n_cols, reps = src
                self._targets = [
                    TargetCluster(name=names[int(i)], replicas=int(reps))
                    for i in spread_batch.unpack_row(packed, n_cols)
                ]
        return self._targets

    @targets.setter
    def targets(self, v) -> None:
        self._targets = v
        self._targets_src = None

    @property
    def feasible(self) -> list[str]:
        if self._feasible is None and self._feasible_src is not None:
            src = self._feasible_src
            if src[0] == "mask":
                from . import spread_batch

                _, names, packed, n_cols = src
                self._feasible = [
                    names[int(i)] for i in spread_batch.unpack_row(packed, n_cols)
                ]
            else:  # ("idx", names, idx_array)
                _, names, idxs = src
                self._feasible = [names[int(i)] for i in idxs]
        return self._feasible if self._feasible is not None else []

    @feasible.setter
    def feasible(self, v) -> None:
        self._feasible = v
        self._feasible_src = None


def filter_phase(
    alive, taint_key, taint_value, taint_effect, api_ok, gvk,
    tol_key, tol_value, tol_effect, tol_op,
    affinity_ok, eviction_ok, prev_member,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
    extra_mask=None, extra_score=None,
):
    """Filter masks + static score WITHOUT the estimator — the
    capacity-independent half of filter_estimate_phase. The candidate
    prepass (sched/candidates.py) runs exactly this over [B, C] and then
    computes the estimator answers compactly over [B, K], so the two
    callers can never drift on feasibility/score semantics."""
    ones = jnp.ones_like(affinity_ok)
    taint_mask = (
        filter_ops.taint_toleration_mask(
            taint_key, taint_value, taint_effect,
            tol_key, tol_value, tol_effect, tol_op,
        )
        if plugin_bits & plugin_mod.BIT_TAINT
        else ones
    )
    api_mask = (
        filter_ops.api_enablement_mask(api_ok, gvk)
        if plugin_bits & plugin_mod.BIT_API
        else ones
    )
    feasible = filter_ops.feasible_mask(
        alive, api_mask, taint_mask, ones,
        affinity_ok if plugin_bits & plugin_mod.BIT_AFFINITY else ones,
        eviction_ok if plugin_bits & plugin_mod.BIT_EVICTION else ones,
    )
    if extra_mask is not None:
        feasible = feasible & jnp.broadcast_to(extra_mask, feasible.shape)
    score = (
        filter_ops.locality_score(prev_member)
        if plugin_bits & plugin_mod.BIT_LOCALITY
        else jnp.zeros(feasible.shape, jnp.int32)
    )
    if extra_score is not None:
        score = score + jnp.broadcast_to(extra_score, score.shape)
    return feasible, score


def filter_estimate_phase(
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    replicas, request, unknown_request, gvk,
    tol_key, tol_value, tol_effect, tol_op,
    affinity_ok, eviction_ok, prev_member,
    req_unique=None, req_idx=None,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
    extra_mask=None, extra_score=None,
):
    """Filters + score + GeneralEstimator — elementwise over (B, C), so the
    mesh path runs it on local (B_l, C_l) tiles before any collective.

    plugin_bits statically selects which fused in-tree plugin terms compile
    in (`--plugins` disable, sched/plugins.py); extra_mask/extra_score are
    the out-of-tree plugins' host-computed contributions.

    Requests naming resources outside the encoded vocabulary behave like a
    missing allocatable key: 0 available everywhere (general.go:166-169)."""
    feasible, score = filter_phase(
        alive, taint_key, taint_value, taint_effect, api_ok, gvk,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, prev_member,
        plugin_bits=plugin_bits,
        extra_mask=extra_mask, extra_score=extra_score,
    )
    if req_unique is not None:
        # requests dedup to the policy set: the [.,C,R] divisions run per
        # DISTINCT vector; rows gather (bit-exact with the dense form)
        est_u, any_u = assign_ops.general_estimate_unique(
            capacity, has_summary, req_unique
        )
        avail = assign_ops.general_estimate_apply(
            est_u, any_u, req_idx, has_summary, replicas
        )
    else:
        avail = assign_ops.general_estimate(capacity, has_summary, request, replicas)
    avail = jnp.where(unknown_request[:, None], 0, avail)
    return feasible, score, avail


def assignment_tail(
    feasible, strategy, static_weight, avail, prev_replicas, tie, replicas,
    fresh, narrow: bool = False, has_agg: bool = True,
):
    """Strategy dispatch + division over FULL fleet rows (the phase that needs
    every cluster column: per-row sort/cumsum, binding.go:112-144). Static +
    dynamic rows share one dispenser pass (row-disjoint — combined_assign
    halves the [B,C] sort work). narrow/has_agg are host-derived static
    specializations (see ArrayScheduler._batch_flags)."""
    dup = assign_ops.duplicated_assign(feasible, replicas)
    is_static = strategy == STATIC_WEIGHT
    is_dyn = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)
    sd = assign_ops.combined_assign(
        feasible, is_static, is_dyn, strategy == AGGREGATED,
        static_weight, avail, prev_replicas, tie, replicas, fresh,
        narrow=narrow, has_agg=has_agg,
    )
    result = jnp.zeros_like(dup)
    result = jnp.where((strategy == DUPLICATED)[:, None], dup, result)
    result = jnp.where((is_static | is_dyn)[:, None], sd.result, result)
    unschedulable = is_dyn & sd.unschedulable
    return result, unschedulable, sd.available_sum


def compact_outputs(feasible, result, topk: int):
    """Top-K sparsification of the decision tensor: the per-binding target
    list is almost always far smaller than C, so the round's device→host
    transfer drops from O(B·C) to O(B·K); rows whose nonzero count exceeds K
    fall back to a dense row fetch on host."""
    top_val, top_idx = jax.lax.top_k(result, topk)
    nnz = (result > 0).sum(-1).astype(jnp.int32)
    feas_count = feasible.sum(-1).astype(jnp.int32)
    return feas_count, nnz, top_idx.astype(jnp.int32), top_val


def _schedule_body(
    # fleet
    alive,
    capacity,
    has_summary,
    taint_key,
    taint_value,
    taint_effect,
    api_ok,
    # batch (dense)
    replicas,
    request,
    unknown_request,
    gvk,
    strategy,
    fresh,
    tol_key,
    tol_value,
    tol_effect,
    tol_op,
    affinity_ok,
    eviction_ok,
    static_weight,
    prev_member,
    prev_replicas,
    tie,
    extra_avail,  # i32[B,C] min-merged registered-estimator answers; -1 = none
    narrow: bool = False,
    has_agg: bool = True,
    req_unique=None,
    req_idx=None,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
    extra_mask=None,
    extra_score=None,
):
    feasible, score, avail = filter_estimate_phase(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, request, unknown_request, gvk,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, prev_member,
        req_unique=req_unique, req_idx=req_idx,
        plugin_bits=plugin_bits,
        extra_mask=extra_mask, extra_score=extra_score,
    )
    # min-merge with registered estimators (-1 sentinel discarded,
    # core/util.go:72-92); gRPC/node-level answers tighten the general bound
    avail = jnp.where(extra_avail >= 0, jnp.minimum(avail, extra_avail), avail)
    result, unschedulable, avail_sum = assignment_tail(
        feasible, strategy, static_weight, avail, prev_replicas, tie, replicas,
        fresh, narrow=narrow, has_agg=has_agg,
    )
    return feasible, score, result, unschedulable, avail_sum, avail


@partial(jax.jit, static_argnames=())
def _schedule_kernel(
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    replicas, request, unknown_request, gvk, strategy, fresh,
    tol_key, tol_value, tol_effect, tol_op,
    affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
    extra_avail,
):
    """Dense-input variant (mesh path / graft entry)."""
    return _schedule_body(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, request, unknown_request, gvk, strategy, fresh,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
        extra_avail,
    )


def tie_from_index(seeds, idx):
    """splitmix64 tie values from explicit per-column 1-based GLOBAL cluster
    indices (u64[C]) — the generalization of _device_tie that lets a caller
    with a REMAPPED column space (the simulation plane's drain scenarios,
    where a drained cluster vanishes from the index range) reproduce exactly
    the tie matrix a fleet without that cluster would have."""
    x = seeds[:, None] ^ idx[None, :]
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x >> jnp.uint64(33)).astype(jnp.int32)


def _device_tie(seeds, n_clusters, offset=0):
    """splitmix64 tie-break expanded on device — bit-identical to
    models.batch.tie_matrix (the deterministic stand-in for the reference's
    crypto-rand tie-break, binding.go:74-79). `offset` shifts the cluster
    index range for column-sharded callers (parallel/mesh.py) so every shard
    reproduces its slice of the global tie matrix."""
    idx = (
        jnp.asarray(offset).astype(jnp.uint64)
        + jnp.arange(1, n_clusters + 1, dtype=jnp.uint64)
    )
    return tie_from_index(seeds, idx)


def decompress_batch(
    aff_masks, aff_idx, weight_tables, weight_idx,
    prev_idx, prev_rep, evict_idx, seeds,
    n_cols: int, col_offset=0,
):
    """Reconstruct the [B, n_cols] tile of the factored batch ON DEVICE
    (gathers + scatters over local HBM — host→device stays O(B·K + P·C)).

    `col_offset` is the global index of this tile's first cluster column:
    0 on the single-chip path; the shard's offset under the mesh (sparse
    prev/eviction entries carry GLOBAL column ids and the tie matrix is
    defined over global indices, so every shard reproduces exactly its slice
    of the dense tensors)."""
    B = aff_idx.shape[0]
    rows = jnp.arange(B)[:, None]
    affinity_ok = aff_masks[aff_idx]
    static_weight = weight_tables[weight_idx]
    # translate global → local column ids; everything out of this tile's
    # range (including the encoder's drop sentinel) lands on n_cols → dropped
    p = prev_idx - col_offset
    p = jnp.where((p >= 0) & (p < n_cols), p, n_cols)
    prev_member = jnp.zeros((B, n_cols), bool).at[rows, p].set(True, mode="drop")
    prev_replicas = (
        jnp.zeros((B, n_cols), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    e = evict_idx - col_offset
    e = jnp.where((e >= 0) & (e < n_cols), e, n_cols)
    eviction_ok = jnp.ones((B, n_cols), bool).at[rows, e].set(False, mode="drop")
    tie = _device_tie(seeds, n_cols, offset=col_offset)
    return affinity_ok, static_weight, prev_member, prev_replicas, eviction_ok, tie


@partial(jax.jit, static_argnames=("topk", "narrow", "has_agg", "plugin_bits"))
def _schedule_kernel_compact(
    # fleet (device-resident)
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    # batch core (tolerations ride the factored [T,4,K] table + per-row idx;
    # dense per-resource requests ride req_unique/req_idx — upload stays
    # O(tables + B), never O(B·K))
    replicas, unknown_request, gvk, strategy, fresh,
    tol_tables, tol_idx,
    # factored [B,C] reconstruction inputs (models/batch.py BindingBatch)
    aff_masks, aff_idx, weight_tables, weight_idx,
    prev_idx, prev_rep, evict_idx, seeds,
    req_unique, req_idx,  # deduped request vectors (policy-level)
    extra_avail,  # i32[B,C] or broadcastable [1,1] sentinel
    extra_mask=None, extra_score=None,  # out-of-tree plugin terms
    topk: int = TOPK_TARGETS,
    narrow: bool = False,
    has_agg: bool = True,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
):
    """Decompress the factored batch on device, then run the solve.

    topk/narrow/has_agg are host-derived static specializations (bounded jit
    cache: 5 top-K buckets x 2 x 2): the compact window shrinks to the
    batch's real target bound, the division sorts use i32 keys when every
    weight provably fits, and the Aggregated truncation sort is compiled out
    when no row needs it."""
    B = replicas.shape[0]
    C = alive.shape[0]
    affinity_ok, static_weight, prev_member, prev_replicas, eviction_ok, tie = (
        decompress_batch(
            aff_masks, aff_idx, weight_tables, weight_idx,
            prev_idx, prev_rep, evict_idx, seeds, C,
        )
    )
    tol = tol_tables[tol_idx]  # [B,4,K] on-device gather
    extra = jnp.broadcast_to(extra_avail, (B, C))
    feasible, score, result, unschedulable, avail_sum, avail = _schedule_body(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, None, unknown_request, gvk, strategy, fresh,
        tol[:, 0], tol[:, 1], tol[:, 2], tol[:, 3],
        affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
        extra, narrow=narrow, has_agg=has_agg,
        req_unique=req_unique, req_idx=req_idx,
        plugin_bits=plugin_bits,
        extra_mask=extra_mask, extra_score=extra_score,
    )
    feas_count, nnz, top_idx, top_val = compact_outputs(
        feasible, result, min(C, topk)
    )
    return (
        feasible, score, result, unschedulable, avail_sum, avail,
        feas_count, nnz, top_idx, top_val,
    )


@partial(jax.jit, static_argnames=("plugin_bits",))
def _filter_kernel_compact(
    # fleet (device-resident)
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    # batch core
    replicas, unknown_request, gvk,
    tol_tables, tol_idx,
    # factored reconstruction inputs (static weights skipped: the division
    # tail decompresses them itself for its row subset)
    aff_masks, aff_idx, prev_idx, prev_rep, evict_idx, seeds,
    req_unique, req_idx,
    extra_avail,
    extra_mask, extra_score,  # out-of-tree plugin terms ([1,1] sentinels)
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
):
    """Filter + estimate ONLY — phase 1 of the partitioned schedule round.
    The division tail runs separately on just the rows that need it
    (_tail_kernel): duplicated/non-workload/spread rows never pay the [B,C]
    dispenser sorts. Returns device-resident (feasible, score, avail,
    prev_replicas, tie, feas_count)."""
    B = replicas.shape[0]
    C = alive.shape[0]
    rows = jnp.arange(B)[:, None]
    tol = tol_tables[tol_idx]  # [B,4,K]
    tol_key, tol_value, tol_effect, tol_op = (
        tol[:, 0], tol[:, 1], tol[:, 2], tol[:, 3],
    )
    affinity_ok = aff_masks[aff_idx]
    p = jnp.where((prev_idx >= 0) & (prev_idx < C), prev_idx, C)
    prev_member = jnp.zeros((B, C), bool).at[rows, p].set(True, mode="drop")
    prev_replicas = (
        jnp.zeros((B, C), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    e = jnp.where((evict_idx >= 0) & (evict_idx < C), evict_idx, C)
    eviction_ok = jnp.ones((B, C), bool).at[rows, e].set(False, mode="drop")
    tie = _device_tie(seeds, C)
    feasible, score, avail = filter_estimate_phase(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect,
        api_ok, replicas, None, unknown_request, gvk,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, prev_member,
        req_unique=req_unique, req_idx=req_idx,
        plugin_bits=plugin_bits,
        extra_mask=extra_mask, extra_score=extra_score,
    )
    extra = jnp.broadcast_to(extra_avail, (B, C))
    avail = jnp.where(extra >= 0, jnp.minimum(avail, extra), avail)
    return feasible, score, avail, prev_replicas, tie, feasible.sum(-1).astype(jnp.int32)


@partial(jax.jit, static_argnames=("topk", "narrow", "has_agg", "narrow16"))
def _tail_kernel(
    feasible, avail, prev_replicas, tie,  # gathered rows of the filter phase
    weight_tables, weight_idx, strategy, replicas, fresh,
    topk: int, narrow: bool, has_agg: bool, narrow16: bool = False,
):
    """Division tail over a row SUBSET (phase 2): the [B,C] dispenser sorts
    run only on rows whose strategy divides replicas; the agg-only
    truncation sort compiles in solely for the Aggregated sub-batch
    (has_agg) — at the flagship mix this halves the sort volume vs the
    monolithic kernel.

    narrow16: emit the compact (idx, val) window as i16 — sound when the
    host proves C < 2**15 and max replicas < 2**15; the tunnel link runs at
    ~40 MB/s, so halving the dominant transfer is wall-clock, not polish."""
    static_weight = weight_tables[weight_idx]
    result, unschedulable, avail_sum = assignment_tail(
        feasible, strategy, static_weight, avail, prev_replicas, tie,
        replicas, fresh, narrow=narrow, has_agg=has_agg,
    )
    C = feasible.shape[1]
    _, nnz, top_idx, top_val = compact_outputs(feasible, result, min(C, topk))
    if narrow16:
        top_idx = top_idx.astype(jnp.int16)
        top_val = top_val.astype(jnp.int16)
    return result, unschedulable, avail_sum, nnz, top_idx, top_val


@jax.jit
def _pack_rows_kernel(feasible):
    """Bit-packed feasible masks for duplicated / non-workload rows — their
    target list IS the feasible set, complete in C/8 bytes per row."""
    from . import spread_batch

    return spread_batch._pack_bits(feasible)


@partial(jax.jit, static_argnames=("k", "narrow16"))
def _feas_idx_kernel(feasible, k: int, narrow16: bool = False):
    """Ascending indices of the (at most k) feasible columns per row — the
    complete target/feasible set for duplicated rows whose affinity popcount
    proves ≤ k candidates (host bound; feasible ⊆ affinity mask). 2k bytes
    per row instead of the packed mask's C/8 — at 10k×5k that is the
    difference between ~80 KB and ~1.6 MB on a ~40 MB/s link."""
    B, C = feasible.shape
    iota = jax.lax.broadcasted_iota(jnp.int32, (B, C), 1)
    key = jnp.where(feasible, iota, jnp.int32(2**30))
    neg, _ = jax.lax.top_k(-key, k)
    idx = -neg
    if narrow16:
        idx = idx.astype(jnp.int16)  # rows slice [:feas_count] before use
    return idx


@jax.jit
def _gather_rows_kernel(a, idx):
    return a[idx]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_rows_kernel(dst, idx, src):
    """In-place (donated) row update of a resident device tensor — the
    dirty-column fleet refresh writes only the changed clusters' rows
    instead of re-uploading the whole fleet encoding. Duplicate indices in
    `idx` write identical rows (callers pad with repeats), so the scatter
    is idempotent."""
    return dst.at[idx].set(src)


def _sorted_pairs(top_idx, top_val):
    """Order each row's compact (cluster idx, replicas) window by cluster
    index, parking the zero-replica padding at the end — shared by every
    decode site so the sentinel logic can never drift."""
    order = np.argsort(
        np.where(top_val > 0, top_idx, np.int32(1 << 30)), axis=1, kind="stable"
    )
    return (
        np.take_along_axis(top_idx, order, 1),
        np.take_along_axis(top_val, order, 1),
    )


def _pad_rows_idx(rows: Sequence[int], bucket_fn) -> tuple[np.ndarray, int]:
    """Pad a row-index list to a jit-cache-friendly bucket (pads repeat the
    first row; callers slice the result back to len(rows))."""
    n = len(rows)
    b = bucket_fn(n)
    idx = np.empty(b, np.int32)
    idx[:n] = rows
    idx[n:] = rows[0] if n else 0
    return idx, n


def _pad_extra_avail(extra_avail, n_cols: int, n_rows: int):
    """Pad caller-provided estimator answers to the kernel shape: columns to
    the (possibly mesh-padded) fleet width, rows to the padded batch — both
    with the -1 no-answer sentinel."""
    if extra_avail.shape[1] < n_cols:
        extra_avail = np.pad(
            extra_avail, [(0, 0), (0, n_cols - extra_avail.shape[1])],
            constant_values=-1,
        )
    if len(extra_avail) < n_rows:
        extra_avail = np.pad(
            extra_avail, [(0, n_rows - len(extra_avail)), (0, 0)],
            constant_values=-1,
        )
    return extra_avail


def fetch_rows(dev_array, rows: Sequence[int], bucket_fn) -> np.ndarray:
    """Fetch a row subset of a device tensor: device-side gather + compact
    transfer, never the full [B,C] fetch (200 MB at the flagship shape)."""
    idx, n = _pad_rows_idx(rows, bucket_fn)
    out = _gather_rows_kernel(dev_array, idx)
    return np.asarray(jax.device_get(out))[:n]


@partial(jax.jit, static_argnames=("n_cols",))
def _row_context_kernel(prev_idx, prev_rep, seeds, n_cols: int):
    """(prev_replicas, tie) dense rows for a row subset — the spread kernels
    need them and the full schedule kernel keeps them internal."""
    B = prev_idx.shape[0]
    rows = jnp.arange(B)[:, None]
    p = jnp.where((prev_idx >= 0) & (prev_idx < n_cols), prev_idx, n_cols)
    prev_replicas = (
        jnp.zeros((B, n_cols), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    tie = _device_tie(seeds, n_cols)
    return prev_replicas, tie


def _restrict_rows(batch: BindingBatch, rows: list[int], affinity_override: np.ndarray) -> BindingBatch:
    """Row-subset of a batch with the spread-selection mask folded into the
    affinity mask (phase-2 candidate restriction). The override masks are
    per-row, so the sub-batch carries them as its own (un-deduped) table."""
    idx = np.asarray(rows)

    def take(a):
        return a[idx]

    return BindingBatch(
        keys=[batch.keys[b] for b in rows],
        uids=[batch.uids[b] for b in rows],
        replicas=take(batch.replicas),
        unknown_request=take(batch.unknown_request),
        gvk=take(batch.gvk),
        strategy=take(batch.strategy),
        fresh=take(batch.fresh),
        tol_tables=batch.tol_tables,
        tol_idx=take(batch.tol_idx),
        aff_masks=affinity_override[idx],
        aff_idx=np.arange(len(rows), dtype=np.int32),
        weight_tables=batch.weight_tables,
        weight_idx=take(batch.weight_idx),
        prev_idx=take(batch.prev_idx),
        prev_rep=take(batch.prev_rep),
        evict_idx=take(batch.evict_idx),
        seeds=take(batch.seeds),
        n_clusters=batch.n_clusters,
        req_unique=batch.req_unique,
        req_idx=None if batch.req_idx is None else take(batch.req_idx),
    )


def resolve_max_bc_elems(override: Optional[int] = None) -> int:
    """THE [B,C]-elements-per-launch budget (HBM envelope): explicit
    override, else KARMADA_TPU_MAX_BC_ELEMS, else 2<<27. Shared by
    ArrayScheduler and the simulation plane so a malformed env var fails
    loudly and identically everywhere."""
    import os

    if override is not None:
        val, src = int(override), "max_bc_elems override"
    else:
        env = os.environ.get("KARMADA_TPU_MAX_BC_ELEMS", "")
        if not env:
            return 2 << 27
        try:
            val = int(env)
        except ValueError:
            raise ValueError(
                f"KARMADA_TPU_MAX_BC_ELEMS={env!r}: must be an integer"
            ) from None
        src = f"KARMADA_TPU_MAX_BC_ELEMS={env!r}"
    if val <= 0:
        raise ValueError(f"{src}: must be positive")
    return val


def resolve_autoshard(override: Optional[bool] = None) -> bool:
    import os

    if override is not None:
        return bool(override)
    return os.environ.get("KARMADA_TPU_AUTOSHARD", "") not in (
        "0", "off", "false",
    )


def pad_batch(batch: BindingBatch, bucket_fn) -> BindingBatch:
    """Pad a batch's row axis to bucket_fn(B) (jit-cache bucketing). Module
    level so non-ArrayScheduler launchers (simulation/engine.py) share the
    exact padding contract — padded rows are strategy 0 / replicas 0 and are
    never decoded."""
    B = batch.size
    Bp = bucket_fn(B)
    if Bp == B:
        return batch
    pad = Bp - B

    def pz(a, fill=0):
        width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, width, constant_values=fill)

    return BindingBatch(
        keys=batch.keys,
        uids=batch.uids,
        replicas=pz(batch.replicas),
        unknown_request=pz(batch.unknown_request),
        gvk=pz(batch.gvk),
        strategy=pz(batch.strategy),
        fresh=pz(batch.fresh),
        tol_tables=batch.tol_tables,
        tol_idx=pz(batch.tol_idx),
        aff_masks=batch.aff_masks,
        aff_idx=pz(batch.aff_idx),  # padded rows → mask row 0 (harmless:
        #   strategy 0/replicas 0 rows are never decoded)
        weight_tables=batch.weight_tables,
        weight_idx=pz(batch.weight_idx),
        prev_idx=pz(batch.prev_idx, fill=batch.n_clusters),
        prev_rep=pz(batch.prev_rep),
        evict_idx=pz(batch.evict_idx, fill=batch.n_clusters),
        seeds=pz(batch.seeds),
        n_clusters=batch.n_clusters,
        req_unique=batch.req_unique,
        req_idx=None if batch.req_idx is None else pz(batch.req_idx),
    )


class ArrayScheduler:
    """Host wrapper: encodes fleet + batches, runs the kernel, decodes
    TargetClusters. Batch sizes are padded to power-of-two buckets to bound
    the jit cache (SURVEY §7 dynamic-shapes note)."""

    def __init__(
        self,
        clusters: Sequence,
        encoder: Optional[FleetEncoder] = None,
        mesh=None,
        plugins: Optional[Sequence[str]] = None,
        plugin_registry=None,
        autoshard: Optional[bool] = None,
        pipeline: Optional[bool] = None,
        bucket_cols: bool = True,
        candidate_k: Optional[int] = None,
    ):
        """`mesh`: optional jax.sharding.Mesh — the solve runs column/row-
        sharded over it (parallel/mesh.py) with identical outputs.
        `plugins`: the `--plugins` enable/disable list (default ["*"]);
        `plugin_registry`: out-of-tree plugins (sched/plugins.py).
        `autoshard`: when no mesh was given and a round's [B,C] footprint
        exceeds the single-chip HBM budget, transparently re-place the fleet
        over a device mesh and run sharded (decision-identical); default on,
        KARMADA_TPU_AUTOSHARD=0 disables.
        `pipeline`: chunked rounds run as the software pipeline
        (sched/pipeline.py — encode/solve/materialize overlapped across
        chunks, bit-identical decisions); default on,
        KARMADA_TPU_PIPELINE=0 disables (the serial row-chunk executor).
        `bucket_cols`: pad the fleet axis C to the shape_bucket lattice
        with dead pad clusters (never Ready ⇒ never feasible ⇒ never
        decoded) so fleet growth inside a bucket re-uses compiled programs
        instead of triggering fresh XLA compiles; decisions are
        bit-identical to the exact-width solve (tests/test_bucketing.py).
        False restores exact fleet width (the parity-suite reference).
        `candidate_k`: top-K candidate sparsification window
        (sched/candidates.py) — rounds on fleets wider than the bucketed
        window solve compact [B, K]; None reads KARMADA_TPU_CANDIDATE_K
        (default 128), 0 pins every round to the exact dense solve."""
        from .compilecache import install_compile_listeners

        install_compile_listeners()
        self.encoder = encoder or FleetEncoder()
        self.bucket_cols = bucket_cols
        self.mesh = mesh
        self._mesh_kernel = None
        self.plugin_registry = plugin_registry or plugin_mod.PluginRegistry()
        self.enabled_plugins = self.plugin_registry.filter(plugins)
        self._plugin_bits = plugin_mod.plugin_bits(self.enabled_plugins)
        self._oot_plugins = self.plugin_registry.out_of_tree(self.enabled_plugins)
        # mesh rounds default to the partitioned single-sync shape: the
        # SAME kernels run with the fleet tensors mesh-sharded and XLA's
        # GSPMD partitioner inserts the collectives (the scaling-book
        # recipe: annotate shardings, let XLA partition). The explicit
        # shard_map kernel remains as the monolithic mode.
        self.mesh_partitioned = True
        # CPU backend, unsharded: route the division-tail sorts through the
        # host selection path (XLA:CPU's comparator-loop sort costs ~40 s at
        # the flagship shape; see ops/assign.py module header). Never under
        # a mesh — shards see partial rows. KARMADA_TPU_HOST_SORTS=0/1
        # overrides.
        import os

        env = os.environ.get("KARMADA_TPU_HOST_SORTS", "")
        if env in ("0", "off", "false"):
            self._host_sorts = False
        elif env in ("1", "on", "true"):
            self._host_sorts = mesh is None
        else:
            self._host_sorts = (
                mesh is None and jax.default_backend() == "cpu"
            )
        # HBM budget for one round's [B,C] working set: phase 1 keeps ~6 live
        # i32/bool [B,C] buffers, so cap B·C per launched round and split
        # oversized batches into equal row chunks (rows are independent —
        # placement-identical by construction). 2^28 elements ≈ 1 GiB per
        # i32 buffer ≈ 6 GiB live on a 16 GiB v5e-1; a sharded mesh divides
        # the per-device footprint, so the cap scales with mesh size.
        self.max_bc_elems = resolve_max_bc_elems()
        self.autoshard = resolve_autoshard(autoshard)
        # pipelined round executor (sched/pipeline.py): chunked rounds
        # overlap encode/solve/materialize across chunks; the stage timer is
        # installed by the driving pipeline for the duration of a round and
        # last_pipeline_stats carries the stage/overlap numbers of the last
        # chunked round (None when the round ran un-chunked)
        self.pipeline_enabled = resolve_pipeline(pipeline)
        self.stage_timer: Optional[StageTimer] = None
        self.last_pipeline_stats: Optional[dict] = None
        # True while a pipelined (overlapping) round drives launch/
        # materialize on separate threads — the cpu-backend tail routing
        # reads it (host twins run on the writer thread, overlapped, so
        # they win at ANY volume there; XLA:CPU division sorts would
        # serialize the whole pipe)
        self._overlap_active = False
        # the batch encoder interns tables and keeps row caches — under the
        # pipeline the writer thread's affinity-retry sub-rounds encode
        # concurrently with the main thread's next-chunk encode, so every
        # encode takes this lock (retries are rare; contention is noise)
        import threading

        self._encode_lock = threading.Lock()
        # cross-round incremental state: any fleet change bumps the epoch
        # (cached decisions are only replayed at the epoch they were solved
        # in); the cache maps binding uid → DecisionEntry
        self.fleet_epoch = 0
        self._decision_cache: dict[str, object] = {}
        self.last_round_stats = {"replayed": 0, "solved": 0}
        # compile delta of the last schedule() round (compile economics):
        # jit_compiles / jit_compile_seconds / jit_persistent_cache_hits
        self.last_compile_stats: dict = {}
        # top-K candidate sparsification (sched/candidates.py): window size
        # resolved once; last_candidate_stats carries the last compact
        # round's effective K and truncation count
        from .candidates import resolve_candidate_k

        self.candidate_k = resolve_candidate_k(candidate_k)
        self.last_candidate_stats: dict = {}
        self.set_clusters(clusters)

    @contextmanager
    def pipeline_context(self, timer: StageTimer, overlap: bool):
        """Install the driving pipeline's stage timer (and the overlap flag
        the tail routing reads) for the duration of one round; restores the
        previous state on exit. The daemon and `_schedule_chunked` both run
        their ChunkPipeline inside this."""
        prev_t, prev_o = self.stage_timer, self._overlap_active
        self.stage_timer = timer
        self._overlap_active = overlap
        try:
            yield
        finally:
            self.stage_timer, self._overlap_active = prev_t, prev_o

    def set_clusters(self, clusters: Sequence,
                     dirty_names: Optional[set] = None) -> None:
        """Re-encode the fleet. With `dirty_names` (the clusters the caller
        knows changed since the last call), the dirty-column fast path
        re-encodes ONLY those clusters and scatters their rows into the
        resident device tensors (buffer donation) — keeping the batch
        encoder's affinity masks and per-binding row cache alive — whenever
        the change is expressible that way; otherwise this falls back to the
        full rebuild. Either way the fleet epoch advances, so incremental
        rounds re-solve every binding against the new snapshot."""
        clusters = list(clusters)
        self.fleet_epoch += 1
        if dirty_names and self._update_dirty_columns(clusters, dirty_names):
            return
        self.n_real_clusters = len(clusters)
        pad = self._fleet_width(len(clusters)) - len(clusters)
        if pad > 0:
            # pad the fleet to the bucketed (and, under a mesh, mesh-
            # divisible) width with DEAD clusters (never Ready ⇒ never
            # feasible ⇒ never decoded): every derived table — batch policy
            # tables, region layout, device tensors — sizes consistently,
            # sharded device_put stays legal, and fleet growth INSIDE a
            # bucket re-uses every compiled program (the compile-economics
            # tentpole, docs/PERF.md; parity pinned by tests/test_bucketing)
            from ..api.cluster import Cluster, ClusterSpec
            from ..api.meta import ObjectMeta

            clusters += [
                Cluster(metadata=ObjectMeta(name=f"__shape-pad-{i}"),
                        spec=ClusterSpec())
                for i in range(pad)
            ]
        self.clusters = clusters
        self.fleet: FleetArrays = self.encoder.encode(self.clusters)
        self.batch_encoder = BatchEncoder(self.encoder, self.fleet, self.clusters)
        # spread-selection fast-path encodings (sched/spread.py array API):
        # cluster-name ascending ranks (sortClusters tie-break) and region ids
        C = len(self.clusters)
        self._name_rank = np.empty(C, np.int32)
        self._name_rank[np.argsort(np.array(self.fleet.names))] = np.arange(C)
        region_ids: dict[str, int] = {}
        self._region_id = np.full(C, -1, np.int32)
        for i, c in enumerate(self.clusters):
            region = c.spec.region
            if region:
                rid = region_ids.setdefault(region, len(region_ids))
                self._region_id[i] = rid
        self._region_names = list(region_ids)
        from . import spread_batch

        self._spread_layout = spread_batch.RegionLayout(
            self._region_id, self._region_names, self._name_rank
        )
        # per-resource capacity ceiling for the narrow-keys bound (host-side
        # proof that every division weight fits i32 — see _batch_flags)
        cap = np.asarray(self.fleet.capacity, np.int64)
        self._max_cap_per_res = (
            cap.max(axis=0) if cap.size else np.zeros(cap.shape[1], np.int64)
        )
        # fleet tensors live on device across rounds (the persistent snapshot
        # that replaces the reference's per-attempt deep copy, cache.go:62-77);
        # re-transferred only on cluster-set change
        f = self.fleet
        if self.mesh is not None:
            self._place_fleet_sharded()
            return
        self._fleet_dev = tuple(
            jax.device_put(x)
            for x in (
                f.alive, f.capacity, f.has_summary,
                f.taint_key, f.taint_value, f.taint_effect, f.api_ok,
            )
        )

    def _fleet_width(self, n_real: int) -> int:
        """Padded fleet width for n_real clusters: the shape_bucket lattice
        point (so cluster add/remove inside a bucket keeps every program
        shape), rounded up to mesh divisibility when a mesh is placed. An
        empty fleet stays empty — there is nothing to schedule against and
        padding it would only fake a nonzero C."""
        if n_real == 0:
            return 0
        width = shape_bucket(n_real) if self.bucket_cols else n_real
        if self.mesh is not None:
            from ..parallel.mesh import AXIS_CLUSTERS

            mesh_c = self.mesh.shape[AXIS_CLUSTERS]
            width += (-width) % mesh_c
        return width

    def _place_fleet_sharded(self) -> None:
        """Place the (cluster-padded) fleet COLUMN-SHARDED over the mesh;
        the partitioned round runs the single-chip kernels on it and GSPMD
        partitions every kernel (no manual padding: XLA handles uneven
        shards). Also refreshes the monolithic kernel's copy when that mode
        is in use."""
        from ..parallel.mesh import AXIS_CLUSTERS
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self._mesh_kernel is not None:
            self._mesh_kernel.set_fleet(self.fleet)

        def put(x, spec):
            return jax.device_put(x, NamedSharding(self.mesh, spec))

        f = self.fleet
        self._fleet_dev = (
            put(f.alive, P(AXIS_CLUSTERS)),
            put(f.capacity, P(AXIS_CLUSTERS, None)),
            put(f.has_summary, P(AXIS_CLUSTERS)),
            put(f.taint_key, P(AXIS_CLUSTERS, None)),
            put(f.taint_value, P(AXIS_CLUSTERS, None)),
            put(f.taint_effect, P(AXIS_CLUSTERS, None)),
            put(f.api_ok, P(AXIS_CLUSTERS, None)),
        )

    def _update_dirty_columns(self, clusters: list, dirty_names) -> bool:
        """Dirty-column fleet refresh. Applies only when the membership is
        unchanged and no dirty cluster changed a mask-relevant field
        (labels / provider / region / zone): affinity masks, the spread
        layout and the weight tables are then provably still valid, so the
        batch encoder — and its per-binding row cache — survives the fleet
        update. Status-driven changes (capacity, readiness, taints, api
        enablements over known GVKs) all take this path. Returns False when
        the delta cannot be expressed in the resident layout.

        Under a mesh (user-provided or autoshard-engaged) the host side of
        the fast path is identical — encode_cols over the pad-preserving
        cluster list, batch encoder kept alive — and only the device
        placement differs: the refreshed tensors re-place sharded instead of
        row-scattering into donated buffers."""
        # self.clusters carries dead shape-pad clusters at the tail (bucketed
        # fleet width); the caller's list never does, so compare against the
        # real prefix
        old = self.clusters[: self.n_real_clusters]
        if len(clusters) != len(old):
            return False
        idx: list[int] = []
        for i, (cn, co) in enumerate(zip(clusters, old)):
            if cn.name != co.name:
                return False  # membership / order changed
            if cn.name in dirty_names:
                if (
                    cn.metadata.labels != co.metadata.labels
                    or cn.spec.provider != co.spec.provider
                    or cn.spec.region != co.spec.region
                    or cn.spec.zone != co.spec.zone
                ):
                    return False  # affinity/spread inputs changed
                idx.append(i)
        if not idx:
            return True  # spurious dirt: nothing to re-encode
        # keep the shape/mesh pad clusters (never dirty: they are synthetic)
        clusters = clusters + self.clusters[len(clusters):]
        fleet = self.encoder.encode_cols(self.fleet, clusters, idx)
        if fleet is None:
            return False  # taint axis would widen / unknown GVK appeared
        self.clusters = clusters
        self.fleet = fleet
        self.batch_encoder.fleet = fleet
        self.batch_encoder.clusters = clusters
        self.batch_encoder.affinity_cache.clusters = clusters
        cap = np.asarray(fleet.capacity, np.int64)
        self._max_cap_per_res = (
            cap.max(axis=0) if cap.size else np.zeros(cap.shape[1], np.int64)
        )
        if self.mesh is not None:
            # sharded tensors re-place whole (still no host re-encode, no
            # encoder rebuild — the expensive parts this path avoids)
            self._place_fleet_sharded()
            return True
        # scatter the dirty rows into the resident device tensors in place
        # (donated buffers — no second fleet copy, no full re-upload); the
        # index list pads to a pow2 bucket with repeats of the first entry
        # so the jit cache stays bounded
        idx_pad, _ = _pad_rows_idx(idx, partial(pow2_bucket, lo=1))
        f = fleet
        srcs = (
            f.alive, f.capacity, f.has_summary,
            f.taint_key, f.taint_value, f.taint_effect, f.api_ok,
        )
        self._fleet_dev = tuple(
            _scatter_rows_kernel(dst, idx_pad, src[idx_pad])
            for dst, src in zip(self._fleet_dev, srcs)
        )
        return True

    def _max_rows_per_round(self, n_cols: int) -> int:
        """Row cap per launched round under the [B,C] HBM budget, floored to
        a _bucket boundary so every full chunk hits one compiled shape. On a
        mesh the budget scales by the BINDINGS-axis size only: division-tail
        buffers are all-gathered to full rows, so a clusters-axis split does
        not shrink their per-device footprint."""
        if self.mesh is not None:
            from ..parallel.mesh import AXIS_BINDINGS

            scale = dict(self.mesh.shape).get(AXIS_BINDINGS, 1)
        else:
            scale = 1
        budget = self.max_bc_elems * scale
        return self._floor_rows(max(8, budget // max(n_cols, 1)))

    @staticmethod
    def _floor_rows(cap: int) -> int:
        """Floor a row cap to a _bucket lattice boundary so every full
        chunk hits one compiled shape."""
        return shape_floor(max(cap, 8))

    def pipeline_chunk_rows(self, n_cols: int) -> int:
        """Per-chunk row cap when the pipeline drives a chunked round: HALF
        the serial per-launch cap, so depth-2 double buffering (one chunk
        solving while the next uploads) keeps the device working set inside
        the serial executor's HBM envelope."""
        return self._floor_rows(max(8, self._max_rows_per_round(n_cols) // 2))

    def round_chunk_rows(self, n_rows: int) -> int:
        """Chunking policy for a daemon-driven pipelined round (the whole
        dirty set, replay included): aim for ~PIPELINE_CHUNKS chunks so the
        estimate/encode/solve/materialize/patch stages have work to overlap,
        floor at PIPELINE_MIN_ROWS, and never exceed the double-buffered HBM
        chunk cap. Returns one chunk (⇒ the pipeline runs serial) for
        rounds too small to fill the pipe — and for out-of-tree-plugin
        rounds (stateful host hooks must not run on two threads). ALWAYS
        bounded by the serial per-launch HBM row cap: a daemon round must
        never dispatch a launch the chunked schedule() path would have
        split."""
        max_rows = self._max_rows_per_round(len(self.fleet.names))
        if not self.pipeline_enabled or self._oot_plugins:
            return min(max(1, n_rows), max_rows)
        if n_rows <= 2 * PIPELINE_MIN_ROWS and n_rows <= max_rows:
            return max(1, n_rows)
        cap = self.pipeline_chunk_rows(len(self.fleet.names))
        target = self._floor_rows(
            max(PIPELINE_MIN_ROWS, n_rows // PIPELINE_CHUNKS)
        )
        return max(8, min(cap, target))

    # THE row-axis bucketing rule: the pow2/1.5× lattice (then 1024-steps
    # past 4096) bounds the jit cache while capping pad waste — the solve is
    # O(B·C), so pad rows are wall-clock waste — and keeps the reachable
    # shape set small enough for the AOT prewarm pass to enumerate
    # (sched/aot.py). Shared with the column axis via _fleet_width.
    _bucket = staticmethod(shape_bucket)

    def _pad(self, batch: BindingBatch) -> BindingBatch:
        return pad_batch(batch, self._bucket)

    _NO_EXTRA = np.full((1, 1), -1, np.int32)  # broadcast sentinel
    _NO_MASK = np.ones((1, 1), bool)
    _NO_SCORE = np.zeros((1, 1), np.int32)

    def _plugin_terms(self, bindings, padded_B: int):
        """Out-of-tree plugins' host-computed [B,C] mask/score terms
        (scheduler.go:241-244 out-of-tree registry merge); broadcastable
        sentinels when none are registered. Plugins see only the REAL
        cluster names — mesh pad columns stay all-feasible / zero-score,
        and padding rows are never decoded."""
        if not self._oot_plugins:
            return self._NO_MASK, self._NO_SCORE
        C = len(self.fleet.names)
        Cr = self.n_real_clusters
        names = self.fleet.names[:Cr]
        n = len(bindings)
        mask = np.ones((padded_B, C), bool)
        score = np.zeros((padded_B, C), np.int32)
        for p in self._oot_plugins:
            if hasattr(p, "mask"):
                mask[:n, :Cr] &= np.asarray(p.mask(bindings, names), bool)
            if hasattr(p, "score"):
                score[:n, :Cr] += np.asarray(p.score(bindings, names), np.int32)
        return mask, score

    def _batch_flags(self, batch: BindingBatch) -> tuple[int, bool, bool]:
        """Host-derived static kernel specializations (cheap numpy passes
        over the factored batch — never over [B,C]):

        - topk: the compact-output window, bucketed to the batch's provable
          per-row target bound (divided rows emit <= spec.replicas targets;
          duplicated rows <= their affinity-mask popcount). Smaller window =
          less top_k work and fewer device->host bytes per round.
        - narrow: True when every division weight provably fits i32, so the
          [B,C] sort keys narrow from i64 (GeneralEstimator answers are
          bounded by max capacity // min positive request per resource;
          static weights by their table max).
        - has_agg: False compiles the Aggregated truncation sort out."""
        max_prev = int(batch.prev_rep.max(initial=0))
        max_repl = int(batch.replicas.max(initial=0))
        req = np.asarray(batch.request, np.int64)
        pos = req > 0
        bound_est = 0
        if pos.any():
            min_req = np.where(pos, req, np.iinfo(np.int64).max).min(axis=0)
            used = pos.any(axis=0)
            per_res = np.where(
                used, self._max_cap_per_res // np.maximum(min_req, 1), 0
            )
            bound_est = int(per_res.max(initial=0))
        max_static = int(batch.weight_tables.max(initial=0))
        i32max = 2**31 - 1
        narrow = (
            max(bound_est, max_repl) + max_prev < i32max and max_static < i32max
        )
        has_agg = bool((batch.strategy == AGGREGATED).any())
        cand = max_repl
        dup = batch.strategy == DUPLICATED
        if dup.any():
            pc = batch.aff_masks.sum(axis=1)
            cand = max(cand, int(pc[batch.aff_idx[dup]].max(initial=0)))
        topk = pow2_bucket(min(cand, TOPK_TARGETS), lo=8)
        return min(topk, TOPK_TARGETS), narrow, has_agg

    def filter_kernel_args(
        self, batch: BindingBatch, extra_avail=None,
        extra_mask=None, extra_score=None,
    ) -> tuple:
        """Positional args of `_filter_kernel_compact` for one padded batch
        — the SINGLE builder shared by the round launch and the AOT prewarm
        pass (sched/aot.py), so prewarmed program shapes can never drift
        from what live rounds dispatch."""
        return (
            *self._fleet_dev,
            batch.replicas, batch.unknown_request, batch.gvk,
            batch.tol_tables, batch.tol_idx,
            batch.aff_masks, batch.aff_idx,
            batch.prev_idx, batch.prev_rep, batch.evict_idx, batch.seeds,
            batch.req_unique, batch.req_idx,
            self._NO_EXTRA if extra_avail is None else extra_avail,
            self._NO_MASK if extra_mask is None else extra_mask,
            self._NO_SCORE if extra_score is None else extra_score,
        )

    def run_kernel(
        self, batch: BindingBatch, extra_avail=None,
        extra_mask=None, extra_score=None,
    ):
        if self.mesh is not None and not self.mesh_partitioned:
            if self._mesh_kernel is None:  # built lazily: the default
                # partitioned mode never needs the second fleet copy
                from ..parallel.mesh import MeshScheduleKernel

                self._mesh_kernel = MeshScheduleKernel(self.mesh)
                self._mesh_kernel.set_fleet(self.fleet)
            return self._mesh_kernel(
                batch, extra_avail,
                extra_mask=extra_mask, extra_score=extra_score,
                plugin_bits=self._plugin_bits,
            )
        if extra_avail is None:
            extra_avail = self._NO_EXTRA
        if extra_mask is None:
            extra_mask = self._NO_MASK
        if extra_score is None:
            extra_score = self._NO_SCORE
        topk, narrow, has_agg = self._batch_flags(batch)
        return _schedule_kernel_compact(
            *self._fleet_dev,
            batch.replicas,
            batch.unknown_request,
            batch.gvk,
            batch.strategy,
            batch.fresh,
            batch.tol_tables,
            batch.tol_idx,
            batch.aff_masks,
            batch.aff_idx,
            batch.weight_tables,
            batch.weight_idx,
            batch.prev_idx,
            batch.prev_rep,
            batch.evict_idx,
            batch.seeds,
            batch.req_unique,
            batch.req_idx,
            extra_avail,
            extra_mask,
            extra_score,
            topk=topk,
            narrow=narrow,
            has_agg=has_agg,
            plugin_bits=self._plugin_bits,
        )

    # -- automatic backend selection (oversized → mesh) -------------------

    def _maybe_autoshard(self, n_rows: int) -> bool:
        """Route oversized rounds through the mesh-sharded solve. The
        single-chip HBM heuristic: phase 1 keeps ~6 live i32/bool [B,C]
        buffers, so a round whose B·C exceeds `max_bc_elems` (the same
        budget that drives row chunking) no longer fits one launch — it
        would serialize into B·C/budget sequential chunks. When more than
        one device is visible, re-placing the fleet over a (bindings,
        clusters) mesh multiplies the budget by the bindings-axis size and
        splits the column work, so the round runs in fewer (ideally one)
        launches — with bit-identical placements (tests/test_parallel.py,
        dryrun_multichip). KARMADA_TPU_AUTOSHARD=0 or autoshard=False
        disables the selector; passing an explicit mesh bypasses it.

        Engagement is deliberately one-way (hysteresis, not an oversight):
        problems that crossed the envelope once tend to recur (cluster
        events re-enqueue the whole binding set), and de-escalating per
        round would re-place the fleet and bump the epoch on every flip —
        each epoch bump forces a full re-solve of the working set, which is
        itself an oversized round that would immediately re-engage the
        mesh. Steady state stays cheap under the mesh: decision replay and
        the dirty-column fleet refresh both work there."""
        if not self.autoshard or self.mesh is not None:
            return False
        if n_rows * len(self.fleet.names) <= self.max_bc_elems:
            return False
        devices = jax.devices()
        if len(devices) < 2:
            return False
        from ..parallel.mesh import make_mesh

        self.mesh = make_mesh(devices)
        self._mesh_kernel = None
        self._host_sorts = False  # never under a mesh: shards see partial rows
        # re-place the fleet sharded (pads clusters to a mesh-divisible
        # width); bumps the fleet epoch, so cached decisions re-solve once
        # on the (decision-identical) sharded path
        self.set_clusters(self.clusters[: self.n_real_clusters])
        return True

    # -- incremental rounds -----------------------------------------------

    def _split_replay(self, bindings: Sequence, extra_avail):
        """Replay-cache consult for one binding list: returns
        (out, dirty_pos, digest_of) where out[i] is the replayed decision or
        None, dirty_pos lists the rows that must solve, and digest_of
        memoizes the per-row estimator-answer digests for the cache writes.

        Estimator-row digests are computed LAZILY — only after the cheap
        epoch check says a cached entry could match, and once more at cache
        write time for dirty rows. An epoch-invalidated round (any cluster
        change) therefore never pays B blake2b passes over [C] rows just to
        discover every entry is stale.

        Out-of-tree plugins compute opaque per-round [B,C] terms on host, so
        their presence disables replay entirely (a plugin's changed answer
        must never be masked by a stale cache)."""
        from .incremental import extra_digest

        n = len(bindings)
        out: list[Optional[ScheduleDecision]] = [None] * n
        digests: list[Optional[bytes]] = [None] * n
        digest_done = [extra_avail is None] * n

        def digest_of(i: int) -> Optional[bytes]:
            if not digest_done[i]:
                digests[i] = extra_digest(extra_avail[i])
                digest_done[i] = True
            return digests[i]

        if self._oot_plugins:
            return out, list(range(n)), digest_of
        cache = self._decision_cache
        epoch = self.fleet_epoch
        dirty_pos: list[int] = []
        for i, rb in enumerate(bindings):
            uid = rb.metadata.uid
            ent = cache.get(uid) if uid else None
            if (
                ent is not None
                and ent.epoch == epoch  # cheap gate before any hashing
                and ent.matches(rb, epoch, digest_of(i))
            ):
                out[i] = ent.decision
            else:
                dirty_pos.append(i)
        return out, dirty_pos, digest_of

    def _cache_decisions(
        self, bindings: Sequence, out, dirty_pos, digest_of, solve_epoch: int,
        round_rows: Optional[int] = None,
    ) -> None:
        """Write the round's dirty decisions back to the replay cache and
        enforce the size bound (entries for deleted bindings must not
        accumulate forever — same policy as the encoder's row cache).
        `round_rows`: the WHOLE round's binding count when the caller is one
        chunk of a larger round — the bound must scale with the round, or a
        >16384-binding round would wipe the live working set on every chunk
        write and defeat replay at exactly the fleet scale it exists for."""
        if self._oot_plugins:
            return  # replay disabled: never cache under opaque plugin terms
        from .incremental import DecisionEntry

        cache = self._decision_cache
        for i in dirty_pos:
            rb = bindings[i]
            if rb.metadata.uid:
                cache[rb.metadata.uid] = DecisionEntry(
                    rb, solve_epoch, digest_of(i), out[i]
                )
        if len(cache) > max(4 * (round_rows or len(bindings)), 16384):
            cache.clear()
            for i, rb in enumerate(bindings):
                if rb.metadata.uid and out[i] is not None:
                    cache[rb.metadata.uid] = DecisionEntry(
                        rb, solve_epoch, digest_of(i), out[i]
                    )

    def schedule_incremental(
        self, bindings: Sequence, extra_avail=None
    ) -> list[ScheduleDecision]:
        """Incremental schedule round: bindings whose solve inputs are
        unchanged since the round that last solved them — same fleet epoch,
        same spec/status inputs, same estimator answers (sched/incremental.py
        DecisionEntry) — replay their cached decision without touching the
        device; only genuinely dirty rows enter `schedule()`. Decisions are
        bit-identical to a cold full solve (the tie-break is UID-seeded),
        which the incremental-vs-cold parity suite pins."""
        if not bindings:
            self.last_round_stats = {"replayed": 0, "solved": 0}
            return []
        bindings = list(bindings)
        out, dirty_pos, digest_of = self._split_replay(bindings, extra_avail)
        if dirty_pos:
            dirty = [bindings[i] for i in dirty_pos]
            sub_extra = None if extra_avail is None else extra_avail[dirty_pos]
            decisions = self.schedule(dirty, extra_avail=sub_extra)
            solve_epoch = self.fleet_epoch  # autoshard may have bumped it
            for i, dec in zip(dirty_pos, decisions):
                out[i] = dec
            self._cache_decisions(bindings, out, dirty_pos, digest_of,
                                  solve_epoch)
        self.last_round_stats = {
            "replayed": len(bindings) - len(dirty_pos),
            "solved": len(dirty_pos),
            # compile attribution of the dirty-row solve (all-replay rounds
            # by definition compiled nothing)
            **(self.last_compile_stats if dirty_pos else {
                "jit_compiles": 0, "jit_compile_seconds": 0.0,
                "jit_persistent_cache_hits": 0,
            }),
        }
        if self.last_pipeline_stats:
            # the dirty-row solve ran chunked: surface its stage/overlap
            # numbers next to the replay split
            self.last_round_stats.update(self.last_pipeline_stats)
        return out

    # -- pipelined chunk API (sched/pipeline.py drives these) --------------

    def launch_chunk(
        self, bindings: Sequence, extra_avail=None,
        round_rows: Optional[int] = None,
    ) -> dict:
        """Launch one pipeline chunk, replay-aware: cached decisions resolve
        immediately (they skip straight to the patch stage); dirty rows
        encode on host and dispatch to the device asynchronously — NO device
        sync happens here. The caller must have routed autoshard for the
        whole round already (`_maybe_autoshard(total_rows)`) and must keep
        chunks within `round_chunk_rows`. `round_rows`: the whole round's
        binding count (scales the replay-cache bound)."""
        bindings = list(bindings)
        out, dirty_pos, digest_of = self._split_replay(bindings, extra_avail)
        state = None
        if dirty_pos:
            dirty = [bindings[i] for i in dirty_pos]
            sub_extra = None if extra_avail is None else extra_avail[dirty_pos]
            state = self._launch_solve(dirty, sub_extra)
        return {
            "bindings": bindings,
            "out": out,
            "dirty_pos": dirty_pos,
            "digest_of": digest_of,
            "state": state,
            "epoch": self.fleet_epoch,
            "round_rows": round_rows,
            "replayed": len(bindings) - len(dirty_pos),
            "solved": len(dirty_pos),
        }

    def materialize_chunk(self, pending: dict) -> list[ScheduleDecision]:
        """Second half of `launch_chunk`: sync + decode the chunk's dirty
        rows, run the ordered-affinity retry loop, write the replay cache,
        and merge with the replayed decisions — decisions return in the
        chunk's binding order.

        Mixed-priority chunks launched through the segmented tiered solve
        (sched/preemption.py launch_tiered) ride the same seam: their
        pending carries the "tiered" marker and materializes here, so the
        StreamPipeline writer needs no routing of its own. Tiered
        decisions never enter the replay cache — they depend on batch
        composition, which the cache cannot key."""
        if pending.get("tiered"):
            from .preemption import materialize_tiered

            with stage_span("materialize", self.stage_timer):
                return materialize_tiered(self, pending)
        out = pending["out"]
        if pending["state"] is not None:
            decisions = self._materialize_solve(pending["state"])
            for i, dec in zip(pending["dirty_pos"], decisions):
                out[i] = dec
            self._cache_decisions(
                pending["bindings"], out, pending["dirty_pos"],
                pending["digest_of"], pending["epoch"],
                round_rows=pending["round_rows"],
            )
        return out

    def schedule(self, bindings: Sequence, extra_avail=None) -> list[ScheduleDecision]:
        """Schedule with the ordered-affinity-terms retry loop
        (scheduleResourceBindingWithClusterAffinities, scheduler.go:562-625):
        bindings whose placement lists `cluster_affinities` start from the
        last observed term and fall through to later terms on failure; the
        applied term's name is recorded on the decision.

        Oversized rounds (B over the per-launch HBM row cap) run as the
        chunked software pipeline (sched/pipeline.py): encode/solve/
        materialize overlap across chunks with double-buffered uploads,
        decisions bit-identical to the serial row-chunk executor."""
        if not bindings:
            return []
        from .compilecache import compile_counts, compile_delta

        bindings = list(bindings)
        self.last_pipeline_stats = None
        snap = compile_counts()
        try:
            self._maybe_autoshard(len(bindings))
            max_rows = self._max_rows_per_round(len(self.fleet.names))
            if len(bindings) > max_rows:
                return self._schedule_chunked(bindings, extra_avail, max_rows)
            return self._materialize_solve(
                self._launch_solve(bindings, extra_avail)
            )
        finally:
            # compile attribution per round: a steady-state round on the
            # bucket lattice must show jit_compiles == 0 here (pinned by
            # tests/test_bucketing.py)
            self.last_compile_stats = compile_delta(snap)
            if self.last_pipeline_stats is not None:
                self.last_pipeline_stats.update(self.last_compile_stats)

    @staticmethod
    def _affinity_terms_of(rb):
        p = rb.spec.placement
        return p.cluster_affinities if p is not None else []

    def _initial_term(self, rb) -> int:
        terms = self._affinity_terms_of(rb)
        if not terms:
            return 0
        observed = rb.status.scheduler_observed_affinity_name
        for i, t in enumerate(terms):
            if t.affinity_name == observed:
                return i
        return 0

    def _launch_solve(self, bindings: list, extra_avail=None):
        """First half of one (≤ max_rows) solve round: resolve the starting
        ordered-affinity terms, encode, and dispatch the device kernels —
        asynchronously, no device sync."""
        term_idx = [self._initial_term(rb) for rb in bindings]
        pending = self._launch_once(bindings, extra_avail, term_idx)
        return (bindings, extra_avail, term_idx, pending)

    def _materialize_solve(self, state) -> list[ScheduleDecision]:
        """Second half: sync + decode, then the ordered-affinity retry loop
        (retried sub-batches solve serially — failures past the first term
        are rare) and the applied term names."""
        bindings, extra_avail, term_idx, pending = state
        decisions = self._materialize_once(pending)
        while True:
            retry = [
                b
                for b, d in enumerate(decisions)
                if not d.ok
                and term_idx[b] + 1 < len(self._affinity_terms_of(bindings[b]))
            ]
            if not retry:
                break
            for b in retry:
                term_idx[b] += 1
            sub_extra = None if extra_avail is None else extra_avail[retry]
            sub_dec = self._schedule_once(
                [bindings[b] for b in retry], sub_extra, [term_idx[b] for b in retry]
            )
            for j, b in enumerate(retry):
                decisions[b] = sub_dec[j]
        for b, d in enumerate(decisions):
            terms = self._affinity_terms_of(bindings[b])
            if terms and d.ok:
                d.affinity_name = terms[term_idx[b]].affinity_name
        return decisions

    def _schedule_chunked(
        self, bindings: list, extra_avail, max_rows: int
    ) -> list[ScheduleDecision]:
        """The oversized-round executor: row chunks under the HBM budget,
        run as the software pipeline when enabled (chunk k+1 encodes and
        dispatches while chunk k's kernels run and chunk k−1 materializes on
        the writer; double-buffered, so chunks are HALF the serial row cap),
        or strictly serially when not. Decisions are bit-identical either
        way — rows are independent and the tie-break is UID-seeded.

        Out-of-tree plugins compute opaque host-side terms whose hooks may
        be stateful — their rounds run the chunks serially (same chunking,
        no thread overlap), exactly as they disable decision replay."""
        pipelined = self.pipeline_enabled and not self._oot_plugins
        cap = (
            min(max_rows, self.pipeline_chunk_rows(len(self.fleet.names)))
            if pipelined
            else max_rows
        )
        # equalized chunk-size schedule: same chunk count as the greedy
        # cap-sized split, but equal lattice-snapped chunks — never more
        # program shapes than greedy, usually one (docs/PERF.md)
        rows = plan_chunk_rows(len(bindings), cap)
        spans = chunk_spans(len(bindings), rows)
        chunks = [
            (
                bindings[s:e],
                None if extra_avail is None else extra_avail[s:e],
            )
            for s, e in spans
        ]
        timer = StageTimer()
        with self.pipeline_context(timer, overlap=pipelined):
            pipe = ChunkPipeline(
                launch=lambda i, c, est: self._launch_solve(c[0], c[1]),
                materialize=self._materialize_solve,
                pipelined=pipelined,
                timer=timer,
                # _materialize_once times its own span (the retry loop's
                # nested sub-rounds then record their stages, not a second
                # blanket materialize span)
                time_materialize=False,
            )
            results = pipe.run(chunks)
        stats = pipe.stats()
        stats["chunks"] = len(spans)
        stats["chunk_rows"] = rows
        self.last_pipeline_stats = stats
        return [d for chunk_dec in results for d in chunk_dec]

    def _classify_spread(self, bindings) -> tuple[list[int], dict, list[int]]:
        """Split spread-constrained rows into the batched device path and the
        per-row exact fallback (cluster-only constraints, cluster MaxGroups
        caps, huge region counts, or divided rows wider than the compact
        window). Placement-only — runs before any kernel."""
        from . import spread as spread_mod
        from . import spread_batch

        batched, cfg_of, fallback = [], {}, []
        layout = self._spread_layout
        # placements are shared across many rows: classify each DISTINCT
        # placement once (ids are stable for the duration of the call —
        # bindings hold the references)
        pl_seen: dict[int, object] = {}
        _MISS = object()
        for b, rb in enumerate(bindings):
            placement = rb.spec.placement
            if placement is None or not placement.spread_constraints:
                continue
            cfg = pl_seen.get(id(placement), _MISS)
            if cfg is _MISS:
                if spread_mod.should_ignore_spread_constraint(placement):
                    cfg = "ignore"
                else:
                    cfg = spread_batch.config_of(placement)
                pl_seen[id(placement)] = cfg
            if cfg == "ignore":
                continue
            if (
                cfg is not None
                and 0 < layout.n_regions <= spread_batch.MAX_REGIONS
                and (cfg.duplicated or rb.spec.replicas <= TOPK_TARGETS)
            ):
                batched.append(b)
                cfg_of[b] = cfg
            else:
                fallback.append(b)
        return batched, cfg_of, fallback

    def _schedule_once(
        self, bindings: Sequence, extra_avail=None, term_indices=None
    ) -> list[ScheduleDecision]:
        return self._materialize_once(
            self._launch_once(bindings, extra_avail, term_indices)
        )

    def _launch_once(
        self, bindings: Sequence, extra_avail=None, term_indices=None
    ) -> dict:
        """Encode + async kernel dispatch for one round; the returned
        pending dict feeds `_materialize_once`. The monolithic (explicit
        shard_map) mesh mode computes its round eagerly — its pending just
        carries the finished decisions, so pipelined callers degrade to
        serial there without a special case."""
        if self.mesh is None or self.mesh_partitioned:
            from . import candidates as cand_mod

            self.last_candidate_stats = {}
            reason = cand_mod.dense_reason(self, bindings)
            if reason is None:
                return cand_mod.launch_candidates(
                    self, bindings, extra_avail, term_indices
                )
            cand_mod.note_fallback(reason)
            return self._launch_once_partitioned(
                bindings, extra_avail, term_indices
            )
        return {
            "decisions": self._schedule_once_monolithic(
                bindings, extra_avail, term_indices
            )
        }

    def _materialize_once(self, pending: dict) -> list[ScheduleDecision]:
        if "decisions" in pending:
            return pending["decisions"]
        if pending.get("candidates"):
            from . import candidates as cand_mod

            return cand_mod.materialize_candidates(self, pending)
        return self._materialize_once_partitioned(pending)

    def _row_class(self, rb, spread_row: bool) -> int:
        """0 = no division tail (dup / non-workload / spread rows),
        1 = static-weight or dynamic-weight tail, 2 = aggregated tail."""
        from ..models.batch import strategy_code

        if spread_row:
            return 0
        strat = strategy_code(rb.spec.placement, rb.spec.replicas)
        if strat == AGGREGATED:
            return 2
        if strat in (STATIC_WEIGHT, DYNAMIC_WEIGHT):
            return 1
        return 0

    def _schedule_once_partitioned(
        self, bindings: Sequence, extra_avail=None, term_indices=None
    ) -> list[ScheduleDecision]:
        return self._materialize_once_partitioned(
            self._launch_once_partitioned(bindings, extra_avail, term_indices)
        )

    def _launch_once_partitioned(
        self, bindings: Sequence, extra_avail=None, term_indices=None
    ) -> dict:
        """LAUNCH half of the single-chip schedule round, partitioned by
        row class:

          phase 1  filter+estimate over ALL rows (one kernel, no sorts)
          phase 2  division tail over ONLY the divided rows — static/dynW
                   rows and Aggregated rows as separate sub-batches so the
                   truncation sort compiles in only where needed
          phase 2' spread selection (device group scoring + host
                   combination search) for spread rows
          packed   duplicated / non-workload targets are bit-packed
                   feasible masks (complete, no top-K overflow)

        Rows are permuted class-contiguous before encoding; decisions are
        unpermuted by the materialize half. Everything here is host encode
        (stage `encode`) plus ASYNC kernel dispatch (stage `solve`) — the
        device sync, host-sort tails, and all decode live in
        `_materialize_once_partitioned`, so a pipelined caller can encode
        and dispatch chunk k+1 while chunk k still computes."""
        n_real = len(bindings)
        if n_real == 0:
            return {"n_real": 0}
        names = self.fleet.names
        C = len(names)
        timer = self.stage_timer

        with stage_span("encode", timer):
            pre_batched, pre_cfg, pre_fallback = self._classify_spread(bindings)
            spread_set = set(pre_batched) | set(pre_fallback)
            cls = np.asarray(
                [
                    self._row_class(rb, b in spread_set)
                    for b, rb in enumerate(bindings)
                ],
                np.int8,
            )
            order = np.argsort(cls, kind="stable")
            bindings = [bindings[i] for i in order]
            cls = cls[order]
            if term_indices is not None:
                term_indices = [term_indices[i] for i in order]
            if extra_avail is not None:
                extra_avail = extra_avail[order]

            # re-derive spread classification in permuted space
            # (placement-only, cheap — avoids index-translation bugs)
            batched_rows, batched_cfg, fallback_rows = self._classify_spread(
                bindings
            )

            with self._encode_lock:
                raw = self.batch_encoder.encode(
                    bindings, term_indices=term_indices
                )
            batch = self._pad(raw)
            if extra_avail is not None:
                extra_avail = _pad_extra_avail(extra_avail, C, len(batch.replicas))

            extra_mask, extra_score = self._plugin_terms(
                bindings, len(batch.replicas)
            )
            _, narrow, _ = self._batch_flags(batch)  # once per round
            narrow16 = C < 2**15 and int(raw.replicas.max(initial=0)) < 2**15

        with stage_span("solve", timer):
            dev_feasible, dev_score, dev_avail, dev_prev, dev_tie, dev_fc = (
                _filter_kernel_compact(
                    *self.filter_kernel_args(
                        batch, extra_avail, extra_mask, extra_score
                    ),
                    plugin_bits=self._plugin_bits,
                )
            )

            # Every phase-2 kernel below depends only on phase-1 DEVICE
            # outputs, never on host values — so all of them are LAUNCHED
            # back to back and the round pays ONE device→host sync (the
            # tunnel adds ~70 ms RTT per sync; the round-2 shape of this
            # loop synced after every sub-phase and serialized RTT + exec
            # four times over). Host-sort tails (cpu backend) defer to the
            # materialize half: their inputs ride THE sync and the numpy
            # twin runs on the writer thread, overlapped with the next
            # chunk's encode + filter kernel.

            # ---- phase 2 launch: division tails per sub-class ----
            tails = []
            for want_cls, has_agg in ((1, False), (2, True)):
                rows = [b for b in range(n_real) if cls[b] == want_cls]
                if not rows:
                    continue
                idx_pad, nr = _pad_rows_idx(rows, self._bucket)
                rsel = idx_pad.astype(np.int64)
                t_feas = _gather_rows_kernel(dev_feasible, idx_pad)
                t_avail = _gather_rows_kernel(dev_avail, idx_pad)
                max_repl = int(raw.replicas[rows].max(initial=0))
                topk = min(
                    pow2_bucket(min(max_repl, TOPK_TARGETS), lo=8), TOPK_TARGETS
                )
                if self._host_sorts and (
                    len(rows) * C >= HOST_TAIL_MIN_ELEMS
                    or self._overlap_active
                ):
                    # the numpy tail wins only once the [rows, C] sort volume
                    # dwarfs its per-row Python overhead; small tails stay on
                    # the (already fast) jit kernel. Deferred: only the
                    # gathered filter outputs cross the device boundary (in
                    # THE sync), the twin itself runs at materialize time.
                    # Under an OVERLAPPING pipeline the twin wins at any
                    # volume: it runs on the writer thread behind the next
                    # chunk's filter kernel, while an XLA:CPU division sort
                    # would serialize the whole pipe (measured 2x per-row
                    # regression when the halved chunks fell under the
                    # threshold).
                    tails.append({
                        "kind": "host", "rows": rows, "nr": nr,
                        "t_feas": t_feas, "t_avail": t_avail, "topk": topk,
                    })
                else:
                    t_prev = _gather_rows_kernel(dev_prev, idx_pad)
                    t_tie = _gather_rows_kernel(dev_tie, idx_pad)
                    t_out = _tail_kernel(
                        t_feas, t_avail, t_prev, t_tie,
                        batch.weight_tables, batch.weight_idx[rsel],
                        batch.strategy[rsel], batch.replicas[rsel],
                        batch.fresh[rsel],
                        topk=topk, narrow=narrow, has_agg=has_agg,
                        narrow16=narrow16,
                    )
                    tails.append({"kind": "dev", "rows": rows, "t_out": t_out})

            # ---- phase 2 launch: duplicated / non-workload target sets ----
            fallback_set = set(fallback_rows)
            mask_rows = [
                b for b in range(n_real)
                if cls[b] == 0 and b not in batched_cfg and b not in fallback_set
            ]
            packed_dev = midx_dev = None
            if mask_rows:
                mask_idx, nm = _pad_rows_idx(mask_rows, self._bucket)
                m_feas = _gather_rows_kernel(dev_feasible, mask_idx)
                pc = raw.aff_masks.sum(axis=1)
                mk = int(pc[raw.aff_idx[np.asarray(mask_rows)]].max(initial=0))
                # the popcount bound is only a bound while feasible ⊆ affinity
                # mask; with ClusterAffinity disabled the kernel substitutes
                # all-ones for affinity, so the index window could truncate —
                # those batches ship complete packed masks instead
                if (
                    self._plugin_bits & plugin_mod.BIT_AFFINITY
                    and 0 < mk <= TOPK_TARGETS
                ):
                    mkb = pow2_bucket(mk, lo=8)
                    midx_dev = _feas_idx_kernel(
                        m_feas, min(mkb, C), narrow16=narrow16
                    )
                else:  # wide rows (full-fleet affinities): complete packed mask
                    packed_dev = _pack_rows_kernel(m_feas)

            # ---- phase 2 launch: spread group scoring ----
            spread_pre = self._spread_prelaunch(
                bindings, batch, batched_rows, batched_cfg,
                dev_feasible, dev_score, dev_avail, dev_prev, dev_tie,
                extra_avail=extra_avail, extra_mask=extra_mask,
                extra_score=extra_score, defer_host=True,
            )

        return {
            "bindings": bindings, "raw": raw, "batch": batch, "cls": cls,
            "order": order, "n_real": n_real,
            "extra_avail": extra_avail, "extra_mask": extra_mask,
            "narrow": narrow,
            "dev": (dev_feasible, dev_score, dev_avail, dev_prev, dev_tie,
                    dev_fc),
            "tails": tails, "packed_dev": packed_dev, "midx_dev": midx_dev,
            "mask_rows": mask_rows,
            "batched_rows": batched_rows, "batched_cfg": batched_cfg,
            "fallback_rows": fallback_rows, "spread_pre": spread_pre,
        }

    def _materialize_once_partitioned(self, p: dict) -> list[ScheduleDecision]:
        """MATERIALIZE half: ONE device→host sync for everything the launch
        half dispatched, then the deferred host-sort twins, decode overlays,
        spread selection, and decision construction (stage `materialize`)."""
        if p["n_real"] == 0:
            return []
        with stage_span("materialize", self.stage_timer):
            return self._materialize_partitioned_inner(p)

    def _materialize_partitioned_inner(self, p: dict) -> list[ScheduleDecision]:
        bindings = p["bindings"]
        raw, batch, cls, order = p["raw"], p["batch"], p["cls"], p["order"]
        n_real = p["n_real"]
        extra_avail, extra_mask = p["extra_avail"], p["extra_mask"]
        narrow = p["narrow"]
        dev_feasible, dev_score, dev_avail, dev_prev, dev_tie, dev_fc = p["dev"]
        tails = p["tails"]
        packed_dev, midx_dev = p["packed_dev"], p["midx_dev"]
        mask_rows = p["mask_rows"]
        batched_rows, batched_cfg = p["batched_rows"], p["batched_cfg"]
        fallback_rows, spread_pre = p["fallback_rows"], p["spread_pre"]
        names = self.fleet.names
        C = len(names)

        unsched = np.zeros(n_real, bool)
        avail_sum = np.zeros(n_real, np.int64)
        row_err: dict[int, str] = {}
        row_target_src: dict[int, tuple] = {}
        row_feas_src: dict[int, tuple] = {}

        spread_fetch = None
        if spread_pre is not None:
            # device group scores, or the deferred host-score INPUTS
            spread_fetch = spread_pre.get("wvf", spread_pre.get("host_inputs"))

        # ---- THE sync ----
        host = jax.device_get((
            dev_fc,
            [t["t_out"][1:] for t in tails if t["kind"] == "dev"],
            (packed_dev, midx_dev),
            spread_fetch,
            [(t["t_feas"], t["t_avail"]) for t in tails if t["kind"] == "host"],
        ))
        feas_count = np.asarray(host[0])[:n_real].astype(np.int64)
        if spread_pre is not None:
            if "wvf" in spread_pre:
                spread_pre["wvf_host"] = host[3]
            else:
                # deferred host group scoring (cpu backend): the numpy twin
                # runs here, on the materialize thread
                from . import spread_batch

                hi = host[3]
                reps_r, need_r, target_r, dupf_r = spread_pre["host_params"]
                W, V, A, fc_h = spread_batch.host_group_score(
                    np.asarray(hi[0]), np.asarray(hi[1]),
                    np.asarray(hi[2]), np.asarray(hi[3]),
                    reps_r, need_r, target_r, dupf_r,
                    layout=self._spread_layout,
                )
                spread_pre["wvf_host"] = (W, V, fc_h)

        # ---- deferred host-sort division tails ----
        dev_vals = iter(host[1])
        host_inputs = iter(host[4])
        decoded_tails = []  # (rows, result_src, vals)
        for t in tails:
            if t["kind"] == "dev":
                decoded_tails.append((t["rows"], t["t_out"][0], next(dev_vals)))
                continue
            rows, nr, topk = t["rows"], t["nr"], t["topk"]
            h_feas, h_avail = next(host_inputs)
            # cpu backend: the division tail runs as numpy — XLA:CPU's
            # comparator-loop sorts cost ~40 s at the flagship shape while
            # the host selection/packed-sort twin lands the same placements
            # in seconds (ops/assign.py host_tail). Only the filter-phase
            # outputs cross from the device; prev/tie reconstruct from the
            # factored batch, and the jit-bucket padding is sliced off.
            rsub = np.asarray(rows, np.int64)
            h_feas = np.asarray(h_feas)[:nr]
            h_avail = np.asarray(h_avail)[:nr]
            pidx = np.asarray(batch.prev_idx)[rsub]
            prep = np.asarray(batch.prev_rep)[rsub]
            h_prev = np.zeros((nr, C), np.int32)
            rr, cc = np.nonzero((pidx >= 0) & (pidx < C))
            h_prev[rr, pidx[rr, cc]] = prep[rr, cc]
            t_out = assign_ops.host_tail(
                h_feas, h_avail, h_prev, np.asarray(batch.seeds)[rsub],
                np.asarray(batch.weight_tables)[batch.weight_idx[rsub]],
                batch.strategy[rsub], batch.replicas[rsub],
                batch.fresh[rsub],
                (STATIC_WEIGHT, DYNAMIC_WEIGHT, AGGREGATED),
                topk=topk,
            )
            decoded_tails.append((rows, t_out[0], t_out[1:]))

        # ---- decode: division tails ----
        for rows, t_res, vals in decoded_tails:
            t_unsched, t_avail_sum, t_nnz, t_ti, t_tv = vals
            tis, tvs = _sorted_pairs(t_ti, t_tv)
            overflow = []
            for k, b in enumerate(rows):
                unsched[b] = bool(t_unsched[k])
                avail_sum[b] = int(t_avail_sum[k])
                n = int(t_nnz[k])
                if n > t_ti.shape[1]:
                    overflow.append((k, b))
                    continue
                row_target_src[b] = ("pairs", names, tis[k, :n], tvs[k, :n])
            if overflow:
                if isinstance(t_res, np.ndarray):  # host tail: no fetch
                    o_res = t_res[[k for k, _ in overflow]]
                else:
                    o_res = fetch_rows(
                        t_res, [k for k, _ in overflow], self._bucket
                    )
                for j, (_, b) in enumerate(overflow):
                    pos = np.nonzero(o_res[j] > 0)[0]
                    row_target_src[b] = (
                        "pairs", names, pos, o_res[j, pos].astype(np.int64)
                    )

        # ---- decode: duplicated / non-workload target sets ----
        if mask_rows:
            packed_h, midx_h = host[2]
            mask_overflow: list[int] = []
            for k, b in enumerate(mask_rows):
                n = int(feas_count[b])
                if n <= 0:
                    continue
                strat = int(raw.strategy[b])
                reps = 0 if strat == NON_WORKLOAD else int(bindings[b].spec.replicas)
                if midx_h is not None:
                    if n > midx_h.shape[1]:
                        # feasible outran the popcount-derived window (the
                        # invariant feasible ⊆ affinity mask failed some other
                        # way) — mirror the tail-overflow contract and fetch
                        # the dense row instead of silently truncating
                        mask_overflow.append(b)
                        continue
                    fidx = np.asarray(midx_h[k][:n], np.int64)
                    row_feas_src[b] = ("idx", names, fidx)
                    row_target_src[b] = (
                        "pairs", names, fidx, np.full(n, reps, np.int64)
                    )
                else:
                    row_feas_src[b] = ("mask", names, packed_h[k], C)
                    row_target_src[b] = ("mask", names, packed_h[k], C, reps)
            if mask_overflow:
                o_feas = fetch_rows(dev_feasible, mask_overflow, self._bucket)
                for j, b in enumerate(mask_overflow):
                    fidx = np.nonzero(o_feas[j])[0]
                    strat = int(raw.strategy[b])
                    reps = (
                        0 if strat == NON_WORKLOAD
                        else int(bindings[b].spec.replicas)
                    )
                    row_feas_src[b] = ("idx", names, fidx)
                    row_target_src[b] = (
                        "pairs", names, fidx,
                        np.full(len(fidx), reps, np.int64),
                    )

        self._spread_overlay(
            bindings, raw, batch, extra_avail, batched_rows, batched_cfg,
            fallback_rows, dev_feasible, dev_score, dev_avail, dev_prev,
            dev_tie, feas_count, unsched, avail_sum,
            row_err, row_target_src, row_feas_src, narrow=narrow,
            pre=spread_pre, extra_mask=extra_mask,
        )

        # ---- build decisions, then unpermute ----
        dec_p: list[ScheduleDecision] = []
        for b, key in enumerate(raw.keys):
            dec = ScheduleDecision(key=key)
            if b in row_feas_src:
                dec._feasible_src = row_feas_src[b]
            if b in row_err:
                dec.error = row_err[b]
            elif feas_count[b] == 0:
                # FitError diagnosis (generic_scheduler.go:83-88)
                dec.error = f"0/{self.n_real_clusters} clusters are available"
            elif unsched[b]:
                dec.error = (
                    f"Clusters available replicas {int(avail_sum[b])} are not "
                    "enough to schedule."
                )
            elif b in row_target_src:
                dec._targets_src = row_target_src[b]
            else:
                # hard invariant: every live (feasible, schedulable) row must
                # have been given a decode source by exactly one of the
                # phase-2 paths above — a misrouted row silently decoding to
                # empty targets would look like a successful no-op placement
                raise AssertionError(
                    "schedule round produced no decode source for live row "
                    f"{key!r} (class {int(cls[b])}, strategy "
                    f"{int(raw.strategy[b])})"
                )
            dec_p.append(dec)
        out: list[Optional[ScheduleDecision]] = [None] * n_real
        for j, dec in enumerate(dec_p):
            out[int(order[j])] = dec
        return out

    def _spread_prelaunch(
        self, bindings, batch, batched_rows, batched_cfg,
        dev_feasible, dev_score, dev_avail, dev_prev, dev_tie,
        extra_avail=None, extra_mask=None, extra_score=None,
        defer_host: bool = False,
    ):
        """LAUNCH the batched-spread group scoring (gathers + one kernel) and
        return the device handles — no sync. The partitioned round folds the
        (W, V, fc) fetch into its single round-trip; callers without that
        discipline fetch from the returned handles themselves.

        `defer_host`: when the cpu-backend host-scoring twin would engage,
        do NOT sync here — return the gathered device handles under
        `host_inputs`/`host_params` and let the materialize half fetch them
        in THE sync and run the numpy twin on its own thread (the pipelined
        launch path must never block on the device)."""
        if not batched_rows:
            return None
        from . import spread_batch

        C = len(self.fleet.names)
        layout = self._spread_layout
        idx_pad, nb = _pad_rows_idx(batched_rows, self._bucket)
        g_feas = _gather_rows_kernel(dev_feasible, idx_pad)
        g_avail = _gather_rows_kernel(dev_avail, idx_pad)
        if dev_prev is not None:
            g_prev = _gather_rows_kernel(dev_prev, idx_pad)
            g_tie = _gather_rows_kernel(dev_tie, idx_pad)
        else:
            g_prev, g_tie = _row_context_kernel(
                batch.prev_idx[idx_pad], batch.prev_rep[idx_pad],
                batch.seeds[idx_pad], n_cols=C,
            )

        S = len(idx_pad)
        need = np.ones(S, np.int64)
        target = np.ones(S, np.int64)
        reps = np.zeros(S, np.int64)
        dupf = np.zeros(S, bool)
        for j, b in enumerate(batched_rows):
            cfg = batched_cfg[b]
            mg = max(cfg.rmin, 1)
            need[j] = cfg.need
            target[j] = -(-bindings[b].spec.replicas // mg)
            reps[j] = bindings[b].spec.replicas
            dupf[j] = cfg.duplicated

        # dedup rows whose SCORING inputs are identical — policy-heavy
        # batches collapse ~25x (5k rows over 200 placements), so only
        # representative rows pay the [S, C] member sort (device or host);
        # the overlay expands (W, V, fc) back through `score_inv`
        rep_of: dict[tuple, int] = {}
        rep_js: list[int] = []
        inv = np.empty(len(batched_rows), np.int64)

        def row_bytes(x, b):
            # per-row term that feeds dev_feasible/score/avail (estimator
            # answers, out-of-tree plugin masks/scores): rows differing in
            # them must never share a scoring representative
            if x is None:
                return None
            arr = np.asarray(x)
            if arr.shape[:1] == (1,) and arr.ndim == 2 and arr.shape[0] == 1:
                return b"same"  # broadcast sentinel: identical for all rows
            return arr[b].tobytes()

        for j, b in enumerate(batched_rows):
            key = (
                int(batch.aff_idx[b]), int(batch.tol_idx[b]),
                int(batch.gvk[b]), int(batch.req_idx[b]),
                bool(batch.unknown_request[b]), int(batch.replicas[b]),
                batch.evict_idx[b].tobytes(),
                batch.prev_idx[b].tobytes(), batch.prev_rep[b].tobytes(),
                int(need[j]), int(target[j]), bool(dupf[j]),
                row_bytes(extra_avail, b), row_bytes(extra_mask, b),
                row_bytes(extra_score, b),
            )
            r = rep_of.get(key)
            if r is None:
                r = len(rep_js)
                rep_of[key] = r
                rep_js.append(j)
            inv[j] = r
        rep_b = [batched_rows[j] for j in rep_js]
        rep_pad, nrep = _pad_rows_idx(rep_b, self._bucket)
        r_feas = _gather_rows_kernel(dev_feasible, rep_pad)
        r_score = _gather_rows_kernel(dev_score, rep_pad)
        r_avail = _gather_rows_kernel(dev_avail, rep_pad)
        if dev_prev is not None:
            r_prev = _gather_rows_kernel(dev_prev, rep_pad)
        else:
            r_prev, _ = _row_context_kernel(
                batch.prev_idx[rep_pad], batch.prev_rep[rep_pad],
                batch.seeds[rep_pad], n_cols=C,
            )
        Sr = len(rep_pad)
        # per-row scalars padded like rep_pad (pads repeat the first row)
        jsel = np.asarray(
            rep_js + [rep_js[0]] * (Sr - nrep), np.int64
        ) if rep_js else np.zeros(Sr, np.int64)
        need_r = need[jsel]
        target_r = target[jsel]
        reps_r = reps[jsel]
        dupf_r = dupf[jsel]

        base = {
            "idx_pad": idx_pad, "nb": nb,
            "g_feas": g_feas, "g_avail": g_avail,
            "g_prev": g_prev, "g_tie": g_tie,
            "score_inv": inv, "score_nrep": nrep,
        }
        if self._host_sorts and (
            Sr * C >= HOST_TAIL_MIN_ELEMS
            or (defer_host and self._overlap_active)
        ):
            # cpu backend: the group-scoring member sort runs as numpy
            # (host_group_score — same outputs, packed np.argsort instead
            # of XLA:CPU's comparator-loop sort); under an overlapping
            # pipeline the twin runs deferred on the writer thread, so it
            # wins at any volume (see the division-tail gate)
            if defer_host:
                base["host_inputs"] = (r_feas, r_score, r_avail, r_prev)
                base["host_params"] = (reps_r, need_r, target_r, dupf_r)
                return base
            h = jax.device_get((r_feas, r_score, r_avail, r_prev))
            W, V, A, fc_dev = spread_batch.host_group_score(
                h[0], h[1], h[2], h[3],
                reps_r, need_r, target_r, dupf_r, layout=layout,
            )
        else:
            score_kernel = (
                spread_batch.group_score_kernel
                if layout.grid_balanced
                else spread_batch.group_score_kernel_segmented  # skewed
            )
            W, V, A, fc_dev = score_kernel(
                r_feas, r_score, r_avail, r_prev,
                reps_r, need_r, target_r, dupf_r, layout=layout,
            )
        base["wvf"] = (W, V, fc_dev)
        return base

    def _spread_overlay(
        self, bindings, raw, batch, extra_avail, batched_rows, batched_cfg,
        fallback_rows, dev_feasible, dev_score, dev_avail, dev_prev, dev_tie,
        feas_count, unsched, avail_sum, row_err, row_target_src, row_feas_src,
        narrow: bool, pre=None, extra_mask=None,
    ) -> None:
        """Spread-constrained rows: batched device path + per-row exact
        fallback. Mutates the decode overlays in place. dev_prev/dev_tie may
        be None (mesh path) — they're rebuilt for the row subset. `pre` is a
        _spread_prelaunch result whose (W, V, fc) the caller already fetched
        (stored under pre["wvf_host"]); without it the overlay launches and
        fetches itself."""
        from . import spread as spread_mod
        from . import spread_batch

        names = self.fleet.names
        C = len(names)
        n_real = len(raw.keys)

        # ---- batched spread path: device group scoring → vectorized host
        # combination search → packed selection masks + divided re-dispense
        if batched_rows:
            layout = self._spread_layout
            if pre is None:
                pre = self._spread_prelaunch(
                    bindings, batch, batched_rows, batched_cfg,
                    dev_feasible, dev_score, dev_avail, dev_prev, dev_tie,
                    extra_avail=extra_avail, extra_mask=extra_mask,
                )
            wvf_host = pre.get("wvf_host")
            if wvf_host is None:
                wvf_host = jax.device_get(pre["wvf"])
            idx_pad, nb = pre["idx_pad"], pre["nb"]
            g_feas, g_avail = pre["g_feas"], pre["g_avail"]
            g_prev, g_tie = pre["g_prev"], pre["g_tie"]
            S = len(idx_pad)
            W, V, fc = wvf_host
            inv = pre.get("score_inv")
            if inv is None:
                W = np.asarray(W)[:nb]
                V = np.asarray(V)[:nb]
                fc = np.asarray(fc)[:nb]
            else:  # expand representative scores back to all rows
                nrep = pre["score_nrep"]
                W = np.asarray(W)[:nrep][inv]
                V = np.asarray(V)[:nrep][inv]
                fc = np.asarray(fc)[:nrep][inv]
            for j, b in enumerate(batched_rows):
                feas_count[b] = fc[j]

            from collections import defaultdict

            j_by_cfg: dict = defaultdict(list)
            for j, b in enumerate(batched_rows):
                if fc[j] > 0:  # 0-feasible rows take the FitError branch
                    j_by_cfg[batched_cfg[b]].append(j)
            chosen = np.zeros((S, layout.n_regions), bool)
            for cfg, js in j_by_cfg.items():
                res = spread_batch.select_regions_batch(W[js], V[js], cfg, layout)
                chosen[js] = res.chosen
                for local, msg in res.errors.items():
                    row_err[batched_rows[js[local]]] = msg
                for local in res.fallback:
                    fallback_rows.append(batched_rows[js[local]])
            fallback_set = set(fallback_rows)

            ok_js = [
                j for j, b in enumerate(batched_rows)
                if fc[j] > 0 and b not in row_err and b not in fallback_set
            ]
            if ok_js:
                # packed selection masks compute for every row on device, but
                # rows sharing (filters, eviction set, chosen regions) have
                # IDENTICAL masks — only representative rows ride the link
                # (5k spread rows over 10 policies ⇒ a few dozen rows)
                packed_all = spread_batch.packed_selection_kernel(
                    g_feas, chosen, layout=layout
                )
                rep_of: dict[tuple, int] = {}
                rep_js: list[int] = []
                rep_idx_of_j: dict[int, int] = {}
                div_js = []
                # the feasible row (hence the packed mask) also folds in the
                # out-of-tree FilterPlugin masks, which are PER-ROW — fold
                # each row's mask digest into the dedup key so rows that only
                # differ in their out-of-tree mask never share a
                # representative
                oot = (
                    extra_mask
                    if self._oot_plugins
                    and extra_mask is not None
                    and extra_mask.shape != (1, 1)
                    else None
                )
                for j in ok_js:
                    b = batched_rows[j]
                    k = (
                        int(raw.aff_idx[b]), int(raw.tol_idx[b]),
                        int(raw.gvk[b]), raw.evict_idx[b].tobytes(),
                        chosen[j].tobytes(),
                        None if oot is None else np.asarray(oot[b]).tobytes(),
                    )
                    r = rep_of.get(k)
                    if r is None:
                        r = len(rep_js)
                        rep_of[k] = r
                        rep_js.append(j)
                    rep_idx_of_j[j] = r
                    if int(raw.strategy[b]) not in (NON_WORKLOAD, DUPLICATED):
                        div_js.append(j)
                rep_pad, nrep = _pad_rows_idx(rep_js, self._bucket)
                packed_reps_dev = _gather_rows_kernel(packed_all, rep_pad)

                tail_dev = None
                if div_js:
                    d_idx, nd = _pad_rows_idx(div_js, self._bucket)
                    d_rows = [batched_rows[j] for j in div_js]
                    d_feas = _gather_rows_kernel(g_feas, d_idx)
                    d_avail = _gather_rows_kernel(g_avail, d_idx)
                    d_prev = _gather_rows_kernel(g_prev, d_idx)
                    d_chosen = chosen[d_idx]
                    d_brows = np.asarray(
                        [batched_rows[j] for j in d_idx], np.int64
                    )
                    d_strategy = raw.strategy[d_brows]
                    d_replicas = raw.replicas[d_brows]
                    d_fresh = raw.fresh[d_brows]
                    max_repl = int(raw.replicas[d_rows].max(initial=0))
                    topk_d = min(
                        pow2_bucket(min(max_repl, TOPK_TARGETS), lo=8),
                        TOPK_TARGETS,
                    )
                    has_agg_d = bool((d_strategy == AGGREGATED).any())
                    if self._host_sorts and (
                        nd * C >= HOST_TAIL_MIN_ELEMS or self._overlap_active
                    ):
                        # the spread re-run's division is the same tail —
                        # run the numpy twin (see the phase-2 host branch);
                        # already on the materialize thread, so under the
                        # pipeline it too wins at any volume
                        h_feas, h_avail, h_prev = jax.device_get(
                            (d_feas, d_avail, d_prev)
                        )
                        rid = np.asarray(layout.rid_orig)
                        chosen_pad = np.concatenate(
                            [np.zeros((nd, 1), bool), np.asarray(d_chosen)[:nd]],
                            axis=1,
                        )
                        sel = np.asarray(h_feas)[:nd] & chosen_pad[:, rid]
                        ht = assign_ops.host_tail(
                            sel, np.asarray(h_avail)[:nd],
                            np.asarray(h_prev)[:nd],
                            np.asarray(batch.seeds)[d_brows[:nd]],
                            np.zeros((nd, C), np.int64),
                            d_strategy[:nd], d_replicas[:nd], d_fresh[:nd],
                            (STATIC_WEIGHT, DYNAMIC_WEIGHT, AGGREGATED),
                            topk=topk_d,
                        )
                        # spread_tail_kernel's output order, feas_count from
                        # the restricted selection
                        tail_dev = (
                            ht[0], ht[1], ht[2],
                            sel.sum(-1).astype(np.int32), ht[3], ht[4], ht[5],
                        )
                    else:
                        d_tie = _gather_rows_kernel(g_tie, d_idx)
                        tail_dev = spread_batch.spread_tail_kernel(
                            d_feas, d_avail, d_prev, d_tie, d_chosen,
                            d_strategy, d_replicas, d_fresh,
                            layout=layout, topk=topk_d,
                            narrow=narrow, has_agg=has_agg_d,
                        )

                # one sync for the packed representatives AND the tail (the
                # dense result tensor tail_dev[0] stays on device — only
                # overflow rows fetch their dense row)
                packed_reps, tail_host = jax.device_get(
                    (packed_reps_dev, None if tail_dev is None else tail_dev[1:])
                )
                packed_reps = np.asarray(packed_reps)[:nrep]
                for j in ok_js:
                    b = batched_rows[j]
                    prow = packed_reps[rep_idx_of_j[j]]
                    row_feas_src[b] = ("mask", names, prow, C)
                    strat = int(raw.strategy[b])
                    if strat == NON_WORKLOAD:
                        row_target_src[b] = ("mask", names, prow, C, 0)
                    elif strat == DUPLICATED:
                        row_target_src[b] = (
                            "mask", names, prow, C,
                            int(bindings[b].spec.replicas),
                        )
                if div_js:
                    un2, as2, fc2, nnz2, ti2, tv2 = tail_host
                    ti2s, tv2s = _sorted_pairs(ti2, tv2)
                    overflow2 = []
                    for k, b in enumerate(d_rows):
                        unsched[b] = bool(un2[k])
                        avail_sum[b] = int(as2[k])
                        feas_count[b] = int(fc2[k])
                        n = int(nnz2[k])
                        if n > ti2.shape[1]:
                            overflow2.append((k, b))
                            continue
                        row_target_src[b] = ("pairs", names, ti2s[k, :n], tv2s[k, :n])
                    if overflow2:
                        if isinstance(tail_dev[0], np.ndarray):
                            o_res = tail_dev[0][[k for k, _ in overflow2]]
                        else:
                            o_res = fetch_rows(
                                tail_dev[0], [k for k, _ in overflow2],
                                self._bucket,
                            )
                        for m, (_, b) in enumerate(overflow2):
                            pos = np.nonzero(o_res[m] > 0)[0]
                            row_target_src[b] = (
                                "pairs", names, pos,
                                o_res[m, pos].astype(np.int64),
                            )

        # ---- fallback spread path: the per-row exact selection + restricted
        # re-run (sched/spread.py stays the semantic spec)
        if fallback_rows:
            fallback_rows = sorted(set(fallback_rows))
            f_feas = fetch_rows(dev_feasible, fallback_rows, self._bucket)
            f_score = fetch_rows(dev_score, fallback_rows, self._bucket)
            f_avail = fetch_rows(dev_avail, fallback_rows, self._bucket)
            sub_affinity = raw.affinity_ok.copy()
            # with ClusterAffinity disabled the kernel substitutes ones for
            # the affinity table, so the spread selection must ride the
            # extra_mask channel instead (it is a SelectClusters restriction,
            # not an affinity-plugin term)
            affinity_on = bool(self._plugin_bits & plugin_mod.BIT_AFFINITY)
            sel_of: dict[int, np.ndarray] = {}
            live_rows = []
            for k, b in enumerate(fallback_rows):
                if not f_feas[k].any():
                    continue  # FitError branch
                rb = bindings[b]
                prev_row = np.zeros(C + 1, np.int32)
                prev_row[raw.prev_idx[b]] = raw.prev_rep[b]
                feas = np.nonzero(f_feas[k])[0]
                try:
                    selected_idx = spread_mod.select_by_spread_arrays(
                        feas,
                        f_score[k, feas],
                        f_avail[k, feas].astype(np.int64) + prev_row[feas],
                        self._name_rank[feas],
                        self._region_id[feas],
                        self._region_names,
                        rb.spec.placement,
                        rb.spec.replicas,
                    )
                except spread_mod.SpreadError as e:
                    row_err[b] = str(e)
                    continue
                mask = np.zeros(C, bool)
                mask[selected_idx] = True
                if affinity_on:
                    sub_affinity[b] &= mask
                else:
                    sel_of[b] = mask
                live_rows.append(b)
            if live_rows:
                sub = _restrict_rows(raw, live_rows, sub_affinity)
                sub_batch = self._pad(sub)
                sub_extra = None
                if extra_avail is not None:
                    sub_extra = extra_avail[live_rows]
                    pad = len(sub_batch.replicas) - len(sub_extra)
                    if pad:
                        sub_extra = np.pad(
                            sub_extra, [(0, pad), (0, 0)], constant_values=-1
                        )
                s_mask, s_score = self._plugin_terms(
                    [bindings[b] for b in live_rows], len(sub_batch.replicas)
                )
                if sel_of:
                    if s_mask.shape == (1, 1):
                        s_mask = np.ones(
                            (len(sub_batch.replicas), C), bool
                        )
                    for j, b in enumerate(live_rows):
                        if b in sel_of:
                            s_mask[j] &= sel_of[b]
                s_out = self.run_kernel(
                    sub_batch, sub_extra, extra_mask=s_mask, extra_score=s_score
                )
                s_feas, s_result, s_unsched, s_avail_sum = jax.device_get(
                    (s_out[0], s_out[2], s_out[3], s_out[4])
                )
                for j, b in enumerate(live_rows):
                    fidx = np.nonzero(s_feas[j])[0]
                    row_feas_src[b] = ("idx", names, fidx)
                    feas_count[b] = len(fidx)
                    if raw.strategy[b] == NON_WORKLOAD:
                        # targets = the selected set, no replica counts
                        row_target_src[b] = (
                            "pairs", names, fidx, np.zeros(len(fidx), np.int64)
                        )
                    else:
                        pos = np.nonzero(s_result[j] > 0)[0]
                        row_target_src[b] = (
                            "pairs", names, pos, s_result[j, pos].astype(np.int64)
                        )
                    unsched[b] = bool(s_unsched[j])
                    avail_sum[b] = int(s_avail_sum[j])

    def _schedule_once_monolithic(
        self, bindings: Sequence, extra_avail=None, term_indices=None
    ) -> list[ScheduleDecision]:
        """One full-kernel round (filter + tail over every row) — the mesh
        path, where the sharded kernel computes everything in one program
        (parallel/mesh.py). Decode mirrors the partitioned path."""
        n_real = len(bindings)
        if n_real == 0:
            return []
        names = self.fleet.names
        C = len(names)
        batched_rows, batched_cfg, fallback_rows = self._classify_spread(bindings)

        with self._encode_lock:
            raw = self.batch_encoder.encode(bindings, term_indices=term_indices)
        batch = self._pad(raw)
        if extra_avail is not None:
            extra_avail = _pad_extra_avail(extra_avail, C, len(batch.replicas))

        extra_mask, extra_score = self._plugin_terms(
            bindings, len(batch.replicas)
        )
        out = self.run_kernel(
            batch, extra_avail, extra_mask=extra_mask, extra_score=extra_score
        )
        dev_feasible, dev_score, dev_result, dev_avail = (
            out[0], out[1], out[2], out[5],
        )
        unsched, avail_sum, feas_count, nnz, top_idx, top_val = jax.device_get(
            (out[3], out[4], out[6], out[7], out[8], out[9])
        )
        unsched = np.array(unsched)[:n_real]
        avail_sum = np.array(avail_sum)[:n_real]
        feas_count = np.array(feas_count)[:n_real].astype(np.int64)

        row_err: dict[int, str] = {}
        row_target_src: dict[int, tuple] = {}
        row_feas_src: dict[int, tuple] = {}

        _, narrow, _ = self._batch_flags(batch)
        self._spread_overlay(
            bindings, raw, batch, extra_avail, batched_rows, batched_cfg,
            fallback_rows, dev_feasible, dev_score, dev_avail, None, None,
            feas_count, unsched, avail_sum,
            row_err, row_target_src, row_feas_src, narrow=narrow,
            extra_mask=extra_mask,
        )

        # vectorized pair extraction for main rows
        Kw = top_idx.shape[1]
        ti_sorted, tv_sorted = _sorted_pairs(top_idx, top_val)
        overflow = [
            b for b in range(n_real)
            if b not in row_target_src and nnz[b] > Kw
            and raw.strategy[b] != NON_WORKLOAD
        ]
        if overflow:
            o_res = fetch_rows(dev_result, overflow, self._bucket)
            for k, b in enumerate(overflow):
                pos = np.nonzero(o_res[k] > 0)[0]
                row_target_src[b] = (
                    "pairs", names, pos, o_res[k, pos].astype(np.int64)
                )
        nonwork = [
            b for b in range(n_real)
            if raw.strategy[b] == NON_WORKLOAD and b not in row_feas_src
            and feas_count[b] > 0
        ]
        if nonwork:
            nw_feas = fetch_rows(dev_feasible, nonwork, self._bucket)
            for k, b in enumerate(nonwork):
                fidx = np.nonzero(nw_feas[k])[0]
                row_feas_src[b] = ("idx", names, fidx)
                row_target_src[b] = (
                    "pairs", names, fidx, np.zeros(len(fidx), np.int64)
                )

        out_decisions: list[ScheduleDecision] = []
        for b, key in enumerate(raw.keys):
            dec = ScheduleDecision(key=key)
            if b in row_feas_src:
                dec._feasible_src = row_feas_src[b]
            if b in row_err:
                dec.error = row_err[b]
            elif feas_count[b] == 0:
                # FitError diagnosis (generic_scheduler.go:83-88)
                dec.error = f"0/{self.n_real_clusters} clusters are available"
            elif unsched[b]:
                dec.error = (
                    f"Clusters available replicas {int(avail_sum[b])} are not "
                    "enough to schedule."
                )
            elif b in row_target_src:
                dec._targets_src = row_target_src[b]
            else:
                n = int(nnz[b])
                dec._targets_src = (
                    "pairs", names, ti_sorted[b, :n], tv_sorted[b, :n]
                )
            out_decisions.append(dec)
        return out_decisions

"""The batched scheduling core: one jitted [B,C] program per round.

TPU reframing of pkg/scheduler/core/generic_scheduler.go:70-115
(Schedule = snapshot → findClustersThatFit → prioritizeClusters →
SelectClusters → AssignReplicas): the per-binding sequential loop becomes a
single fused device program over all dirty bindings. The fleet snapshot is the
persistent device encoding (models/fleet.py) instead of an O(N) deep copy per
attempt (cache/cache.go:62-77).

Spread-constraint selection is layered on in sched/spread.py; without spread
constraints SelectClusters returns every feasible cluster (common.go:32-39
with empty constraints).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api.work import TargetCluster
from ..models.batch import (
    AGGREGATED,
    BatchEncoder,
    BindingBatch,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    NON_WORKLOAD,
    STATIC_WEIGHT,
)
from ..models.fleet import FleetArrays, FleetEncoder
from ..ops import assign as assign_ops
from ..ops import filters as filter_ops


@dataclass
class ScheduleDecision:
    key: str
    targets: Optional[list[TargetCluster]] = None
    error: str = ""  # non-empty ⇒ unschedulable / fit error
    feasible: list[str] = field(default_factory=list)
    score: Optional[np.ndarray] = None

    @property
    def ok(self) -> bool:
        return not self.error


@partial(jax.jit, static_argnames=())
def _schedule_kernel(
    # fleet
    alive,
    capacity,
    has_summary,
    taint_key,
    taint_value,
    taint_effect,
    api_ok,
    # batch
    replicas,
    request,
    unknown_request,
    gvk,
    strategy,
    fresh,
    tol_key,
    tol_value,
    tol_effect,
    tol_op,
    affinity_ok,
    eviction_ok,
    static_weight,
    prev_member,
    prev_replicas,
    tie,
    extra_avail,  # i32[B,C] min-merged registered-estimator answers; -1 = none
):
    taint_mask = filter_ops.taint_toleration_mask(
        taint_key, taint_value, taint_effect, tol_key, tol_value, tol_effect, tol_op
    )
    api_mask = filter_ops.api_enablement_mask(api_ok, gvk)
    feasible = filter_ops.feasible_mask(
        alive, api_mask, taint_mask, jnp.ones_like(affinity_ok), affinity_ok, eviction_ok
    )
    score = filter_ops.locality_score(prev_member)

    # Estimation (GeneralEstimator path; additional estimators min-merge in).
    # Requests naming resources outside the encoded vocabulary behave like a
    # missing allocatable key: 0 available everywhere (general.go:166-169).
    avail = assign_ops.general_estimate(capacity, has_summary, request, replicas)
    avail = jnp.where(unknown_request[:, None], 0, avail)
    # min-merge with registered estimators (-1 sentinel discarded,
    # core/util.go:72-92); gRPC/node-level answers tighten the general bound
    avail = jnp.where(extra_avail >= 0, jnp.minimum(avail, extra_avail), avail)

    # All strategies computed batched, row-selected by strategy code.
    dup = assign_ops.duplicated_assign(feasible, replicas)
    static = assign_ops.static_weight_assign(
        feasible, static_weight, prev_replicas, tie, replicas
    )
    dyn = assign_ops.dynamic_assign(
        feasible,
        avail,
        prev_replicas,
        tie,
        replicas,
        fresh,
        strategy == AGGREGATED,
    )

    result = jnp.zeros_like(dup)
    result = jnp.where((strategy == DUPLICATED)[:, None], dup, result)
    result = jnp.where((strategy == STATIC_WEIGHT)[:, None], static, result)
    is_dyn = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)
    result = jnp.where(is_dyn[:, None], dyn.result, result)
    unschedulable = is_dyn & dyn.unschedulable
    return feasible, score, result, unschedulable, dyn.available_sum, avail


def _restrict_rows(batch: BindingBatch, rows: list[int], affinity_override: np.ndarray) -> BindingBatch:
    """Row-subset of a batch with the spread-selection mask folded into the
    affinity mask (phase-2 candidate restriction)."""
    idx = np.asarray(rows)

    def take(a):
        return a[idx]

    return BindingBatch(
        keys=[batch.keys[b] for b in rows],
        uids=[batch.uids[b] for b in rows],
        replicas=take(batch.replicas),
        request=take(batch.request),
        unknown_request=take(batch.unknown_request),
        gvk=take(batch.gvk),
        strategy=take(batch.strategy),
        fresh=take(batch.fresh),
        tol_key=take(batch.tol_key),
        tol_value=take(batch.tol_value),
        tol_effect=take(batch.tol_effect),
        tol_op=take(batch.tol_op),
        affinity_ok=affinity_override[idx],
        eviction_ok=take(batch.eviction_ok),
        static_weight=take(batch.static_weight),
        prev_member=take(batch.prev_member),
        prev_replicas=take(batch.prev_replicas),
        tie=take(batch.tie),
    )


class ArrayScheduler:
    """Host wrapper: encodes fleet + batches, runs the kernel, decodes
    TargetClusters. Batch sizes are padded to power-of-two buckets to bound
    the jit cache (SURVEY §7 dynamic-shapes note)."""

    def __init__(self, clusters: Sequence, encoder: Optional[FleetEncoder] = None):
        self.encoder = encoder or FleetEncoder()
        self.set_clusters(clusters)

    def set_clusters(self, clusters: Sequence) -> None:
        self.clusters = list(clusters)
        self.fleet: FleetArrays = self.encoder.encode(self.clusters)
        self.batch_encoder = BatchEncoder(self.encoder, self.fleet, self.clusters)

    @staticmethod
    def _bucket(n: int) -> int:
        b = 8
        while b < n:
            b *= 2
        return b

    def _pad(self, batch: BindingBatch) -> BindingBatch:
        B = batch.size
        Bp = self._bucket(B)
        if Bp == B:
            return batch
        pad = Bp - B

        def pz(a):
            width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, width)

        return BindingBatch(
            keys=batch.keys,
            uids=batch.uids,
            replicas=pz(batch.replicas),
            request=pz(batch.request),
            unknown_request=pz(batch.unknown_request),
            gvk=pz(batch.gvk),
            strategy=pz(batch.strategy),
            fresh=pz(batch.fresh),
            tol_key=pz(batch.tol_key),
            tol_value=pz(batch.tol_value),
            tol_effect=pz(batch.tol_effect),
            tol_op=pz(batch.tol_op),
            affinity_ok=pz(batch.affinity_ok),
            eviction_ok=pz(batch.eviction_ok),
            static_weight=pz(batch.static_weight),
            prev_member=pz(batch.prev_member),
            prev_replicas=pz(batch.prev_replicas),
            tie=pz(batch.tie),
        )

    def run_kernel(self, batch: BindingBatch, extra_avail=None):
        if extra_avail is None:
            extra_avail = np.full(
                (len(batch.replicas), len(self.fleet.names)), -1, np.int32
            )
        f = self.fleet
        return _schedule_kernel(
            f.alive,
            f.capacity,
            f.has_summary,
            f.taint_key,
            f.taint_value,
            f.taint_effect,
            f.api_ok,
            batch.replicas,
            batch.request,
            batch.unknown_request,
            batch.gvk,
            batch.strategy,
            batch.fresh,
            batch.tol_key,
            batch.tol_value,
            batch.tol_effect,
            batch.tol_op,
            batch.affinity_ok,
            batch.eviction_ok,
            batch.static_weight,
            batch.prev_member,
            batch.prev_replicas,
            batch.tie,
            extra_avail,
        )

    def schedule(self, bindings: Sequence, extra_avail=None) -> list[ScheduleDecision]:
        if not bindings:
            return []
        raw = self.batch_encoder.encode(bindings)
        batch = self._pad(raw)
        if extra_avail is not None and len(extra_avail) < len(batch.replicas):
            pad = len(batch.replicas) - len(extra_avail)
            extra_avail = np.pad(extra_avail, [(0, pad), (0, 0)], constant_values=-1)
        feasible, score, result, unsched, avail_sum, avail = (
            np.array(x) for x in self.run_kernel(batch, extra_avail)
        )

        # Phase 2: spread-constrained rows restrict candidates via the host
        # combinatorial selection (SelectClusters, common.go:32-39), then the
        # assignment kernel re-runs over the restricted feasible set.
        from . import spread as spread_mod

        spread_errors: dict[int, str] = {}
        spread_rows: list[int] = []
        for b, rb in enumerate(bindings):
            placement = rb.spec.placement
            if (
                placement is not None
                and placement.spread_constraints
                and feasible[b].any()
                # statically-ignored constraints select every feasible cluster
                # (select_clusters.go:63-77) — the restriction re-run is a no-op
                and not spread_mod.should_ignore_spread_constraint(placement)
            ):
                spread_rows.append(b)
        if spread_rows:
            sub_affinity = raw.affinity_ok.copy()
            live_rows = []
            for b in spread_rows:
                rb = bindings[b]
                details = [
                    spread_mod.ClusterDetail(
                        name=self.fleet.names[i],
                        index=int(i),
                        score=int(score[b, i]),
                        available=int(avail[b, i]) + int(raw.prev_replicas[b, i]),
                        region=self.clusters[i].spec.region,
                        zone=self.clusters[i].spec.zone,
                        provider=self.clusters[i].spec.provider,
                    )
                    for i in np.nonzero(feasible[b])[0]
                ]
                try:
                    selected = spread_mod.select_clusters_by_spread(
                        details, rb.spec.placement, rb.spec.replicas
                    )
                except spread_mod.SpreadError as e:
                    spread_errors[b] = str(e)
                    continue
                mask = np.zeros(len(self.fleet.names), bool)
                mask[[d.index for d in selected]] = True
                sub_affinity[b] &= mask
                live_rows.append(b)
            if live_rows:
                sub = _restrict_rows(raw, live_rows, sub_affinity)
                sub_batch = self._pad(sub)
                sub_extra = None
                if extra_avail is not None:
                    sub_extra = extra_avail[live_rows]
                    pad = len(sub_batch.replicas) - len(sub_extra)
                    if pad:
                        sub_extra = np.pad(sub_extra, [(0, pad), (0, 0)], constant_values=-1)
                s_feas, s_score, s_result, s_unsched, s_avail_sum, _ = jax.tree.map(
                    np.asarray, self.run_kernel(sub_batch, sub_extra)
                )
                for j, b in enumerate(live_rows):
                    feasible[b] = s_feas[j]
                    score[b] = s_score[j]
                    result[b] = s_result[j]
                    unsched[b] = s_unsched[j]
                    avail_sum[b] = s_avail_sum[j]

        names = self.fleet.names
        out: list[ScheduleDecision] = []
        for b, key in enumerate(raw.keys):
            feas_idx = np.nonzero(feasible[b])[0]
            dec = ScheduleDecision(
                key=key, feasible=[names[i] for i in feas_idx], score=score[b]
            )
            if b in spread_errors:
                dec.error = spread_errors[b]
                out.append(dec)
                continue
            if feas_idx.size == 0:
                # FitError diagnosis (generic_scheduler.go:83-88)
                dec.error = f"0/{len(names)} clusters are available"
                out.append(dec)
                continue
            if unsched[b]:
                dec.error = (
                    f"Clusters available replicas {int(avail_sum[b])} are not "
                    "enough to schedule."
                )
                out.append(dec)
                continue
            if raw.strategy[b] == NON_WORKLOAD:
                dec.targets = [TargetCluster(name=names[i], replicas=0) for i in feas_idx]
            else:
                pos = np.nonzero(result[b] > 0)[0]
                # removeZeroReplicasCluster (common.go:60-66)
                dec.targets = [
                    TargetCluster(name=names[i], replicas=int(result[b, i])) for i in pos
                ]
            out.append(dec)
        return out

"""The batched scheduling core: one jitted [B,C] program per round.

TPU reframing of pkg/scheduler/core/generic_scheduler.go:70-115
(Schedule = snapshot → findClustersThatFit → prioritizeClusters →
SelectClusters → AssignReplicas): the per-binding sequential loop becomes a
single fused device program over all dirty bindings. The fleet snapshot is the
persistent device encoding (models/fleet.py) instead of an O(N) deep copy per
attempt (cache/cache.go:62-77).

Spread-constraint selection is layered on in sched/spread.py; without spread
constraints SelectClusters returns every feasible cluster (common.go:32-39
with empty constraints).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api.work import TargetCluster
from ..models.batch import (
    AGGREGATED,
    BatchEncoder,
    BindingBatch,
    DUPLICATED,
    DYNAMIC_WEIGHT,
    NON_WORKLOAD,
    STATIC_WEIGHT,
)
from ..models.fleet import FleetArrays, FleetEncoder
from ..ops import assign as assign_ops
from ..ops import filters as filter_ops

# compact-output width: covers every row whose target count is <= this
# (divided rows are bounded by spec.replicas; wider duplicated rows fetch
# their dense result row as a fallback)
TOPK_TARGETS = 128


@dataclass
class ScheduleDecision:
    key: str
    targets: Optional[list[TargetCluster]] = None
    error: str = ""  # non-empty ⇒ unschedulable / fit error
    feasible: list[str] = field(default_factory=list)
    score: Optional[np.ndarray] = None
    affinity_name: str = ""  # applied ordered-affinity term (scheduler.go:562-625)

    @property
    def ok(self) -> bool:
        return not self.error


def filter_estimate_phase(
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    replicas, request, unknown_request, gvk,
    tol_key, tol_value, tol_effect, tol_op,
    affinity_ok, eviction_ok, prev_member,
):
    """Filters + score + GeneralEstimator — elementwise over (B, C), so the
    mesh path runs it on local (B_l, C_l) tiles before any collective.

    Requests naming resources outside the encoded vocabulary behave like a
    missing allocatable key: 0 available everywhere (general.go:166-169)."""
    taint_mask = filter_ops.taint_toleration_mask(
        taint_key, taint_value, taint_effect, tol_key, tol_value, tol_effect, tol_op
    )
    api_mask = filter_ops.api_enablement_mask(api_ok, gvk)
    feasible = filter_ops.feasible_mask(
        alive, api_mask, taint_mask, jnp.ones_like(affinity_ok), affinity_ok, eviction_ok
    )
    score = filter_ops.locality_score(prev_member)
    avail = assign_ops.general_estimate(capacity, has_summary, request, replicas)
    avail = jnp.where(unknown_request[:, None], 0, avail)
    return feasible, score, avail


def assignment_tail(
    feasible, strategy, static_weight, avail, prev_replicas, tie, replicas,
    fresh, narrow: bool = False, has_agg: bool = True,
):
    """Strategy dispatch + division over FULL fleet rows (the phase that needs
    every cluster column: per-row sort/cumsum, binding.go:112-144). Static +
    dynamic rows share one dispenser pass (row-disjoint — combined_assign
    halves the [B,C] sort work). narrow/has_agg are host-derived static
    specializations (see ArrayScheduler._batch_flags)."""
    dup = assign_ops.duplicated_assign(feasible, replicas)
    is_static = strategy == STATIC_WEIGHT
    is_dyn = (strategy == DYNAMIC_WEIGHT) | (strategy == AGGREGATED)
    sd = assign_ops.combined_assign(
        feasible, is_static, is_dyn, strategy == AGGREGATED,
        static_weight, avail, prev_replicas, tie, replicas, fresh,
        narrow=narrow, has_agg=has_agg,
    )
    result = jnp.zeros_like(dup)
    result = jnp.where((strategy == DUPLICATED)[:, None], dup, result)
    result = jnp.where((is_static | is_dyn)[:, None], sd.result, result)
    unschedulable = is_dyn & sd.unschedulable
    return result, unschedulable, sd.available_sum


def compact_outputs(feasible, result, topk: int):
    """Top-K sparsification of the decision tensor: the per-binding target
    list is almost always far smaller than C, so the round's device→host
    transfer drops from O(B·C) to O(B·K); rows whose nonzero count exceeds K
    fall back to a dense row fetch on host."""
    top_val, top_idx = jax.lax.top_k(result, topk)
    nnz = (result > 0).sum(-1).astype(jnp.int32)
    feas_count = feasible.sum(-1).astype(jnp.int32)
    return feas_count, nnz, top_idx.astype(jnp.int32), top_val


def _schedule_body(
    # fleet
    alive,
    capacity,
    has_summary,
    taint_key,
    taint_value,
    taint_effect,
    api_ok,
    # batch (dense)
    replicas,
    request,
    unknown_request,
    gvk,
    strategy,
    fresh,
    tol_key,
    tol_value,
    tol_effect,
    tol_op,
    affinity_ok,
    eviction_ok,
    static_weight,
    prev_member,
    prev_replicas,
    tie,
    extra_avail,  # i32[B,C] min-merged registered-estimator answers; -1 = none
    narrow: bool = False,
    has_agg: bool = True,
):
    feasible, score, avail = filter_estimate_phase(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, request, unknown_request, gvk,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, prev_member,
    )
    # min-merge with registered estimators (-1 sentinel discarded,
    # core/util.go:72-92); gRPC/node-level answers tighten the general bound
    avail = jnp.where(extra_avail >= 0, jnp.minimum(avail, extra_avail), avail)
    result, unschedulable, avail_sum = assignment_tail(
        feasible, strategy, static_weight, avail, prev_replicas, tie, replicas,
        fresh, narrow=narrow, has_agg=has_agg,
    )
    return feasible, score, result, unschedulable, avail_sum, avail


@partial(jax.jit, static_argnames=())
def _schedule_kernel(
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    replicas, request, unknown_request, gvk, strategy, fresh,
    tol_key, tol_value, tol_effect, tol_op,
    affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
    extra_avail,
):
    """Dense-input variant (mesh path / graft entry)."""
    return _schedule_body(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, request, unknown_request, gvk, strategy, fresh,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
        extra_avail,
    )


def _device_tie(seeds, n_clusters, offset=0):
    """splitmix64 tie-break expanded on device — bit-identical to
    models.batch.tie_matrix (the deterministic stand-in for the reference's
    crypto-rand tie-break, binding.go:74-79). `offset` shifts the cluster
    index range for column-sharded callers (parallel/mesh.py) so every shard
    reproduces its slice of the global tie matrix."""
    idx = (
        jnp.asarray(offset).astype(jnp.uint64)
        + jnp.arange(1, n_clusters + 1, dtype=jnp.uint64)
    )[None, :]
    x = seeds[:, None] ^ idx
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    x = x ^ (x >> jnp.uint64(31))
    return (x >> jnp.uint64(33)).astype(jnp.int32)


def decompress_batch(
    aff_masks, aff_idx, weight_tables, weight_idx,
    prev_idx, prev_rep, evict_idx, seeds,
    n_cols: int, col_offset=0,
):
    """Reconstruct the [B, n_cols] tile of the factored batch ON DEVICE
    (gathers + scatters over local HBM — host→device stays O(B·K + P·C)).

    `col_offset` is the global index of this tile's first cluster column:
    0 on the single-chip path; the shard's offset under the mesh (sparse
    prev/eviction entries carry GLOBAL column ids and the tie matrix is
    defined over global indices, so every shard reproduces exactly its slice
    of the dense tensors)."""
    B = aff_idx.shape[0]
    rows = jnp.arange(B)[:, None]
    affinity_ok = aff_masks[aff_idx]
    static_weight = weight_tables[weight_idx]
    # translate global → local column ids; everything out of this tile's
    # range (including the encoder's drop sentinel) lands on n_cols → dropped
    p = prev_idx - col_offset
    p = jnp.where((p >= 0) & (p < n_cols), p, n_cols)
    prev_member = jnp.zeros((B, n_cols), bool).at[rows, p].set(True, mode="drop")
    prev_replicas = (
        jnp.zeros((B, n_cols), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    e = evict_idx - col_offset
    e = jnp.where((e >= 0) & (e < n_cols), e, n_cols)
    eviction_ok = jnp.ones((B, n_cols), bool).at[rows, e].set(False, mode="drop")
    tie = _device_tie(seeds, n_cols, offset=col_offset)
    return affinity_ok, static_weight, prev_member, prev_replicas, eviction_ok, tie


@partial(jax.jit, static_argnames=("topk", "narrow", "has_agg"))
def _schedule_kernel_compact(
    # fleet (device-resident)
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    # batch core
    replicas, request, unknown_request, gvk, strategy, fresh,
    tol_key, tol_value, tol_effect, tol_op,
    # factored [B,C] reconstruction inputs (models/batch.py BindingBatch)
    aff_masks, aff_idx, weight_tables, weight_idx,
    prev_idx, prev_rep, evict_idx, seeds,
    extra_avail,  # i32[B,C] or broadcastable [1,1] sentinel
    topk: int = TOPK_TARGETS,
    narrow: bool = False,
    has_agg: bool = True,
):
    """Decompress the factored batch on device, then run the solve.

    topk/narrow/has_agg are host-derived static specializations (bounded jit
    cache: 5 top-K buckets x 2 x 2): the compact window shrinks to the
    batch's real target bound, the division sorts use i32 keys when every
    weight provably fits, and the Aggregated truncation sort is compiled out
    when no row needs it."""
    B = replicas.shape[0]
    C = alive.shape[0]
    affinity_ok, static_weight, prev_member, prev_replicas, eviction_ok, tie = (
        decompress_batch(
            aff_masks, aff_idx, weight_tables, weight_idx,
            prev_idx, prev_rep, evict_idx, seeds, C,
        )
    )
    extra = jnp.broadcast_to(extra_avail, (B, C))
    feasible, score, result, unschedulable, avail_sum, avail = _schedule_body(
        alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
        replicas, request, unknown_request, gvk, strategy, fresh,
        tol_key, tol_value, tol_effect, tol_op,
        affinity_ok, eviction_ok, static_weight, prev_member, prev_replicas, tie,
        extra, narrow=narrow, has_agg=has_agg,
    )
    feas_count, nnz, top_idx, top_val = compact_outputs(
        feasible, result, min(C, topk)
    )
    return (
        feasible, score, result, unschedulable, avail_sum, avail,
        feas_count, nnz, top_idx, top_val,
    )


def _restrict_rows(batch: BindingBatch, rows: list[int], affinity_override: np.ndarray) -> BindingBatch:
    """Row-subset of a batch with the spread-selection mask folded into the
    affinity mask (phase-2 candidate restriction). The override masks are
    per-row, so the sub-batch carries them as its own (un-deduped) table."""
    idx = np.asarray(rows)

    def take(a):
        return a[idx]

    return BindingBatch(
        keys=[batch.keys[b] for b in rows],
        uids=[batch.uids[b] for b in rows],
        replicas=take(batch.replicas),
        request=take(batch.request),
        unknown_request=take(batch.unknown_request),
        gvk=take(batch.gvk),
        strategy=take(batch.strategy),
        fresh=take(batch.fresh),
        tol_key=take(batch.tol_key),
        tol_value=take(batch.tol_value),
        tol_effect=take(batch.tol_effect),
        tol_op=take(batch.tol_op),
        aff_masks=affinity_override[idx],
        aff_idx=np.arange(len(rows), dtype=np.int32),
        weight_tables=batch.weight_tables,
        weight_idx=take(batch.weight_idx),
        prev_idx=take(batch.prev_idx),
        prev_rep=take(batch.prev_rep),
        evict_idx=take(batch.evict_idx),
        seeds=take(batch.seeds),
        n_clusters=batch.n_clusters,
    )


class ArrayScheduler:
    """Host wrapper: encodes fleet + batches, runs the kernel, decodes
    TargetClusters. Batch sizes are padded to power-of-two buckets to bound
    the jit cache (SURVEY §7 dynamic-shapes note)."""

    def __init__(
        self,
        clusters: Sequence,
        encoder: Optional[FleetEncoder] = None,
        mesh=None,
    ):
        """`mesh`: optional jax.sharding.Mesh — the solve runs column/row-
        sharded over it (parallel/mesh.py) with identical outputs."""
        self.encoder = encoder or FleetEncoder()
        self.mesh = mesh
        self._mesh_kernel = None
        self.set_clusters(clusters)

    def set_clusters(self, clusters: Sequence) -> None:
        self.clusters = list(clusters)
        self.fleet: FleetArrays = self.encoder.encode(self.clusters)
        self.batch_encoder = BatchEncoder(self.encoder, self.fleet, self.clusters)
        # spread-selection fast-path encodings (sched/spread.py array API):
        # cluster-name ascending ranks (sortClusters tie-break) and region ids
        C = len(self.clusters)
        self._name_rank = np.empty(C, np.int32)
        self._name_rank[np.argsort(np.array(self.fleet.names))] = np.arange(C)
        region_ids: dict[str, int] = {}
        self._region_id = np.full(C, -1, np.int32)
        for i, c in enumerate(self.clusters):
            region = c.spec.region
            if region:
                rid = region_ids.setdefault(region, len(region_ids))
                self._region_id[i] = rid
        self._region_names = list(region_ids)
        # per-resource capacity ceiling for the narrow-keys bound (host-side
        # proof that every division weight fits i32 — see _batch_flags)
        cap = np.asarray(self.fleet.capacity, np.int64)
        self._max_cap_per_res = (
            cap.max(axis=0) if cap.size else np.zeros(cap.shape[1], np.int64)
        )
        # fleet tensors live on device across rounds (the persistent snapshot
        # that replaces the reference's per-attempt deep copy, cache.go:62-77);
        # re-transferred only on cluster-set change
        if self.mesh is not None:
            from ..parallel.mesh import MeshScheduleKernel

            if self._mesh_kernel is None:
                self._mesh_kernel = MeshScheduleKernel(self.mesh)
            self._mesh_kernel.set_fleet(self.fleet)
            self._fleet_dev = None
            return
        f = self.fleet
        self._fleet_dev = tuple(
            jax.device_put(x)
            for x in (
                f.alive, f.capacity, f.has_summary,
                f.taint_key, f.taint_value, f.taint_effect, f.api_ok,
            )
        )

    @staticmethod
    def _bucket(n: int) -> int:
        """Power-of-two buckets up to 2048, then 2048-multiples: bounds the
        jit cache while capping pad waste at large B (10k pads to 10240, not
        16384 — the solve is O(B·C), so pad waste is wall-clock waste)."""
        b = 8
        while b < n and b < 2048:
            b *= 2
        if n <= b:
            return b
        return ((n + 2047) // 2048) * 2048

    def _pad(self, batch: BindingBatch) -> BindingBatch:
        B = batch.size
        Bp = self._bucket(B)
        if Bp == B:
            return batch
        pad = Bp - B

        def pz(a, fill=0):
            width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, width, constant_values=fill)

        return BindingBatch(
            keys=batch.keys,
            uids=batch.uids,
            replicas=pz(batch.replicas),
            request=pz(batch.request),
            unknown_request=pz(batch.unknown_request),
            gvk=pz(batch.gvk),
            strategy=pz(batch.strategy),
            fresh=pz(batch.fresh),
            tol_key=pz(batch.tol_key),
            tol_value=pz(batch.tol_value),
            tol_effect=pz(batch.tol_effect),
            tol_op=pz(batch.tol_op),
            aff_masks=batch.aff_masks,
            aff_idx=pz(batch.aff_idx),  # padded rows → mask row 0 (harmless:
            #   strategy 0/replicas 0 rows are never decoded)
            weight_tables=batch.weight_tables,
            weight_idx=pz(batch.weight_idx),
            prev_idx=pz(batch.prev_idx, fill=batch.n_clusters),
            prev_rep=pz(batch.prev_rep),
            evict_idx=pz(batch.evict_idx, fill=batch.n_clusters),
            seeds=pz(batch.seeds),
            n_clusters=batch.n_clusters,
        )

    _NO_EXTRA = np.full((1, 1), -1, np.int32)  # broadcast sentinel

    def _batch_flags(self, batch: BindingBatch) -> tuple[int, bool, bool]:
        """Host-derived static kernel specializations (cheap numpy passes
        over the factored batch — never over [B,C]):

        - topk: the compact-output window, bucketed to the batch's provable
          per-row target bound (divided rows emit <= spec.replicas targets;
          duplicated rows <= their affinity-mask popcount). Smaller window =
          less top_k work and fewer device->host bytes per round.
        - narrow: True when every division weight provably fits i32, so the
          [B,C] sort keys narrow from i64 (GeneralEstimator answers are
          bounded by max capacity // min positive request per resource;
          static weights by their table max).
        - has_agg: False compiles the Aggregated truncation sort out."""
        max_prev = int(batch.prev_rep.max(initial=0))
        max_repl = int(batch.replicas.max(initial=0))
        req = np.asarray(batch.request, np.int64)
        pos = req > 0
        bound_est = 0
        if pos.any():
            min_req = np.where(pos, req, np.iinfo(np.int64).max).min(axis=0)
            used = pos.any(axis=0)
            per_res = np.where(
                used, self._max_cap_per_res // np.maximum(min_req, 1), 0
            )
            bound_est = int(per_res.max(initial=0))
        max_static = int(batch.weight_tables.max(initial=0))
        i32max = 2**31 - 1
        narrow = (
            max(bound_est, max_repl) + max_prev < i32max and max_static < i32max
        )
        has_agg = bool((batch.strategy == AGGREGATED).any())
        cand = max_repl
        dup = batch.strategy == DUPLICATED
        if dup.any():
            pc = batch.aff_masks.sum(axis=1)
            cand = max(cand, int(pc[batch.aff_idx[dup]].max(initial=0)))
        topk = 8
        while topk < min(cand, TOPK_TARGETS):
            topk *= 2
        return min(topk, TOPK_TARGETS), narrow, has_agg

    def run_kernel(self, batch: BindingBatch, extra_avail=None):
        if self._mesh_kernel is not None:
            return self._mesh_kernel(batch, extra_avail)
        if extra_avail is None:
            extra_avail = self._NO_EXTRA
        topk, narrow, has_agg = self._batch_flags(batch)
        return _schedule_kernel_compact(
            *self._fleet_dev,
            batch.replicas,
            batch.request,
            batch.unknown_request,
            batch.gvk,
            batch.strategy,
            batch.fresh,
            batch.tol_key,
            batch.tol_value,
            batch.tol_effect,
            batch.tol_op,
            batch.aff_masks,
            batch.aff_idx,
            batch.weight_tables,
            batch.weight_idx,
            batch.prev_idx,
            batch.prev_rep,
            batch.evict_idx,
            batch.seeds,
            extra_avail,
            topk=topk,
            narrow=narrow,
            has_agg=has_agg,
        )

    def schedule(self, bindings: Sequence, extra_avail=None) -> list[ScheduleDecision]:
        """Schedule with the ordered-affinity-terms retry loop
        (scheduleResourceBindingWithClusterAffinities, scheduler.go:562-625):
        bindings whose placement lists `cluster_affinities` start from the
        last observed term and fall through to later terms on failure; the
        applied term's name is recorded on the decision."""
        if not bindings:
            return []

        def terms_of(rb):
            p = rb.spec.placement
            return p.cluster_affinities if p is not None else []

        def initial_term(rb) -> int:
            terms = terms_of(rb)
            if not terms:
                return 0
            observed = rb.status.scheduler_observed_affinity_name
            for i, t in enumerate(terms):
                if t.affinity_name == observed:
                    return i
            return 0

        term_idx = [initial_term(rb) for rb in bindings]
        decisions = self._schedule_once(bindings, extra_avail, term_idx)
        while True:
            retry = [
                b
                for b, d in enumerate(decisions)
                if not d.ok and term_idx[b] + 1 < len(terms_of(bindings[b]))
            ]
            if not retry:
                break
            for b in retry:
                term_idx[b] += 1
            sub_extra = None if extra_avail is None else extra_avail[retry]
            sub_dec = self._schedule_once(
                [bindings[b] for b in retry], sub_extra, [term_idx[b] for b in retry]
            )
            for j, b in enumerate(retry):
                decisions[b] = sub_dec[j]
        for b, d in enumerate(decisions):
            terms = terms_of(bindings[b])
            if terms and d.ok:
                d.affinity_name = terms[term_idx[b]].affinity_name
        return decisions

    def _schedule_once(
        self, bindings: Sequence, extra_avail=None, term_indices=None
    ) -> list[ScheduleDecision]:
        raw = self.batch_encoder.encode(bindings, term_indices=term_indices)
        batch = self._pad(raw)
        if extra_avail is not None and len(extra_avail) < len(batch.replicas):
            pad = len(batch.replicas) - len(extra_avail)
            extra_avail = np.pad(extra_avail, [(0, pad), (0, 0)], constant_values=-1)
        out = self.run_kernel(batch, extra_avail)
        dev_feasible, dev_score, dev_result, dev_unsched, dev_avail_sum, dev_avail = out[:6]
        # one batched device_get for the compact outputs (a single tunnel
        # round-trip instead of one per array)
        unsched, avail_sum, feas_count, nnz, top_idx, top_val = jax.device_get(
            (dev_unsched, dev_avail_sum, out[6], out[7], out[8], out[9])
        )
        # the spread re-run overwrites per-row entries; device_get buffers are
        # read-only views
        unsched = np.array(unsched)
        avail_sum = np.array(avail_sum)
        feas_count = np.array(feas_count)
        # dense tensors are fetched lazily: only the phases that need full
        # rows (spread selection, non-workload target lists, top-K overflow)
        dense_cache: dict[str, np.ndarray] = {}

        def dense(name: str) -> np.ndarray:
            a = dense_cache.get(name)
            if a is None:
                a = np.asarray({"feasible": dev_feasible, "score": dev_score,
                                "result": dev_result, "avail": dev_avail}[name])
                dense_cache[name] = a
            return a

        # Phase 2: spread-constrained rows restrict candidates via the host
        # combinatorial selection (SelectClusters, common.go:32-39), then the
        # assignment kernel re-runs over the restricted feasible set.
        from . import spread as spread_mod

        spread_errors: dict[int, str] = {}
        spread_rows: list[int] = []
        for b, rb in enumerate(bindings):
            placement = rb.spec.placement
            if (
                placement is not None
                and placement.spread_constraints
                and feas_count[b] > 0
                # statically-ignored constraints select every feasible cluster
                # (select_clusters.go:63-77) — the restriction re-run is a no-op
                and not spread_mod.should_ignore_spread_constraint(placement)
            ):
                spread_rows.append(b)
        # sparse decode state; spread-restricted rows overwrite their entries
        row_targets: dict[int, list[tuple[int, int]]] = {}
        row_feasible: dict[int, np.ndarray] = {}
        if spread_rows:
            feasible = dense("feasible")
            score = dense("score")
            avail = dense("avail")
            sub_affinity = raw.affinity_ok.copy()
            prev_dense = raw.prev_replicas  # dense view materialized once
            live_rows = []
            for b in spread_rows:
                rb = bindings[b]
                # array fast path: per-row lexsort + cumsum group scoring over
                # the kernel's rows — no per-cluster Python objects
                # (group_clusters.go:88-330 semantics, parity-tested against
                # the ClusterDetail implementation)
                feas = np.nonzero(feasible[b])[0]
                try:
                    selected_idx = spread_mod.select_by_spread_arrays(
                        feas,
                        score[b, feas],
                        avail[b, feas].astype(np.int64) + prev_dense[b, feas],
                        self._name_rank[feas],
                        self._region_id[feas],
                        self._region_names,
                        rb.spec.placement,
                        rb.spec.replicas,
                    )
                except spread_mod.SpreadError as e:
                    spread_errors[b] = str(e)
                    continue
                mask = np.zeros(len(self.fleet.names), bool)
                mask[selected_idx] = True
                sub_affinity[b] &= mask
                live_rows.append(b)
            if live_rows:
                sub = _restrict_rows(raw, live_rows, sub_affinity)
                sub_batch = self._pad(sub)
                sub_extra = None
                if extra_avail is not None:
                    sub_extra = extra_avail[live_rows]
                    pad = len(sub_batch.replicas) - len(sub_extra)
                    if pad:
                        sub_extra = np.pad(sub_extra, [(0, pad), (0, 0)], constant_values=-1)
                s_out = self.run_kernel(sub_batch, sub_extra)
                s_feas, s_result, s_unsched, s_avail_sum = jax.device_get(
                    (s_out[0], s_out[2], s_out[3], s_out[4])
                )
                for j, b in enumerate(live_rows):
                    row_feasible[b] = np.nonzero(s_feas[j])[0]
                    feas_count[b] = int(s_feas[j].sum())
                    pos = np.nonzero(s_result[j] > 0)[0]
                    row_targets[b] = [(int(i), int(s_result[j, i])) for i in pos]
                    unsched[b] = s_unsched[j]
                    avail_sum[b] = s_avail_sum[j]

        names = self.fleet.names
        C = len(names)
        # rows whose target set overflowed the top-K window fetch dense rows
        overflow = [
            b for b in range(len(raw.keys))
            if b not in row_targets and nnz[b] > top_idx.shape[1]
        ]
        # NON_WORKLOAD rows need the full feasible set as their target list
        nonwork = [
            b for b in range(len(raw.keys))
            if raw.strategy[b] == NON_WORKLOAD and b not in row_feasible
            and feas_count[b] > 0
        ]
        if overflow:
            result_dense = dense("result")
            for b in overflow:
                pos = np.nonzero(result_dense[b] > 0)[0]
                row_targets[b] = [(int(i), int(result_dense[b, i])) for i in pos]
        if nonwork:
            feasible_dense = dense("feasible")
            for b in nonwork:
                row_feasible[b] = np.nonzero(feasible_dense[b])[0]

        out_decisions: list[ScheduleDecision] = []
        for b, key in enumerate(raw.keys):
            dec = ScheduleDecision(key=key)
            if b in row_feasible:
                dec.feasible = [names[i] for i in row_feasible[b]]
            if b in spread_errors:
                dec.error = spread_errors[b]
                out_decisions.append(dec)
                continue
            if feas_count[b] == 0:
                # FitError diagnosis (generic_scheduler.go:83-88)
                dec.error = f"0/{C} clusters are available"
                out_decisions.append(dec)
                continue
            if unsched[b]:
                dec.error = (
                    f"Clusters available replicas {int(avail_sum[b])} are not "
                    "enough to schedule."
                )
                out_decisions.append(dec)
                continue
            if raw.strategy[b] == NON_WORKLOAD:
                feas_idx = row_feasible.get(b, np.empty(0, np.int64))
                dec.targets = [TargetCluster(name=names[i], replicas=0) for i in feas_idx]
            elif b in row_targets:
                dec.targets = [
                    TargetCluster(name=names[i], replicas=rep)
                    for i, rep in sorted(row_targets[b])
                ]
            else:
                # compact path: the top-K window holds every nonzero target
                n = int(nnz[b])
                pairs = sorted(
                    (int(top_idx[b, k]), int(top_val[b, k])) for k in range(n)
                )
                dec.targets = [
                    TargetCluster(name=names[i], replicas=rep) for i, rep in pairs
                ]
            out_decisions.append(dec)
        return out_decisions

"""Scheduler plugin registry: the `--plugins` enable/disable surface and the
out-of-tree extension seam.

TPU reframing of pkg/scheduler/framework (Framework/FilterPlugin/ScorePlugin
interface.go:45-212; Registry + Filter runtime/registry.go:30-103; the
`--plugins` flag semantics scheduler.go:254-258 / options.go:163-164): the
six in-tree plugins are FUSED [B,C] mask/score terms inside the jitted
filter kernel, so "enabling" a plugin here selects which terms the kernel
compiles in (a static specialization), and out-of-tree plugins contribute
host-computed [B,C] mask/score terms that ride into the solve as extra
inputs — the moral equivalent of the reference's out-of-tree registry merge
(scheduler.go:241-244).
"""
from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

# In-tree plugin names (plugins/registry.go:30-39).
API_ENABLEMENT = "APIEnablement"
TAINT_TOLERATION = "TaintToleration"
CLUSTER_AFFINITY = "ClusterAffinity"
SPREAD_CONSTRAINT = "SpreadConstraint"
CLUSTER_LOCALITY = "ClusterLocality"
CLUSTER_EVICTION = "ClusterEviction"
IN_TREE = (
    API_ENABLEMENT,
    TAINT_TOLERATION,
    CLUSTER_AFFINITY,
    SPREAD_CONSTRAINT,
    CLUSTER_LOCALITY,
    CLUSTER_EVICTION,
)

# static kernel bits for the fused in-tree terms. SpreadConstraint has no
# bit ON PURPOSE: in the reference the plugin is only the field-presence
# FILTER (spread_constraint.go:49); the selection algorithm itself runs in
# SelectClusters regardless of the plugin registry (core/common.go:32-39),
# and this build's selection already handles clusters without the spread
# field (they are regionless and never join a group) — so disabling the
# plugin is a faithful no-op here, exactly like the reference.
BIT_API = 1
BIT_TAINT = 2
BIT_AFFINITY = 4
BIT_EVICTION = 8
BIT_LOCALITY = 16
ALL_PLUGIN_BITS = BIT_API | BIT_TAINT | BIT_AFFINITY | BIT_EVICTION | BIT_LOCALITY
_BIT_OF = {
    API_ENABLEMENT: BIT_API,
    TAINT_TOLERATION: BIT_TAINT,
    CLUSTER_AFFINITY: BIT_AFFINITY,
    CLUSTER_EVICTION: BIT_EVICTION,
    CLUSTER_LOCALITY: BIT_LOCALITY,
}


def plugin_bits(enabled: Iterable[str]) -> int:
    bits = 0
    for name in enabled:
        bits |= _BIT_OF.get(name, 0)
    return bits


class FilterPlugin:
    """Out-of-tree filter seam (framework/interface.go:62-69): return a
    bool[B, C] feasibility mask for the round's bindings × clusters."""

    name = "filter"

    def mask(self, bindings: Sequence, cluster_names: Sequence[str]) -> np.ndarray:
        raise NotImplementedError


class ScorePlugin:
    """Out-of-tree score seam (framework/interface.go:183-194): return an
    i32[B, C] score term, summed with the in-tree scores
    (generic_scheduler.go:166-172 sums plugins)."""

    name = "score"

    def score(self, bindings: Sequence, cluster_names: Sequence[str]) -> np.ndarray:
        raise NotImplementedError


class PluginRegistry:
    """In-tree names + registered out-of-tree plugins, with the reference's
    Register/Unregister/Filter semantics (runtime/registry.go:38-103)."""

    def __init__(self) -> None:
        self._out_of_tree: dict[str, object] = {}

    def register(self, plugin) -> None:
        name = plugin.name
        if name in IN_TREE or name in self._out_of_tree:
            raise ValueError(f"a plugin named {name} already exists")
        self._out_of_tree[name] = plugin

    def unregister(self, name: str) -> None:
        if name not in self._out_of_tree:
            raise ValueError(f"no plugin named {name} exists")
        del self._out_of_tree[name]

    def factory_names(self) -> list[str]:
        return sorted((*IN_TREE, *self._out_of_tree))

    def filter(self, names: Optional[Sequence[str]]) -> set[str]:
        """registry.Filter(names): '*' enables everything, 'foo' enables
        foo, '-foo' disables foo (registry.go:73-103).

        Order quirks are REFERENCE-FAITHFUL, not accidents: a '-foo' that
        precedes every enable is skipped (registry.go:95 requires a
        non-empty result before deleting), and multiple leading dashes all
        strip (Go strings.TrimLeft(name, "-") == str.lstrip('-'))."""
        names = list(names) if names else ["*"]
        enabled: set[str] = set()
        all_names = set(self.factory_names())
        for name in names:
            if name == "*":
                enabled |= all_names
                break
        for name in names:
            if name in all_names:
                enabled.add(name)
                continue
            if name.startswith("-") and enabled:
                enabled.discard(name.lstrip("-"))
        return enabled

    def out_of_tree(self, enabled: set[str]) -> list:
        return [p for n, p in sorted(self._out_of_tree.items()) if n in enabled]

"""Workload-class scheduling: priority tiers, preemption, gang placement —
all as batched array solves (ROADMAP item 3, docs/SCHEDULING.md).

Three capabilities, one math:

- **Priority tiers as a segmented solve.** A micro-batch whose rows carry
  more than one `schedule_priority` solves as ONE device launch with
  tier-ordered capacity consumption: the kernel loops over the (statically
  padded) tier count, runs the standard `_schedule_body` program for every
  row, commits only the active tier's rows, subtracts their resource
  consumption from the capacity matrix, and hands the residual to the next
  tier. Bit-identical to solving the tiers as separate sequential rounds
  against capacity-decremented fleets (`solve_tiers_sequential` below is
  the executable contract; tests/test_preemption.py pins it on the
  single-chip and mesh legs) — but it stays one launch, so solves-per-tick
  is O(1) in the tier count.

- **Preemption as a second solve pass.** When a binding whose
  `preemption_policy` is PreemptLowerPriority places short, the planner
  builds a victim-augmented capacity matrix — placed replicas of
  strictly-lower-priority bindings become reclaimable capacity — and
  re-solves the whole preemptor batch once over [B, C] (one launch per
  distinct preemptor priority; usually one). Victim selection then
  minimizes disruption per cluster: fewest victims first (largest
  reclaimable cut within the lowest priority level), lowest priority
  first, youngest placement as the tie-break. The plan commits atomically:
  victim replica reductions (flowing through the existing
  graceful-eviction tasks) and the preemptor's placement in ONE rv-checked
  `update_batch` cohort — all or nothing.

- **Gang groups.** Bindings sharing `gang_name` co-admit as a cohort of
  `gang_size` members: the queue-side GangCoordinator (sched/queue.py)
  holds partial gangs until complete or a timeout rejects them, the solved
  cohort passes a joint all-K-fully-placed feasibility check, and the K
  placements commit in one all-or-nothing `update_batch` (scheduler.py
  `_patch_gang`) — a mid-cohort stale-epoch veto re-admits the whole gang.

Scope notes (documented limitations, all enforced in `wants_tiers`):
rows carrying spread constraints or ordered multi-term affinities are
host-driven searches and solve through the standard (unsegmented) path
inside a tiered batch — the tier residual models the array-path rows;
out-of-tree-plugin rounds never tier (stateful host hooks). Registered
estimator answers (`extra_avail`) are snapshot-constant across tiers: the
residual applies to the GeneralEstimator capacity bound, exactly as a
sequential replay between which no member state changed. Tiered and
preemption solves never enter the decision replay cache — their outputs
depend on batch composition, which the cache cannot key.
"""
from __future__ import annotations

import logging
from dataclasses import dataclass, field
from functools import partial
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api.policy import PREEMPT_LOWER_PRIORITY
from ..api.work import ResourceBinding, TargetCluster
from ..models.batch import AGGREGATED, DUPLICATED, NON_WORKLOAD, pow2_bucket
from ..models.fleet import to_int_units
from .core import (
    ArrayScheduler,
    ScheduleDecision,
    TOPK_TARGETS,
    _device_tie,
    _pad_extra_avail,
    _schedule_body,
    _sorted_pairs,
    compact_outputs,
    pad_batch,
)
from . import plugins as plugin_mod

log = logging.getLogger(__name__)


class _LaunchCounter:
    """Process-global tiered/preemption solve-launch counter — the
    acceptance seam for the one-launch invariants (a tiered micro-batch is
    ONE kernel dispatch regardless of tier count; a preemption pass is one
    per distinct preemptor priority)."""

    def __init__(self) -> None:
        self.tiered = 0
        self.preempt = 0


LAUNCHES = _LaunchCounter()


def priority_of(rb) -> int:
    return rb.spec.schedule_priority or 0


def gang_of(rb) -> str:
    """The binding's gang identity, or "" when it schedules solo (a gang
    of one is just a binding)."""
    if rb.spec.gang_name and (rb.spec.gang_size or 0) > 1:
        return rb.spec.gang_name
    return ""


def wants_tiers(array: ArrayScheduler, bindings: Sequence) -> bool:
    """Route a batch through the segmented tiered solve? Only when rows
    actually span more than one priority — a uniform batch is exactly the
    existing solve — and never under out-of-tree plugins (stateful host
    hooks; they also disable replay for the same reason)."""
    if len(bindings) < 2 or array._oot_plugins:
        return False
    it = iter(bindings)
    first = priority_of(next(it))
    return any(priority_of(rb) != first for rb in it)


# --------------------------------------------------------------------------
# the tiered kernel
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_tiers", "topk", "has_agg",
                                   "plugin_bits", "speculate"))
def _tiered_kernel(
    # fleet (device-resident; capacity may be a victim-augmented override)
    alive, capacity, has_summary, taint_key, taint_value, taint_effect, api_ok,
    tier_of,  # i32[B] tier index per row (0 = highest priority)
    # factored batch (models/batch.py BindingBatch, padded)
    replicas, unknown_request, gvk, strategy, fresh,
    tol_tables, tol_idx, aff_masks, aff_idx, weight_tables, weight_idx,
    prev_idx, prev_rep, evict_idx, seeds, req_unique, req_idx,
    extra_avail,  # i32[B,C] or [1,1] -1 sentinel
    request_dense,  # i64[B,R] per-replica requests (consumption accounting)
    reclaim,  # i64[n_tiers,C,R] reclaimable capacity per tier ([1,1,1]
    #   zeros sentinel when speculate is off)
    n_tiers: int = 1,
    topk: int = TOPK_TARGETS,
    has_agg: bool = True,
    plugin_bits: int = plugin_mod.ALL_PLUGIN_BITS,
    speculate: bool = False,
):
    """Decompress the factored batch ONCE, then run the schedule body once
    per tier with tier-ordered capacity consumption: tier t's committed
    rows subtract `placed_replicas x request` from the capacity matrix
    before tier t+1 solves. The Python loop unrolls inside the jit (n_tiers
    is static, padded to a pow2 bucket), so the whole segmented solve is
    ONE device launch. Feasibility is capacity-independent (alive / taints
    / api / affinity / eviction only), so it is computed once.

    `speculate` adds the preemption SECOND PASS to the same launch: every
    tier also solves over `cap + reclaim[t]` — the capacity that would
    exist if every strictly-lower-priority placed replica were evicted —
    WITHOUT registered-estimator answers (they cannot model victim-freed
    capacity, exactly like the standalone planner). A short placement's
    preemption plan then reads its augmented decision from this launch
    instead of paying a second one."""
    B = replicas.shape[0]
    C = alive.shape[0]
    rows = jnp.arange(B)[:, None]
    tol = tol_tables[tol_idx]  # [B,4,K]
    affinity_ok = aff_masks[aff_idx]
    static_weight = weight_tables[weight_idx]
    p = jnp.where((prev_idx >= 0) & (prev_idx < C), prev_idx, C)
    prev_member = jnp.zeros((B, C), bool).at[rows, p].set(True, mode="drop")
    prev_replicas = (
        jnp.zeros((B, C), jnp.int32).at[rows, p].set(prev_rep, mode="drop")
    )
    e = jnp.where((evict_idx >= 0) & (evict_idx < C), evict_idx, C)
    eviction_ok = jnp.ones((B, C), bool).at[rows, e].set(False, mode="drop")
    tie = _device_tie(seeds, C)
    extra = jnp.broadcast_to(extra_avail, (B, C))
    no_extra = jnp.broadcast_to(jnp.int32(-1), (B, C))

    def body(cap_t, extra_t):
        return _schedule_body(
            alive, cap_t, has_summary, taint_key, taint_value, taint_effect,
            api_ok,
            replicas, None, unknown_request, gvk, strategy, fresh,
            tol[:, 0], tol[:, 1], tol[:, 2], tol[:, 3],
            affinity_ok, eviction_ok, static_weight, prev_member,
            prev_replicas, tie, extra_t,
            narrow=False, has_agg=has_agg,
            req_unique=req_unique, req_idx=req_idx,
            plugin_bits=plugin_bits,
        )

    cap = capacity
    out_result = out_unsched = out_asum = feasible = None
    aug_result = aug_unsched = aug_asum = None
    for t in range(n_tiers):
        feas_t, _score, res_t, unsch_t, asum_t, _avail = body(cap, extra)
        m = tier_of == t
        # consumption counts only rows that PLACE: an unschedulable row's
        # partial dispenser output never commits (the decode answers an
        # error), so the sequential reference subtracts nothing for it —
        # the residual must match
        placed = jnp.where((m & ~unsch_t)[:, None], res_t, 0)
        if feasible is None:
            feasible = feas_t
            out_result = placed
            out_unsched = m & unsch_t
            out_asum = jnp.where(m, asum_t, 0)
        else:
            out_result = jnp.where(m[:, None], res_t, out_result)
            out_unsched = jnp.where(m, unsch_t, out_unsched)
            out_asum = jnp.where(m, asum_t, out_asum)
        if speculate:
            _f, _s, ares_t, aunsch_t, aasum_t, _a = body(
                cap + reclaim[t], no_extra,
            )
            if aug_result is None:
                aug_result = jnp.where(m[:, None], ares_t, 0)
                aug_unsched = m & aunsch_t
                aug_asum = jnp.where(m, aasum_t, 0)
            else:
                aug_result = jnp.where(m[:, None], ares_t, aug_result)
                aug_unsched = jnp.where(m, aunsch_t, aug_unsched)
                aug_asum = jnp.where(m, aasum_t, aug_asum)
        if t + 1 < n_tiers:
            cons = placed.astype(jnp.int64).T @ request_dense  # [C,R]
            cap = jnp.maximum(cap - cons, 0)
    feas_count, nnz, top_idx, top_val = compact_outputs(
        feasible, out_result, topk
    )
    out = (out_unsched, out_asum, feas_count, nnz, top_idx, top_val,
           out_result)
    if speculate:
        _fc, aug_nnz, aug_idx, aug_val = compact_outputs(
            feasible, aug_result, topk
        )
        out += (aug_unsched, aug_asum, aug_nnz, aug_idx, aug_val, aug_result)
    return out


def _batch_static_flags(raw, n_cols: int) -> tuple[int, bool]:
    """(topk, has_agg) for a raw (unpadded) batch. Unlike the main solve's
    content-derived window (ArrayScheduler._batch_flags), topk here is
    pinned to the FLEET width bucket: tiered batches mix re-admitted
    victims with fresh preemptors, so a content-derived bound flips its
    bucket as victim replica counts drift — every flip a fresh XLA
    compile in the middle of a preemption wave (bench-surfaced). The
    fixed window costs a slightly larger device→host transfer on small
    batches and keeps the steady state at zero compiles; rows wider than
    the window still fall back to a dense row fetch."""
    topk = min(pow2_bucket(max(n_cols, 1), lo=8), TOPK_TARGETS)
    return max(topk, 1), bool((raw.strategy == AGGREGATED).any())


def _tier_assignment(bindings: Sequence) -> tuple[np.ndarray, int]:
    """tier_of[i] per row (0 = highest priority) + the tier count padded to
    a pow2 bucket so the jit cache stays bounded; pad tiers have no rows
    and are no-ops (empty commit mask, zero consumption)."""
    prios = np.asarray([priority_of(rb) for rb in bindings], np.int64)
    uniq = np.unique(prios)[::-1]  # descending: tier 0 = highest
    tier_of = np.searchsorted(-uniq, -prios).astype(np.int32)
    return tier_of, int(pow2_bucket(len(uniq), lo=1))


def _eligible_rows(bindings: Sequence) -> tuple[list[int], list[int]]:
    """Split a batch into tiered-kernel rows and standard-path rows (spread
    constraints / ordered multi-term affinities are host-driven searches
    the dense kernel does not cover — same partition the simulation engine
    applies)."""
    kernel_rows, std_rows = [], []
    for i, rb in enumerate(bindings):
        p = rb.spec.placement
        if p is not None and (p.spread_constraints or p.cluster_affinities):
            std_rows.append(i)
        else:
            kernel_rows.append(i)
    return kernel_rows, std_rows


_NO_RECLAIM = np.zeros((1, 1, 1), np.int64)


def _launch_kernel_rows(array: ArrayScheduler, bindings: list,
                        extra_avail, capacity_override=None,
                        reclaim_tiers=None,
                        count: str = "tiered") -> dict:
    """Encode + dispatch the tiered kernel for kernel-eligible rows; the
    returned state feeds `_materialize_kernel_rows`. No device sync here —
    the pipelined caller materializes on the writer thread. With
    `reclaim_tiers` (i64[n_tiers,C,R]) the launch also solves the
    speculative victim-augmented pass in the same dispatch."""
    with array._encode_lock:
        raw = array.batch_encoder.encode(bindings)
    batch = pad_batch(raw, array._bucket)
    C = len(array.fleet.names)
    tier_of, n_tiers = _tier_assignment(bindings)
    tier_pad = np.zeros(len(batch.replicas), np.int32)
    tier_pad[: len(bindings)] = tier_of
    if extra_avail is not None:
        extra_np = _pad_extra_avail(
            np.asarray(extra_avail, np.int32), C, len(batch.replicas)
        )
    else:
        extra_np = ArrayScheduler._NO_EXTRA
    topk, has_agg = _batch_static_flags(raw, C)
    topk = min(topk, max(C, 1))
    fleet_dev = array._fleet_dev
    if capacity_override is not None:
        fleet_dev = (
            fleet_dev[0], jnp.asarray(capacity_override, jnp.int64),
            *fleet_dev[2:],
        )
    speculate = reclaim_tiers is not None
    kernel_args = (
        *fleet_dev, tier_pad,
        batch.replicas, batch.unknown_request, batch.gvk, batch.strategy,
        batch.fresh, batch.tol_tables, batch.tol_idx, batch.aff_masks,
        batch.aff_idx, batch.weight_tables, batch.weight_idx,
        batch.prev_idx, batch.prev_rep, batch.evict_idx, batch.seeds,
        batch.req_unique, batch.req_idx,
        extra_np, np.asarray(batch.request, np.int64),
        reclaim_tiers if speculate else _NO_RECLAIM,
    )
    # top-K candidate sparsification (sched/candidates.py): wide fleets run
    # the compact tiered kernel — same tier/consumption/speculation
    # semantics over [B, K] candidate windows; k=0 means dense (narrow
    # fleet, disabled, or a duplicated row whose target set must never
    # truncate)
    from . import candidates as cand_mod

    cand_k = cand_mod.tiered_k(array, raw, C)
    cand_dev = None
    if cand_k:
        out = cand_mod._tiered_candidate_kernel(
            *kernel_args,
            n_tiers=n_tiers, k=cand_k, topk=topk, has_agg=has_agg,
            plugin_bits=array._plugin_bits, speculate=speculate,
        )
        cand_dev = out[-1]
    else:
        if cand_mod.compact_width_ok(array):
            cand_mod.note_fallback("duplicated")
        elif getattr(array, "candidate_k", 0):
            cand_mod.note_fallback("small_fleet")
        out = _tiered_kernel(
            *kernel_args,
            n_tiers=n_tiers, topk=topk, has_agg=has_agg,
            plugin_bits=array._plugin_bits,
            speculate=speculate,
        )
    if count == "tiered":
        LAUNCHES.tiered += 1
    else:
        LAUNCHES.preempt += 1
    return {"raw": raw, "out": out, "n": len(bindings),
            "names": array.fleet.names, "n_tiers": n_tiers,
            "speculate": speculate, "cand_dev": cand_dev}


def _decode_rows(raw, names, real, rows_j, unsched, asum, feas_count, nnz,
                 tis, tvs, window, result_dev, cand_dev=None) -> dict:
    """Decode a set of kernel rows into ScheduleDecisions (the simulation
    engine's decode, single-scenario): compact top-K pairs, unschedulable/
    empty-feasible errors in the live solver's vocabulary, dense-row fetch
    for rows whose target set overflows the window. With `cand_dev`
    (compact tiered kernel), result columns are candidate-window LOCAL and
    the overflow fetch maps them to global cluster ids through the
    per-row candidate index."""
    decisions: dict[int, ScheduleDecision] = {}
    overflow: list[tuple[int, ScheduleDecision]] = []
    for j in rows_j:
        key = raw.keys[j]
        strat = int(raw.strategy[j])
        if feas_count[j] == 0:
            decisions[j] = ScheduleDecision(
                key, error=f"0/{real} clusters are available",
            )
        elif unsched[j]:
            decisions[j] = ScheduleDecision(
                key,
                error=(f"Clusters available replicas {int(asum[j])} are "
                       "not enough to schedule."),
            )
        elif strat == NON_WORKLOAD:
            decisions[j] = ScheduleDecision(key, targets=[])
        elif int(nnz[j]) > window:
            dec = ScheduleDecision(key)
            decisions[j] = dec
            overflow.append((j, dec))
        else:
            k = int(nnz[j])
            decisions[j] = ScheduleDecision(key, targets=[
                TargetCluster(name=names[int(tis[j, t])],
                              replicas=int(tvs[j, t]))
                for t in range(k)
            ])
    if overflow:
        rows = np.asarray([j for j, _ in overflow])
        if cand_dev is None:
            dense = np.asarray(jax.device_get(result_dev[rows]))
            cand = None
        else:
            dense, cand = (
                np.asarray(a)
                for a in jax.device_get((result_dev[rows], cand_dev[rows]))
            )
        for m, (_, dec) in enumerate(overflow):
            pos = np.nonzero(dense[m] > 0)[0]
            ids = pos if cand is None else cand[m, pos]
            dec.targets = [
                TargetCluster(name=names[int(i)], replicas=int(dense[m, p]))
                for i, p in zip(ids, pos)
            ]
    return decisions


def _materialize_kernel_rows(state: dict,
                             armed: Sequence[int] = ()
                             ) -> list[ScheduleDecision]:
    """Sync + decode the tiered kernel outputs. With a speculative launch,
    `armed` rows also decode their victim-augmented decision onto
    `decision.speculative` — the preemption pass reads it from there
    instead of launching a second solve."""
    raw, n, names = state["raw"], state["n"], state["names"]
    speculate = state.get("speculate", False)
    host = [np.asarray(a)
            for a in jax.device_get(state["out"][:6] + (
                state["out"][7:12] if speculate else ()))]
    (unsched, asum, feas_count, nnz, top_idx, top_val) = host[:6]
    result_dev = state["out"][6]
    tis, tvs = _sorted_pairs(top_idx[:n], top_val[:n])
    window = top_idx.shape[1]
    real = sum(1 for nm in names if not nm.startswith("__shape-pad-"))
    cand_dev = state.get("cand_dev")
    decoded = _decode_rows(
        raw, names, real, range(n), unsched, asum, feas_count, nnz,
        tis, tvs, window, result_dev, cand_dev=cand_dev,
    )
    decisions = [decoded[j] for j in range(n)]
    if speculate and armed:
        (a_unsched, a_asum, a_nnz, a_idx, a_val) = host[6:11]
        a_tis, a_tvs = _sorted_pairs(a_idx[:n], a_val[:n])
        aug = _decode_rows(
            raw, names, real, list(armed), a_unsched, a_asum, feas_count,
            a_nnz, a_tis, a_tvs, a_idx.shape[1], state["out"][12],
            cand_dev=cand_dev,
        )
        for j, dec in aug.items():
            decisions[j].speculative = dec
    return decisions


def armed_for_preemption(rb) -> bool:
    """Does this row want the speculative victim-augmented second pass?
    PreemptLowerPriority, non-gang (cutting into a gang's cohort would
    break its all-or-nothing contract, and a gang preemptor commits whole
    or not at all — out of scope, documented)."""
    return (rb.spec.preemption_policy == PREEMPT_LOWER_PRIORITY
            and not gang_of(rb))


# armed-row speculation cap: a handful of preemption-armed rows must not
# drag a HUGE uniform-priority chunk off the partitioned solve path (which
# has the host-sort twins and the replay cache) — past this row count the
# chunk solves normally and a short-placed preemptor falls back to the
# standalone planner's launch (one extra solve per preemption, correct
# either way). Mixed-priority chunks always tier: the residual semantics
# require the segmented launch regardless of size.
SPECULATE_MAX_ROWS = 512


def wants_workload_solve(array: ArrayScheduler, bindings: Sequence,
                         preemption: bool = True) -> bool:
    """Route a batch through the workload-class launch? Mixed priorities
    (the segmented tiered solve) or any preemption-armed row (the
    speculative second pass rides the same launch, bounded by
    SPECULATE_MAX_ROWS). Never under out-of-tree plugins (stateful host
    hooks)."""
    if not bindings or array._oot_plugins:
        return False
    if (preemption and len(bindings) <= SPECULATE_MAX_ROWS
            and any(armed_for_preemption(rb) for rb in bindings)):
        return True
    return wants_tiers(array, bindings)


def _tier_reclaim(array: ArrayScheduler, bindings: list, placed) -> tuple:
    """(reclaim i64[n_tiers,C,R], armed row indices) for a speculative
    launch: per tier carrying an armed row, every strictly-lower-priority
    placed replica's request folds into that tier's reclaimable matrix.
    Tiers without armed rows stay zero (nothing reads their pass)."""
    armed = [i for i, rb in enumerate(bindings)
             if armed_for_preemption(rb)]
    if not armed or placed is None:
        return None, armed
    resources = array.encoder.resources
    names = array.fleet.names
    col_of = {nm: c for c, nm in enumerate(names)}
    tier_of, n_tiers = _tier_assignment(bindings)
    C, R = len(names), len(resources)
    reclaim = np.zeros((n_tiers, C, R), np.int64)
    for t in sorted({int(tier_of[i]) for i in armed}):
        row = next(i for i in armed if tier_of[i] == t)
        for rb in victim_candidates(placed, bindings[row]):
            units = _request_units(rb, resources)
            for tc in rb.spec.clusters:
                c = col_of.get(tc.name)
                if c is not None and tc.replicas > 0:
                    reclaim[t, c] += units * tc.replicas
    return reclaim, armed


def launch_tiered(array: ArrayScheduler, bindings: Sequence,
                  extra_avail=None, placed=None) -> dict:
    """Launch one workload-class batch — drop-in for
    `ArrayScheduler.launch_chunk` (the pending dict rides the same
    materialize seam; `materialize_chunk` dispatches on the "tiered"
    marker). Mixed priorities solve as the segmented tiered pass;
    preemption-armed rows additionally solve their victim-augmented
    variant in the SAME launch (`placed` is the victim-candidate
    snapshot). Spread/multi-term rows take the standard path inside the
    same pending; tiered decisions never enter the replay cache (their
    outputs depend on batch composition)."""
    bindings = list(bindings)
    kernel_rows, std_rows = _eligible_rows(bindings)
    state = std_state = None
    armed: list[int] = []
    if kernel_rows:
        krows = [bindings[i] for i in kernel_rows]
        sub_extra = (None if extra_avail is None
                     else np.asarray(extra_avail)[kernel_rows])
        reclaim, armed = _tier_reclaim(array, krows, placed)
        state = _launch_kernel_rows(
            array, krows, sub_extra, reclaim_tiers=reclaim,
        )
    if std_rows:
        sub_extra = (None if extra_avail is None
                     else np.asarray(extra_avail)[std_rows])
        std_state = array._launch_solve([bindings[i] for i in std_rows],
                                        sub_extra)
    return {
        "tiered": True, "bindings": bindings,
        "kernel_rows": kernel_rows, "std_rows": std_rows,
        "state": state, "std_state": std_state, "armed": armed,
        "replayed": 0, "solved": len(bindings),
        "n_tiers": state["n_tiers"] if state else 1,
    }


def materialize_tiered(array: ArrayScheduler,
                       pending: dict) -> list[ScheduleDecision]:
    out: list[Optional[ScheduleDecision]] = [None] * len(pending["bindings"])
    if pending["state"] is not None:
        for i, dec in zip(
            pending["kernel_rows"],
            _materialize_kernel_rows(pending["state"],
                                     armed=pending.get("armed", ())),
        ):
            out[i] = dec
    if pending["std_state"] is not None:
        for i, dec in zip(pending["std_rows"],
                          array._materialize_solve(pending["std_state"])):
            out[i] = dec
    return out


# --------------------------------------------------------------------------
# the sequential reference (the executable parity contract)
# --------------------------------------------------------------------------


def solve_tiers_sequential(clusters: Sequence, bindings: Sequence,
                           ) -> list[ScheduleDecision]:
    """THE contract the tiered kernel is pinned against: solve each
    priority tier (descending) as its own cold ArrayScheduler round on a
    fleet whose allocated capacity has grown by every higher tier's placed
    consumption — exactly what running the tiers as separate sequential
    rounds against refreshed summaries would do. O(tiers) launches and a
    fleet re-encode per tier; exists for tests and documentation, never on
    a hot path."""
    import copy

    bindings = list(bindings)
    decisions: list[Optional[ScheduleDecision]] = [None] * len(bindings)
    cur = [copy.deepcopy(c) for c in clusters]
    prios = sorted({priority_of(rb) for rb in bindings}, reverse=True)
    for prio in prios:
        rows = [i for i, rb in enumerate(bindings)
                if priority_of(rb) == prio]
        sched = ArrayScheduler(cur)
        for i, dec in zip(rows, sched.schedule([bindings[i] for i in rows])):
            decisions[i] = dec
        # decrement: this tier's placements enter `allocated`, so the next
        # tier's capacity = allocatable - allocated shrinks exactly as the
        # kernel's consumption subtraction does
        by_name = {c.name: c for c in cur}
        for i in rows:
            dec = decisions[i]
            rb = bindings[i]
            if not dec.ok or not dec.targets:
                continue
            rr = rb.spec.replica_requirements
            req = rr.resource_request if rr is not None else {}
            for tc in dec.targets:
                c = by_name.get(tc.name)
                if c is None or c.status.resource_summary is None:
                    continue
                rs = c.status.resource_summary
                for rname, val in req.items():
                    rs.allocated[rname] = (
                        rs.allocated.get(rname, 0.0) + val * tc.replicas
                    )
    return decisions


# --------------------------------------------------------------------------
# preemption: plan / commit / preview
# --------------------------------------------------------------------------


@dataclass
class VictimCut:
    """One victim replica reduction: `replicas` reclaimed from `cluster`."""

    key: str  # victim binding namespace/name
    cluster: str
    replicas: int
    priority: int = 0


@dataclass
class PreemptionPlan:
    key: str  # preemptor binding namespace/name
    priority: int = 0
    feasible: bool = False
    error: str = ""
    targets: list[TargetCluster] = field(default_factory=list)
    victims: list[VictimCut] = field(default_factory=list)

    def victim_keys(self) -> list[str]:
        seen: list[str] = []
        for v in self.victims:
            if v.key not in seen:
                seen.append(v.key)
        return seen


def victim_candidates(bindings: Sequence, preemptor) -> list:
    """Placed bindings the preemptor may evict from: strictly lower
    priority, same scheduler, not suspended/deleting, and not gang members
    (cutting one member would break its gang's all-or-nothing contract)."""
    prio = priority_of(preemptor)
    sched_name = preemptor.spec.scheduler_name or ""
    out = []
    for rb in bindings:
        if rb.metadata.key() == preemptor.metadata.key():
            continue
        if priority_of(rb) >= prio:
            continue
        if not rb.spec.clusters:
            continue
        if (rb.spec.scheduler_name or "") != sched_name:
            continue
        if rb.metadata.deletion_timestamp is not None:
            continue
        if rb.spec.scheduling_suspended() or gang_of(rb):
            continue
        out.append(rb)
    return out


# request-unit vectors memoized per (uid, generation, resource vocab):
# candidate sets are stable across preemption waves, and rebuilding a few
# hundred tiny arrays per plan was measurable host time on the decision
# path. Bounded — cleared wholesale when it outgrows the working set.
_UNITS_MEMO: dict = {}


def _request_units(rb, resources: Sequence[str]) -> np.ndarray:
    """Per-replica request in the fleet's integer units (cpu milli), zero
    for resources outside the vocabulary — the same conversion the fleet
    encoder applies to summaries."""
    key = (rb.metadata.uid, rb.metadata.generation, len(resources))
    hit = _UNITS_MEMO.get(key) if rb.metadata.uid else None
    if hit is not None:
        return hit
    req = np.zeros(len(resources), np.int64)
    rr = rb.spec.replica_requirements
    if rr is not None:
        for rname, val in rr.resource_request.items():
            try:
                r = resources.index(rname)
            except ValueError:
                continue
            req[r] = to_int_units(rname, val)
    if rb.metadata.uid:
        if len(_UNITS_MEMO) > 16384:
            _UNITS_MEMO.clear()
        _UNITS_MEMO[key] = req
    return req


def plan_preemption(array: ArrayScheduler, placed: Sequence,
                    preemptors: Sequence,
                    ledger: Optional[PlanLedger] = None,
                    ) -> list[PreemptionPlan]:
    """Second solve pass for a batch of short-placed preemptors: ONE
    victim-augmented [B, C] launch per distinct preemptor priority, then
    host-side minimal-disruption victim selection. Pure — reads the fleet
    encoding and the supplied binding snapshots, mutates nothing; the
    caller owns the atomic commit (and POST /simulate's preview calls this
    exact function, which is what makes the previewed victim set identical
    to the live one)."""
    resources = array.encoder.resources
    names = array.fleet.names
    col_of = {nm: c for c, nm in enumerate(names)}
    if ledger is None:
        ledger = PlanLedger(np.asarray(array.fleet.capacity, np.int64))
    plans: list[PreemptionPlan] = []
    by_prio: dict[int, list] = {}
    for rb in preemptors:
        by_prio.setdefault(priority_of(rb), []).append(rb)
    for prio in sorted(by_prio, reverse=True):
        group = by_prio[prio]
        cands = victim_candidates(placed, group[0])
        plans.extend(_plan_priority_group(
            array, group, cands, prio, resources, names, col_of, ledger,
        ))
    return plans


def _plan_priority_group(array, group, cands, prio, resources, names,
                         col_of, ledger=None) -> list[PreemptionPlan]:
    C = len(names)
    R = len(resources)
    if not cands:
        return [PreemptionPlan(
            key=rb.metadata.key(), priority=prio,
            error="no lower-priority replicas to reclaim",
        ) for rb in group]
    # reclaimable capacity: every strictly-lower-priority placed replica's
    # request, folded per cluster
    reclaim = np.zeros((C, R), np.int64)
    for rb in cands:
        units = _request_units(rb, resources)
        for tc in rb.spec.clusters:
            c = col_of.get(tc.name)
            if c is not None and tc.replicas > 0:
                reclaim[c] += units * tc.replicas
    capacity = np.asarray(array.fleet.capacity, np.int64) + reclaim
    state = _launch_kernel_rows(array, list(group), None,
                                capacity_override=capacity, count="preempt")
    decisions = _materialize_kernel_rows(state)
    return _plans_from_decisions(array, group, decisions, cands, prio,
                                 resources, names, col_of, ledger=ledger)


class PlanLedger:
    """Cross-group planning accounting: one preemption pass may plan
    several priority groups (and mix the speculative and standalone
    paths), and each group's victim selection must see the free capacity
    and victim replicas EARLIER groups already claimed — without this,
    two preemptors in one batch each count the same free units / the same
    reclaimable victim as covering their own deficit, and the joint
    commit overcommits the cluster (review-surfaced)."""

    def __init__(self, free: np.ndarray):
        self.free_left = np.maximum(np.asarray(free, np.int64), 0).copy()
        self.victim_cut: dict[tuple[str, int], int] = {}

    def cut_so_far(self, key: str, c: int) -> int:
        return self.victim_cut.get((key, int(c)), 0)

    def note_cut(self, key: str, c: int, replicas: int) -> None:
        k = (key, int(c))
        self.victim_cut[k] = self.victim_cut.get(k, 0) + replicas


def _plans_from_decisions(array, group, decisions, cands, prio, resources,
                          names, col_of,
                          ledger: Optional[PlanLedger] = None,
                          ) -> list[PreemptionPlan]:
    """The host half of a preemption plan: victim selection for a group of
    SOLVED augmented decisions — shared verbatim by the standalone planner
    (plan_preemption, which the preview uses) and the speculative in-launch
    path (plan_from_speculative), so the two can never select different
    victims for the same solve. Deficits accumulate per cluster so two
    preemptors landing on one cluster select a joint victim set; `ledger`
    carries the accounting ACROSS groups within one pass."""
    C = len(names)
    R = len(resources)
    cand_units = {
        rb.metadata.key(): _request_units(rb, resources) for rb in cands
    }
    if ledger is None:
        ledger = PlanLedger(np.asarray(array.fleet.capacity, np.int64))
    deficit = np.zeros((C, R), np.int64)
    plans = []
    for rb, dec in zip(group, decisions):
        plan = PreemptionPlan(key=rb.metadata.key(), priority=prio)
        if dec is None or not dec.ok:
            plan.error = (dec.error if dec is not None else "") \
                or "preemption solve placed short"
            plans.append(plan)
            continue
        plan.feasible = True
        plan.targets = list(dec.targets or [])
        units = _request_units(rb, resources)
        for tc in plan.targets:
            c = col_of.get(tc.name)
            if c is not None:
                deficit[c] += units * tc.replicas
        plans.append(plan)
    need = np.maximum(deficit - ledger.free_left, 0)
    # this group's placements consume the free units first; later groups
    # see only the remainder
    ledger.free_left = np.maximum(ledger.free_left - deficit, 0)
    victims = _select_victims(need, cands, cand_units, col_of, names,
                              ledger=ledger)
    feasible_plans = [p for p in plans if p.feasible]
    if victims is None:
        # the greedy could not cover the deficit (a candidate vanished
        # between snapshot and plan): the plans are not safely committable
        for p in feasible_plans:
            p.feasible = False
            p.error = "victim selection could not cover the deficit"
        return plans
    for p in feasible_plans:
        p.victims = victims
    return plans


def plan_from_speculative(array, placed, pairs,
                          ledger: Optional[PlanLedger] = None,
                          ) -> list[PreemptionPlan]:
    """Preemption plans for rows whose victim-augmented decision already
    rode the admission launch (decision.speculative): ZERO extra solves —
    only the host victim-selection half runs. `pairs` is
    [(binding, speculative_decision), ...]."""
    resources = array.encoder.resources
    names = array.fleet.names
    col_of = {nm: c for c, nm in enumerate(names)}
    if ledger is None:
        ledger = PlanLedger(np.asarray(array.fleet.capacity, np.int64))
    by_prio: dict[int, list] = {}
    for rb, dec in pairs:
        by_prio.setdefault(priority_of(rb), []).append((rb, dec))
    plans: list[PreemptionPlan] = []
    for prio in sorted(by_prio, reverse=True):
        group = by_prio[prio]
        cands = victim_candidates(placed, group[0][0])
        if not cands:
            plans.extend(PreemptionPlan(
                key=rb.metadata.key(), priority=prio,
                error="no lower-priority replicas to reclaim",
            ) for rb, _d in group)
            continue
        plans.extend(_plans_from_decisions(
            array, [rb for rb, _d in group], [d for _rb, d in group],
            cands, prio, resources, names, col_of, ledger=ledger,
        ))
    return plans


def _select_victims(need: np.ndarray, cands, cand_units, col_of,
                    names, ledger: Optional[PlanLedger] = None,
                    ) -> Optional[list[VictimCut]]:
    """Minimal-disruption greedy per cluster: iterate candidate priorities
    ascending (lowest first); within a priority take the candidate
    covering the most deficit first (fewest victims), youngest placement
    as the tie-break; cut only as many replicas as the deficit requires
    (partial reductions, not whole evictions). Deterministic: final
    tie-break is the binding key.

    Candidate features are assembled as flat arrays once and ordered with
    one lexsort per cluster — per-candidate numpy calls inside a sort key
    were the planner's host hot spot (bench-surfaced)."""
    cuts: list[VictimCut] = []
    deficit_cols = np.nonzero(need.any(axis=1))[0]
    if not len(deficit_cols):
        return cuts
    # candidate features, one pass: replicas-on-cluster per deficit col
    n = len(cands)
    prio = np.fromiter((priority_of(rb) for rb in cands), np.int64, n)
    age = np.fromiter(
        ((rb.status.last_scheduled_time or 0.0) for rb in cands),
        np.float64, n,
    )
    units_mat = np.stack([cand_units[rb.metadata.key()] for rb in cands]) \
        if n else np.zeros((0, need.shape[1]), np.int64)
    keys = [rb.metadata.key() for rb in cands]
    key_rank = np.argsort(np.argsort(keys))
    on_cluster = np.zeros((n, len(deficit_cols)), np.int64)
    col_pos = {int(c): i for i, c in enumerate(deficit_cols)}
    for i, rb in enumerate(cands):
        for tc in rb.spec.clusters:
            p = col_pos.get(col_of.get(tc.name, -1))
            if p is not None:
                on_cluster[i, p] = tc.replicas
    for p, c in enumerate(deficit_cols):
        rem = need[c].copy()
        on_c = on_cluster[:, p]
        helps = (on_c > 0) & ((units_mat > 0) & (rem[None, :] > 0)).any(1)
        idx = np.nonzero(helps)[0]
        if len(idx):
            cover = np.minimum(
                units_mat[idx] * on_c[idx, None], rem[None, :]
            ).sum(1)
            # order: priority asc, coverage desc, youngest first, key asc
            order = np.lexsort((key_rank[idx], -age[idx], -cover,
                                prio[idx]))
            for i in idx[order]:
                if not (rem > 0).any():
                    break
                units = units_mat[i]
                sel = (units > 0) & (rem > 0)
                if not sel.any():
                    continue
                # minimal cut covering the remaining deficit this victim
                # can address, capped by its replicas on the cluster MINUS
                # whatever an earlier group in this pass already claimed
                avail = int(on_c[i])
                if ledger is not None:
                    avail -= ledger.cut_so_far(keys[i], int(c))
                cut = int(min(avail, int(
                    -(-rem[sel] // units[sel]).max()
                )))
                if cut <= 0:
                    continue
                rem = np.maximum(rem - units * cut, 0)
                if ledger is not None:
                    ledger.note_cut(keys[i], int(c), cut)
                cuts.append(VictimCut(
                    key=keys[i], cluster=names[int(c)], replicas=cut,
                    priority=int(prio[i]),
                ))
        if (rem > 0).any():
            return None
    return cuts


def preview_preemption(clusters: Sequence, bindings: Sequence,
                       preemptor) -> PreemptionPlan:
    """POST /simulate's preemption preview: plan against a fresh fleet
    encoding of the same snapshot the live planner would see — identical
    victim set by construction (shared plan_preemption), zero store
    mutation. `preemptor` is an existing (typically pending) binding; its
    current placement, if any, is ignored (the plan answers 'where would
    it land and who pays')."""
    import copy

    pre = copy.deepcopy(preemptor)
    pre.spec.clusters = []
    array = ArrayScheduler(sorted(clusters, key=lambda c: c.name))
    placed = [rb for rb in bindings
              if rb.metadata.key() != pre.metadata.key()]
    plans = plan_preemption(array, placed, [pre])
    return plans[0]

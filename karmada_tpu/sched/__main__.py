"""Scheduler daemon: `python -m karmada_tpu.sched --server URL`.

The reference's cmd/scheduler binary as its own OS process — and the
north-star deployment shape: the process that owns the accelerator runs
the batched [B,C] solve, attached to a scheduler-less control plane
(`python -m karmada_tpu.server --controllers "*,-scheduler"`) over the
serving API. ResourceBinding/Cluster watches stream in over HTTP
(RemoteStore), scheduling results patch back the same way; optional
per-cluster scheduler-estimators are reached over the wire-compatible
gRPC client.

Example:
    python -m karmada_tpu.server --controllers "*,-scheduler" &
    python -m karmada_tpu.sched --server http://127.0.0.1:<port> \\
        --estimator m1=127.0.0.1:10352
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.sched")
    ap.add_argument("--server", required=True,
                    help="control-plane URL (http:// or https://)")
    ap.add_argument("--estimator", action="append", default=[],
                    metavar="CLUSTER=HOST:PORT",
                    help="scheduler-estimator gRPC address per member "
                         "cluster; repeatable")
    ap.add_argument("--plugins", default="*",
                    help="reference --plugins semantics (enable/disable "
                         "filter and score plugins)")
    ap.add_argument("--scheduler-name", default="default-scheduler")
    ap.add_argument("--interval", type=float, default=0.2,
                    help="seconds between queue drains")
    ap.add_argument("--platform", default="",
                    help="pin the jax platform (e.g. cpu); default = the "
                         "ambient backend (TPU where available)")
    ap.add_argument("--bearer-token", default="")
    ap.add_argument("--cacert", default="")
    args = ap.parse_args()

    if args.platform == "cpu":
        from ..testing.cpumesh import force_cpu_mesh

        force_cpu_mesh(1)
    elif args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from ..estimator.client import EstimatorRegistry, parse_estimator_flags
    from ..runtime.controller import Runtime
    from ..server.remote import RemoteStore
    from .scheduler import SchedulerDaemon

    addresses = parse_estimator_flags(args.estimator)
    registry = None
    if addresses:
        from ..estimator.service import GrpcSchedulerEstimator

        registry = EstimatorRegistry()
        registry.register_replica_estimator(
            "scheduler-estimator", GrpcSchedulerEstimator(addresses.get)
        )

    store = RemoteStore(
        args.server,
        token=args.bearer_token or os.environ.get("KARMADA_TOKEN") or None,
        cafile=args.cacert or os.environ.get("KARMADA_CACERT") or None,
    )
    runtime = Runtime()
    plugins = [p.strip() for p in args.plugins.split(",") if p.strip()]
    SchedulerDaemon(
        store, runtime, scheduler_name=args.scheduler_name,
        estimator_registry=registry, plugins=plugins,
    )
    print(f"karmada-tpu scheduler attached to {args.server}", flush=True)
    try:
        while True:
            try:
                runtime.settle()
            except Exception:  # noqa: BLE001 - survive transient plane errors
                import logging

                logging.getLogger(__name__).exception("scheduling drain")
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        store.close()


if __name__ == "__main__":
    sys.exit(main())

"""Scheduler daemon: `python -m karmada_tpu.sched --server URL`.

The reference's cmd/scheduler binary as its own OS process — and the
north-star deployment shape: the process that owns the accelerator runs
the batched [B,C] solve, attached to a scheduler-less control plane
(`python -m karmada_tpu.server --controllers "*,-scheduler"`) over the
serving API. ResourceBinding/Cluster watches stream in over HTTP
(RemoteStore), scheduling results patch back the same way; optional
per-cluster scheduler-estimators are reached over the wire-compatible
gRPC client.

Leader election (reference: scheduler.go:33-34,188 — the binary refuses to
schedule until it holds the lock): every instance competes for the
`karmada-scheduler` LeaderLease; only the leader drains the queue and
patches placements, and its writes carry the lease's fencing token so a
deposed leader's in-flight patches bounce with 409. Non-leaders run HOT:
watches attached, fleet encoders built, jit cache primed by a dry solve —
takeover happens within one lease TTL with no cold-start.

Example (HA pair):
    python -m karmada_tpu.server --controllers "*,-scheduler" &
    python -m karmada_tpu.sched --server http://127.0.0.1:<port> &
    python -m karmada_tpu.sched --server http://127.0.0.1:<port> &
"""
from __future__ import annotations

import argparse
import os
import sys
import threading
import time


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.sched")
    ap.add_argument("--server", required=True,
                    help="control-plane URL (http:// or https://)")
    ap.add_argument("--estimator", action="append", default=[],
                    metavar="CLUSTER=HOST:PORT",
                    help="scheduler-estimator gRPC address per member "
                         "cluster; repeatable")
    ap.add_argument("--plugins", default="*",
                    help="reference --plugins semantics (enable/disable "
                         "filter and score plugins)")
    ap.add_argument("--scheduler-name", default="default-scheduler")
    ap.add_argument("--scheduler-shards", type=int, default=1,
                    help="total shard slots in the scheduler plane; this "
                         "process serves the slot named by --shard-index "
                         "and admits only the bindings whose ns/uid "
                         "rendezvous-hashes to it (docs/SCHEDULING.md "
                         "'Sharded plane')")
    ap.add_argument("--shard-index", type=int, default=0,
                    help="which shard slot this process serves (0-based; "
                         "leader-elects on the karmada-sched-shard-<i> "
                         "lease). Run one process per slot")
    ap.add_argument("--interval", type=float, default=0.2,
                    help="max-sleep fallback between wakeups: the daemon "
                         "wakes on every enqueue (condition variable), so "
                         "this only bounds how long an IDLE leader sleeps "
                         "before its renew/prewarm housekeeping tick")
    ap.add_argument("--batch-delay-ms", type=float, default=5.0,
                    help="streaming admission batching delay: how long a "
                         "trickle of watch events may coalesce into one "
                         "micro-batch before it launches (latency floor vs "
                         "batch efficiency; a backlog always admits "
                         "immediately). See docs/PERF.md 'Streaming "
                         "scheduler'")
    ap.add_argument("--max-batch-rows", type=int, default=0,
                    help="drain quota per streaming micro-batch (0 = auto: "
                         "shape-bucket-floored, capped by the pipeline's "
                         "per-chunk HBM row budget)")
    ap.add_argument("--no-streaming", action="store_true",
                    help="disable the streaming admission service and "
                         "restore the discrete batch-round drain loop "
                         "(decisions are identical either way; streaming "
                         "only changes WHEN work is admitted). KARMADA_TPU_"
                         "STREAMING=0 is the env equivalent; this flag wins")
    ap.add_argument("--platform", default="",
                    help="pin the jax platform (e.g. cpu); default = the "
                         "ambient backend (TPU where available)")
    ap.add_argument("--bearer-token", default="")
    ap.add_argument("--cacert", default="")
    ap.add_argument("--no-leader-elect", action="store_true",
                    help="skip leader election and always schedule "
                         "(single-instance legacy topology; UNSAFE with "
                         "more than one scheduler daemon)")
    ap.add_argument("--lease-name", default="",
                    help="election lease name (default karmada-scheduler; "
                         "one lease per --scheduler-name partition)")
    ap.add_argument("--lease-duration", type=float, default=10.0,
                    help="lease TTL seconds; takeover happens within this")
    ap.add_argument("--renew-interval", type=float, default=0.0,
                    help="seconds between renews (default TTL/3)")
    ap.add_argument("--identity", default="",
                    help="election identity (default hostname_pid)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve GET /metrics on this port (0 = ephemeral, "
                         "printed on stdout; -1 disables)")
    ap.add_argument("--scrape-token-file", default="",
                    help="dedicated READ-ONLY token accepted on GET "
                         "/metrics only (the Prometheus credential no "
                         "longer needs to be the full wire token)")
    ap.add_argument("--enable-pprof", action="store_true",
                    help="serve /debug/pprof (sampled whole-process CPU "
                         "profile + heap) on --pprof-port; protected by "
                         "the wire token OR the --scrape-token-file "
                         "credential, like /metrics")
    ap.add_argument("--pprof-port", type=int, default=0,
                    help="port for --enable-pprof (0 = ephemeral, printed)")
    ap.add_argument("--compile-cache-dir", default="",
                    help="persistent XLA compilation-cache directory "
                         "(docs/PERF.md compile economics): compiled round "
                         "programs persist across processes, so a cold boot "
                         "or failover re-uses every shape any previous "
                         "process compiled. Default: KARMADA_TPU_COMPILE_"
                         "CACHE env; 'off' disables")
    ap.add_argument("--no-aot-prewarm", action="store_true",
                    help="skip the standby's background AOT pass that "
                         "compiles the round kernels over the reachable "
                         "shape-bucket lattice (sched/aot.py); the dry-"
                         "solve prewarm still runs. KARMADA_TPU_AOT_"
                         "PREWARM=0 is the env equivalent; this flag wins")
    ap.add_argument("--no-pipeline", action="store_true",
                    help="disable the pipelined round executor (serial "
                         "estimate→encode→solve→materialize→patch chain; "
                         "see docs/PERF.md — decisions are identical either "
                         "way). KARMADA_TPU_PIPELINE=0 is the env "
                         "equivalent; this flag wins")
    ap.add_argument("--breaker-failures", type=int, default=3,
                    help="consecutive estimator failures before a member's "
                         "circuit breaker opens (docs/ROBUSTNESS.md)")
    ap.add_argument("--breaker-open-seconds", type=float, default=5.0,
                    help="seconds an open breaker fast-fails before the "
                         "half-open probe")
    args = ap.parse_args()

    sharded = args.scheduler_shards > 1
    if args.scheduler_shards < 1:
        ap.error("--scheduler-shards must be >= 1")
    if not 0 <= args.shard_index < args.scheduler_shards:
        ap.error(f"--shard-index {args.shard_index} out of range for "
                 f"--scheduler-shards {args.scheduler_shards}")

    if args.platform == "cpu":
        from ..testing.cpumesh import force_cpu_mesh

        force_cpu_mesh(1)
    elif args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from .. import faults
    from .compilecache import (
        describe_cache,
        enable_persistent_cache,
        resolve_cache_dir,
    )

    # the persistent compilation cache wires BEFORE any kernel compiles so
    # the boot's own compiles land on disk; the hit/miss state of the boot
    # is logged loudly (enable_persistent_cache) and counted on /metrics
    cache_dir = resolve_cache_dir(args.compile_cache_dir)
    if cache_dir:
        n = enable_persistent_cache(cache_dir)
        print(describe_cache(cache_dir, n), flush=True)
    else:
        print("compile cache: disabled (set --compile-cache-dir or "
              "KARMADA_TPU_COMPILE_CACHE; every process recompiles)",
              flush=True)

    from ..api.coordination import LEASE_SCHEDULER
    from ..coordination.elector import Elector, default_identity
    from ..estimator.client import EstimatorRegistry, parse_estimator_flags
    from ..runtime.controller import Runtime
    from ..server.metricsserver import start_metrics_server
    from ..server.remote import RemoteStore
    from .scheduler import SchedulerDaemon

    # chaos plans are env-gated (KARMADA_TPU_FAULT_PLAN); install at boot so
    # a malformed plan aborts the daemon instead of silently running clean
    if faults.install_from_env() is not None:
        print("faults: chaos plan installed from "
              f"{faults.ENV_FAULT_PLAN}", flush=True)

    breakers = faults.BreakerRegistry(
        failure_threshold=args.breaker_failures,
        open_seconds=args.breaker_open_seconds,
    )
    addresses = parse_estimator_flags(args.estimator)
    registry = None
    if addresses:
        from ..estimator.service import GrpcSchedulerEstimator

        registry = EstimatorRegistry(breakers=breakers)
        registry.register_replica_estimator(
            "scheduler-estimator",
            GrpcSchedulerEstimator(addresses.get, breakers=breakers),
        )

    token = args.bearer_token or os.environ.get("KARMADA_TOKEN") or None
    store = RemoteStore(
        args.server,
        token=token,
        cafile=args.cacert or os.environ.get("KARMADA_CACERT") or None,
    )
    runtime = Runtime()
    plugins = [p.strip() for p in args.plugins.split(",") if p.strip()]
    daemon_kwargs = dict(
        scheduler_name=args.scheduler_name,
        estimator_registry=registry, plugins=plugins,
        pipeline=False if args.no_pipeline else None,
        aot_prewarm=False if args.no_aot_prewarm else None,
    )
    if sharded:
        from .shards import ShardedDaemon

        daemon = ShardedDaemon(
            store, runtime, args.shard_index, args.scheduler_shards,
            **daemon_kwargs,
        )
        print(f"sharded plane: serving shard {args.shard_index} of "
              f"{args.scheduler_shards}", flush=True)
    else:
        daemon = SchedulerDaemon(store, runtime, **daemon_kwargs)
    metrics_srv = start_metrics_server(
        args.metrics_port, token=token,
        scrape_token_file=args.scrape_token_file,
    )
    from ..tracing import start_profile_server

    profile_srv = start_profile_server(
        args.enable_pprof, port=args.pprof_port, token=token,
        scrape_token_file=args.scrape_token_file,
    )

    if sharded:
        from ..api.sharding import shard_lease_name

        lease_name = args.lease_name or shard_lease_name(args.shard_index)
    else:
        lease_name = args.lease_name or (
            LEASE_SCHEDULER if args.scheduler_name == "default-scheduler"
            else f"karmada-scheduler-{args.scheduler_name}"
        )
    identity = args.identity or default_identity()
    leading = threading.Event()
    lease_token = [0]
    elector = None
    if args.no_leader_elect:
        leading.set()
        if sharded:
            daemon.xshards.start()
            daemon.publish_status(leader=identity, force=True)
    else:
        def started(token_: int) -> None:
            store.set_fence(lease_name, token_)
            daemon.abandon_prewarm()  # the leader's first round must not
            #   share the backend with a background compile walk
            lease_token[0] = token_
            if sharded:
                # takeover: the coordinator resumes pending cross-shard
                # cohorts, and the re-list re-places whatever the deposed
                # leader had in flight (its patches bounce on the fence)
                daemon.xshards.start()
                daemon.relist()
            leading.set()
            if sharded:
                daemon.publish_status(leader=identity, token=token_,
                                      force=True)
            print(f"leader: {identity} acquired lease {lease_name} "
                  f"(fencing token {token_})", flush=True)

        def stopped(reason: str) -> None:
            leading.clear()
            lease_token[0] = 0
            if sharded:
                daemon.xshards.stop()
                daemon.publish_status(force=True)
            store.clear_fence()
            print(f"leader: {identity} lost lease {lease_name} ({reason})",
                  flush=True)

        elector = Elector(
            store, lease_name, identity,
            lease_duration=args.lease_duration,
            renew_interval=args.renew_interval or None,
            on_started_leading=started, on_stopped_leading=stopped,
        )
        elector.step()  # synchronous first try: a lone daemon leads at once
        elector.run()

    streaming = not args.no_streaming and os.environ.get(
        "KARMADA_TPU_STREAMING", ""
    ) not in ("0", "off", "false")
    service = None
    if streaming:
        service = daemon.streaming(
            batch_delay=max(0.0, args.batch_delay_ms) / 1000.0,
            interval=args.interval,
            max_batch=args.max_batch_rows,
        )
        print(f"streaming admission: on (batch delay "
              f"{args.batch_delay_ms:g} ms; leader-only — docs/PERF.md)",
              flush=True)
    wake = threading.Event()
    if service is None:
        # batch mode still gets the condition-variable wakeup: an enqueue
        # interrupts the sleep, --interval is only the max-sleep fallback
        daemon.controller.queue.on_add = wake.set

    print(f"karmada-tpu scheduler attached to {args.server}", flush=True)
    # hot standby: encoders + jit cache warm before (and while) not leading
    daemon.prewarm()
    try:
        while True:
            if leading.is_set():
                if service is not None:
                    # blocks while leading: event-driven micro-batch
                    # admission (returns on leadership loss). serve()'s
                    # entry (_ensure_fleet) reads the store BEFORE its
                    # in-loop survival wraps — a transient store error
                    # there must back off and retry, not kill the daemon
                    try:
                        service.serve(
                            should_stop=lambda: not leading.is_set(),
                            idle=(lambda: daemon.publish_status(
                                leader=identity, token=lease_token[0]))
                            if sharded else None,
                        )
                    except Exception:  # noqa: BLE001 - survive transients
                        import logging

                        logging.getLogger(__name__).exception(
                            "streaming admission service")
                        time.sleep(args.interval)
                else:
                    try:
                        runtime.settle()
                    except Exception:  # noqa: BLE001 - survive transients
                        import logging

                        logging.getLogger(__name__).exception(
                            "scheduling drain")
                    if sharded:
                        daemon.publish_status(leader=identity,
                                              token=lease_token[0])
                    wake.wait(args.interval)
                    wake.clear()
            else:
                daemon.prewarm()  # re-warm on cluster churn while standby
                time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if service is not None:
            service.stop()
        if elector is not None:
            elector.stop(release=True)
        if sharded:
            daemon.xshards.stop()
        if metrics_srv is not None:
            metrics_srv.stop()
        if profile_srv is not None:
            profile_srv.stop()
        store.close()


if __name__ == "__main__":
    sys.exit(main())

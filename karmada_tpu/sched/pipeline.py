"""Pipelined round executor: overlap estimator fan-out, host encode, device
solve, and store patching.

A schedule round decomposes into five explicit stages:

    estimate     per-member estimator fan-out (chunk-shard RPC sweep)
    encode       dirty-row host encode (classify / permute / factored batch)
    solve        device kernel dispatch (JAX dispatch is async — launching
                 returns immediately with device handles)
    materialize  device_get + decision decompress/decode
    patch        store writes per decision

and the executor here runs them as a chunked software pipeline with double
buffering (GPipe, Huang et al. 2019; asynchronous dispatch per Pathways,
Barham et al. 2022): while chunk k's kernels run on device, chunk k+1's
estimator answers are prefetched on a worker thread and its rows are encoded
and dispatched on the main thread, and chunk k−1's decisions are
materialized and patched on a bounded in-order writer. The host never idles
waiting for the device, and the device never idles waiting for host encode.

Guarantees (pinned by tests/test_pipeline.py):

- **Bit-identical decisions.** Rows are independent and the tie-break is
  UID-seeded, so placements do not depend on chunk boundaries; the
  pipelined executor produces exactly the serial executor's decisions.
- **Write ordering.** The writer materializes and patches chunks strictly
  in submission order, and within a chunk in binding order — per binding
  UID the store sees exactly the serial executor's write sequence.
- **Bounded in-flight work.** At most `depth` launched-but-unmaterialized
  chunks exist at any moment (double buffering at the default depth=2);
  callers halve the per-chunk row budget so the device working set stays
  inside the serial executor's HBM envelope.

Every stage records a wall-time histogram
(`karmada_schedule_stage_seconds{stage}`), and `ChunkPipeline.stats()`
reports the per-round overlap ratio: total stage seconds divided by the
round's wall seconds. Serial execution sits at ~1.0; a pipelined round
above 1.0 is overlapping by construction — the win is observable, not
asserted.

`KARMADA_TPU_PIPELINE=0` (or `ArrayScheduler(pipeline=False)`) disables
overlap everywhere; the stages then run inline in order with the same
timing instrumentation, which is the bench's serial comparison leg.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

from ..metrics import schedule_stage_seconds

STAGES = ("estimate", "encode", "solve", "materialize", "patch")

# bounded in-flight chunks: the "double" in double buffering — one chunk
# materializing while the next solves (callers size chunks so depth x chunk
# stays inside the serial executor's per-launch HBM budget)
DEFAULT_DEPTH = 2


def resolve_pipeline(override: Optional[bool] = None) -> bool:
    """Pipeline enablement: explicit override, else KARMADA_TPU_PIPELINE
    (0/off/false disables), else on."""
    if override is not None:
        return bool(override)
    return os.environ.get("KARMADA_TPU_PIPELINE", "") not in (
        "0", "off", "false",
    )


class StageTimer:
    """Thread-safe per-stage wall-time accumulator.

    Every `stage()` span observes `karmada_schedule_stage_seconds{stage}`
    and adds to this round's per-stage totals; `trace` (optional) receives
    (stage, tag, event, t) at span begin/end — the fake-clock stage-trace
    tests reconstruct the interleaving from it. `clock` is injectable for
    those tests."""

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        trace: Optional[Callable[[str, object, str, float], None]] = None,
    ) -> None:
        self.clock = clock
        self.trace = trace
        self._lock = threading.Lock()
        self.totals: dict[str, float] = {}

    @contextmanager
    def stage(self, name: str, tag=None):
        t0 = self.clock()
        if self.trace is not None:
            self.trace(name, tag, "begin", t0)
        try:
            yield
        finally:
            t1 = self.clock()
            if self.trace is not None:
                self.trace(name, tag, "end", t1)
            dt = t1 - t0
            schedule_stage_seconds.observe(dt, stage=name)
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt


@contextmanager
def stage_span(name: str, timer: Optional[StageTimer] = None, tag=None):
    """One stage span: into `timer` when a pipeline is driving the round,
    else straight to the histogram (serial single-round callers get stage
    observability too)."""
    if timer is not None:
        with timer.stage(name, tag=tag):
            yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        schedule_stage_seconds.observe(time.perf_counter() - t0, stage=name)


class _Done:
    pass


_DONE = _Done()


class StreamPipeline:
    """Open-ended chunk stream: the launch→materialize→patch tail of the
    pipeline without a fixed chunk list.

    `ChunkPipeline` runs a round whose chunks are all known up front; the
    streaming scheduler (sched/streaming.py) has no round — micro-batches
    form one at a time as watch events accumulate, and each is submitted
    the moment it exists. This class owns the shared machinery: `submit()`
    launches a chunk on the caller's thread (host encode + async device
    dispatch, no sync) and hands it to a writer thread that materializes
    and patches chunks strictly in submission order, while a semaphore
    bounds launched-but-unretired chunks at `depth` (the same double
    buffering bound — in-flight device work never exceeds depth × chunk).
    The caller's thread is free the moment `submit()` returns: the
    admission loop goes back to accumulating the NEXT micro-batch while
    this one solves on device, which is exactly how new work is admitted
    into the gaps of an already-running pipeline.

    Failure semantics match ChunkPipeline: the first exception from any
    stage aborts the stream — later submitted chunks drain un-executed,
    `submit()` returns None once aborted, and `close()` re-raises (or
    returns quietly with `.failure` set when `raise_failure=False`, for
    callers that must sequence their own cleanup first). `chunk_of()`
    exposes the un-retired chunks so an aborting caller can re-enqueue
    their work instead of losing it."""

    def __init__(
        self,
        launch: Callable,
        *,
        materialize: Optional[Callable] = None,
        patch: Optional[Callable] = None,
        depth: int = DEFAULT_DEPTH,
        timer: Optional[StageTimer] = None,
        time_materialize: bool = True,
        keep_results: bool = True,
        name: str = "sched-stream-writer",
    ) -> None:
        self.launch = launch
        self.materialize = materialize
        self.patch = patch
        self.depth = max(1, depth)
        self.timer = timer or StageTimer()
        self.time_materialize = time_materialize
        # a long-lived stream (the streaming daemon runs ONE for its whole
        # leadership) must not accumulate per-chunk state: with
        # keep_results=False the writer drops a chunk's result and its
        # chunk ref the moment it retires cleanly
        self.keep_results = keep_results
        self.failure: Optional[BaseException] = None
        self._abort = threading.Event()
        self._slots = threading.Semaphore(self.depth)
        # the launch-slot semaphore already bounds in-flight chunks to
        # `depth`; the queue bound (+1 for the close sentinel) makes the
        # invariant structural (thread-hygiene rule: every ring bounded)
        self._q: queue.Queue = queue.Queue(maxsize=self.depth + 1)
        self._lock = threading.Lock()
        self._retired_cv = threading.Condition(self._lock)
        self._results: dict[int, object] = {}
        self._pending_chunks: dict[int, object] = {}
        self._submitted = 0
        self._retired = 0
        self._closed = False
        self._writer = threading.Thread(
            target=self._writer_main, name=name, daemon=True
        )
        self._writer.start()

    # -- caller side -------------------------------------------------------

    def submit(self, chunk, est=None,
               timeout: Optional[float] = None) -> Optional[int]:
        """Launch `chunk` on this thread and queue it for the writer.
        Blocks while `depth` chunks are already in flight — bounded by
        `timeout` when given (a writer wedged in a hung patch holds every
        slot; an unbounded acquire would pin the caller forever). Returns
        the chunk's stream index, or None when the stream aborted or the
        slot wait timed out (distinguish via `.aborted`; on timeout no
        state was touched — the caller may retry). A `launch` exception
        propagates here, after its slot is returned."""
        if self._closed:
            raise RuntimeError("stream already closed")
        if timeout is None:
            self._slots.acquire()
        elif not self._slots.acquire(timeout=timeout):
            return None
        if self._abort.is_set():
            self._slots.release()
            return None
        i = self._submitted
        try:
            pending = self.launch(i, chunk, est)
        except BaseException:
            self._slots.release()
            raise
        self._submitted = i + 1
        with self._lock:
            self._pending_chunks[i] = chunk
        self._q.put((i, chunk, pending))
        return i

    def abort(self) -> None:
        """Stop executing: chunks not yet materialized drain un-patched
        (their work is recoverable via `unretired_chunks`)."""
        self._abort.set()

    @property
    def aborted(self) -> bool:
        return self._abort.is_set()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted chunk has retired (materialized and
        patched, or abort-drained). True unless the timeout hit."""
        with self._retired_cv:
            return self._retired_cv.wait_for(
                lambda: self._retired >= self._submitted, timeout
            )

    def unretired_chunks(self) -> list:
        """Chunks submitted but not fully patched (abort/failure leftovers;
        empty after a clean drain) — the caller re-admits their work."""
        with self._lock:
            return [
                self._pending_chunks[i] for i in sorted(self._pending_chunks)
            ]

    def close(self, raise_failure: bool = True,
              timeout: Optional[float] = None) -> dict[int, object]:
        """Shut the writer down once the queued chunks drain; returns the
        per-index results. Re-raises the first stage failure unless
        `raise_failure=False` (then read `.failure`). Idempotent.
        `timeout` bounds the writer join: a writer WEDGED in a stage (a
        hung store patch, a stuck device sync) would otherwise block the
        caller forever — on expiry the stream aborts, records a failure,
        and the (daemon) writer thread is abandoned; its chunks stay
        recoverable via `unretired_chunks()`."""
        if not self._closed:
            self._closed = True
            self._q.put(_DONE)
        self._writer.join(timeout)
        if self._writer.is_alive():
            self._abort.set()
            if self.failure is None:
                self.failure = RuntimeError(
                    f"stream writer did not retire within {timeout}s"
                )
        if raise_failure and self.failure is not None:
            raise self.failure
        with self._lock:
            return dict(self._results)

    # -- writer side -------------------------------------------------------

    def _materialize_one(self, i: int, pending):
        if self.materialize is None:
            return pending
        if self.time_materialize:
            with self.timer.stage("materialize", tag=i):
                return self.materialize(pending)
        return self.materialize(pending)

    def _writer_main(self) -> None:
        while True:
            item = self._q.get()
            if item is _DONE:
                return
            i, chunk, pending = item
            try:
                if self._abort.is_set():
                    continue  # drain without executing past a failure
                try:
                    result = self._materialize_one(i, pending)
                    if self.patch is not None:
                        with self.timer.stage("patch", tag=i):
                            self.patch(i, chunk, result)
                    with self._lock:
                        self._pending_chunks.pop(i, None)
                        if self.keep_results:
                            self._results[i] = result
                except BaseException as e:  # noqa: BLE001 - close() re-raises
                    if self.failure is None:
                        self.failure = e
                    self._abort.set()
            finally:
                self._slots.release()  # chunk fully retired: slot frees
                with self._retired_cv:
                    self._retired += 1
                    self._retired_cv.notify_all()


class ChunkPipeline:
    """The chunked software pipeline.

    Callbacks (any may be None except `launch`):

      estimate(chunk)            -> est        (prefetch thread, stage
                                                "estimate")
      launch(index, chunk, est)  -> pending    (main thread; times its own
                                                encode/solve stages via the
                                                shared timer)
      materialize(pending)       -> result     (writer thread, stage
                                                "materialize" unless the
                                                callee times finer spans)
      patch(index, chunk, result)              (writer thread, stage
                                                "patch")

    `run(chunks)` returns the per-chunk results in order. Chunks are
    materialized/patched strictly in submission order; at most `depth`
    launched chunks wait for the writer. With `pipelined=False` the same
    callbacks run inline in order — the serial executor with identical
    instrumentation.

    The first exception from any stage aborts the round: the remaining
    chunks are neither launched nor patched, and the exception re-raises on
    the caller's thread (the scheduler's per-key error isolation then takes
    over, exactly as for a serial round)."""

    def __init__(
        self,
        launch: Callable,
        *,
        estimate: Optional[Callable] = None,
        materialize: Optional[Callable] = None,
        patch: Optional[Callable] = None,
        depth: int = DEFAULT_DEPTH,
        pipelined: bool = True,
        timer: Optional[StageTimer] = None,
        time_materialize: bool = True,
    ) -> None:
        self.launch = launch
        self.estimate = estimate
        self.materialize = materialize
        self.patch = patch
        self.depth = max(1, depth)
        self.pipelined = pipelined
        self.timer = timer or StageTimer()
        # callees that time their own finer materialize spans set this False
        self.time_materialize = time_materialize
        self.wall_seconds = 0.0

    # -- serial leg --------------------------------------------------------

    def _run_serial(self, chunks: Sequence) -> list:
        out = []
        for i, chunk in enumerate(chunks):
            est = None
            if self.estimate is not None:
                with self.timer.stage("estimate", tag=i):
                    est = self.estimate(chunk)
            pending = self.launch(i, chunk, est)
            result = self._materialize_one(i, pending)
            if self.patch is not None:
                with self.timer.stage("patch", tag=i):
                    self.patch(i, chunk, result)
            out.append(result)
        return out

    def _materialize_one(self, i: int, pending):
        if self.materialize is None:
            return pending
        if self.time_materialize:
            with self.timer.stage("materialize", tag=i):
                return self.materialize(pending)
        return self.materialize(pending)

    # -- pipelined leg -----------------------------------------------------

    def _run_pipelined(self, chunks: Sequence) -> list:
        """A fixed chunk list is just a stream that closes after its last
        submit: the launch/materialize/patch tail (writer thread, in-order
        patching, depth-bounded double buffering) is StreamPipeline's; this
        leg only adds the estimate PREFETCH — chunk i+1's estimator fan-out
        runs on a worker thread while chunk i encodes and solves, which
        needs the full chunk list and so cannot live in the open-ended
        stream."""
        n = len(chunks)
        stream = StreamPipeline(
            launch=self.launch, materialize=self.materialize,
            patch=self.patch, depth=self.depth, timer=self.timer,
            time_materialize=self.time_materialize,
            name="sched-pipeline-writer",
        )

        est_box: dict[int, object] = {}
        est_lock = threading.Lock()
        est_ready: dict[int, threading.Event] = {}
        est_err: list[BaseException] = []

        def prefetch(i: int) -> None:
            try:
                with self.timer.stage("estimate", tag=i):
                    est = self.estimate(chunks[i])
                with est_lock:
                    est_box[i] = est
            except BaseException as e:  # noqa: BLE001
                est_err.append(e)
                stream.abort()
            finally:
                est_ready[i].set()

        prefetcher: Optional[threading.Thread] = None

        def start_prefetch(i: int) -> Optional[threading.Thread]:
            if self.estimate is None or i >= n:
                return None
            est_ready[i] = threading.Event()
            t = threading.Thread(
                target=prefetch, args=(i,),
                name="sched-pipeline-estimate", daemon=True,
            )
            t.start()
            return t

        try:
            prefetcher = start_prefetch(0)
            for i, chunk in enumerate(chunks):
                est = None
                if self.estimate is not None:
                    est_ready[i].wait()
                    if est_err:
                        break
                    with est_lock:
                        est = est_box.pop(i)
                    # chunk i+1's fan-out runs while chunk i encodes/solves
                    prefetcher = start_prefetch(i + 1)
                if stream.submit(chunk, est) is None:
                    break  # a stage failed: stop launching, drain below
        finally:
            # close() drains the queued chunks and joins the writer; a
            # launch exception propagates from the try body AFTER cleanup
            results = stream.close(raise_failure=False)
            if prefetcher is not None:
                prefetcher.join()
        if est_err:
            raise est_err[0]
        if stream.failure is not None:
            raise stream.failure
        return [results.get(i) for i in range(n)]

    def run(self, chunks: Sequence) -> list:
        t0 = time.perf_counter()
        try:
            if not self.pipelined or len(chunks) <= 1:
                return self._run_serial(chunks)
            return self._run_pipelined(chunks)
        finally:
            self.wall_seconds = time.perf_counter() - t0

    def stats(self) -> dict:
        """Per-round pipeline stats: stage seconds, wall seconds, and the
        overlap ratio (total stage seconds / wall seconds; ~1.0 serial,
        >1.0 when stages overlapped)."""
        totals = dict(self.timer.totals)
        busy = sum(totals.values())
        wall = self.wall_seconds
        return {
            "pipelined": self.pipelined,
            "stage_seconds": {k: round(v, 6) for k, v in totals.items()},
            "wall_seconds": round(wall, 6),
            "overlap_ratio": round(busy / wall, 4) if wall > 0 else 0.0,
        }


def chunk_spans(total: int, rows: int) -> list[tuple[int, int]]:
    """[start, end) spans chunking `total` rows at `rows` per chunk."""
    rows = max(1, rows)
    return [(s, min(s + rows, total)) for s in range(0, total, rows)]


def plan_chunk_rows(total: int, cap: int) -> int:
    """Equalized chunk-size schedule: the rows-per-chunk that splits `total`
    into the same number of chunks a greedy cap-sized split would, but with
    EQUAL chunks snapped to the shape_bucket lattice. The greedy schedule
    (cap, cap, ..., remainder) wastes twice — the ragged tail pads to its
    own (different) bucket, compiling a second program per kernel, and the
    full chunks may sit just above a lattice point, padding maximally. At
    the 40k×20k flagship the greedy split is 12288×3 + 3136 (two compiled
    shapes, 3.1k pad rows); the equalized split is 10240×4 — one shape,
    960 pad rows (the profiled chunk-size half of the HBM-chunking fix,
    docs/PERF.md compile economics).

    The guarantee is "never more program shapes than the greedy split,
    usually one" — NOT always one: when the tail chunk falls below the
    rows bucket's predecessor lattice point (e.g. total=2100, cap=2048 →
    1536 + 564, buckets {1536, 768}), the round still pads two shapes;
    both are on the lattice, so they amortize across rounds either way."""
    from ..models.batch import shape_bucket

    cap = max(1, cap)
    if total <= cap:
        return cap
    n_chunks = -(-total // cap)
    rows = shape_bucket(-(-total // n_chunks))
    return min(rows, cap)

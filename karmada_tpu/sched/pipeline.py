"""Pipelined round executor: overlap estimator fan-out, host encode, device
solve, and store patching.

A schedule round decomposes into five explicit stages:

    estimate     per-member estimator fan-out (chunk-shard RPC sweep)
    encode       dirty-row host encode (classify / permute / factored batch)
    solve        device kernel dispatch (JAX dispatch is async — launching
                 returns immediately with device handles)
    materialize  device_get + decision decompress/decode
    patch        store writes per decision

and the executor here runs them as a chunked software pipeline with double
buffering (GPipe, Huang et al. 2019; asynchronous dispatch per Pathways,
Barham et al. 2022): while chunk k's kernels run on device, chunk k+1's
estimator answers are prefetched on a worker thread and its rows are encoded
and dispatched on the main thread, and chunk k−1's decisions are
materialized and patched on a bounded in-order writer. The host never idles
waiting for the device, and the device never idles waiting for host encode.

Guarantees (pinned by tests/test_pipeline.py):

- **Bit-identical decisions.** Rows are independent and the tie-break is
  UID-seeded, so placements do not depend on chunk boundaries; the
  pipelined executor produces exactly the serial executor's decisions.
- **Write ordering.** The writer materializes and patches chunks strictly
  in submission order, and within a chunk in binding order — per binding
  UID the store sees exactly the serial executor's write sequence.
- **Bounded in-flight work.** At most `depth` launched-but-unmaterialized
  chunks exist at any moment (double buffering at the default depth=2);
  callers halve the per-chunk row budget so the device working set stays
  inside the serial executor's HBM envelope.

Every stage records a wall-time histogram
(`karmada_schedule_stage_seconds{stage}`), and `ChunkPipeline.stats()`
reports the per-round overlap ratio: total stage seconds divided by the
round's wall seconds. Serial execution sits at ~1.0; a pipelined round
above 1.0 is overlapping by construction — the win is observable, not
asserted.

`KARMADA_TPU_PIPELINE=0` (or `ArrayScheduler(pipeline=False)`) disables
overlap everywhere; the stages then run inline in order with the same
timing instrumentation, which is the bench's serial comparison leg.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, Sequence

from ..metrics import schedule_stage_seconds

STAGES = ("estimate", "encode", "solve", "materialize", "patch")

# bounded in-flight chunks: the "double" in double buffering — one chunk
# materializing while the next solves (callers size chunks so depth x chunk
# stays inside the serial executor's per-launch HBM budget)
DEFAULT_DEPTH = 2


def resolve_pipeline(override: Optional[bool] = None) -> bool:
    """Pipeline enablement: explicit override, else KARMADA_TPU_PIPELINE
    (0/off/false disables), else on."""
    if override is not None:
        return bool(override)
    return os.environ.get("KARMADA_TPU_PIPELINE", "") not in (
        "0", "off", "false",
    )


class StageTimer:
    """Thread-safe per-stage wall-time accumulator.

    Every `stage()` span observes `karmada_schedule_stage_seconds{stage}`
    and adds to this round's per-stage totals; `trace` (optional) receives
    (stage, tag, event, t) at span begin/end — the fake-clock stage-trace
    tests reconstruct the interleaving from it. `clock` is injectable for
    those tests."""

    def __init__(
        self,
        clock: Callable[[], float] = time.perf_counter,
        trace: Optional[Callable[[str, object, str, float], None]] = None,
    ) -> None:
        self.clock = clock
        self.trace = trace
        self._lock = threading.Lock()
        self.totals: dict[str, float] = {}

    @contextmanager
    def stage(self, name: str, tag=None):
        t0 = self.clock()
        if self.trace is not None:
            self.trace(name, tag, "begin", t0)
        try:
            yield
        finally:
            t1 = self.clock()
            if self.trace is not None:
                self.trace(name, tag, "end", t1)
            dt = t1 - t0
            schedule_stage_seconds.observe(dt, stage=name)
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt


@contextmanager
def stage_span(name: str, timer: Optional[StageTimer] = None, tag=None):
    """One stage span: into `timer` when a pipeline is driving the round,
    else straight to the histogram (serial single-round callers get stage
    observability too)."""
    if timer is not None:
        with timer.stage(name, tag=tag):
            yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        schedule_stage_seconds.observe(time.perf_counter() - t0, stage=name)


class _Done:
    pass


_DONE = _Done()


class ChunkPipeline:
    """The chunked software pipeline.

    Callbacks (any may be None except `launch`):

      estimate(chunk)            -> est        (prefetch thread, stage
                                                "estimate")
      launch(index, chunk, est)  -> pending    (main thread; times its own
                                                encode/solve stages via the
                                                shared timer)
      materialize(pending)       -> result     (writer thread, stage
                                                "materialize" unless the
                                                callee times finer spans)
      patch(index, chunk, result)              (writer thread, stage
                                                "patch")

    `run(chunks)` returns the per-chunk results in order. Chunks are
    materialized/patched strictly in submission order; at most `depth`
    launched chunks wait for the writer. With `pipelined=False` the same
    callbacks run inline in order — the serial executor with identical
    instrumentation.

    The first exception from any stage aborts the round: the remaining
    chunks are neither launched nor patched, and the exception re-raises on
    the caller's thread (the scheduler's per-key error isolation then takes
    over, exactly as for a serial round)."""

    def __init__(
        self,
        launch: Callable,
        *,
        estimate: Optional[Callable] = None,
        materialize: Optional[Callable] = None,
        patch: Optional[Callable] = None,
        depth: int = DEFAULT_DEPTH,
        pipelined: bool = True,
        timer: Optional[StageTimer] = None,
        time_materialize: bool = True,
    ) -> None:
        self.launch = launch
        self.estimate = estimate
        self.materialize = materialize
        self.patch = patch
        self.depth = max(1, depth)
        self.pipelined = pipelined
        self.timer = timer or StageTimer()
        # callees that time their own finer materialize spans set this False
        self.time_materialize = time_materialize
        self.wall_seconds = 0.0

    # -- serial leg --------------------------------------------------------

    def _run_serial(self, chunks: Sequence) -> list:
        out = []
        for i, chunk in enumerate(chunks):
            est = None
            if self.estimate is not None:
                with self.timer.stage("estimate", tag=i):
                    est = self.estimate(chunk)
            pending = self.launch(i, chunk, est)
            result = self._materialize_one(i, pending)
            if self.patch is not None:
                with self.timer.stage("patch", tag=i):
                    self.patch(i, chunk, result)
            out.append(result)
        return out

    def _materialize_one(self, i: int, pending):
        if self.materialize is None:
            return pending
        if self.time_materialize:
            with self.timer.stage("materialize", tag=i):
                return self.materialize(pending)
        return self.materialize(pending)

    # -- pipelined leg -----------------------------------------------------

    def _writer_main(self, q: queue.Queue, results: list, failure: list,
                     abort: threading.Event,
                     slots: threading.Semaphore) -> None:
        while True:
            item = q.get()
            if item is _DONE:
                return
            i, chunk, pending = item
            try:
                if abort.is_set():
                    continue  # drain without executing past a failure
                try:
                    result = self._materialize_one(i, pending)
                    if self.patch is not None:
                        with self.timer.stage("patch", tag=i):
                            self.patch(i, chunk, result)
                    results[i] = result
                except BaseException as e:  # noqa: BLE001 - re-raised by run()
                    failure.append(e)
                    abort.set()
            finally:
                slots.release()  # chunk fully retired: its launch slot frees

    def _run_pipelined(self, chunks: Sequence) -> list:
        n = len(chunks)
        results: list = [None] * n
        failure: list[BaseException] = []
        abort = threading.Event()
        # the double-buffering bound: a launch slot is held from dispatch
        # until the writer retires the chunk, so at most `depth` chunks are
        # launched-but-unmaterialized (device working set = depth x chunk)
        slots = threading.Semaphore(self.depth)
        q: queue.Queue = queue.Queue()
        writer = threading.Thread(
            target=self._writer_main, args=(q, results, failure, abort, slots),
            name="sched-pipeline-writer", daemon=True,
        )
        writer.start()

        est_box: dict[int, object] = {}
        est_lock = threading.Lock()
        est_ready: dict[int, threading.Event] = {}
        est_err: list[BaseException] = []

        def prefetch(i: int) -> None:
            try:
                with self.timer.stage("estimate", tag=i):
                    est = self.estimate(chunks[i])
                with est_lock:
                    est_box[i] = est
            except BaseException as e:  # noqa: BLE001
                est_err.append(e)
                abort.set()
            finally:
                est_ready[i].set()

        prefetcher: Optional[threading.Thread] = None

        def start_prefetch(i: int) -> Optional[threading.Thread]:
            if self.estimate is None or i >= n:
                return None
            est_ready[i] = threading.Event()
            t = threading.Thread(
                target=prefetch, args=(i,),
                name="sched-pipeline-estimate", daemon=True,
            )
            t.start()
            return t

        try:
            prefetcher = start_prefetch(0)
            for i, chunk in enumerate(chunks):
                est = None
                if self.estimate is not None:
                    est_ready[i].wait()
                    if est_err:
                        break
                    with est_lock:
                        est = est_box.pop(i)
                    # chunk i+1's fan-out runs while chunk i encodes/solves
                    prefetcher = start_prefetch(i + 1)
                slots.acquire()  # wait for a double-buffer slot
                if abort.is_set():
                    slots.release()
                    break
                pending = self.launch(i, chunk, est)
                q.put((i, chunk, pending))
        finally:
            q.put(_DONE)
            writer.join()
            if prefetcher is not None:
                prefetcher.join()
        if est_err:
            raise est_err[0]
        if failure:
            raise failure[0]
        return results

    def run(self, chunks: Sequence) -> list:
        t0 = time.perf_counter()
        try:
            if not self.pipelined or len(chunks) <= 1:
                return self._run_serial(chunks)
            return self._run_pipelined(chunks)
        finally:
            self.wall_seconds = time.perf_counter() - t0

    def stats(self) -> dict:
        """Per-round pipeline stats: stage seconds, wall seconds, and the
        overlap ratio (total stage seconds / wall seconds; ~1.0 serial,
        >1.0 when stages overlapped)."""
        totals = dict(self.timer.totals)
        busy = sum(totals.values())
        wall = self.wall_seconds
        return {
            "pipelined": self.pipelined,
            "stage_seconds": {k: round(v, 6) for k, v in totals.items()},
            "wall_seconds": round(wall, 6),
            "overlap_ratio": round(busy / wall, 4) if wall > 0 else 0.0,
        }


def chunk_spans(total: int, rows: int) -> list[tuple[int, int]]:
    """[start, end) spans chunking `total` rows at `rows` per chunk."""
    rows = max(1, rows)
    return [(s, min(s + rows, total)) for s in range(0, total, rows)]


def plan_chunk_rows(total: int, cap: int) -> int:
    """Equalized chunk-size schedule: the rows-per-chunk that splits `total`
    into the same number of chunks a greedy cap-sized split would, but with
    EQUAL chunks snapped to the shape_bucket lattice. The greedy schedule
    (cap, cap, ..., remainder) wastes twice — the ragged tail pads to its
    own (different) bucket, compiling a second program per kernel, and the
    full chunks may sit just above a lattice point, padding maximally. At
    the 40k×20k flagship the greedy split is 12288×3 + 3136 (two compiled
    shapes, 3.1k pad rows); the equalized split is 10240×4 — one shape,
    960 pad rows (the profiled chunk-size half of the HBM-chunking fix,
    docs/PERF.md compile economics).

    The guarantee is "never more program shapes than the greedy split,
    usually one" — NOT always one: when the tail chunk falls below the
    rows bucket's predecessor lattice point (e.g. total=2100, cap=2048 →
    1536 + 564, buckets {1536, 768}), the round still pads two shapes;
    both are on the lattice, so they amortize across rounds either way."""
    from ..models.batch import shape_bucket

    cap = max(1, cap)
    if total <= cap:
        return cap
    n_chunks = -(-total // cap)
    rows = shape_bucket(-(-total // n_chunks))
    return min(rows, cap)

"""karmada-search (Q1, reference: pkg/search/ 9.7k LoC): ResourceRegistry
cache + aggregated search API + proxy.

- ResourceCache (pkg/search/controller.go): per-ResourceRegistry collection of
  member objects for the selected (cluster, resource) pairs, kept fresh by a
  level-triggered sweep (the reference uses per-cluster dynamic informers; the
  sweep is our resync).
- search API (pkg/search/apiserver.go): federation-wide list with cluster
  annotations.
- proxy (pkg/search/proxy/controller.go:94,277 Connect): route GET/LIST to
  the cached member objects — the "single pane of glass".
- backend stores (pkg/search/backendstore): pluggable sinks; the default
  keeps objects in memory, the OpenSearch one ships documents to a cluster
  (stubbed offline: it records what it would index).
"""
from __future__ import annotations

from typing import Optional, Protocol

from ..api.unstructured import Unstructured

CLUSTER_ANNOTATION = "resource.karmada.io/cached-from-cluster"


class BackendStore(Protocol):
    def index(self, cluster: str, obj: Unstructured) -> None: ...
    def remove(self, cluster: str, gvk: str, namespace: str, name: str) -> None: ...


class InMemoryBackend:
    """Default backend: dict keyed (cluster, gvk, ns/name)."""

    def __init__(self) -> None:
        self.docs: dict[tuple, dict] = {}

    def index(self, cluster: str, obj: Unstructured) -> None:
        key = (cluster, f"{obj.api_version}/{obj.kind}", obj.namespace, obj.name)
        self.docs[key] = obj.to_dict()

    def remove(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        self.docs.pop((cluster, gvk, namespace, name), None)


class OpenSearchBackend:
    """OpenSearch sink (backendstore/opensearch.go). Network egress is not
    available in this environment, so documents are queued with the bulk
    requests that would be sent; `flushed` exposes them for inspection."""

    def __init__(self, addresses: list[str]):
        self.addresses = addresses
        self.pending: list[dict] = []

    def index(self, cluster: str, obj: Unstructured) -> None:
        self.pending.append(
            {
                "_op": "index",
                "_index": f"{obj.kind.lower()}s",
                "_id": f"{cluster}/{obj.namespace}/{obj.name}",
                "doc": obj.to_dict(),
            }
        )

    def remove(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        self.pending.append(
            {"_op": "delete", "_id": f"{cluster}/{namespace}/{name}", "_index": gvk}
        )


class ResourceCache:
    """The registry-driven member-object cache + aggregated search API."""

    def __init__(self, store, members: dict):
        self.store = store
        self.members = members
        # (cluster, gvk, ns, name) -> Unstructured
        self._cache: dict[tuple, Unstructured] = {}
        self._backends: dict[str, BackendStore] = {}

    def backend_for(self, registry) -> BackendStore:
        name = registry.metadata.name
        be = self._backends.get(name)
        if be is None:
            cfg = registry.spec.backend_store
            if cfg is not None and cfg.type == "opensearch":
                be = OpenSearchBackend(cfg.addresses)
            else:
                be = InMemoryBackend()
            self._backends[name] = be
        return be

    def _selected_clusters(self, registry) -> list[str]:
        clusters = sorted(c.metadata.name for c in self.store.list("Cluster"))
        affinity = registry.spec.target_cluster
        if affinity.cluster_names:
            clusters = [c for c in clusters if c in affinity.cluster_names]
        if affinity.exclude:
            clusters = [c for c in clusters if c not in affinity.exclude]
        return clusters

    def sweep(self) -> int:
        """Refresh the cache from every registry's selected members (informer
        resync). Returns the number of cached objects."""
        fresh: dict[tuple, Unstructured] = {}
        for registry in self.store.list("ResourceRegistry"):
            backend = self.backend_for(registry)
            wanted = {(s.api_version, s.kind) for s in registry.spec.resource_selectors}
            for cname in self._selected_clusters(registry):
                member = self.members.get(cname)
                if member is None:
                    continue
                for obj in member.objects():
                    if (obj.api_version, obj.kind) not in wanted:
                        continue
                    key = (cname, f"{obj.api_version}/{obj.kind}", obj.namespace, obj.name)
                    copy = Unstructured(obj.to_dict())
                    copy.metadata.annotations[CLUSTER_ANNOTATION] = cname
                    copy.sync_meta()
                    fresh[key] = copy
                    backend.index(cname, copy)
        removed = set(self._cache) - set(fresh)
        for key in removed:
            cluster, gvk, ns, name = key
            for be in self._backends.values():
                be.remove(cluster, gvk, ns, name)
        self._cache = fresh
        return len(self._cache)

    # -- aggregated search API -------------------------------------------

    def search(
        self,
        api_version: str,
        kind: str,
        namespace: str = "",
        name: str = "",
        clusters: Optional[list[str]] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[Unstructured]:
        gvk = f"{api_version}/{kind}"
        out = []
        for (cname, g, ns, n), obj in sorted(self._cache.items()):
            if g != gvk:
                continue
            if namespace and ns != namespace:
                continue
            if name and n != name:
                continue
            if clusters and cname not in clusters:
                continue
            if label_selector and any(
                obj.metadata.labels.get(k) != v for k, v in label_selector.items()
            ):
                continue
            out.append(obj)
        return out


class SearchProxy:
    """Single-pane proxy (proxy/controller.go Connect): GET/LIST routed to the
    cache, falling through to the live member for objects not yet cached."""

    def __init__(self, cache: ResourceCache):
        self.cache = cache

    def get(self, cluster: str, api_version: str, kind: str,
            name: str, namespace: str = "") -> Optional[Unstructured]:
        hit = self.cache._cache.get((cluster, f"{api_version}/{kind}", namespace, name))
        if hit is not None:
            return hit
        member = self.cache.members.get(cluster)
        if member is None:
            return None
        return member.get(api_version, kind, name, namespace)

    def list(self, cluster: str, api_version: str, kind: str,
             namespace: str = "") -> list[Unstructured]:
        out = [
            obj
            for (cname, gvk, ns, _), obj in sorted(self.cache._cache.items())
            if cname == cluster and gvk == f"{api_version}/{kind}"
            and (not namespace or ns == namespace)
        ]
        if out:
            return out
        member = self.cache.members.get(cluster)
        if member is None:
            return []
        return [
            o for o in member.store.list(f"{api_version}/{kind}", namespace)
        ]

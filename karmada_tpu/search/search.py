"""karmada-search (Q1, reference: pkg/search/ 9.7k LoC): ResourceRegistry
cache + aggregated search API + proxy.

- ResourceCache (pkg/search/controller.go): per-ResourceRegistry collection of
  member objects for the selected (cluster, resource) pairs, kept fresh by a
  level-triggered sweep (the reference uses per-cluster dynamic informers; the
  sweep is our resync).
- search API (pkg/search/apiserver.go): federation-wide list with cluster
  annotations.
- proxy (pkg/search/proxy/controller.go:94,277 Connect): route GET/LIST/WATCH
  to the cached member objects — the "single pane of glass". WATCH is served
  from the cache's live event bus: member-store events (the per-cluster
  dynamic informers of the reference) flow through registry selection into
  the cache and out to watch subscribers.
- backend stores (pkg/search/backendstore): pluggable sinks; the default
  keeps objects in memory, the OpenSearch one builds wire-correct REST
  requests (index create / bulk upsert / delete) against an injectable
  transport (the default transport buffers — no egress in this sandbox).
"""
from __future__ import annotations

import json
import time as _time
from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..api.unstructured import Unstructured

CLUSTER_ANNOTATION = "resource.karmada.io/cached-from-cluster"


# -- registry selection (shared: ResourceCache, agent summary publishing) --


def selected_clusters(store, registry) -> list[str]:
    """The clusters a ResourceRegistry's target affinity selects, from
    the plane's Cluster objects (reference: registry targetCluster)."""
    clusters = sorted(c.metadata.name for c in store.list("Cluster"))
    affinity = registry.spec.target_cluster
    if affinity.cluster_names:
        clusters = [c for c in clusters if c in affinity.cluster_names]
    if affinity.exclude:
        clusters = [c for c in clusters if c not in affinity.exclude]
    return clusters


def selection_map(store) -> dict[tuple, set]:
    """(api_version, kind) -> set of selected clusters, over every
    ResourceRegistry. One walk; callers cache and invalidate on
    ResourceRegistry/Cluster events. The agent's heartbeat uses the same
    map to decide which summaries its cluster owes the search plane —
    one selection semantic, two consumers."""
    sel: dict[tuple, set] = {}
    for registry in store.list("ResourceRegistry"):
        clusters = set(selected_clusters(store, registry))
        for s in registry.spec.resource_selectors:
            sel.setdefault((s.api_version, s.kind), set()).update(clusters)
    return sel


class BackendStore(Protocol):
    def index(self, cluster: str, obj: Unstructured) -> None: ...
    def remove(self, cluster: str, gvk: str, namespace: str, name: str) -> None: ...


class InMemoryBackend:
    """Default backend: dict keyed (cluster, gvk, ns/name)."""

    def __init__(self) -> None:
        self.docs: dict[tuple, dict] = {}

    def index(self, cluster: str, obj: Unstructured) -> None:
        key = (cluster, f"{obj.api_version}/{obj.kind}", obj.namespace, obj.name)
        self.docs[key] = obj.to_dict()

    def remove(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        self.docs.pop((cluster, gvk, namespace, name), None)


OPENSEARCH_INDEX_PREFIX = "kubernetes"

# index bootstrap body (opensearch.go:41-116 `mapping`): single shard, no
# replicas; metadata name/namespace/resourceVersion as keyword-subfielded
# text; labels/annotations/spec/status stored but not indexed
OPENSEARCH_INDEX_BODY: dict = {
    "settings": {"index": {"number_of_shards": 1, "number_of_replicas": 0}},
    "mappings": {
        "properties": {
            "apiVersion": {"type": "text"},
            "kind": {"type": "text"},
            "metadata": {
                "properties": {
                    "annotations": {"type": "object", "enabled": False},
                    "creationTimestamp": {"type": "text"},
                    "deletionTimestamp": {"type": "text"},
                    "labels": {"type": "object", "enabled": False},
                    "name": {
                        "type": "text",
                        "fields": {
                            "keyword": {"type": "keyword", "ignore_above": 256}
                        },
                    },
                    "namespace": {
                        "type": "text",
                        "fields": {
                            "keyword": {"type": "keyword", "ignore_above": 256}
                        },
                    },
                    "ownerReferences": {"type": "text"},
                    "resourceVersion": {
                        "type": "text",
                        "fields": {
                            "keyword": {"type": "keyword", "ignore_above": 256}
                        },
                    },
                }
            },
            "spec": {"type": "object", "enabled": False},
            "status": {"type": "object", "enabled": False},
        }
    },
}


@dataclass
class HttpRequest:
    """One OpenSearch REST call, fully serialized (what would go on the
    wire; the host/port comes from the configured addresses)."""

    method: str
    path: str
    headers: dict = field(default_factory=dict)
    body: bytes = b""


class OpenSearchTransport(Protocol):
    def perform(self, request: HttpRequest) -> tuple[int, bytes]: ...


class BufferingTransport:
    """Default transport: egress is unavailable in this sandbox, so fully
    serialized requests buffer here instead of being sent (bounded — the
    buffer exists for inspection, not durability). A real deployment
    injects an HTTP transport with the same `perform`."""

    MAX_REQUESTS = 256

    def __init__(self) -> None:
        self.requests: list[HttpRequest] = []

    def perform(self, request: HttpRequest) -> tuple[int, bytes]:
        self.requests.append(request)
        if len(self.requests) > self.MAX_REQUESTS:
            del self.requests[: -self.MAX_REQUESTS]
        return 200, b"{}"


def _rfc3339(ts: float) -> str:
    return _time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(ts))


def _jline(obj: dict) -> bytes:
    return json.dumps(obj, separators=(",", ":"), sort_keys=False).encode()


class OpenSearchBackend:
    """OpenSearch sink speaking the real REST wire format
    (backendstore/opensearch.go:127-260 behavior):

    - index name `kubernetes-{kind lowercase}` (`indexName`, :249-253);
      first use issues `PUT /{index}` with the settings+mappings body.
    - upsert documents carry apiVersion/kind, a pruned metadata block
      (name/namespace/creationTimestamp RFC3339/labels/annotations/
      deletionTimestamp), the cache-source annotation, and spec/status as
      JSON-ENCODED STRINGS (:202-218) — not nested objects.
    - documents are addressed by uid (`DocumentID: us.GetUID()`, :173-175).
    - where the reference issues one IndexRequest/DeleteRequest per event
      (its own `// TODO: bulk` markers at :158,185), operations here queue
      and `flush()` ships ONE `POST /_bulk` NDJSON body per sweep.

    Requests go through an injectable transport; the default buffers them
    (no egress in this sandbox) — the wire bytes are real either way."""

    def __init__(
        self,
        addresses: list[str],
        transport: Optional[OpenSearchTransport] = None,
        prefix: str = OPENSEARCH_INDEX_PREFIX,
        flush_threshold: int = 0,
    ):
        self.addresses = addresses
        self.transport = transport or BufferingTransport()
        self.prefix = prefix
        # > 0: auto-flush when the queue reaches this many ops, so a
        # heavy sweep ships several right-sized _bulk bodies instead of
        # one giant request (OpenSearch's http.max_content_length would
        # reject it); 0 keeps the one-bulk-per-sweep default
        self.flush_threshold = flush_threshold
        self._indices: set[str] = set()
        # queued ops, each an atomic NDJSON line group: (action,) for
        # deletes, (action, source) for upserts — bounded so a persistent
        # transport outage cannot grow the retry queue without limit (every
        # sweep re-appends a full re-index; upserts are idempotent, so
        # dropping the OLDEST ops on overflow converges once the transport
        # recovers)
        self._bulk: list[tuple[bytes, ...]] = []
        # (cluster, gvk, ns, name) -> uid: deletes address by uid like the
        # reference, but the remove() contract doesn't carry one
        self._doc_ids: dict[tuple, str] = {}
        self.pending: list[dict] = []  # op-level view for inspection

    def _index_name(self, kind: str) -> str:
        return f"{self.prefix}-{kind.lower()}"

    def _ensure_index(self, name: str) -> None:
        if name in self._indices:
            return
        status, body = self.transport.perform(
            HttpRequest(
                method="PUT",
                path=f"/{name}",
                headers={"Content-Type": "application/json"},
                body=_jline(OPENSEARCH_INDEX_BODY),
            )
        )
        # resource_already_exists_exception counts as success (:257-260);
        # any other error leaves the index unmarked so the next touch retries
        if status < 300 or b"resource_already_exists_exception" in body:
            self._indices.add(name)

    def document_of(self, cluster: str, obj: Unstructured) -> dict:
        """The exact document body the reference upserts (:203-218)."""
        annotations = dict(obj.metadata.annotations)
        annotations[CLUSTER_ANNOTATION] = cluster
        d = obj.to_dict()
        dts = obj.metadata.deletion_timestamp
        return {
            "apiVersion": obj.api_version,
            "kind": obj.kind,
            "metadata": {
                "name": obj.name,
                "namespace": obj.namespace,
                "creationTimestamp": _rfc3339(obj.metadata.creation_timestamp),
                "labels": dict(obj.metadata.labels),
                "annotations": annotations,
                "deletionTimestamp": None if dts is None else _rfc3339(dts),
            },
            "spec": json.dumps(d.get("spec"), separators=(",", ":")),
            "status": json.dumps(d.get("status"), separators=(",", ":")),
        }

    def index(self, cluster: str, obj: Unstructured) -> None:
        name = self._index_name(obj.kind)
        self._ensure_index(name)
        doc_id = obj.metadata.uid or f"{cluster}/{obj.namespace}/{obj.name}"
        gvk = f"{obj.api_version}/{obj.kind}"
        self._doc_ids[(cluster, gvk, obj.namespace, obj.name)] = doc_id
        doc = self.document_of(cluster, obj)
        self._bulk.append(
            (_jline({"index": {"_index": name, "_id": doc_id}}), _jline(doc))
        )
        self._trim_bulk()
        self._note_pending(
            {"_op": "index", "_index": name, "_id": doc_id, "doc": doc}
        )
        self._maybe_flush()

    def remove(self, cluster: str, gvk: str, namespace: str, name: str) -> None:
        kind = gvk.rsplit("/", 1)[-1]
        index = self._index_name(kind)
        doc_id = self._doc_ids.pop(
            (cluster, gvk, namespace, name), f"{cluster}/{namespace}/{name}"
        )
        self._bulk.append(
            (_jline({"delete": {"_index": index, "_id": doc_id}}),)
        )
        self._trim_bulk()
        self._note_pending(
            {"_op": "delete", "_index": index, "_id": doc_id}
        )
        self._maybe_flush()

    MAX_PENDING = 1024  # `pending` is an inspection view, not durability
    MAX_BULK_OPS = 65536  # retry-queue bound (see _bulk comment)

    def _maybe_flush(self) -> None:
        """The flush threshold: queue reached N ops -> ship now. A failed
        send leaves the queue intact (flush's contract), so the next op
        past the threshold simply retries — no extra state."""
        if self.flush_threshold and len(self._bulk) >= self.flush_threshold:
            try:
                self.flush()
            except Exception:  # noqa: BLE001 — transport outage: retry later
                pass

    def _trim_bulk(self) -> None:
        if len(self._bulk) <= self.MAX_BULK_OPS:
            return
        # drop the OLDEST upserts first: every sweep re-enqueues live
        # documents, so a dropped upsert converges, but a delete fires only
        # once (on the indexed→gone transition) and must survive the trim
        overflow = len(self._bulk) - self.MAX_BULK_OPS
        kept: list[tuple[bytes, ...]] = []
        for op in self._bulk:
            if overflow > 0 and len(op) == 2:  # (action, source) = upsert
                overflow -= 1
                continue
            kept.append(op)
        if overflow > 0:  # pathological: deletes alone exceed the bound
            kept = kept[overflow:]
        self._bulk = kept

    def _note_pending(self, op: dict) -> None:
        self.pending.append(op)
        if len(self.pending) > self.MAX_PENDING:
            del self.pending[: -self.MAX_PENDING]

    def flush(self) -> Optional[tuple[int, bytes]]:
        """Ship everything queued since the last flush as one `POST /_bulk`
        (NDJSON: action line [+ source line], newline-terminated). The queue
        drains only on a successful send — a transport exception or error
        status leaves it intact for the next flush."""
        if not self._bulk:
            return None
        body = b"\n".join(
            line for op in self._bulk for line in op
        ) + b"\n"
        status, resp = self.transport.perform(
            HttpRequest(
                method="POST",
                path="/_bulk",
                headers={"Content-Type": "application/x-ndjson"},
                body=body,
            )
        )
        if status < 300:
            self._bulk = []
        return status, resp


class ResourceCache:
    """The registry-driven member-object cache + aggregated search API."""

    def __init__(self, store, members: dict, index=None):
        self.store = store
        self.members = members
        # the shared columnar index (search/columnar.py) this cache feeds
        # as its LIVE leg — the same rows the agents' ClusterObjectSummary
        # feed converges to, keyed identically so the two legs are
        # idempotent over each other; None = dict cache only
        self.index = index
        # (cluster, gvk, ns, name) -> Unstructured
        self._cache: dict[tuple, Unstructured] = {}
        self._backends: dict[str, BackendStore] = {}
        # registry name -> keys its backend indexed last sweep (removals
        # route only to the backends that actually hold the document)
        self._indexed: dict[str, set] = {}
        # live event bus: member-store events that pass registry selection
        # update the cache incrementally and fan out here — this is what
        # proxy WATCH serves (controller.go:277 routes watch to the cache)
        self._watchers: list = []  # handler(cluster, event, Unstructured)
        self._attached: set[str] = set()
        # (api_version, kind) -> selected clusters, rebuilt lazily when a
        # ResourceRegistry or Cluster changes: the live handler runs on
        # every member write, so it must not deepcopy-list the store
        self._selection: Optional[dict[tuple, set]] = None
        store.watch("ResourceRegistry", self._invalidate_selection, replay=False)
        store.watch("Cluster", self._invalidate_selection, replay=False)

    def _invalidate_selection(self, event: str, obj) -> None:
        self._selection = None

    def _selection_map(self) -> dict[tuple, set]:
        sel = self._selection
        if sel is None:
            sel = selection_map(self.store)
            self._selection = sel
        return sel

    # -- live member informers -------------------------------------------

    def attach_member(self, member) -> None:
        """Subscribe to one member's object events (the reference's
        per-cluster dynamic informer). Idempotent per cluster name."""
        if member.name in self._attached:
            return
        self._attached.add(member.name)
        cname = member.name

        def handler(kind: str, event: str, obj) -> None:
            if not isinstance(obj, Unstructured):
                return
            if not self._selected_by_any_registry(cname, obj):
                return
            key = (cname, f"{obj.api_version}/{obj.kind}", obj.namespace, obj.name)
            annotated = Unstructured(obj.to_dict())
            annotated.metadata.annotations[CLUSTER_ANNOTATION] = cname
            annotated.sync_meta()
            if event == "DELETED":
                self._cache.pop(key, None)
            else:
                self._cache[key] = annotated
            self._feed_index(key, event, annotated)
            for w in list(self._watchers):
                w(cname, event, annotated)

        member.store.watch_all(handler, replay=False)

    def detach_member(self, name: str) -> None:
        """Forget an unjoined cluster's cached objects (its store — and the
        subscription into it — is garbage with the membership)."""
        self._attached.discard(name)
        for key in [k for k in self._cache if k[0] == name]:
            del self._cache[key]
        if self.index is not None:
            self.index.drop_cluster(name, rv=self.store.current_rv)

    def _feed_index(self, key: tuple, event: str, annotated) -> None:
        """The live leg of the columnar index: rows stamped with the
        PLANE store's rv at observation (summaries carry their own commit
        rv) so at_rv pins mean the same thing on both legs."""
        if self.index is None:
            return
        from ..metrics import search_ingest_rows
        from .columnar import field_pairs_of

        cluster, gvk, ns, name = key
        rv = self.store.current_rv
        if event == "DELETED":
            if self.index.remove(cluster, gvk, ns, name, rv=rv):
                search_ingest_rows.inc(feed="live", op="remove")
        else:
            self.index.upsert(
                cluster, gvk, ns, name,
                labels=dict(annotated.metadata.labels),
                fields=field_pairs_of(annotated.to_dict()),
                rv=rv, doc=annotated)
            search_ingest_rows.inc(feed="live", op="upsert")

    def _selected_by_any_registry(self, cluster: str, obj) -> bool:
        return cluster in self._selection_map().get(
            (obj.api_version, obj.kind), ()
        )

    def watch(self, handler, *, replay: bool = True):
        """Subscribe to cache events; handler(cluster, event, obj). With
        replay, current cache content is delivered as ADDED first (informer
        list+watch). Returns an unsubscribe callable."""
        if replay:
            for (cname, _, _, _), obj in sorted(self._cache.items()):
                handler(cname, "ADDED", obj)
        self._watchers.append(handler)

        def unsubscribe() -> None:
            if handler in self._watchers:
                self._watchers.remove(handler)

        return unsubscribe

    def backend_for(self, registry) -> BackendStore:
        name = registry.metadata.name
        be = self._backends.get(name)
        if be is None:
            cfg = registry.spec.backend_store
            if cfg is not None and cfg.type == "opensearch":
                be = OpenSearchBackend(
                    cfg.addresses,
                    flush_threshold=getattr(cfg, "flush_threshold", 0))
            else:
                be = InMemoryBackend()
            self._backends[name] = be
        return be

    def _selected_clusters(self, registry) -> list[str]:
        return selected_clusters(self.store, registry)

    def sweep(self) -> int:
        """Refresh the cache from every registry's selected members (informer
        resync). Returns the number of cached objects."""
        fresh: dict[tuple, Unstructured] = {}
        indexed_now: dict[str, set] = {}
        for registry in self.store.list("ResourceRegistry"):
            backend = self.backend_for(registry)
            keys = indexed_now.setdefault(registry.metadata.name, set())
            wanted = {(s.api_version, s.kind) for s in registry.spec.resource_selectors}
            for cname in self._selected_clusters(registry):
                member = self.members.get(cname)
                if member is None:
                    continue
                for obj in member.objects():
                    if (obj.api_version, obj.kind) not in wanted:
                        continue
                    key = (cname, f"{obj.api_version}/{obj.kind}", obj.namespace, obj.name)
                    copy = Unstructured(obj.to_dict())
                    copy.metadata.annotations[CLUSTER_ANNOTATION] = cname
                    copy.sync_meta()
                    fresh[key] = copy
                    backend.index(cname, copy)
                    keys.add(key)
        # removals route only to the backend that actually indexed the key;
        # a deleted registry's backend gets its removals + final flush BEFORE
        # being dropped (its documents must leave the external store too)
        for name, be in list(self._backends.items()):
            gone = self._indexed.get(name, set()) - indexed_now.get(name, set())
            for key in gone:
                cluster, gvk, ns, oname = key
                be.remove(cluster, gvk, ns, oname)
        stale = set(self._cache) - set(fresh)
        self._indexed = indexed_now
        self._cache = fresh
        if self.index is not None:
            # reconcile the columnar live leg against the refreshed cache
            # (upserts are change-suppressed in the index — a quiet sweep
            # republishes the tip with a fresh rv stamp, no array rebuild)
            rv = self.store.current_rv
            for key in stale:
                cluster, gvk, ns, oname = key
                self.index.remove(cluster, gvk, ns, oname, rv=rv)
            for key, obj in fresh.items():
                self._feed_index(key, "MODIFIED", obj)
            from ..metrics import search_index_objects

            snap = self.index.publish(rv=rv)
            search_index_objects.set(snap.count)
        # backends that batch (OpenSearch bulk) ship one request per sweep;
        # one backend's transport outage must not abort the others
        for name, be in list(self._backends.items()):
            flush = getattr(be, "flush", None)
            if flush is not None:
                try:
                    flush()
                except Exception:  # noqa: BLE001 — per-backend isolation
                    pass
            if name not in indexed_now:  # registry deleted: drop after flush
                self._backends.pop(name)
                self._indexed.pop(name, None)
        return len(self._cache)

    # -- aggregated search API -------------------------------------------

    def search(
        self,
        api_version: str,
        kind: str,
        namespace: str = "",
        name: str = "",
        clusters: Optional[list[str]] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[Unstructured]:
        gvk = f"{api_version}/{kind}"
        out = []
        for (cname, g, ns, n), obj in sorted(self._cache.items()):
            if g != gvk:
                continue
            if namespace and ns != namespace:
                continue
            if name and n != name:
                continue
            if clusters and cname not in clusters:
                continue
            if label_selector and any(
                obj.metadata.labels.get(k) != v for k, v in label_selector.items()
            ):
                continue
            out.append(obj)
        return out


class SearchProxy:
    """Single-pane proxy (proxy/controller.go:277 Connect): GET/LIST/WATCH
    routed to the cache, GET/LIST falling through to the live member for
    objects not yet cached."""

    def __init__(self, cache: ResourceCache):
        self.cache = cache

    def watch(self, handler, *, cluster: str = "", api_version: str = "",
              kind: str = "", namespace: str = "", name: str = "",
              replay: bool = True):
        """Watch member objects through the proxy: handler(cluster, event,
        obj), filtered like the Connect request path. Returns unsubscribe."""

        def filt(cname: str, event: str, obj) -> None:
            if cluster and cname != cluster:
                return
            if api_version and obj.api_version != api_version:
                return
            if kind and obj.kind != kind:
                return
            if namespace and obj.namespace != namespace:
                return
            if name and obj.name != name:
                return
            handler(cname, event, obj)

        return self.cache.watch(filt, replay=replay)

    def get(self, cluster: str, api_version: str, kind: str,
            name: str, namespace: str = "") -> Optional[Unstructured]:
        hit = self.cache._cache.get((cluster, f"{api_version}/{kind}", namespace, name))
        if hit is not None:
            return hit
        member = self.cache.members.get(cluster)
        if member is None:
            return None
        return member.get(api_version, kind, name, namespace)

    def list(self, cluster: str, api_version: str, kind: str,
             namespace: str = "") -> list[Unstructured]:
        out = [
            obj
            for (cname, gvk, ns, _), obj in sorted(self.cache._cache.items())
            if cname == cluster and gvk == f"{api_version}/{kind}"
            and (not namespace or ns == namespace)
        ]
        if out:
            return out
        member = self.cache.members.get(cluster)
        if member is None:
            return []
        return [
            o for o in member.store.list(f"{api_version}/{kind}", namespace)
        ]

"""Search query IR + vectorized execution over a columnar Snapshot.

`GET /search` and `karmadactl search` parse kubectl selector syntax into
a small frozen IR (Query of Terms), and `execute` compiles each term to
one vectorized mask over the snapshot's int columns:

* `k=v` / `k==v`    -> (label_pairs == pair_id).any(axis=1)
* `k!=v`            -> ~that (k8s semantics: a missing key MATCHES !=)
* `k` / `!k`        -> (label_keys == key_id).any(axis=1) / ~that
* `k in (a,b)`      -> np.isin(label_pairs, pair_ids).any(axis=1)
* `k notin (a,b)`   -> ~that (missing key matches, like the reference)
* field selectors   -> same shapes over field_pairs
* name substring    -> evaluated over the NAME DICTIONARY (unique
  strings, np.char.find), then np.isin(name_col, matching_ids) — the
  classic dictionary-encoded trick: V substring tests instead of N.

Matching never grows a dictionary: unknown strings `peek` to None and
the term matches nothing (or everything, for the negated forms).

Results come back in the snapshot's pre-sorted (cluster, gvk, ns, name)
order — byte-identical to the dict cache's `sorted(cache.items())`.
"""
from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from .columnar import PAIR_SEP, ColumnarIndex, Snapshot, SnapshotExpired


class QueryError(ValueError):
    """Unparseable selector syntax (maps to HTTP 400 / CLIError)."""


# term ops over label columns; field terms reuse EQ/NEQ/IN/NOTIN
EQ, NEQ, EXISTS, NEXISTS, IN, NOTIN = (
    "eq", "neq", "exists", "nexists", "in", "notin")


@dataclass(frozen=True)
class Term:
    op: str
    key: str
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class Query:
    """One compiled search request. Empty fields mean "no constraint"."""

    api_version: str = ""
    kind: str = ""
    namespace: str = ""
    name: str = ""            # exact
    name_contains: str = ""   # substring over the name dictionary
    clusters: tuple[str, ...] = ()
    labels: tuple[Term, ...] = ()
    fields: tuple[Term, ...] = ()
    limit: int = 0


_SET_TERM = re.compile(
    r"^(?P<key>[^!=,()\s]+)\s+(?P<op>in|notin)\s+\((?P<vals>[^()]*)\)$")
_KEY = re.compile(r"^[^!=,()\s]+$")
_VAL = re.compile(r"^[^!=,()\s]*$")  # empty is legal (`k=` matches "")


def _split_terms(selector: str) -> list[str]:
    """Split on top-level commas (commas inside `in (...)` sets bind to
    the set, not the term list)."""
    terms, depth, cur = [], 0, []
    for ch in selector:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(depth - 1, 0)
        if ch == "," and depth == 0:
            terms.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    terms.append("".join(cur))
    return [t.strip() for t in terms if t.strip()]


def _parse_term(raw: str, *, allow_sets: bool) -> Term:
    m = _SET_TERM.match(raw)
    if m:
        if not allow_sets:
            raise QueryError(
                f"set operator in field selector: {raw!r} "
                f"(field selectors support =, ==, != only)")
        vals = tuple(v.strip() for v in m.group("vals").split(",")
                     if v.strip())
        if not vals:
            raise QueryError(f"empty value set in {raw!r}")
        return Term(IN if m.group("op") == "in" else NOTIN,
                    m.group("key"), vals)
    if "!=" in raw:
        key, _, val = raw.partition("!=")
        key, val = key.strip(), val.strip()
        if not key or not _KEY.match(key) or not _VAL.match(val):
            raise QueryError(f"bad selector term {raw!r}")
        return Term(NEQ, key, (val,))
    if "=" in raw:
        key, _, val = raw.partition("==") if "==" in raw \
            else raw.partition("=")
        key, val = key.strip(), val.strip()
        if not key or not _KEY.match(key) or not _VAL.match(val):
            raise QueryError(f"bad selector term {raw!r}")
        return Term(EQ, key, (val,))
    if raw.startswith("!"):
        key = raw[1:].strip()
        if not key or not _KEY.match(key):
            raise QueryError(f"bad selector term {raw!r}")
        if not allow_sets:
            raise QueryError(
                f"existence operator in field selector: {raw!r}")
        return Term(NEXISTS, key)
    if not _KEY.match(raw):
        raise QueryError(f"bad selector term {raw!r}")
    if not allow_sets:
        raise QueryError(f"existence operator in field selector: {raw!r}")
    return Term(EXISTS, raw)


def parse_label_selector(selector: str) -> tuple[Term, ...]:
    """kubectl -l grammar: `k=v`, `k==v`, `k!=v`, `k`, `!k`,
    `k in (a,b)`, `k notin (a,b)`, comma-joined (AND)."""
    return tuple(_parse_term(t, allow_sets=True)
                 for t in _split_terms(selector or ""))


def parse_field_selector(selector: str) -> tuple[Term, ...]:
    """kubectl --field-selector grammar: `k=v`, `k==v`, `k!=v` only."""
    return tuple(_parse_term(t, allow_sets=False)
                 for t in _split_terms(selector or ""))


def compile_query(params: dict) -> Query:
    """Build the IR from /search query parameters (also the CLI's path).
    Recognized keys: kind, apiVersion, namespace, name, nameContains,
    clusters (csv), labelSelector, fieldSelector, limit."""
    try:
        limit = int(params.get("limit") or 0)
    except (TypeError, ValueError):
        raise QueryError(f"bad limit {params.get('limit')!r}")
    clusters = tuple(
        c.strip() for c in (params.get("clusters") or "").split(",")
        if c.strip())
    return Query(
        api_version=params.get("apiVersion", "") or "",
        kind=params.get("kind", "") or "",
        namespace=params.get("namespace", "") or "",
        name=params.get("name", "") or "",
        name_contains=params.get("nameContains", "") or "",
        clusters=clusters,
        labels=parse_label_selector(params.get("labelSelector", "") or ""),
        fields=parse_field_selector(params.get("fieldSelector", "") or ""),
        limit=max(limit, 0),
    )


def _pair_mask(matrix: np.ndarray, interner, key: str,
               values: tuple[str, ...]) -> np.ndarray:
    """Rows whose padded pair matrix holds ANY of key=value. Unknown
    pairs peek to None (never id 0 — that's the pad) and drop out."""
    n = matrix.shape[0]
    ids = [interner.peek(f"{key}{PAIR_SEP}{v}") for v in values]
    ids = [i for i in ids if i]  # None and the 0 pad both excluded
    if not ids or matrix.shape[1] == 0:
        return np.zeros(n, bool)
    if len(ids) == 1:
        return (matrix == ids[0]).any(axis=1)
    return np.isin(matrix, np.asarray(ids, np.int32)).any(axis=1)


def _key_mask(keys: np.ndarray, interner, key: str) -> np.ndarray:
    kid = interner.peek(key)
    if not kid or keys.shape[1] == 0:
        return np.zeros(keys.shape[0], bool)
    return (keys == kid).any(axis=1)


def _term_mask(snap: Snapshot, term: Term, *, fields: bool) -> np.ndarray:
    pairs = snap.field_pairs if fields else snap.label_pairs
    interner = snap.fpairs if fields else snap.lpairs
    if term.op == EQ:
        return _pair_mask(pairs, interner, term.key, term.values)
    if term.op == NEQ:
        return ~_pair_mask(pairs, interner, term.key, term.values)
    if term.op == IN:
        return _pair_mask(pairs, interner, term.key, term.values)
    if term.op == NOTIN:
        return ~_pair_mask(pairs, interner, term.key, term.values)
    if term.op == EXISTS:
        return _key_mask(snap.label_keys, snap.lkeys, term.key)
    if term.op == NEXISTS:
        return ~_key_mask(snap.label_keys, snap.lkeys, term.key)
    raise QueryError(f"unknown term op {term.op!r}")


def execute(snap: Snapshot, query: Query) -> list:
    """One mask-and-gather pass; returns the matching docs in the
    snapshot's deterministic (cluster, gvk, ns, name) order."""
    n = snap.count
    if n == 0:
        return []
    mask = np.ones(n, bool)
    if query.kind:
        if query.api_version:
            gid = snap.gvks.peek(f"{query.api_version}/{query.kind}")
            if not gid:
                return []
            mask &= snap.gvk_ids == gid
        else:
            # kind-only match: scan the (tiny) gvk dictionary for any
            # apiVersion carrying this Kind, then one isin over the column
            suffix = f"/{query.kind}"
            gids = np.nonzero(np.array(
                [s.endswith(suffix) for s in snap.gvk_dict], bool))[0]
            if gids.size == 0:
                return []
            mask &= np.isin(snap.gvk_ids, gids.astype(np.int32))
    elif query.api_version:
        prefix = f"{query.api_version}/"
        gids = np.nonzero(np.array(
            [s.startswith(prefix) for s in snap.gvk_dict], bool))[0]
        if gids.size == 0:
            return []
        mask &= np.isin(snap.gvk_ids, gids.astype(np.int32))
    if query.namespace:
        nid = snap.namespaces.peek(query.namespace)
        if not nid:
            return []
        mask &= snap.ns_ids == nid
    if query.name:
        mid = snap.names.peek(query.name)
        if not mid:
            return []
        mask &= snap.name_ids == mid
    if query.name_contains:
        # dictionary-encoded substring: V vectorized tests over the name
        # dictionary, then membership over the column. The dictionary was
        # materialized at publish, so id -> position is exact.
        hits = np.char.find(
            snap.name_dict.astype(str), query.name_contains) >= 0
        hits[0] = False  # id 0 is "absent", never a real name
        ids = np.nonzero(hits)[0]
        if ids.size == 0:
            return []
        mask &= np.isin(snap.name_ids, ids.astype(np.int32))
    if query.clusters:
        cids = [snap.clusters.peek(c) for c in query.clusters]
        cids = [c for c in cids if c]
        if not cids:
            return []
        mask &= np.isin(snap.cluster_ids, np.asarray(cids, np.int32))
    for term in query.labels:
        mask &= _term_mask(snap, term, fields=False)
    for term in query.fields:
        mask &= _term_mask(snap, term, fields=True)
    idx = np.nonzero(mask)[0]
    if query.limit:
        idx = idx[:query.limit]
    return [snap.docs[i] for i in idx]


@dataclass
class QueryResult:
    rv: int
    items: list = field(default_factory=list)
    elapsed_s: float = 0.0
    # leaders over the wire also report the fleet replication floor — the
    # highest at_rv every replica can serve (0 when unknown/not replicated)
    replicated_rv: int = 0


def run_query(index: ColumnarIndex, query: Query, *,
              at_rv: Optional[int] = None,
              trace_id: str = "") -> QueryResult:
    """The instrumented entry point every serving surface (apiserver,
    karmadactl, bench) shares: snapshot selection (at_rv pin), timed
    execute, `karmada_search_*` metrics, and — when tracing is on and the
    caller carries a trace id — a `search_query` span, closing the
    ingest->index->query causal chain (docs/SEARCH.md)."""
    from ..metrics import search_queries, search_query_seconds

    snap = index.snapshot(at_rv=at_rv)  # SnapshotExpired propagates
    t0 = time.time()
    items = execute(snap, query)
    elapsed = time.time() - t0
    search_queries.inc(pinned="true" if at_rv is not None else "false")
    search_query_seconds.observe(elapsed, exemplar=trace_id or None)
    if trace_id:
        from ..tracing import tracer

        if tracer.enabled:
            tracer.record_trace(
                trace_id, "search_query", t0, t0 + elapsed,
                rows=snap.count, matched=len(items), rv=snap.rv)
    return QueryResult(rv=snap.rv, items=items, elapsed_s=elapsed)


__all__ = [
    "EQ", "NEQ", "EXISTS", "NEXISTS", "IN", "NOTIN",
    "Query", "QueryError", "QueryResult", "Term",
    "SnapshotExpired",
    "compile_query", "execute", "parse_field_selector",
    "parse_label_selector", "run_query",
]

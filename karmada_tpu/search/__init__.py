from .columnar import (
    ColumnarIndex,
    Snapshot,
    SnapshotExpired,
    field_pairs_of,
)
from .ingest import SearchIngestor
from .query import (
    Query,
    QueryError,
    QueryResult,
    Term,
    compile_query,
    execute,
    parse_field_selector,
    parse_label_selector,
    run_query,
)
from .search import (
    BackendStore,
    InMemoryBackend,
    OpenSearchBackend,
    ResourceCache,
    SearchProxy,
    selected_clusters,
    selection_map,
)

__all__ = [
    "BackendStore",
    "ColumnarIndex",
    "InMemoryBackend",
    "OpenSearchBackend",
    "Query",
    "QueryError",
    "QueryResult",
    "ResourceCache",
    "SearchIngestor",
    "SearchProxy",
    "Snapshot",
    "SnapshotExpired",
    "Term",
    "compile_query",
    "execute",
    "field_pairs_of",
    "parse_field_selector",
    "parse_label_selector",
    "run_query",
    "selected_clusters",
    "selection_map",
]

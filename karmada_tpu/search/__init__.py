from .search import (
    BackendStore,
    InMemoryBackend,
    OpenSearchBackend,
    ResourceCache,
    SearchProxy,
)

__all__ = [
    "BackendStore",
    "InMemoryBackend",
    "OpenSearchBackend",
    "ResourceCache",
    "SearchProxy",
]

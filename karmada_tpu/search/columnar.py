"""Columnar member-object index: the fleet-wide search plane's storage.

The dict cache in search.py answers "find every failing pod across 5k
clusters" with a Python loop per object. This module holds the same
objects arrow-style — parallel int columns keyed by interned
cluster/gvk/namespace/name ids, plus padded [N, L] matrices of interned
label/field (key, value) pairs — so a selector compiles to one
vectorized mask-and-gather (query.py) instead of a per-object scan.

Layout (docs/SEARCH.md):

* dictionaries (`utils/interner.py`): one per column family. Id 0 is
  "absent"; ids are first-seen ordered. Matching uses `peek` — a value
  never interned matches nothing and NEVER grows the vocabulary.
* builder: growable Python-list columns + a (cluster, gvk, ns, name) →
  row dict; deletes tombstone the row onto a free list, upserts reuse it.
* snapshots: `publish(rv)` compacts live rows SORTED by their
  (cluster, gvk, ns, name) string key — byte-identical order to the dict
  cache's `sorted(cache.items())` — into immutable numpy arrays stamped
  with the plane rv. The last `ring` snapshots are retained so a query
  pinned `at_rv=R` is served from the newest snapshot whose rv <= R:
  ingest churn after the pin is invisible, the watch-cache rv discipline
  applied to search (docs/SEARCH.md "rv semantics").

The builder/swap lock is a `make_lock` seam: under KARMADA_TPU_LOCKCHECK
the lock-order watchdog sees every hold. Queries never take it — they
read a published snapshot reference, and snapshots are immutable.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ..analysis.lockorder import make_lock
from ..utils.interner import Interner

# Interned (key, value) pairs are joined on the unit separator — a byte
# that cannot appear in a label key or value — so "a=b,c" style values
# cannot alias a different (key, value) split.
PAIR_SEP = "\x1f"

# snapshots retained for at_rv pins; a pin older than the ring answers
# "expired" (the k8s 410 Gone analogue), never a newer snapshot
DEFAULT_RING = 32


class SnapshotExpired(LookupError):
    """The requested at_rv pin predates every retained snapshot."""


def pair_id(interner: Interner, key: str, value: str) -> int:
    return interner.id(f"{key}{PAIR_SEP}{value}")


def peek_pair(interner: Interner, key: str, value: str) -> Optional[int]:
    return interner.peek(f"{key}{PAIR_SEP}{value}")


def field_pairs_of(doc: dict) -> dict[str, str]:
    """The field-selector surface of an object: metadata.name/namespace
    plus every SCALAR one level under spec/status (`status.phase` et al),
    stringified the way `kubectl --field-selector` compares them."""
    meta = doc.get("metadata") or {}
    out = {
        "metadata.name": str(meta.get("name", "")),
        "metadata.namespace": str(meta.get("namespace", "")),
    }
    for top in ("spec", "status"):
        block = doc.get(top)
        if not isinstance(block, dict):
            continue
        for k, v in block.items():
            if isinstance(v, bool):
                out[f"{top}.{k}"] = "true" if v else "false"
            elif isinstance(v, (str, int, float)):
                out[f"{top}.{k}"] = str(v)
    return out


@dataclass(frozen=True)
class Snapshot:
    """One immutable published view: compacted parallel arrays over live
    rows, pre-sorted by (cluster, gvk, ns, name) so any mask's gather
    comes out in the dict cache's deterministic order."""

    rv: int
    cluster_ids: np.ndarray  # [N] int32
    gvk_ids: np.ndarray      # [N] int32
    ns_ids: np.ndarray       # [N] int32
    name_ids: np.ndarray     # [N] int32
    rvs: np.ndarray          # [N] int64: per-row ingest rv (<= self.rv)
    label_pairs: np.ndarray  # [N, L] int32, 0-padded interned k=v pairs
    label_keys: np.ndarray   # [N, L] int32, 0-padded interned bare keys
    field_pairs: np.ndarray  # [N, F] int32, 0-padded interned field k=v
    docs: tuple              # [N] annotated Unstructured refs
    # shared dictionaries (append-only; every id this snapshot holds is
    # already assigned, so concurrent growth cannot reorder a lookup)
    clusters: Interner
    gvks: Interner
    namespaces: Interner
    names: Interner
    lpairs: Interner
    lkeys: Interner
    fpairs: Interner
    name_dict: np.ndarray    # [V] unicode: the name dictionary at publish
    gvk_dict: np.ndarray     # [G] unicode: the gvk dictionary at publish

    @property
    def count(self) -> int:
        return int(self.cluster_ids.shape[0])


_EMPTY_I32 = np.zeros(0, np.int32)


def _doc_rv(doc: Any):
    """Change-suppression signal: the object's own resourceVersion (the
    MEMBER store's stamp, carried in the manifest), or None when absent —
    None never equals None-with-a-doc swap because both sides compare."""
    if doc is None:
        return None
    try:
        return doc.metadata.resource_version
    except AttributeError:
        return None


def _empty_snapshot(idx: "ColumnarIndex") -> Snapshot:
    return Snapshot(
        rv=0,
        cluster_ids=_EMPTY_I32, gvk_ids=_EMPTY_I32, ns_ids=_EMPTY_I32,
        name_ids=_EMPTY_I32, rvs=np.zeros(0, np.int64),
        label_pairs=np.zeros((0, 0), np.int32),
        label_keys=np.zeros((0, 0), np.int32),
        field_pairs=np.zeros((0, 0), np.int32),
        docs=(),
        clusters=idx.clusters, gvks=idx.gvks, namespaces=idx.namespaces,
        names=idx.names, lpairs=idx.lpairs, lkeys=idx.lkeys,
        fpairs=idx.fpairs,
        name_dict=np.array([""], dtype=object),
        gvk_dict=np.array([""], dtype=object),
    )


class ColumnarIndex:
    """Builder + snapshot ring. Writers (the ResourceCache live feed and
    the SearchIngestor worker) call upsert/remove then publish; readers
    take `snapshot()` and run query.execute against it lock-free."""

    def __init__(self, ring: int = DEFAULT_RING):
        self._lock = make_lock("search.index._lock")
        self.clusters = Interner()
        self.gvks = Interner()
        self.namespaces = Interner()
        self.names = Interner()
        self.lpairs = Interner()   # label "key<US>value" pairs
        self.lkeys = Interner()    # bare label keys (exists/!key)
        self.fpairs = Interner()   # field "key<US>value" pairs
        # builder columns (row-parallel); tombstoned rows keep their slot
        self._keys: list[Optional[tuple]] = []   # (cluster, gvk, ns, name)
        self._cluster: list[int] = []
        self._gvk: list[int] = []
        self._ns: list[int] = []
        self._name: list[int] = []
        self._rv: list[int] = []
        self._lp: list[tuple[int, ...]] = []
        self._lk: list[tuple[int, ...]] = []
        self._fp: list[tuple[int, ...]] = []
        self._docs: list[Any] = []
        self._rows: dict[tuple, int] = {}
        self._free: list[int] = []
        self._dirty = False
        self._max_rv = 0
        self._cluster_rv: dict[str, int] = {}
        self._snap = _empty_snapshot(self)
        self._ring: deque = deque(maxlen=max(ring, 1))
        self._ring.append(self._snap)
        self.publishes = 0

    # -- writes -----------------------------------------------------------

    def upsert(self, cluster: str, gvk: str, namespace: str, name: str, *,
               labels: Optional[dict] = None, fields: Optional[dict] = None,
               rv: int = 0, doc: Any = None) -> bool:
        """Insert or replace one row. `doc` is the fully annotated object
        the query plane materializes (immutable by contract — the cache
        annotates its own copy). `rv` is the plane rv this row state was
        observed at; rows never move backwards in rv.

        Change-suppressed: a re-report of an unchanged row (same selector
        surface, same object resourceVersion) notes the freshness rv but
        neither dirties the builder nor advances the row's rv — the
        periodic sweep's full re-feed then republishes the snapshot tip
        with a new stamp instead of rebuilding the arrays. Returns True
        when the row actually changed."""
        key = (cluster, gvk, namespace, name)
        # intern OUTSIDE the row lock: interners have their own locks and
        # the ids are stable whoever assigns them first
        cid = self.clusters.id(cluster)
        gid = self.gvks.id(gvk)
        nid = self.namespaces.id(namespace)
        mid = self.names.id(name)
        lp = tuple(sorted(
            pair_id(self.lpairs, k, v) for k, v in (labels or {}).items()))
        lk = tuple(sorted(self.lkeys.id(k) for k in (labels or {})))
        fp = tuple(sorted(
            pair_id(self.fpairs, k, v) for k, v in (fields or {}).items()))
        with self._lock:
            row = self._rows.get(key)
            if (row is not None
                    and lp == self._lp[row] and lk == self._lk[row]
                    and fp == self._fp[row]
                    and _doc_rv(doc) == _doc_rv(self._docs[row])):
                self._note_rv(cluster, rv)
                return False
            if row is None:
                if self._free:
                    row = self._free.pop()
                    self._keys[row] = key
                    self._cluster[row] = cid
                    self._gvk[row] = gid
                    self._ns[row] = nid
                    self._name[row] = mid
                    self._rv[row] = rv
                    self._lp[row] = lp
                    self._lk[row] = lk
                    self._fp[row] = fp
                    self._docs[row] = doc
                else:
                    row = len(self._keys)
                    self._keys.append(key)
                    self._cluster.append(cid)
                    self._gvk.append(gid)
                    self._ns.append(nid)
                    self._name.append(mid)
                    self._rv.append(rv)
                    self._lp.append(lp)
                    self._lk.append(lk)
                    self._fp.append(fp)
                    self._docs.append(doc)
                self._rows[key] = row
            else:
                self._rv[row] = max(self._rv[row], rv)
                self._lp[row] = lp
                self._lk[row] = lk
                self._fp[row] = fp
                self._docs[row] = doc
            self._note_rv(cluster, rv)
            self._dirty = True
            return True

    def remove(self, cluster: str, gvk: str, namespace: str, name: str,
               rv: int = 0) -> bool:
        """Tombstone one row; no-op (False) when absent — removal is
        level-triggered and both feeds may race to report the same gone
        object."""
        key = (cluster, gvk, namespace, name)
        with self._lock:
            row = self._rows.pop(key, None)
            self._note_rv(cluster, rv)
            if row is None:
                return False
            self._keys[row] = None
            self._docs[row] = None
            self._lp[row] = ()
            self._lk[row] = ()
            self._fp[row] = ()
            self._free.append(row)
            self._dirty = True
            return True

    def drop_cluster(self, cluster: str, rv: int = 0) -> int:
        """Forget every row of an unjoined cluster (detach path)."""
        with self._lock:
            rows = [(k, r) for k, r in self._rows.items() if k[0] == cluster]
            for key, row in rows:
                del self._rows[key]
                self._keys[row] = None
                self._docs[row] = None
                self._lp[row] = ()
                self._lk[row] = ()
                self._fp[row] = ()
                self._free.append(row)
            self._cluster_rv.pop(cluster, None)
            if rows:
                self._dirty = True
            if rv:
                self._max_rv = max(self._max_rv, rv)
            return len(rows)

    def _note_rv(self, cluster: str, rv: int) -> None:
        """Caller holds self._lock."""
        if rv:
            self._max_rv = max(self._max_rv, rv)
            prev = self._cluster_rv.get(cluster, 0)
            self._cluster_rv[cluster] = max(prev, rv)

    # -- publish / snapshots ---------------------------------------------

    def publish(self, rv: Optional[int] = None) -> Snapshot:
        """Compact live rows into an immutable Snapshot stamped `rv`
        (default: the max rv folded so far) and push it onto the ring.
        Ring rvs stay monotone — a publish stamped below the current tip
        re-stamps AT the tip, so an at_rv pin can never resolve to two
        different states for one rv. Clean republish (no writes since the
        last publish) shares the tip's arrays and only re-stamps."""
        with self._lock:
            stamp = self._max_rv if rv is None else max(rv, self._max_rv)
            stamp = max(stamp, self._snap.rv)
            if not self._dirty:
                if stamp == self._snap.rv:
                    return self._snap
                snap = Snapshot(
                    rv=stamp,
                    cluster_ids=self._snap.cluster_ids,
                    gvk_ids=self._snap.gvk_ids, ns_ids=self._snap.ns_ids,
                    name_ids=self._snap.name_ids, rvs=self._snap.rvs,
                    label_pairs=self._snap.label_pairs,
                    label_keys=self._snap.label_keys,
                    field_pairs=self._snap.field_pairs,
                    docs=self._snap.docs,
                    clusters=self.clusters, gvks=self.gvks,
                    namespaces=self.namespaces, names=self.names,
                    lpairs=self.lpairs, lkeys=self.lkeys, fpairs=self.fpairs,
                    name_dict=self._snap.name_dict,
                    gvk_dict=self._snap.gvk_dict,
                )
            else:
                live = sorted(self._rows.items())  # by string key tuple
                n = len(live)
                rows = [r for _, r in live]
                lmax = max((len(self._lp[r]) for r in rows), default=0)
                fmax = max((len(self._fp[r]) for r in rows), default=0)
                lp = np.zeros((n, lmax), np.int32)
                lk = np.zeros((n, lmax), np.int32)
                fp = np.zeros((n, fmax), np.int32)
                for i, r in enumerate(rows):
                    pairs = self._lp[r]
                    lp[i, :len(pairs)] = pairs
                    keys = self._lk[r]
                    lk[i, :len(keys)] = keys
                    fpairs = self._fp[r]
                    fp[i, :len(fpairs)] = fpairs
                snap = Snapshot(
                    rv=stamp,
                    cluster_ids=np.fromiter(
                        (self._cluster[r] for r in rows), np.int32, n),
                    gvk_ids=np.fromiter(
                        (self._gvk[r] for r in rows), np.int32, n),
                    ns_ids=np.fromiter(
                        (self._ns[r] for r in rows), np.int32, n),
                    name_ids=np.fromiter(
                        (self._name[r] for r in rows), np.int32, n),
                    rvs=np.fromiter(
                        (self._rv[r] for r in rows), np.int64, n),
                    label_pairs=lp, label_keys=lk, field_pairs=fp,
                    docs=tuple(self._docs[r] for r in rows),
                    clusters=self.clusters, gvks=self.gvks,
                    namespaces=self.namespaces, names=self.names,
                    lpairs=self.lpairs, lkeys=self.lkeys, fpairs=self.fpairs,
                    name_dict=np.array(self.names.strings(), dtype=object),
                    gvk_dict=np.array(self.gvks.strings(), dtype=object),
                )
            self._snap = snap
            self._dirty = False
            self._ring.append(snap)
            self.publishes += 1
            return snap

    def snapshot(self, at_rv: Optional[int] = None) -> Snapshot:
        """Current snapshot, or — pinned — the newest retained snapshot
        whose rv <= at_rv. Raises SnapshotExpired when the pin predates
        the ring (serving a NEWER state would break the pin's guarantee;
        the caller maps this to 410)."""
        with self._lock:
            if at_rv is None:
                return self._snap
            for snap in reversed(self._ring):
                if snap.rv <= at_rv:
                    return snap
            raise SnapshotExpired(
                f"at_rv {at_rv} predates the snapshot ring "
                f"(oldest retained rv {self._ring[0].rv})")

    # -- freshness / stats -----------------------------------------------

    def cluster_rvs(self) -> dict[str, int]:
        """Per-cluster highest folded rv — the freshness ledger the ingest
        lag gauge compares against the store's acked rv."""
        with self._lock:
            return dict(self._cluster_rv)

    def stats(self) -> dict:
        with self._lock:
            return {
                "rows": len(self._rows),
                "tombstones": len(self._free),
                "published_rv": self._snap.rv,
                "published_rows": self._snap.count,
                "max_rv": self._max_rv,
                "ring": len(self._ring),
                "publishes": self.publishes,
                "dict_sizes": {
                    "clusters": len(self.clusters),
                    "gvks": len(self.gvks),
                    "namespaces": len(self.namespaces),
                    "names": len(self.names),
                    "label_pairs": len(self.lpairs),
                    "label_keys": len(self.lkeys),
                    "field_pairs": len(self.fpairs),
                },
            }


__all__ = [
    "ColumnarIndex",
    "Snapshot",
    "SnapshotExpired",
    "PAIR_SEP",
    "field_pairs_of",
    "pair_id",
    "peek_pair",
]

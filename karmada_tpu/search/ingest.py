"""Plane-side search ingest: ClusterObjectSummary -> columnar index.

Agents publish per-(cluster, gvk) ClusterObjectSummary objects on their
heartbeat through the coalesced agent-status path (agent/agent.py). This
worker watches the plane store for them and folds each one — wholly
replacing that (cluster, gvk) slice of the ColumnarIndex — then
publishes a snapshot stamped with the summary's store rv.

The attach rides `Store.add_event_sink`: the sink runs UNDER the store
lock in rv order (the same contract the watch cache rides), so the queue
the worker drains is revision-consistent with the prime sweep — and on a
replication FOLLOWER the identical sink sees the leader's original rvs
and event types, which is what makes follower-served search answers
byte-identical to the leader's at the same min_rv barrier (tested in
tests/test_search_columnar.py).

The under-lock sink does the minimum: bounded append + notify. Folding,
publishing, metrics, and tracing happen on the worker thread. Overflow
of the bounded queue sets a resync flag — the worker re-lists every
summary from the store (level-triggered recovery) instead of losing the
dropped events.
"""
from __future__ import annotations

import copy
import threading
import time
from typing import Optional

from ..analysis.lockorder import make_lock
from ..api.search import KIND_CLUSTER_OBJECT_SUMMARY
from ..api.unstructured import Unstructured
from .columnar import ColumnarIndex, field_pairs_of
from .search import CLUSTER_ANNOTATION


class SearchIngestor:
    """One per serving plane (leader or follower). `close()` detaches the
    sink and joins the worker."""

    QUEUE_MAX = 4096

    def __init__(self, store, index: ColumnarIndex, *, start: bool = True):
        self.store = store
        self.index = index
        self._cv = threading.Condition(make_lock("search.ingest._cv"))
        self._pending: list = []  # bounded by QUEUE_MAX; overflow -> resync
        self._resync = False
        self._busy = False
        self._stop = False
        # (cluster, gvk) -> the (ns, name) keys the last fold installed,
        # so a replacement summary retracts exactly the vanished rows
        self._slice_keys: dict[tuple, set] = {}
        self.folded = 0
        self._thread = threading.Thread(
            target=self._run, name="search-ingest", daemon=True)
        # prime runs under the store lock for every stored object: the
        # queue starts revision-consistent with the event feed
        self.attach_rv = store.add_event_sink(self._sink, prime=self._prime)
        if start:
            self._thread.start()

    # -- under-lock feed (rv-ordered, minimum work) -----------------------

    def _prime(self, kind: str, obj) -> None:
        if kind == KIND_CLUSTER_OBJECT_SUMMARY:
            with self._cv:
                self._pending.append(("ADDED", obj))

    def _sink(self, kind: str, event: str, obj) -> None:
        if kind != KIND_CLUSTER_OBJECT_SUMMARY:
            return
        with self._cv:
            if len(self._pending) >= self.QUEUE_MAX:
                self._resync = True
            else:
                self._pending.append((event, obj))
            self._cv.notify()

    # -- worker -----------------------------------------------------------

    def _run(self) -> None:
        from ..metrics import search_ingest_queue_depth

        while True:
            with self._cv:
                while not (self._pending or self._resync or self._stop):
                    self._cv.wait(timeout=0.5)
                if self._stop and not self._pending and not self._resync:
                    return
                batch = self._pending
                self._pending = []
                resync = self._resync
                self._resync = False
                self._busy = True
                search_ingest_queue_depth.set(0)
            try:
                self._drain(batch, resync)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _drain(self, batch: list, resync: bool) -> None:
        from ..metrics import (
            search_freshness_lag_rvs,
            search_index_objects,
            search_ingest_resyncs,
            search_publishes,
        )

        if resync:
            search_ingest_resyncs.inc()
            # level-triggered recovery: the re-list below runs AFTER the
            # queue swap, so it supersedes every event that was pending —
            # replaying those on top would resurrect stale slices
            batch = [("MODIFIED", s) for s in
                     self.store.list(KIND_CLUSTER_OBJECT_SUMMARY)]
        if not batch:
            return
        t0 = time.time()
        max_rv = 0
        touched: set = set()
        for event, summary in batch:
            rv = self._fold(event, summary)
            max_rv = max(max_rv, rv)
            touched.add(summary.cluster)
        snap = self.index.publish(rv=max_rv)
        self.folded += len(batch)
        search_publishes.inc()
        search_index_objects.set(snap.count)
        store_rv = self.store.current_rv
        for cluster, folded_rv in self.index.cluster_rvs().items():
            search_freshness_lag_rvs.set(
                max(store_rv - folded_rv, 0), cluster=cluster)
        from ..tracing import tracer

        if tracer.enabled:
            # the ingest leg of the ingest->index->query chain: one span
            # per drain on a per-plane trace, attrs carrying the fold size
            # and the rv the published snapshot pins
            tracer.record_trace(
                "search-ingest", "search_fold", t0, time.time(),
                summaries=len(batch), rv=snap.rv,
                clusters=len(touched))

    def _fold(self, event: str, summary) -> int:
        """Replace one (cluster, gvk) slice; returns the summary's store
        rv (the freshness stamp for that cluster)."""
        from ..metrics import search_ingest_rows

        cluster = summary.cluster
        gvk = summary.gvk
        key = (cluster, gvk)
        rv = int(getattr(summary.metadata, "resource_version", 0) or 0)
        fresh: set = set()
        if event != "DELETED":
            for row in summary.rows:
                # deep-copy before annotating: the sink hands us the
                # store's committed object by reference, and mutating its
                # manifest would race every concurrent store deepcopy
                doc = Unstructured(copy.deepcopy(row.manifest))
                doc.metadata.annotations[CLUSTER_ANNOTATION] = cluster
                doc.sync_meta()
                self.index.upsert(
                    cluster, gvk, row.namespace, row.name,
                    labels=row.labels, fields=row.fields, rv=rv, doc=doc)
                fresh.add((row.namespace, row.name))
            search_ingest_rows.inc(len(fresh) or 0, feed="summary",
                                   op="upsert")
        gone = self._slice_keys.get(key, set()) - fresh
        for ns, name in gone:
            self.index.remove(cluster, gvk, ns, name, rv=rv)
        if gone:
            search_ingest_rows.inc(len(gone), feed="summary", op="remove")
        if fresh:
            self._slice_keys[key] = fresh
        else:
            self._slice_keys.pop(key, None)
        return rv

    # -- control ----------------------------------------------------------

    def flush(self, timeout: float = 10.0) -> bool:
        """Wait until every event enqueued so far is folded AND published
        (the test/step barrier). False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending or self._resync or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=min(remaining, 0.25))
        return True

    def close(self) -> None:
        self.store.remove_event_sink(self._sink)
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


__all__ = ["SearchIngestor"]

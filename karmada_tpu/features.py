"""Feature gates.

Parity with pkg/features/features.go:33-101: same gate names, same defaults
(Failover β off, GracefulEviction β on, PropagateDeps β on,
CustomizedClusterResourceModeling β on, PropagationPolicyPreemption α off,
MultiClusterService α off, ResourceQuotaEstimate α off,
StatefulFailoverInjection α off, PriorityBasedScheduling α off).

A module-level default gate set mirrors the reference's global
features.FeatureGate; components take an optional FeatureGates so tests can
flip gates without global state.
"""
from __future__ import annotations

FAILOVER = "Failover"
GRACEFUL_EVICTION = "GracefulEviction"
PROPAGATE_DEPS = "PropagateDeps"
CUSTOMIZED_CLUSTER_RESOURCE_MODELING = "CustomizedClusterResourceModeling"
# the operator-facing gate string matches the reference exactly
# (features.go:50: "PropagationPolicyPreemption")
POLICY_PREEMPTION = "PropagationPolicyPreemption"
MULTI_CLUSTER_SERVICE = "MultiClusterService"
RESOURCE_QUOTA_ESTIMATE = "ResourceQuotaEstimate"
STATEFUL_FAILOVER_INJECTION = "StatefulFailoverInjection"
PRIORITY_BASED_SCHEDULING = "PriorityBasedScheduling"

DEFAULTS: dict[str, bool] = {
    FAILOVER: False,
    GRACEFUL_EVICTION: True,
    PROPAGATE_DEPS: True,
    CUSTOMIZED_CLUSTER_RESOURCE_MODELING: True,
    POLICY_PREEMPTION: False,
    MULTI_CLUSTER_SERVICE: False,
    RESOURCE_QUOTA_ESTIMATE: False,
    STATEFUL_FAILOVER_INJECTION: False,
    PRIORITY_BASED_SCHEDULING: False,
}


class FeatureGates:
    def __init__(self, overrides: dict[str, bool] | None = None):
        self._state = dict(DEFAULTS)
        if overrides:
            self.set_from_map(overrides)

    def enabled(self, name: str) -> bool:
        try:
            return self._state[name]
        except KeyError:
            raise KeyError(f"unknown feature gate {name!r}") from None

    def set(self, name: str, value: bool) -> None:
        if name not in self._state:
            raise KeyError(f"unknown feature gate {name!r}")
        self._state[name] = value

    def set_from_map(self, overrides: dict[str, bool]) -> None:
        for k, v in overrides.items():
            self.set(k, v)


# The process-default gate set (reference: features.FeatureGate global).
default_gates = FeatureGates()

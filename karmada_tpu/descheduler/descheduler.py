"""Descheduler: shrink assignments stuck unschedulable so the scheduler can
re-place the freed replicas elsewhere.

Parity with pkg/descheduler (EST5, descheduler.go:141-240): every
--descheduling-interval (default 2m) sweep all ResourceBindings with
Divided+Dynamic placements (core/filter.go:35), find clusters where
ready < assigned (GetUndesiredClusters, core/helper.go:99-109), ask the
unschedulable estimators how many replicas cannot ever start (min-merge,
helper.go:62-96), reduce spec.clusters[i].replicas by that count — never below
ready (updateScheduleResult:207) — and update the binding. The scheduler then
sees replicas-changed (scheduler.go:408) and scale-up re-places the freed
replicas on clusters with headroom.
"""
from __future__ import annotations

from typing import Optional

from ..api.policy import (
    DIVISION_PREFERENCE_AGGREGATED,
    DIVISION_PREFERENCE_WEIGHTED,
    REPLICA_SCHEDULING_DIVIDED,
)
from ..api.work import ResourceBinding, TargetCluster
from ..runtime.controller import Clock
from ..store.store import Store

DEFAULT_DESCHEDULING_INTERVAL = 120.0  # seconds (cmd/descheduler/app/options)
DEFAULT_UNSCHEDULABLE_THRESHOLD = 300.0  # 5m (descheduler options)


def eligible(rb: ResourceBinding) -> bool:
    """FilterBindings (descheduler/core/filter.go:35): Divided + dynamic
    division only (Aggregated or Weighted with dynamicWeight)."""
    p = rb.spec.placement
    if p is None or p.replica_scheduling is None:
        return False
    rs = p.replica_scheduling
    if rs.replica_scheduling_type != REPLICA_SCHEDULING_DIVIDED:
        return False
    if rs.replica_division_preference == DIVISION_PREFERENCE_AGGREGATED:
        return True
    return (
        rs.replica_division_preference == DIVISION_PREFERENCE_WEIGHTED
        and rs.weight_preference is not None
        and bool(rs.weight_preference.dynamic_weight)
    )


def ready_replicas_by_cluster(rb: ResourceBinding) -> dict[str, int]:
    """Parsed from aggregatedStatus (core/helper.go:120-142)."""
    out: dict[str, int] = {}
    for item in rb.status.aggregated_status:
        status = item.status or {}
        out[item.cluster_name] = int(status.get("readyReplicas", 0) or 0)
    return out


class Descheduler:
    def __init__(
        self,
        store: Store,
        estimator_registry,
        clock: Optional[Clock] = None,
        unschedulable_threshold: float = DEFAULT_UNSCHEDULABLE_THRESHOLD,
        interval: float = DEFAULT_DESCHEDULING_INTERVAL,
    ) -> None:
        self.store = store
        self.registry = estimator_registry
        self.clock = clock or Clock()
        self.threshold = unschedulable_threshold
        self.interval = interval
        self._last_run: Optional[float] = None

    def tick(self) -> int:
        """Run one sweep if the interval elapsed; returns bindings updated."""
        now = self.clock.now()
        if self._last_run is not None and now - self._last_run < self.interval:
            return 0
        self._last_run = now
        return self.deschedule_once()

    def deschedule_once(self) -> int:
        updated = 0
        for rb in self.store.list("ResourceBinding"):
            if not eligible(rb):
                continue
            if self._deschedule_binding(rb):
                updated += 1
        return updated

    def _proposed_targets(self, rb: ResourceBinding):
        """The eviction set for one binding: the shrunk spec.clusters this
        sweep would write, or None when nothing shrinks. Pure — shared by
        the live sweep and the dry-run preflight so the two can never use
        different shrink logic."""
        ready = ready_replicas_by_cluster(rb)
        undesired = [
            tc.name for tc in rb.spec.clusters if ready.get(tc.name, 0) < tc.replicas
        ]
        if not undesired:
            return None
        unschedulable = dict(
            zip(
                undesired,
                self.registry.min_unschedulable(undesired, rb.spec.resource, self.threshold),
            )
        )
        new_clusters = []
        changed = False
        for tc in rb.spec.clusters:
            n = unschedulable.get(tc.name, 0)
            if n > 0:
                # shrink by the unschedulable count, floored at ready
                target = max(tc.replicas - n, ready.get(tc.name, 0))
                if target != tc.replicas:
                    changed = True
                new_clusters.append(TargetCluster(name=tc.name, replicas=target))
            else:
                new_clusters.append(tc)
        return new_clusters if changed else None

    def _deschedule_binding(self, rb: ResourceBinding) -> bool:
        new_clusters = self._proposed_targets(rb)
        if new_clusters is None:
            return False
        fresh = self.store.try_get("ResourceBinding", rb.name, rb.namespace)
        if fresh is None:
            return False
        fresh.spec.clusters = new_clusters
        self.store.update(fresh)
        return True

    def deschedule_dryrun(self, diff_limit: int = 16):
        """--dry-run mode: compute the eviction set, then — instead of
        patching bindings — run the shrunk copies through the simulation
        engine (the scheduler's own solve, simulation/engine.py) and report
        what the re-placement WOULD do, diffed against the bindings'
        current assignments. Touches neither the store nor the estimators'
        state; returns a SimulationReport that is NOT persisted.

        The simulated before-image is the live spec.clusters; the after
        image is the baseline solve of the shrunk copies (the scheduler
        sees replicas-changed and scale-up re-places the freed replicas —
        exactly what deschedule_once would trigger)."""
        import copy as copy_mod

        from ..api.simulation import (
            SCENARIO_COMPOSITE,
            Scenario,
            ScenarioReport,
            SimulationReport,
        )
        from ..simulation import Simulator, diff_placements

        proposals = []
        for rb in self.store.list("ResourceBinding"):
            if not eligible(rb):
                continue
            new_clusters = self._proposed_targets(rb)
            if new_clusters is not None:
                proposals.append((rb, new_clusters))
        report = SimulationReport()
        report.metadata.name = "descheduler-dry-run"
        if not proposals:
            return report
        clusters = sorted(
            self.store.list("Cluster"), key=lambda c: c.metadata.name
        )
        shrunk = []
        current_placements: dict[str, list] = {}
        for rb, new_clusters in proposals:
            m = copy_mod.deepcopy(rb)
            m.spec.clusters = new_clusters
            shrunk.append(m)
            current_placements[rb.metadata.key()] = list(rb.spec.clusters)
        sim = Simulator(clusters)
        # the live re-solve min-merges registered-estimator answers
        # (sched/scheduler.py batch_estimates) — the preflight must see the
        # same tightened availability, or it reports freed replicas landing
        # on clusters the real solve will reject (None when this registry
        # carries only unschedulable estimators, e.g. the daemon path)
        extra = None
        batch_estimates = getattr(self.registry, "batch_estimates", None)
        if batch_estimates is not None:
            extra = batch_estimates(shrunk, [c.metadata.name for c in clusters])
        baseline, _ = sim.simulate(shrunk, [], extra_avail=extra)
        baseline.scenario = Scenario(
            kind=SCENARIO_COMPOSITE, name="descheduler-evictions",
        )
        row = diff_placements(current_placements, {}, baseline,
                              limit=diff_limit)
        row = ScenarioReport(
            scenario=row.scenario, displaced=row.displaced,
            unplaceable=row.unplaceable, injected=len(shrunk),
            overcommitted=row.overcommitted, diffs=row.diffs,
        )
        report.scenarios = [row]
        report.bindings = len(shrunk)
        report.clusters = len(clusters)
        report.batched_solves = sim.last_stats.get("batched_solves", 0)
        report.fallback_solves = sim.last_stats.get("fallback_solves", 0)
        return report

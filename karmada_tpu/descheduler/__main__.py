"""Descheduler daemon: `python -m karmada_tpu.descheduler --server URL ...`.

The reference's cmd/descheduler binary (descheduler.go:141): a standalone
process that, every --descheduling-interval, lists Divided+Dynamic
bindings over the control-plane API, asks the per-cluster scheduler
estimators for unschedulable counts over gRPC, and shrinks assignments so
the scheduler re-places the freed replicas. Here the control-plane side
rides RemoteStore and the estimator side the wire-compatible gRPC client.

Example:
    python -m karmada_tpu.descheduler --server http://127.0.0.1:7443 \\
        --estimator m1=127.0.0.1:10352 --estimator m2=127.0.0.1:10353
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.descheduler")
    ap.add_argument("--server", required=True,
                    help="control-plane URL (http:// or https://)")
    ap.add_argument("--estimator", action="append", default=[],
                    metavar="CLUSTER=HOST:PORT",
                    help="scheduler-estimator address per member cluster; "
                         "repeatable. Clusters without one fall back to the "
                         "binding's aggregated ready counts alone")
    ap.add_argument("--interval", type=float, default=120.0,
                    help="seconds between sweeps (--descheduling-interval)")
    ap.add_argument("--threshold", type=float, default=300.0,
                    help="unschedulable-threshold seconds")
    ap.add_argument("--once", action="store_true",
                    help="run one sweep and exit (prints the update count); "
                         "operator-invoked, so it skips leader election")
    ap.add_argument("--details", type=int, default=16,
                    help="max per-binding diffs carried in the --dry-run "
                         "report (-1 = all)")
    ap.add_argument("--dry-run", action="store_true",
                    help="compute the eviction set, run it through the "
                         "what-if simulator instead of patching bindings, "
                         "and print the displacement report (JSON). Mutates "
                         "nothing; implies --once")
    ap.add_argument("--scrape-token-file", default="",
                    help="dedicated READ-ONLY token accepted on GET "
                         "/metrics only (the Prometheus credential no "
                         "longer needs to be the full wire token)")
    ap.add_argument("--bearer-token", default="")
    ap.add_argument("--cacert", default="")
    ap.add_argument("--no-leader-elect", action="store_true",
                    help="sweep without holding the karmada-descheduler "
                         "lease (UNSAFE with more than one instance)")
    ap.add_argument("--lease-duration", type=float, default=15.0)
    ap.add_argument("--identity", default="",
                    help="election identity (default hostname_pid)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve GET /metrics on this port (0 = ephemeral, "
                         "printed on stdout; -1 disables)")
    args = ap.parse_args()

    # host-plane process: never let an ambient TPU backend init block startup
    from ..testing.cpumesh import force_cpu_mesh

    force_cpu_mesh(1)

    from ..estimator.client import EstimatorRegistry, parse_estimator_flags
    from ..server.remote import RemoteStore
    from .descheduler import Descheduler

    addresses = parse_estimator_flags(args.estimator)
    registry = EstimatorRegistry()
    if addresses:
        from ..estimator.service import GrpcSchedulerEstimator

        registry.register_unschedulable_estimator(
            "scheduler-estimator", GrpcSchedulerEstimator(addresses.get)
        )

    token = args.bearer_token or os.environ.get("KARMADA_TOKEN") or None
    store = RemoteStore(
        args.server,
        token=token,
        cafile=args.cacert or os.environ.get("KARMADA_CACERT") or None,
    )
    d = Descheduler(store, registry, interval=args.interval,
                    unschedulable_threshold=args.threshold)
    if args.dry_run:
        import dataclasses
        import json

        report = d.deschedule_dryrun(
            diff_limit=(1 << 20) if args.details < 0 else args.details
        )
        row = report.scenarios[0] if report.scenarios else None
        print(json.dumps({
            "dry_run": True,
            "evicted_bindings": report.bindings,
            "displaced": row.displaced if row else 0,
            "unplaceable": row.unplaceable if row else 0,
            "overcommitted": row.overcommitted if row else [],
            "diffs": [dataclasses.asdict(di) for di in (row.diffs if row else [])],
        }), flush=True)
        return
    if args.once:
        n = d.deschedule_once()
        print(f"descheduled {n} binding(s)", flush=True)
        return

    from ..api.coordination import LEASE_DESCHEDULER
    from ..coordination.elector import Elector, default_identity
    from ..server.metricsserver import start_metrics_server

    metrics_srv = start_metrics_server(
        args.metrics_port, token=token,
        scrape_token_file=args.scrape_token_file,
    )
    identity = args.identity or default_identity()
    elector = None
    if not args.no_leader_elect:
        def started(token_: int) -> None:
            store.set_fence(LEASE_DESCHEDULER, token_)
            print(f"leader: {identity} acquired lease {LEASE_DESCHEDULER} "
                  f"(fencing token {token_})", flush=True)

        def stopped(reason: str) -> None:
            store.clear_fence()
            print(f"leader: {identity} lost lease {LEASE_DESCHEDULER} "
                  f"({reason})", flush=True)

        elector = Elector(
            store, LEASE_DESCHEDULER, identity,
            lease_duration=args.lease_duration,
            on_started_leading=started, on_stopped_leading=stopped,
        )
        elector.step()
        elector.run()
    print(f"karmada-tpu descheduler sweeping {args.server} "
          f"every {args.interval:.0f}s", flush=True)
    try:
        while True:
            if elector is None or elector.is_leader:
                try:
                    n = d.deschedule_once()
                    if n:
                        print(f"descheduled {n} binding(s)", flush=True)
                except Exception:  # noqa: BLE001 - survive transient errors
                    import logging

                    logging.getLogger(__name__).exception("descheduling sweep")
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if elector is not None:
            elector.stop(release=True)
        if metrics_srv is not None:
            metrics_srv.stop()


if __name__ == "__main__":
    sys.exit(main())

"""Descheduler daemon: `python -m karmada_tpu.descheduler --server URL ...`.

The reference's cmd/descheduler binary (descheduler.go:141): a standalone
process that, every --descheduling-interval, lists Divided+Dynamic
bindings over the control-plane API, asks the per-cluster scheduler
estimators for unschedulable counts over gRPC, and shrinks assignments so
the scheduler re-places the freed replicas. Here the control-plane side
rides RemoteStore and the estimator side the wire-compatible gRPC client.

Example:
    python -m karmada_tpu.descheduler --server http://127.0.0.1:7443 \\
        --estimator m1=127.0.0.1:10352 --estimator m2=127.0.0.1:10353
"""
from __future__ import annotations

import argparse
import os
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.descheduler")
    ap.add_argument("--server", required=True,
                    help="control-plane URL (http:// or https://)")
    ap.add_argument("--estimator", action="append", default=[],
                    metavar="CLUSTER=HOST:PORT",
                    help="scheduler-estimator address per member cluster; "
                         "repeatable. Clusters without one fall back to the "
                         "binding's aggregated ready counts alone")
    ap.add_argument("--interval", type=float, default=120.0,
                    help="seconds between sweeps (--descheduling-interval)")
    ap.add_argument("--threshold", type=float, default=300.0,
                    help="unschedulable-threshold seconds")
    ap.add_argument("--once", action="store_true",
                    help="run one sweep and exit (prints the update count)")
    ap.add_argument("--bearer-token", default="")
    ap.add_argument("--cacert", default="")
    args = ap.parse_args()

    # host-plane process: never let an ambient TPU backend init block startup
    from ..testing.cpumesh import force_cpu_mesh

    force_cpu_mesh(1)

    from ..estimator.client import EstimatorRegistry, parse_estimator_flags
    from ..server.remote import RemoteStore
    from .descheduler import Descheduler

    addresses = parse_estimator_flags(args.estimator)
    registry = EstimatorRegistry()
    if addresses:
        from ..estimator.service import GrpcSchedulerEstimator

        registry.register_unschedulable_estimator(
            "scheduler-estimator", GrpcSchedulerEstimator(addresses.get)
        )

    store = RemoteStore(
        args.server,
        token=args.bearer_token or os.environ.get("KARMADA_TOKEN") or None,
        cafile=args.cacert or os.environ.get("KARMADA_CACERT") or None,
    )
    d = Descheduler(store, registry, interval=args.interval,
                    unschedulable_threshold=args.threshold)
    if args.once:
        n = d.deschedule_once()
        print(f"descheduled {n} binding(s)", flush=True)
        return
    print(f"karmada-tpu descheduler sweeping {args.server} "
          f"every {args.interval:.0f}s", flush=True)
    try:
        while True:
            try:
                n = d.deschedule_once()
                if n:
                    print(f"descheduled {n} binding(s)", flush=True)
            except Exception:  # noqa: BLE001 - survive transient plane errors
                import logging

                logging.getLogger(__name__).exception("descheduling sweep")
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())

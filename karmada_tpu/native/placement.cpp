// Native first-fit pod placement kernel (the member-side "kube-scheduler"
// loop of the estimator plane — reference behavior: estimate.go's per-node
// math applied greedily; this is the host-side hot loop when simulating
// 5k-node members, kept native per SURVEY §2's data-plane note).
//
// Contract (all arrays int64, row-major):
//   alloc     [N*R]  node allocatable per resource
//   requested [N*R]  already-requested per resource (MUTATED)
//   pod_count [N]    pods on node (MUTATED)
//   allowed   [N]    max pods per node
//   node_ok   [N]    1 = claim-feasible node
//   req       [R]    per-pod request
//   fits      [N]    OUT: pods placed on each node this call
// returns: number of pods placed (<= replicas)
extern "C" long long first_fit_place(
    long long* alloc,
    long long* requested,
    long long* pod_count,
    const long long* allowed,
    const unsigned char* node_ok,
    const long long* req,
    long long* fits,
    long long n_nodes,
    long long n_resources,
    long long replicas) {
  long long remaining = replicas;
  for (long long i = 0; i < n_nodes; ++i) {
    fits[i] = 0;
    if (remaining <= 0 || !node_ok[i]) continue;
    long long fit = allowed[i] - pod_count[i];
    if (fit <= 0) continue;
    const long long* arow = alloc + i * n_resources;
    long long* rrow = requested + i * n_resources;
    for (long long r = 0; r < n_resources && fit > 0; ++r) {
      if (req[r] <= 0) continue;
      long long rest = arow[r] - rrow[r];
      long long by_res = rest > 0 ? rest / req[r] : 0;
      if (by_res < fit) fit = by_res;
    }
    if (fit <= 0) continue;
    if (fit > remaining) fit = remaining;
    for (long long r = 0; r < n_resources; ++r) rrow[r] += req[r] * fit;
    pod_count[i] += fit;
    fits[i] = fit;
    remaining -= fit;
  }
  return replicas - remaining;
}

// Batched node-level MaxAvailableReplicas (estimate.go:88-112 hot loop 3):
// for B requests x N nodes, sum over feasible nodes of
// min(free_pod_slots, min_r floor((alloc-requested)/req)).
//   answers [B] OUT
extern "C" void max_available_replicas(
    const long long* alloc,
    const long long* requested,
    const long long* pod_count,
    const long long* allowed,
    const unsigned char* node_ok,  // [B*N]
    const long long* req,          // [B*R]
    long long* answers,            // [B]
    long long n_nodes,
    long long n_resources,
    long long n_requests) {
  for (long long b = 0; b < n_requests; ++b) {
    const long long* breq = req + b * n_resources;
    const unsigned char* bok = node_ok + b * n_nodes;
    long long total = 0;
    for (long long i = 0; i < n_nodes; ++i) {
      if (!bok[i]) continue;
      long long fit = allowed[i] - pod_count[i];
      if (fit <= 0) continue;
      const long long* arow = alloc + i * n_resources;
      const long long* rrow = requested + i * n_resources;
      for (long long r = 0; r < n_resources && fit > 0; ++r) {
        if (breq[r] <= 0) continue;
        long long rest = arow[r] - rrow[r];
        long long by_res = rest > 0 ? rest / breq[r] : 0;
        if (by_res < fit) fit = by_res;
      }
      if (fit > 0) total += fit;
    }
    answers[b] = total;
  }
}

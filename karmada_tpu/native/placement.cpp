// Native first-fit pod placement kernel (the member-side "kube-scheduler"
// loop of the estimator plane — reference behavior: estimate.go's per-node
// math applied greedily; this is the host-side hot loop when simulating
// 5k-node members, kept native per SURVEY §2's data-plane note).
//
// Contract (all arrays int64, row-major):
//   alloc     [N*R]  node allocatable per resource
//   requested [N*R]  already-requested per resource (MUTATED)
//   pod_count [N]    pods on node (MUTATED)
//   allowed   [N]    max pods per node
//   node_ok   [N]    1 = claim-feasible node
//   req       [R]    per-pod request
//   fits      [N]    OUT: pods placed on each node this call
// returns: number of pods placed (<= replicas)
extern "C" long long first_fit_place(
    long long* alloc,
    long long* requested,
    long long* pod_count,
    const long long* allowed,
    const unsigned char* node_ok,
    const long long* req,
    long long* fits,
    long long n_nodes,
    long long n_resources,
    long long replicas) {
  long long remaining = replicas;
  for (long long i = 0; i < n_nodes; ++i) {
    fits[i] = 0;
    if (remaining <= 0 || !node_ok[i]) continue;
    long long fit = allowed[i] - pod_count[i];
    if (fit <= 0) continue;
    const long long* arow = alloc + i * n_resources;
    long long* rrow = requested + i * n_resources;
    for (long long r = 0; r < n_resources && fit > 0; ++r) {
      if (req[r] <= 0) continue;
      long long rest = arow[r] - rrow[r];
      long long by_res = rest > 0 ? rest / req[r] : 0;
      if (by_res < fit) fit = by_res;
    }
    if (fit <= 0) continue;
    if (fit > remaining) fit = remaining;
    for (long long r = 0; r < n_resources; ++r) rrow[r] += req[r] * fit;
    pod_count[i] += fit;
    fits[i] = fit;
    remaining -= fit;
  }
  return replicas - remaining;
}

// Batched node-level MaxAvailableReplicas (estimate.go:88-112 hot loop 3):
// for B requests x N nodes, sum over feasible nodes of
// min(free_pod_slots, min_r floor((alloc-requested)/req)).
//   answers [B] OUT
extern "C" void max_available_replicas(
    const long long* alloc,
    const long long* requested,
    const long long* pod_count,
    const long long* allowed,
    const unsigned char* node_ok,  // [B*N]
    const long long* req,          // [B*R]
    long long* answers,            // [B]
    long long n_nodes,
    long long n_resources,
    long long n_requests) {
  for (long long b = 0; b < n_requests; ++b) {
    const long long* breq = req + b * n_resources;
    const unsigned char* bok = node_ok + b * n_nodes;
    long long total = 0;
    for (long long i = 0; i < n_nodes; ++i) {
      if (!bok[i]) continue;
      long long fit = allowed[i] - pod_count[i];
      if (fit <= 0) continue;
      const long long* arow = alloc + i * n_resources;
      const long long* rrow = requested + i * n_resources;
      for (long long r = 0; r < n_resources && fit > 0; ++r) {
        if (breq[r] <= 0) continue;
        long long rest = arow[r] - rrow[r];
        long long by_res = rest > 0 ? rest / breq[r] : 0;
        if (by_res < fit) fit = by_res;
      }
      if (fit > 0) total += fit;
    }
    answers[b] = total;
  }
}

// Class-collapsed spread-selection DFS (sched/spread_batch.py
// _select_row_class_dfs), batched: one call processes every row of a
// constraint config. Semantics mirror the Python implementation exactly —
// the reference DFS's record-and-return enumeration over class count
// vectors, the (sum_w, sum_v) maximum, and the discovery-order tie-break
// (lexicographically smallest canonical position sequence; since class
// start positions ascend, comparing sequences degenerates to a walk over
// per-class counts).
//
// Contract (row r owns classes row_off[r] .. row_off[r+1]):
//   cls_v, cls_w [total]   class value / weight
//   cls_m        [total]   class multiplicity
//   row_off      [n_rows+1]
//   kmax_row     [n_rows]  per-row path-length cap (>= kmin)
//   out_counts   [total]   OUT winner counts (zeroed by caller)
//   out_status   [n_rows]  OUT 1 = winner, 0 = none feasible, -1 = budget
// returns 0
namespace {

struct DfsCtx {
  const long long* v;
  const long long* w;
  const long long* m;
  long long K;
  long long kmin, kmax, cmin;
  long long budget;
  long long* counts;      // scratch, length K
  long long* best_counts; // OUT winner, length K
  long long best_w, best_v;
  bool found;
  bool budget_hit;
};

// canonical order: first differing class; the one still holding members
// there comes lexicographically FIRST (its next position is earlier)
static bool canonical_less(const long long* a, const long long* b, long long K) {
  for (long long k = 0; k < K; ++k) {
    if (a[k] != b[k]) return a[k] > b[k];
  }
  return false;
}

static void dfs(DfsCtx& ctx, long long k, long long size, long long sv,
                long long sw) {
  if (--ctx.budget <= 0) {
    ctx.budget_hit = true;
    return;
  }
  if (k == ctx.K || ctx.budget_hit) return;
  // j = 0 (skip this class)
  dfs(ctx, k + 1, size, sv, sw);
  if (ctx.budget_hit) return;
  long long jmax = ctx.m[k];
  if (jmax > ctx.kmax - size) jmax = ctx.kmax - size;
  for (long long j = 1; j <= jmax; ++j) {
    long long size_j = size + j;
    long long sv_j = sv + j * ctx.v[k];
    long long sw_j = sw + j * ctx.w[k];
    if (sv_j >= ctx.cmin && size_j >= ctx.kmin) {
      // recorded: the subset DFS returns at the first satisfied prefix
      ctx.counts[k] = j;
      if (!ctx.found || sw_j > ctx.best_w ||
          (sw_j == ctx.best_w && sv_j > ctx.best_v) ||
          (sw_j == ctx.best_w && sv_j == ctx.best_v &&
           canonical_less(ctx.counts, ctx.best_counts, ctx.K))) {
        ctx.best_w = sw_j;
        ctx.best_v = sv_j;
        for (long long i = 0; i < ctx.K; ++i) ctx.best_counts[i] = ctx.counts[i];
        ctx.found = true;
      }
      ctx.counts[k] = 0;
      break;
    }
    ctx.counts[k] = j;
    dfs(ctx, k + 1, size_j, sv_j, sw_j);
    ctx.counts[k] = 0;
    if (ctx.budget_hit) return;
  }
}

}  // namespace

extern "C" long long class_dfs_batch(
    const long long* cls_v,
    const long long* cls_w,
    const long long* cls_m,
    const long long* row_off,
    const long long* kmax_row,
    long long n_rows,
    long long kmin,
    long long cmin,
    long long budget,
    long long* out_counts,
    long long* out_status) {
  // scratch sized to the widest row
  long long max_k = 0;
  for (long long r = 0; r < n_rows; ++r) {
    long long K = row_off[r + 1] - row_off[r];
    if (K > max_k) max_k = K;
  }
  long long* counts = new long long[max_k > 0 ? max_k : 1];
  long long* best = new long long[max_k > 0 ? max_k : 1];
  for (long long r = 0; r < n_rows; ++r) {
    long long off = row_off[r];
    long long K = row_off[r + 1] - off;
    for (long long i = 0; i < K; ++i) counts[i] = 0;
    DfsCtx ctx;
    ctx.v = cls_v + off;
    ctx.w = cls_w + off;
    ctx.m = cls_m + off;
    ctx.K = K;
    ctx.kmin = kmin;
    ctx.kmax = kmax_row[r];
    ctx.cmin = cmin;
    ctx.budget = budget;
    ctx.counts = counts;
    ctx.best_counts = best;
    ctx.best_w = 0;
    ctx.best_v = 0;
    ctx.found = false;
    ctx.budget_hit = false;
    dfs(ctx, 0, 0, 0, 0);
    if (ctx.budget_hit) {
      out_status[r] = -1;
    } else if (!ctx.found) {
      out_status[r] = 0;
    } else {
      out_status[r] = 1;
      for (long long i = 0; i < K; ++i) out_counts[off + i] = best[i];
    }
  }
  delete[] counts;
  delete[] best;
  return 0;
}

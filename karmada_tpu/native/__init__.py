"""Native (C++) runtime kernels with lazy compilation and Python fallback.

The TPU compute path is JAX/XLA (ops/); these are the *host-side* data-plane
kernels — the greedy pod-placement loop and the node-level estimate sweep that
run per member cluster (the reference's estimator server hot loops,
estimate.go:88-112). Compiled once per environment with g++ into a cached
shared library; every entry point has a numpy fallback so the framework works
without a toolchain.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "placement.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    cache_dir = os.path.join(tempfile.gettempdir(), "karmada_tpu_native")
    os.makedirs(cache_dir, exist_ok=True)
    so_path = os.path.join(cache_dir, f"placement-{digest}.so")
    if not os.path.exists(so_path):
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-o", tmp, _SRC]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except (subprocess.SubprocessError, OSError, FileNotFoundError):
            return None
        os.replace(tmp, so_path)
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    LL = ctypes.c_longlong
    LLP = ctypes.POINTER(LL)
    U8P = ctypes.POINTER(ctypes.c_ubyte)
    lib.first_fit_place.restype = LL
    lib.first_fit_place.argtypes = [LLP, LLP, LLP, LLP, U8P, LLP, LLP, LL, LL, LL]
    lib.max_available_replicas.restype = None
    lib.max_available_replicas.argtypes = [LLP, LLP, LLP, LLP, U8P, LLP, LLP, LL, LL, LL]
    lib.class_dfs_batch.restype = LL
    lib.class_dfs_batch.argtypes = [LLP, LLP, LLP, LLP, LLP, LL, LL, LL, LL,
                                    LLP, LLP]
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is None and not _tried:
        with _lock:
            if _lib is None and not _tried:
                _lib = _build()
                _tried = True
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def _ll(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))


def _u8(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte))


def first_fit_place(
    alloc: np.ndarray,      # i64[N,R]
    requested: np.ndarray,  # i64[N,R] — mutated
    pod_count: np.ndarray,  # i64[N]  — mutated
    allowed: np.ndarray,    # i64[N]
    node_ok: np.ndarray,    # bool[N]
    req: np.ndarray,        # i64[R]
    replicas: int,
) -> tuple[int, np.ndarray]:
    """Greedy first-fit; returns (placed, fits[N]). Mutates requested/pod_count."""
    N, R = alloc.shape
    fits = np.zeros(N, dtype=np.int64)
    lib = get_lib()
    if lib is not None:
        alloc = np.ascontiguousarray(alloc, dtype=np.int64)
        req64 = np.ascontiguousarray(req, dtype=np.int64)
        ok = np.ascontiguousarray(node_ok, dtype=np.uint8)
        placed = int(
            lib.first_fit_place(
                _ll(alloc), _ll(requested), _ll(pod_count), _ll(allowed),
                _u8(ok), _ll(req64), _ll(fits), N, R, int(replicas),
            )
        )
        return placed, fits
    # -- fallback: vectorized numpy scan ---------------------------------
    remaining = int(replicas)
    for i in range(N):
        if remaining <= 0 or not node_ok[i]:
            continue
        fit = int(allowed[i] - pod_count[i])
        if fit <= 0:
            continue
        rest = alloc[i] - requested[i]
        with np.errstate(divide="ignore"):
            by_res = np.where(req > 0, rest // np.maximum(req, 1), np.iinfo(np.int64).max)
        fit = max(0, min(fit, int(by_res.min()), remaining))
        if fit > 0:
            requested[i] += req * fit
            pod_count[i] += fit
            fits[i] = fit
            remaining -= fit
    return replicas - remaining, fits


def max_available_replicas_native(
    alloc: np.ndarray,      # i64[N,R]
    requested: np.ndarray,  # i64[N,R]
    pod_count: np.ndarray,  # i64[N]
    allowed: np.ndarray,    # i64[N]
    node_ok: np.ndarray,    # bool[B,N]
    req: np.ndarray,        # i64[B,R]
) -> Optional[np.ndarray]:
    """Batched estimate via the native kernel; None when unavailable (caller
    uses the jitted XLA kernel instead)."""
    lib = get_lib()
    if lib is None:
        return None
    B, N = node_ok.shape
    R = alloc.shape[1]
    answers = np.zeros(B, dtype=np.int64)
    alloc = np.ascontiguousarray(alloc, dtype=np.int64)
    requested = np.ascontiguousarray(requested, dtype=np.int64)
    ok = np.ascontiguousarray(node_ok, dtype=np.uint8)
    req = np.ascontiguousarray(req, dtype=np.int64)
    lib.max_available_replicas(
        _ll(alloc), _ll(requested), _ll(pod_count), _ll(allowed),
        _u8(ok), _ll(req), _ll(answers), N, R, B,
    )
    return answers


def class_dfs_batch(
    cls_v: np.ndarray,      # i64[total] class values, rows concatenated
    cls_w: np.ndarray,      # i64[total] class weights
    cls_m: np.ndarray,      # i64[total] class multiplicities
    row_off: np.ndarray,    # i64[n_rows+1] row offsets into cls_*
    kmax_row: np.ndarray,   # i64[n_rows]
    kmin: int,
    cmin: int,
    budget: int,
) -> "Optional[tuple[np.ndarray, np.ndarray]]":
    """Batched class-collapsed spread-selection DFS
    (sched/spread_batch._select_row_class_dfs semantics). Returns
    (counts i64[total], status i64[n_rows]: 1 winner / 0 none-feasible /
    -1 budget) or None when the native library is unavailable (callers run
    the Python per-row path instead)."""
    lib = get_lib()
    if lib is None:
        return None
    n_rows = len(row_off) - 1
    cls_v = np.ascontiguousarray(cls_v, dtype=np.int64)
    cls_w = np.ascontiguousarray(cls_w, dtype=np.int64)
    cls_m = np.ascontiguousarray(cls_m, dtype=np.int64)
    row_off = np.ascontiguousarray(row_off, dtype=np.int64)
    kmax_row = np.ascontiguousarray(kmax_row, dtype=np.int64)
    counts = np.zeros(len(cls_v), np.int64)
    status = np.zeros(n_rows, np.int64)
    lib.class_dfs_batch(
        _ll(cls_v), _ll(cls_w), _ll(cls_m), _ll(row_off), _ll(kmax_row),
        n_rows, int(kmin), int(cmin), int(budget), _ll(counts), _ll(status),
    )
    return counts, status

"""Event recording (reference: pkg/events/events.go — the reasons registry —
plus the EventRecorder usage in scheduler.go:964-1010 which records events on
both the binding and the referenced template).

Events are plain store objects (kind "Event") so the query plane and CLI can
list them like any other resource; a bounded ring per recorder prevents
unbounded growth in long-lived processes.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .api.meta import ObjectMeta, new_uid

# Reasons registry (pkg/events/events.go). Grouped as in the reference.
REASON_SCHEDULE_BINDING_SUCCEED = "ScheduleBindingSucceed"
REASON_SCHEDULE_BINDING_FAILED = "ScheduleBindingFailed"
REASON_DESCHEDULE_BINDING_SUCCEED = "DescheduleBindingSucceed"
REASON_DESCHEDULE_BINDING_FAILED = "DescheduleBindingFailed"
REASON_EVICT_WORKLOAD_FROM_CLUSTER_SUCCEED = "EvictWorkloadFromClusterSucceed"
REASON_EVICT_WORKLOAD_FROM_CLUSTER_FAILED = "EvictWorkloadFromClusterFailed"
REASON_SYNC_WORK_SUCCEED = "SyncWorkSucceed"
REASON_SYNC_WORK_FAILED = "SyncWorkFailed"
REASON_APPLY_POLICY_SUCCEED = "ApplyPolicySucceed"
REASON_APPLY_POLICY_FAILED = "ApplyPolicyFailed"
REASON_PREEMPT_POLICY_SUCCEED = "PreemptPolicySucceed"
REASON_PREEMPT_POLICY_FAILED = "PreemptPolicyFailed"
REASON_CLUSTER_NOT_READY = "ClusterNotReady"
REASON_CLUSTER_READY = "ClusterReady"
REASON_TAINT_CLUSTER_SUCCEED = "TaintClusterSucceed"
REASON_WORK_DISPATCHING = "WorkDispatching"

TYPE_NORMAL = "Normal"
TYPE_WARNING = "Warning"


@dataclass
class Event:
    kind: str = "Event"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_name: str = ""
    involved_namespace: str = ""
    type: str = TYPE_NORMAL
    reason: str = ""
    message: str = ""
    count: int = 1
    timestamp: float = 0.0

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


class EventRecorder:
    """Records events into the store, deduplicating consecutive identical
    (object, reason, message) tuples by bumping `count` (client-go recorder
    aggregation behavior)."""

    def __init__(self, store, clock=None, max_events: int = 2048):
        self.store = store
        self.clock = clock
        self.max_events = max_events
        self._order: list[str] = []  # store keys, oldest first

    def event(
        self,
        obj,
        etype: str,
        reason: str,
        message: str,
    ) -> Event:
        involved_kind = getattr(obj, "kind", "")
        meta: Optional[ObjectMeta] = getattr(obj, "metadata", None)
        involved_name = meta.name if meta else ""
        involved_ns = meta.namespace if meta else ""
        ts = self.clock.now() if self.clock else 0.0

        # dedup against the most recent event for the same object+reason
        for key in reversed(self._order):
            ns, _, name = key.partition("/")
            prev = self.store.try_get("Event", name, ns)
            if prev is None:
                continue
            if (
                prev.involved_kind == involved_kind
                and prev.involved_name == involved_name
                and prev.involved_namespace == involved_ns
            ):
                if prev.reason == reason and prev.message == message:
                    prev.count += 1
                    prev.timestamp = ts
                    self.store.update(prev)
                    return prev
                break

        ev = Event(
            metadata=ObjectMeta(name=new_uid("event"), namespace=involved_ns),
            involved_kind=involved_kind,
            involved_name=involved_name,
            involved_namespace=involved_ns,
            type=etype,
            reason=reason,
            message=message,
            timestamp=ts,
        )
        self.store.create(ev)
        self._order.append(ev.metadata.key())
        while len(self._order) > self.max_events:
            key = self._order.pop(0)
            ns, _, name = key.partition("/")
            self.store.delete("Event", name, ns)
        return ev

    def events_for(self, obj) -> list[Event]:
        meta = getattr(obj, "metadata", None)
        if meta is None:
            return []
        return [
            e
            for e in self.store.list("Event")
            if e.involved_kind == getattr(obj, "kind", "")
            and e.involved_name == meta.name
            and e.involved_namespace == meta.namespace
        ]

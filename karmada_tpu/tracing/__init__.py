"""Observability: local utiltrace spans, pprof endpoints, and the
fleet-wide distributed placement-tracing plane (docs/OBSERVABILITY.md).

- `Trace` (tracing/utiltrace.py) — k8s.io/utils/trace-style local spans
  logged only when slow (ref estimate.go:37-38);
- `ProfileServer` (tracing/profile.py) — opt-in /debug/pprof endpoints,
  single-flight captures, scrape-token protected;
- `tracer` / `Span` / `TraceCollector` (tracing/spans.py, collect.py) —
  per-binding causal traces from template write to member apply, head
  sampling + forced tail sampling of SLO breaches, `X-Karmada-Trace`
  context propagation, served at GET /traces and rendered by
  `karmadactl trace binding`;
- `slo_report()` — the per-stage p50/p99 attribution table the fleet
  soak emits (ROADMAP item 5a).
"""
from .collect import TraceCollector
from .profile import ProfileServer, _sample_all_threads, start_profile_server
from .render import critical_path, render_waterfall
from .spans import (
    APPLY_SPAN_ANNOTATION,
    TRACE_HEADER,
    PlacementTracer,
    Span,
    current_context,
    format_trace_header,
    new_span_id,
    parse_trace_header,
    slo_report,
    trace_context,
    tracer,
)
from .utiltrace import DEFAULT_SLOW_THRESHOLD_S, Trace, logger

__all__ = [
    "APPLY_SPAN_ANNOTATION",
    "DEFAULT_SLOW_THRESHOLD_S",
    "PlacementTracer",
    "ProfileServer",
    "Span",
    "TRACE_HEADER",
    "Trace",
    "TraceCollector",
    "critical_path",
    "current_context",
    "format_trace_header",
    "logger",
    "new_span_id",
    "parse_trace_header",
    "render_waterfall",
    "slo_report",
    "start_profile_server",
    "trace_context",
    "tracer",
    "_sample_all_threads",
]

"""Plane-side trace collection off the store event sink.

The collector rides `Store.add_event_sink` — the under-lock, rv-ordered
seam the watch cache uses — so its timestamps are the commit order, not a
watcher race. It must therefore stay FAST and never call back into the
store; everything it does is bounded dict work on the process-global
tracer.

Three event families matter:

- template-kind writes (any Unstructured gvk, i.e. a kind carrying an
  apiVersion prefix): remember the commit wall time per object in a
  bounded LRU — the anchor for the template_write -> detector_match span
  when the binding appears;
- ResourceBinding ADDED: begin the binding's trace and emit the
  template_write / detector_match / binding_create spans from the
  remembered anchor;
- Work events carrying the `trace.karmada.io/apply-span` annotation: the
  pull-mode agent's apply timing, shipped on the existing coalesced
  agent-status write — lifted here into a member_apply span on the owning
  binding's trace, deduped by the annotation's span id so coalescer
  replays and redirect re-sends can't double-count.
"""
from __future__ import annotations

import json
import logging
import time
from collections import OrderedDict
from typing import Optional

from .spans import APPLY_SPAN_ANNOTATION, PlacementTracer
from .spans import tracer as global_tracer

log = logging.getLogger(__name__)

_TPL_LRU = 4096


class TraceCollector:
    def __init__(self, store, use_tracer: Optional[PlacementTracer] = None):
        self.store = store
        self.tracer = use_tracer or global_tracer
        self._tpl: OrderedDict[tuple[str, str], float] = OrderedDict()
        self._attached = False
        self._warned = False

    def attach(self) -> None:
        if not self._attached:
            self.store.add_event_sink(self._sink)
            self._attached = True

    def detach(self) -> None:
        if self._attached:
            self.store.remove_event_sink(self._sink)
            self._attached = False

    # -- the sink (runs UNDER the store lock; never raise) -----------------

    def _sink(self, kind: str, event: str, obj) -> None:
        t = self.tracer
        if not t.enabled:
            return
        try:
            if kind == "ResourceBinding":
                if event == "ADDED":
                    self._on_binding_added(obj)
            elif kind == "Work" or kind.endswith("/Work"):
                self._on_work(obj)
            elif "/" in kind:
                # an Unstructured (template) kind: remember the commit time
                self._tpl[(kind, obj.metadata.key())] = time.time()
                while len(self._tpl) > _TPL_LRU:
                    self._tpl.popitem(last=False)
        except Exception:  # noqa: BLE001 - a sink raise surfaces to the mutator
            if not self._warned:
                self._warned = True
                log.exception("trace collector sink failed (logged once)")

    def _on_binding_added(self, rb) -> None:
        t = self.tracer
        key = rb.metadata.key()
        rec = t.begin(key, rb.metadata.uid or key)
        if rec is None:
            return
        now = time.time()
        ref = getattr(rb.spec, "resource", None)
        if ref is not None and ref.kind:
            tpl_kind = f"{ref.api_version}/{ref.kind}"
            # same key format ObjectMeta.key() produced in the sink
            tpl_key = (f"{ref.namespace}/{ref.name}" if ref.namespace
                       else ref.name)
            ts = self._tpl.get((tpl_kind, tpl_key))
            if ts is not None:
                t.record(key, "template_write", ts, ts)
                t.record(key, "detector_match", ts, now,
                         template=f"{tpl_kind} {tpl_key}")
        t.record(key, "binding_create", now, now)

    def _on_work(self, work) -> None:
        raw = work.metadata.annotations.get(APPLY_SPAN_ANNOTATION)
        if not raw:
            return
        try:
            span = json.loads(raw)
        except ValueError:
            return
        from ..api.work import (
            WORK_BINDING_NAME_LABEL,
            WORK_BINDING_NAMESPACE_LABEL,
        )

        ns = work.metadata.labels.get(WORK_BINDING_NAMESPACE_LABEL)
        name = work.metadata.labels.get(WORK_BINDING_NAME_LABEL)
        if not name:
            return
        # same key format ObjectMeta.key() produced when the trace began:
        # a cluster-scoped binding's key is the bare name
        self.tracer.record(
            f"{ns}/{name}" if ns else name, "member_apply",
            float(span.get("start") or 0.0), float(span.get("end") or 0.0),
            span_id=str(span.get("id") or ""), placed=True,
            cluster=str(span.get("cluster") or ""),
        )

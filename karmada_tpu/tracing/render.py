"""Waterfall rendering for `karmadactl trace binding <ns>/<name>`.

Pure text formatting over a trace dict (PlacementTracer.get /
GET /traces): one row per span, offset + duration + a proportional bar,
with the CRITICAL PATH — the chain of spans that actually gates the
end-to-end latency — marked so the operator reads WHERE the time went
without arithmetic.
"""
from __future__ import annotations

from typing import Optional

BAR_WIDTH = 36


def critical_path(spans: list[dict]) -> set[int]:
    """Indices of the spans on the critical path: walk forward from the
    trace start, at each point taking the overlapping span that extends
    the frontier furthest (gaps jump to the next span by start time).
    Instant markers (zero duration) never gate anything."""
    # the "placement" span is the admission->patch ENVELOPE (the SLO
    # measurement), not a stage — it would shadow every stage inside it
    timed = [(i, s) for i, s in enumerate(spans)
             if s["end"] > s["start"] and s["name"] != "placement"]
    if not timed:
        return set()
    timed.sort(key=lambda t: (t[1]["start"], -t[1]["end"]))
    path: set[int] = set()
    frontier = min(s["start"] for _, s in timed)
    j = 0
    while j < len(timed):
        # candidates overlapping the frontier
        best = None
        for i, s in timed[j:]:
            if s["start"] > frontier + 1e-9:
                break
            if s["end"] > frontier + 1e-9 and (
                    best is None or s["end"] > best[1]["end"]):
                best = (i, s)
        if best is None:
            # gap: jump to the next span that starts past the frontier
            nxt = next(((i, s) for i, s in timed
                        if s["start"] > frontier + 1e-9), None)
            if nxt is None:
                break
            best = nxt
        path.add(best[0])
        frontier = best[1]["end"]
        while j < len(timed) and timed[j][1]["end"] <= frontier + 1e-9:
            j += 1
    return path


def render_waterfall(trace: Optional[dict]) -> str:
    if not trace:
        return ("no trace retained for this binding (head sampling may "
                "have dropped it — see docs/OBSERVABILITY.md sampling "
                "knobs; slow bindings above the SLO threshold are always "
                "retained)")
    spans = trace.get("spans") or []
    head = (f"TRACE {trace.get('key') or trace.get('trace_id')}  "
            f"trace_id={trace.get('trace_id')}  epoch={trace.get('epoch')}  "
            f"retained={trace.get('retained') or 'pending'}")
    if trace.get("placement_s") is not None:
        head += f"  placement={trace['placement_s'] * 1e3:.1f}ms"
    if not spans:
        return head + "\n  (no spans recorded)"
    t0 = min(s["start"] for s in spans)
    t1 = max(s["end"] for s in spans)
    total = max(t1 - t0, 1e-9)
    crit = critical_path(spans)
    lines = [head, f"  window {total * 1e3:.1f}ms  "
                   f"({len(spans)} spans; * = critical path)"]
    for i, s in enumerate(spans):
        off = s["start"] - t0
        dur = max(0.0, s["end"] - s["start"])
        pre = int(round(off / total * BAR_WIDTH))
        width = max(1, int(round(dur / total * BAR_WIDTH))) if dur else 0
        pre = min(pre, BAR_WIDTH - max(width, 1))
        bar = "·" * pre + ("█" * width if width else "▏") \
            + "·" * max(0, BAR_WIDTH - pre - max(width, 1))
        mark = "*" if i in crit else " "
        name = s["name"]
        attrs = s.get("attrs") or {}
        suffix = ""
        if attrs.get("cluster"):
            suffix = f"  [{attrs['cluster']}]"
        elif attrs.get("launch"):
            suffix = f"  [{attrs['launch']}]"
        lines.append(
            f" {mark} {name:<20} {off * 1e3:>9.1f}ms "
            f"{dur * 1e3:>9.1f}ms  |{bar}|{suffix}"
        )
    crit_names = [spans[i]["name"] for i in sorted(
        crit, key=lambda i: spans[i]["start"])]
    if crit_names:
        lines.append("  critical path: " + " -> ".join(crit_names))
    return "\n".join(lines)

"""utiltrace-style local spans with slow-path logging.

Parity with the reference's k8s.io/utils/trace usage: named spans with
fields and nested steps, logged ONLY when the total duration crosses a
threshold (ref pkg/estimator/server/estimate.go:37-38 logs estimates slower
than 100 ms with per-step timing). This is the PROCESS-LOCAL aid; the
fleet-wide causal layer lives in tracing/spans.py (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

logger = logging.getLogger("karmada_tpu.trace")

DEFAULT_SLOW_THRESHOLD_S = 0.100  # estimate.go:38


@dataclass
class _Step:
    msg: str
    at: float


@dataclass
class Trace:
    """utiltrace.Trace: step() marks checkpoints; log_if_long() emits the
    whole span breakdown when the total exceeds the threshold."""

    name: str
    fields: dict = field(default_factory=dict)
    clock: Callable[[], float] = time.perf_counter
    sink: Optional[Callable[[str], None]] = None  # default: logger.warning

    def __post_init__(self):
        self.start = self.clock()
        self.steps: list[_Step] = []

    def step(self, msg: str) -> None:
        self.steps.append(_Step(msg, self.clock()))

    def duration(self) -> float:
        return self.clock() - self.start

    def log_if_long(self, threshold_s: float = DEFAULT_SLOW_THRESHOLD_S) -> bool:
        """Emit the span if it ran long; returns whether it was emitted."""
        total = self.duration()
        if total < threshold_s:
            return False
        parts = [f'"{self.name}"']
        if self.fields:
            parts.append(
                " ".join(f"{k}={v}" for k, v in self.fields.items())
            )
        parts.append(f"total={total * 1e3:.1f}ms:")
        prev = self.start
        for s in self.steps:
            parts.append(f"[{(s.at - prev) * 1e3:.1f}ms] {s.msg};")
            prev = s.at
        tail = total - (prev - self.start)
        if self.steps and tail > 0:
            parts.append(f"[{tail * 1e3:.1f}ms] (rest)")
        line = "Trace " + " ".join(parts)
        (self.sink or logger.warning)(line)
        return True

"""pprof-equivalent profile endpoints (ref pkg/sharedcli/profileflag).

`ProfileServer` serves whole-process sampled CPU profiles (all threads'
stacks) and heap snapshots (tracemalloc) for a live process, opt-in like
the reference's --enable-pprof. Wired into the server/sched/agent daemons
behind `--enable-pprof` and protected by the same read-only scrape token
the /metrics routes accept (docs/OBSERVABILITY.md) — an unauthenticated
profile endpoint leaks source paths and timing, and the capture itself is
expensive enough to be a DoS lever.

Captures are SINGLE-FLIGHT: a profile request holds a ThreadingHTTPServer
handler thread for up to 30 s, so concurrent requests are bounded to one
in-flight capture and the rest answer 429 instead of silently stacking
handler threads behind each other.
"""
from __future__ import annotations

import json
import threading
import time
import tracemalloc
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse


def _sample_all_threads(seconds: float, interval: float = 0.01) -> str:
    """Statistical whole-process CPU profile: periodically snapshot every
    thread's stack (sys._current_frames) and count frames. cProfile is
    per-thread — enabling it in the HTTP handler would only ever profile the
    handler's own sleep — so sampling is the honest pprof-style view of a
    live multi-threaded process."""
    import sys

    me = threading.get_ident()
    counts: dict[tuple[str, int, str], int] = {}
    samples = 0
    deadline = time.perf_counter() + seconds
    while time.perf_counter() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            f = frame
            while f is not None:
                key = (f.f_code.co_filename, f.f_lineno, f.f_code.co_name)
                counts[key] = counts.get(key, 0) + 1
                f = f.f_back
        samples += 1
        time.sleep(interval)
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:60]
    lines = [f"samples: {samples} (interval {interval * 1e3:.0f}ms, all threads)"]
    for (fname, lineno, func), n in top:
        lines.append(f"{n:6d}  {func}  {fname}:{lineno}")
    return "\n".join(lines)


class _ProfileHandler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def do_GET(self):  # noqa: N802 (http.server API)
        srv: ProfileServer = self.server.profile_server  # type: ignore[attr-defined]
        if not srv.auth_ok(self):
            self._err(401, "unauthorized")
            return
        url = urlparse(self.path)
        if url.path == "/debug/pprof/profile":
            try:
                seconds = float(parse_qs(url.query).get("seconds", ["2"])[0])
            except ValueError:
                self._err(400, "seconds must be a number")
                return
            # single-flight: one in-flight capture; a 30 s sample must not
            # pile concurrent requests onto more handler threads
            if not srv.capture_slot.acquire(blocking=False):
                self._err(429, "a profile capture is already in flight; "
                               "retry when it completes")
                return
            try:
                self._ok(_sample_all_threads(min(seconds, 30.0)))
            finally:
                srv.capture_slot.release()
        elif url.path == "/debug/pprof/heap":
            if not tracemalloc.is_tracing():
                # tracking starts now; only allocations made from this point
                # are attributable (same lazy-start shape as pprof heap)
                tracemalloc.start()
                self._ok("tracemalloc started; re-request for allocation data")
                return
            snap = tracemalloc.take_snapshot()
            top = snap.statistics("lineno")[:50]
            self._ok("\n".join(str(s) for s in top) or "no tracked allocations")
        elif url.path == "/debug/pprof/":
            self._ok(json.dumps({"endpoints": ["profile?seconds=N", "heap"]}))
        else:
            self.send_error(404)

    def _ok(self, body: str) -> None:
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _err(self, status: int, msg: str) -> None:
        data = json.dumps({"error": msg}).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class ProfileServer:
    """pkg/sharedcli/profileflag equivalent: opt-in /debug/pprof endpoints.

    `token` / `scrape_token` guard every route with the same policy as
    GET /metrics (either credential is accepted; with neither configured
    the loopback default stays open)."""

    def __init__(self, enable_pprof: bool = False, bind_address: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 scrape_token: Optional[str] = None):
        self.enabled = enable_pprof
        self._token = token
        self._scrape_token = scrape_token
        self.capture_slot = threading.BoundedSemaphore(1)
        self._server: Optional[ThreadingHTTPServer] = None
        self.port = 0
        if enable_pprof:
            self._server = ThreadingHTTPServer((bind_address, port), _ProfileHandler)
            self._server.profile_server = self  # type: ignore[attr-defined]
            self.port = self._server.server_address[1]
            t = threading.Thread(target=self._server.serve_forever, daemon=True)
            t.start()

    def auth_ok(self, handler) -> bool:
        from ..server.metricsserver import scrape_auth_ok

        return scrape_auth_ok(handler, self._token, self._scrape_token)

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None


def start_profile_server(enabled: bool, port: int = 0,
                         host: str = "127.0.0.1",
                         token: Optional[str] = None,
                         scrape_token_file: str = "",
                         scrape_token: Optional[str] = None,
                         ) -> Optional[ProfileServer]:
    """Daemon-main helper mirroring metricsserver.start_metrics_server:
    materializes the --scrape-token-file credential (shared with /metrics;
    pass `scrape_token` directly when the daemon already resolved it) and
    prints the bound URL so drivers can find the ephemeral port."""
    if not enabled:
        return None
    if scrape_token is None and scrape_token_file:
        from ..server.tlsmaterial import ensure_token

        scrape_token = ensure_token(scrape_token_file)
    srv = ProfileServer(enable_pprof=True, bind_address=host, port=port,
                        token=token, scrape_token=scrape_token)
    print(f"pprof: serving on http://{host}:{srv.port}/debug/pprof/",
          flush=True)
    return srv

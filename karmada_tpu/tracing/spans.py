"""Distributed placement tracing: causal spans from template write to
member apply (docs/OBSERVABILITY.md).

Every binding gets a trace keyed by (uid, admission epoch); components
along the placement pipeline append COMPLETED spans (start/end wall-clock
seconds — cross-process comparable) as the binding moves through them:

    template_write -> detector_match -> binding_create -> queue_wait
    (gang_hold / queue_aging as their own spans) -> solve (one shared
    launch fanned to its member rows) -> commit (the rv-checked batch
    cohort) -> work_fanout -> member_apply -> status_aggregation

Sampling is decided at PLACEMENT time, not admission time, so forced tail
sampling is possible: spans for every binding accumulate in a bounded
pending map (cheap tuple appends), and when the placement latency is known
the trace is RETAINED iff it head-samples (deterministic: crc32(trace_id)
modulo the sampling ratio — every process agrees without coordination) OR
the latency breached the placement-SLO slow threshold. Dropped traces cost
a dict pop. Retained traces land in a bounded ring served at GET /traces
and keep accepting the post-placement spans (Work fan-out, member apply,
status aggregation) that arrive after the placement patched.

Cross-process propagation rides the `X-Karmada-Trace` header on
RemoteStore HTTP writes (the receiving plane records the server-side
commit span under the caller's context; span ids are generated once per
LOGICAL write so replay-idempotent retries and 409-redirect re-sends
dedup to exactly one span) and the coalesced agent-status path for
pull-mode apply spans (the agent stamps its apply timing onto the Work as
the `trace.karmada.io/apply-span` annotation; the plane's TraceCollector
lifts it — same id under replay, so coalescer re-sends can't double-count).

Knobs (env, also constructor args): KARMADA_TPU_TRACE_SAMPLE (head
sampling ratio 1/N, default 64; 1 = sample everything, 0 disables
head sampling entirely so only SLO breaches retain),
KARMADA_TPU_TRACE_SLOW_MS (tail-sampling threshold, default 1000 — the
placement-SLO histogram's slow bucket), KARMADA_TPU_TRACING=0 (off).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from collections import OrderedDict
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Optional

TRACE_HEADER = "X-Karmada-Trace"

# Work annotation carrying the pull-mode apply span (agent -> plane over
# the existing coalesced agent-status write; see agent/agent.py)
APPLY_SPAN_ANNOTATION = "trace.karmada.io/apply-span"

DEFAULT_HEAD_SAMPLE = 64       # 1 in 64 traces head-sample
DEFAULT_SLOW_PLACEMENT_S = 1.0  # tail-sample anything slower than this
DEFAULT_RING_CAPACITY = 512    # retained traces
DEFAULT_PENDING_CAP = 16384    # in-flight (pre-placement) traces


@dataclass
class Span:
    name: str
    start: float                 # wall seconds (time.time)
    end: float
    span_id: str = ""
    parent_id: str = ""
    attrs: dict = field(default_factory=dict)

    def duration(self) -> float:
        return max(0.0, self.end - self.start)

    def to_dict(self) -> dict:
        d = {"name": self.name, "start": self.start, "end": self.end,
             "duration_ms": round(self.duration() * 1e3, 3)}
        if self.span_id:
            d["span_id"] = self.span_id
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class TraceRecord:
    __slots__ = ("trace_id", "uid", "key", "epoch", "started", "admitted",
                 "spans", "retained", "placement_s")

    def __init__(self, trace_id: str, uid: str, key: str, epoch: int):
        self.trace_id = trace_id
        self.uid = uid
        self.key = key                 # binding "namespace/name" ("" = orphan)
        self.epoch = epoch
        self.started = time.time()
        self.admitted: Optional[float] = None
        self.spans: list[Span] = []
        self.retained = ""             # "" pending | "head" | "slo"
        self.placement_s: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id, "uid": self.uid, "key": self.key,
            "epoch": self.epoch, "started": self.started,
            "retained": self.retained,
            "placement_s": self.placement_s,
            "spans": [s.to_dict() for s in sorted(
                self.spans, key=lambda s: (s.start, s.end))],
        }

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id, "key": self.key, "epoch": self.epoch,
            "retained": self.retained, "placement_s": self.placement_s,
            "spans": len(self.spans),
        }


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class PlacementTracer:
    """Process-global trace buffer + sampler. Every method is a cheap
    no-op when `enabled` is False; all state is bounded. The lock is a
    LEAF — no method calls out while holding it, so recording from
    under-lock store sinks, watch handlers, and the pipeline writer can
    never invert."""

    def __init__(self, head_sample: Optional[int] = None,
                 slow_threshold_s: Optional[float] = None,
                 capacity: int = DEFAULT_RING_CAPACITY,
                 pending_cap: int = DEFAULT_PENDING_CAP):
        self.enabled = os.environ.get("KARMADA_TPU_TRACING", "") not in (
            "0", "off", "false")
        self.head_sample = (
            _env_int("KARMADA_TPU_TRACE_SAMPLE", DEFAULT_HEAD_SAMPLE)
            if head_sample is None else head_sample
        )
        if slow_threshold_s is None:
            slow_threshold_s = _env_int(
                "KARMADA_TPU_TRACE_SLOW_MS",
                int(DEFAULT_SLOW_PLACEMENT_S * 1000)) / 1000.0
        self.slow_threshold_s = slow_threshold_s
        self.capacity = capacity
        self.pending_cap = pending_cap
        self._lock = threading.Lock()
        self._pending: OrderedDict[str, TraceRecord] = OrderedDict()
        self._ring: OrderedDict[str, TraceRecord] = OrderedDict()
        self._by_key: dict[str, str] = {}   # key -> retained trace_id
        self._tid_pending: dict[str, TraceRecord] = {}
        self._marks: dict[tuple[str, str], float] = {}
        self._seen: OrderedDict[tuple[str, str], None] = OrderedDict()
        self._sid = itertools.count(1)
        # drops/evictions are observable, not silent (docs/OBSERVABILITY.md)
        self.evicted = 0

    # -- sampling ----------------------------------------------------------

    def head_sampled(self, trace_id: str) -> bool:
        """Deterministic head decision: a pure function of the trace id, so
        every process (plane, scheduler, agent) agrees without any
        coordination. head_sample<=0 means NO head sampling (tail only);
        1 samples everything."""
        if self.head_sample <= 0:
            return False
        if self.head_sample == 1:
            return True
        return zlib.crc32(trace_id.encode()) % self.head_sample == 0

    # -- trace lifecycle ---------------------------------------------------

    def _insert_pending(self, key: str, rec: TraceRecord) -> None:
        """Insert a fresh pending record and enforce the bound (caller
        holds the lock)."""
        self._pending[key] = rec
        self._tid_pending[rec.trace_id] = rec
        while len(self._pending) > self.pending_cap:
            _, old = self._pending.popitem(last=False)
            self._tid_pending.pop(old.trace_id, None)
            self.evicted += 1

    def begin(self, key: str, uid: str, epoch: int = 0
              ) -> Optional[TraceRecord]:
        """Start (or return) the pending trace for a binding key. Called by
        the plane collector at binding create and by the scheduler at
        admission; setdefault semantics mirror AdmissionLog._admitted — a
        pending stretch has ONE trace however many events coalesce into it."""
        if not self.enabled:
            return None
        with self._lock:
            rec = self._pending.get(key)
            if rec is None:
                rec = TraceRecord(f"{uid}:{epoch}", uid, key, epoch)
                self._insert_pending(key, rec)
            return rec

    def admit(self, key: str, uid: str, epoch: int) -> None:
        """Queue admission (the streaming AdmissionLog's note): stamp the
        admitted-at wall time — the start of the queue_wait span — and
        re-key a collector-begun trace to its real (uid, epoch) identity.
        Only the FIRST admission of a pending stretch sticks (coalesced
        re-events keep the original clock, exactly like the SLO histogram)."""
        if not self.enabled:
            return
        with self._lock:
            rec = self._pending.get(key)
            if rec is None:
                rec = TraceRecord(f"{uid}:{epoch}", uid, key, epoch)
                self._insert_pending(key, rec)
            if rec.admitted is None:
                rec.admitted = time.time()
                if rec.epoch != epoch:
                    # the collector began this trace at binding create with
                    # a provisional epoch; adopt the admission epoch — the
                    # trace KEY of the data model (uid, admission epoch)
                    self._tid_pending.pop(rec.trace_id, None)
                    rec.epoch = epoch
                    rec.trace_id = f"{rec.uid}:{epoch}"
                    self._tid_pending[rec.trace_id] = rec

    def drained(self, key: str, aging_step: float = 0.0,
                **attrs: Any) -> None:
        """The binding left the queue into a micro-batch: close the
        queue_wait span (admission -> drain), with the aged portion as its
        own queue_aging span when the wait crossed the queue's aging step.
        `attrs` ride the queue_wait span (the sharded plane stamps which
        shard's queue held the key)."""
        if not self.enabled:
            return
        now = time.time()
        with self._lock:
            rec = self._pending.get(key)
            if rec is None or rec.admitted is None:
                return
            rec.spans.append(Span("queue_wait", rec.admitted, now,
                                  attrs=dict(attrs)))
            if aging_step > 0 and now - rec.admitted > aging_step:
                rec.spans.append(Span(
                    "queue_aging", rec.admitted + aging_step, now,
                    attrs={"aging_step_s": aging_step}))

    def record(self, key: str, name: str, start: float, end: float,
               span_id: str = "", parent_id: str = "", placed: bool = False,
               **attrs: Any) -> None:
        """Append a completed span to the binding's trace. `placed=False`
        (the pre-placement stages: detector, solve, commit) targets the
        PENDING stretch; `placed=True` (the stages that land AFTER the
        placement patched: work fan-out, member apply, status aggregation)
        targets the RETAINED trace only — the patch's own watch event
        opens a fresh pending stretch for the key, and appending there
        would attach this placement's tail to the next stretch's trace.
        No-op when the binding has no live trace (dropped by sampling)."""
        if not self.enabled:
            return
        with self._lock:
            if placed:
                tid = self._by_key.get(key)
                rec = self._ring.get(tid) if tid else None
                if rec is not None and end < rec.started:
                    # causal guard: a post-placement span that ENDED before
                    # this trace even began belongs to a PREVIOUS placement
                    # (e.g. the apply-span annotation preserved on a Work
                    # the controller rewrote for a re-placed binding) — it
                    # must not stretch the new waterfall backwards
                    return
            else:
                rec = self._pending.get(key)
            if rec is None:
                return
            if span_id:
                k = (rec.trace_id, span_id)
                if k in self._seen:
                    return
                self._remember(k)
            rec.spans.append(Span(name, start, end, span_id=span_id,
                                  parent_id=parent_id, attrs=dict(attrs)))

    def record_trace(self, trace_id: str, name: str, start: float,
                     end: float, span_id: str = "", **attrs: Any) -> None:
        """Append a span by TRACE id — the cross-process entry point (the
        apiserver's commit span under an X-Karmada-Trace header). With a
        span_id, replays dedup to exactly one span. An unknown trace id
        begins an orphan pending record so remote-context spans are not
        silently lost."""
        if not self.enabled:
            return
        with self._lock:
            if span_id:
                k = (trace_id, span_id)
                if k in self._seen:
                    return
                self._remember(k)
            rec = self._tid_pending.get(trace_id) or self._ring.get(trace_id)
            if rec is None:
                rec = TraceRecord(trace_id, trace_id.rsplit(":", 1)[0],
                                  "", 0)
                self._insert_pending(f"~{trace_id}", rec)
            rec.spans.append(Span(name, start, end, span_id=span_id,
                                  attrs=dict(attrs)))

    def mark(self, key: str, name: str) -> None:
        """Open a long-running mark (gang hold) closed by unmark()."""
        if not self.enabled:
            return
        with self._lock:
            self._marks.setdefault((key, name), time.time())
            # bound abandoned marks (a gang that timed out and never
            # re-offered): drop the oldest insertion past the cap
            while len(self._marks) > 4096:
                del self._marks[next(iter(self._marks))]

    def unmark(self, key: str, name: str, **attrs: Any) -> None:
        if not self.enabled:
            return
        with self._lock:
            t0 = self._marks.pop((key, name), None)
            if t0 is None:
                return
            rec = self._pending.get(key)
            if rec is not None:
                rec.spans.append(Span(name, t0, time.time(),
                                      attrs=dict(attrs)))

    def finish_placement(self, key: str, latency_s: Optional[float]
                         ) -> Optional[str]:
        """The placement patched: decide retention. Retained = head-sampled
        (deterministic) OR the latency breached the SLO slow threshold
        (forced tail sampling — the slow trace survives even when head
        sampling would drop it). Returns the trace id when retained (the
        caller feeds it to the SLO histogram as the bucket exemplar)."""
        if not self.enabled:
            return None
        now = time.time()
        with self._lock:
            rec = self._pending.pop(key, None)
            if rec is None:
                return None
            self._tid_pending.pop(rec.trace_id, None)
            rec.placement_s = latency_s
            slow = (latency_s is not None
                    and latency_s >= self.slow_threshold_s)
            head = self.head_sampled(rec.trace_id)
            if not (head or slow):
                return None
            rec.retained = "head" if head else "slo"
            if rec.admitted is not None:
                rec.spans.append(Span("placement", rec.admitted, now,
                                      attrs={"latency_s": latency_s}))
            self._ring[rec.trace_id] = rec
            self._by_key[key] = rec.trace_id
            while len(self._ring) > self.capacity:
                tid, old = self._ring.popitem(last=False)
                if self._by_key.get(old.key) == tid:
                    del self._by_key[old.key]
            return rec.trace_id

    def settle(self, key: str) -> None:
        """The pending stretch resolved without a measured placement
        (clean drain, suspension, invalidation): drop the trace."""
        if not self.enabled:
            return
        with self._lock:
            rec = self._pending.pop(key, None)
            if rec is not None:
                self._tid_pending.pop(rec.trace_id, None)

    def forget(self, key: str) -> None:
        if not self.enabled:
            return
        with self._lock:
            rec = self._pending.pop(key, None)
            if rec is not None:
                self._tid_pending.pop(rec.trace_id, None)
            for mk in [m for m in self._marks if m[0] == key]:
                del self._marks[mk]

    # -- serving -----------------------------------------------------------

    def traces(self) -> list[dict]:
        with self._lock:
            return [r.summary() for r in reversed(self._ring.values())]

    def get(self, trace_id: Optional[str] = None,
            key: Optional[str] = None) -> Optional[dict]:
        with self._lock:
            rec = None
            if trace_id:
                rec = self._ring.get(trace_id) or self._tid_pending.get(
                    trace_id)
            elif key:
                tid = self._by_key.get(key)
                rec = (self._ring.get(tid) if tid
                       else self._pending.get(key))
            return None if rec is None else rec.to_dict()

    def retained(self) -> list[TraceRecord]:
        with self._lock:
            return list(self._ring.values())

    def config(self) -> dict:
        return {
            "enabled": self.enabled,
            "head_sample": self.head_sample,
            "slow_threshold_s": self.slow_threshold_s,
            "capacity": self.capacity,
            "pending": len(self._pending),
            "retained": len(self._ring),
            "evicted": self.evicted,
        }

    def reset(self) -> None:
        with self._lock:
            self._pending.clear()
            self._ring.clear()
            self._by_key.clear()
            self._tid_pending.clear()
            self._marks.clear()
            self._seen.clear()
            self.evicted = 0

    def _remember(self, k: tuple[str, str]) -> None:
        """Bounded span-id dedup memory (caller holds the lock)."""
        self._seen[k] = None
        while len(self._seen) > 4096:
            self._seen.popitem(last=False)


# the process-global tracer every component records into
tracer = PlacementTracer()


def new_span_id() -> str:
    """Globally-unique span id for a LOGICAL operation. Generate once per
    logical write, BEFORE any retry loop — replays and redirect re-sends
    then carry the same id and the receiver dedups to one span."""
    return "w" + os.urandom(6).hex()


# -- cross-process context (X-Karmada-Trace) --------------------------------

_ctx = threading.local()


def current_context() -> Optional[tuple[str, str, bool]]:
    """(trace_id, span_id, sampled) of the innermost active context on
    this thread, or None."""
    stack = getattr(_ctx, "stack", None)
    return stack[-1] if stack else None


@contextmanager
def trace_context(trace_id: str, span_id: str = "", sampled: bool = True):
    """Run a block under a propagated trace context: RemoteStore writes
    issued inside it carry the X-Karmada-Trace header."""
    stack = getattr(_ctx, "stack", None)
    if stack is None:
        stack = _ctx.stack = []
    stack.append((trace_id, span_id or new_span_id(), sampled))
    try:
        yield
    finally:
        stack.pop()


def format_trace_header(trace_id: str, span_id: str,
                        sampled: bool = True) -> str:
    return f"{trace_id};{span_id};s={'1' if sampled else '0'}"


def parse_trace_header(raw: str) -> Optional[tuple[str, str, bool]]:
    """-> (trace_id, span_id, sampled) or None on a malformed header (a
    bad header must never fail the carrying request)."""
    if not raw:
        return None
    parts = raw.split(";")
    if len(parts) < 2 or not parts[0] or not parts[1]:
        return None
    sampled = True
    for p in parts[2:]:
        if p.strip() == "s=0":
            sampled = False
    return parts[0], parts[1], sampled


# -- SLO attribution (the soak's report artifact; ROADMAP item 5a) ----------

def _pctl(values: list[float], q: float) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    i = min(len(s) - 1, max(0, int(round(q * len(s))) - 1))
    return s[i]


def slo_report(from_tracer: Optional[PlacementTracer] = None) -> dict:
    """Roll the retained traces into the per-stage p50/p99 attribution
    table — WHERE placement time goes, not just that it was slow. This is
    the SLO report artifact the fleet soak (ROADMAP item 5a) emits next to
    its BENCH_*.json lines."""
    t = from_tracer or tracer
    stage_durs: dict[str, list[float]] = {}
    placements: list[float] = []
    recs = t.retained()
    for rec in recs:
        if rec.placement_s is not None:
            placements.append(rec.placement_s)
        for s in rec.spans:
            stage_durs.setdefault(s.name, []).append(s.duration())
    return {
        "n_traces": len(recs),
        "head_sample": t.head_sample,
        "slow_threshold_s": t.slow_threshold_s,
        "tail_sampled": sum(1 for r in recs if r.retained == "slo"),
        "stages": {
            name: {
                "n": len(durs),
                "p50_ms": round(_pctl(durs, 0.50) * 1e3, 3),
                "p99_ms": round(_pctl(durs, 0.99) * 1e3, 3),
            }
            for name, durs in sorted(stage_durs.items())
        },
        "placement": {
            "n": len(placements),
            "p50_ms": round(_pctl(placements, 0.50) * 1e3, 3),
            "p99_ms": round(_pctl(placements, 0.99) * 1e3, 3),
        },
    }

"""Pull-agent daemon: `python -m karmada_tpu.agent --server URL --cluster N`.

The reference's cmd/agent binary (agent.go:73,135): a process running in
the member's trust domain that registers its Cluster with the control
plane, receives Works over the watch stream, applies them to its member,
reflects status, and heartbeats its lease. Here the member is the
in-memory simulator (the framework's member-cluster substrate); everything
crosses the real network boundary via RemoteStore.
"""
from __future__ import annotations

import argparse
import signal
import sys


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.agent")
    ap.add_argument("--server", required=True,
                    help="control-plane URL (http:// or https://)")
    ap.add_argument("--cluster", required=True, help="member cluster name")
    ap.add_argument("--region", default="")
    ap.add_argument("--zone", default="")
    ap.add_argument("--provider", default="")
    ap.add_argument("--cpu", type=float, default=100.0,
                    help="allocatable CPU cores")
    ap.add_argument("--memory-gib", type=float, default=400.0)
    ap.add_argument("--pods", type=float, default=1000.0)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between settle+heartbeat steps")
    ap.add_argument("--bearer-token", default="",
                    help="daemon --token-file credential (KARMADA_TOKEN)")
    ap.add_argument("--cacert", default="",
                    help="daemon --tls-dir ca.pem (KARMADA_CACERT)")
    args = ap.parse_args()

    # host-plane process: never let an ambient TPU backend init block startup
    from ..testing.cpumesh import force_cpu_mesh

    force_cpu_mesh(1)

    import os

    from ..api.meta import CPU, MEMORY
    from ..members.member import MemberConfig
    from .remote_agent import RemoteAgentSession

    GiB = 1024.0**3
    session = RemoteAgentSession(
        args.server,
        MemberConfig(
            name=args.cluster, sync_mode="Pull", region=args.region,
            zone=args.zone, provider=args.provider,
            allocatable={CPU: args.cpu, MEMORY: args.memory_gib * GiB,
                         "pods": args.pods},
        ),
        token=args.bearer_token or os.environ.get("KARMADA_TOKEN") or None,
        cafile=args.cacert or os.environ.get("KARMADA_CACERT") or None,
    )
    session.register()
    session.run(interval=args.interval)
    print(f"agent {args.cluster} registered with {args.server}", flush=True)

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            signal.pause()
    except KeyboardInterrupt:
        pass
    session.close()


if __name__ == "__main__":
    sys.exit(main())

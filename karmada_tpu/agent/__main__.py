"""Pull-agent daemon: `python -m karmada_tpu.agent --server URL --cluster N`.

The reference's cmd/agent binary (agent.go:73,135): a process running in
the member's trust domain that registers its Cluster with the control
plane, receives Works over the watch stream, applies them to its member,
reflects status, and heartbeats its lease. Here the member is the
in-memory simulator (the framework's member-cluster substrate); everything
crosses the real network boundary via RemoteStore.

Leader election (agent.go runs behind the same leaderelection package):
two agents started for one --cluster compete for the
`karmada-agent-<cluster>` LeaderLease — only the holder registers,
heartbeats, and applies Works; the standby idles until promoted, so a
member's heartbeat never comes from two processes at once and the active
agent's status writes are fenced.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.agent")
    ap.add_argument("--server", required=True,
                    help="control-plane URL (http:// or https://)")
    ap.add_argument("--cluster", required=True, help="member cluster name")
    ap.add_argument("--region", default="")
    ap.add_argument("--zone", default="")
    ap.add_argument("--provider", default="")
    ap.add_argument("--cpu", type=float, default=100.0,
                    help="allocatable CPU cores")
    ap.add_argument("--memory-gib", type=float, default=400.0)
    ap.add_argument("--pods", type=float, default=1000.0)
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between settle+heartbeat steps")
    ap.add_argument("--bearer-token", default="",
                    help="daemon --token-file credential (KARMADA_TOKEN)")
    ap.add_argument("--cacert", default="",
                    help="daemon --tls-dir ca.pem (KARMADA_CACERT)")
    ap.add_argument("--no-leader-elect", action="store_true",
                    help="skip the per-cluster agent election (UNSAFE with "
                         "two agents for one cluster)")
    ap.add_argument("--lease-duration", type=float, default=10.0)
    ap.add_argument("--identity", default="",
                    help="election identity (default hostname_pid)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve GET /metrics on this port (0 = ephemeral, "
                         "printed on stdout; -1 disables)")
    ap.add_argument("--scrape-token-file", default="",
                    help="dedicated READ-ONLY token accepted on GET "
                         "/metrics only (the Prometheus credential no "
                         "longer needs to be the full wire token)")
    ap.add_argument("--enable-pprof", action="store_true",
                    help="serve /debug/pprof (sampled whole-process CPU "
                         "profile + heap) on --pprof-port; protected by "
                         "the wire token OR the --scrape-token-file "
                         "credential, like /metrics")
    ap.add_argument("--pprof-port", type=int, default=0,
                    help="port for --enable-pprof (0 = ephemeral, printed)")
    args = ap.parse_args()

    # host-plane process: never let an ambient TPU backend init block startup
    from ..testing.cpumesh import force_cpu_mesh

    force_cpu_mesh(1)

    from .. import faults
    from ..api.coordination import agent_lease_name
    from ..api.meta import CPU, MEMORY
    from ..coordination.elector import Elector, default_identity
    from ..members.member import MemberConfig
    from ..server.metricsserver import start_metrics_server
    from .remote_agent import RemoteAgentSession

    # env-gated chaos plan (KARMADA_TPU_FAULT_PLAN): the agent's apply and
    # HTTP boundaries inject from the same replayable schedule
    if faults.install_from_env() is not None:
        print(f"faults: chaos plan installed from {faults.ENV_FAULT_PLAN}",
              flush=True)

    token = args.bearer_token or os.environ.get("KARMADA_TOKEN") or None
    GiB = 1024.0**3
    session = RemoteAgentSession(
        args.server,
        MemberConfig(
            name=args.cluster, sync_mode="Pull", region=args.region,
            zone=args.zone, provider=args.provider,
            allocatable={CPU: args.cpu, MEMORY: args.memory_gib * GiB,
                         "pods": args.pods},
        ),
        token=token,
        cafile=args.cacert or os.environ.get("KARMADA_CACERT") or None,
    )
    metrics_srv = start_metrics_server(
        args.metrics_port, token=token,
        scrape_token_file=args.scrape_token_file,
    )
    from ..tracing import start_profile_server

    profile_srv = start_profile_server(
        args.enable_pprof, port=args.pprof_port, token=token,
        scrape_token_file=args.scrape_token_file,
    )

    lease = agent_lease_name(args.cluster)
    identity = args.identity or default_identity()
    leading = threading.Event()
    registered = threading.Event()
    elector = None

    def announce_active() -> None:
        session.register()
        registered.set()
        print(f"agent {args.cluster} registered with {args.server}",
              flush=True)

    if args.no_leader_elect:
        leading.set()
    else:
        def started(token_: int) -> None:
            session.store.set_fence(lease, token_)
            leading.set()
            print(f"leader: {identity} acquired lease {lease} "
                  f"(fencing token {token_})", flush=True)

        def stopped(reason: str) -> None:
            leading.clear()
            session.store.clear_fence()
            print(f"leader: {identity} lost lease {lease} ({reason})",
                  flush=True)

        elector = Elector(
            session.store, lease, identity,
            lease_duration=args.lease_duration,
            on_started_leading=started, on_stopped_leading=stopped,
        )
        elector.step()  # lone agent becomes active before the first print
        elector.run()
        if not leading.is_set():
            print(f"agent {args.cluster} standing by for lease {lease}",
                  flush=True)

    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            if leading.is_set():
                try:
                    if not registered.is_set():
                        announce_active()
                    session.step()
                except Exception:  # noqa: BLE001 - agent must keep serving
                    import logging

                    logging.getLogger(__name__).exception("agent step")
                time.sleep(args.interval)
            else:
                # standby: wake promptly on promotion
                leading.wait(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if elector is not None:
            elector.stop(release=True)
        if metrics_srv is not None:
            metrics_srv.stop()
        if profile_srv is not None:
            profile_srv.stop()
        session.close()


if __name__ == "__main__":
    sys.exit(main())

"""Pull-mode agent connected over the network (agent.go:73,135).

The reference agent is a separate binary given a karmada-apiserver
kubeconfig: it registers its Cluster, watches its execution namespace for
Work, applies manifests to the member it sits in, reflects status, and
heartbeats a Lease. `RemoteAgentSession` is that binary's body for the TPU
build: everything crosses the serving seam via `RemoteStore` — the control
plane never holds an in-process handle to this member.

    session = RemoteAgentSession("http://127.0.0.1:7443", MemberConfig(
        name="edge-1", sync_mode="Pull", allocatable={...}))
    session.register()          # Cluster object + first heartbeat
    ...
    session.step()              # drain delivered Works (or .run() to loop)
"""
from __future__ import annotations

import threading
from typing import Optional

from ..interpreter.interpreter import ResourceInterpreter
from ..members.member import InMemoryMember, MemberConfig, cluster_object_for
from ..runtime.controller import Runtime
from ..server.remote import RemoteStore
from .agent import KarmadaAgent


class RemoteAgentSession:
    def __init__(self, url: str, config: MemberConfig,
                 member: Optional[InMemoryMember] = None,
                 token: Optional[str] = None, cafile: Optional[str] = None,
                 status_flush_delay: float = 0.005,
                 metrics_reports: bool = False,
                 search_reports: bool = False,
                 wire: str = "auto"):
        """`status_flush_delay`: the agent-side write-coalescing knob —
        per-Work status reports buffer this many seconds and commit as one
        POST /objects/batch instead of one round-trip each (a thousand
        agents reporting after a surge stop serializing on per-request
        overhead). 0 restores per-object writes.

        `metrics_reports=True`: publish this member's WorkloadMetricsReport
        on every heartbeat (the elasticity plane's feed, docs/ELASTICITY.md)
        — riding the same coalescing buffer, so utilization reporting adds
        zero extra round-trips to the status batch.

        `search_reports=True`: publish registry-selected member objects as
        ClusterObjectSummary on every heartbeat (the search plane's remote
        ingest feed, docs/SEARCH.md), on the same buffer again."""
        if config.sync_mode != "Pull":
            raise ValueError("remote agents serve Pull clusters")
        self.config = config
        # `wire` rides through to the transport: "auto" (default) lets the
        # coalesced status batches upgrade to the negotiated binary codec
        # once the control plane advertises it; "json" pins the baseline
        self.store = RemoteStore(url, token=token, cafile=cafile, wire=wire)
        self.member = member or InMemoryMember(config)
        self.runtime = Runtime()
        interpreter = ResourceInterpreter()
        interpreter.load_thirdparty()
        self.agent = KarmadaAgent(self.store, self.member, interpreter,
                                  self.runtime,
                                  status_flush_delay=status_flush_delay,
                                  metrics_reports=metrics_reports,
                                  search_reports=search_reports)
        # the agent's own workStatus controller (agent.go:248-433 runs
        # execution + workStatus + clusterStatus member-side): reflect this
        # member's object status into work.status over the wire
        from ..controllers.status import WorkStatusController

        self.work_status = WorkStatusController(
            self.store, {config.name: self.member}, interpreter, self.runtime,
            namespace=self.agent.namespace,  # only this member's Works
            # both report planes share one coalescing buffer: a drain's
            # condition + reflection writes for the same Work merge
            status_coalescer=self.agent._status_coalescer,
        )
        self.work_status.watch_member(self.member)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def register(self) -> None:
        """generateClusterInControllerPlane (agent.go:437): create-or-refresh
        the Cluster object and heartbeat once so the lease is live before
        the scheduler can consider the cluster."""
        from ..store.store import ConflictError

        fresh = cluster_object_for(self.config)
        for _ in range(8):
            existing = self.store.try_get("Cluster", self.config.name)
            if existing is None:
                self.store.create(fresh)
                break
            # restart with changed config: refresh what this agent owns
            # (spec identity + reported capacity) without clobbering
            # control-plane-written state (taints, conditions, remedies);
            # check_rv + retry so a concurrent control-plane write between
            # our read and write is never silently reverted
            existing.spec.sync_mode = fresh.spec.sync_mode
            existing.spec.provider = fresh.spec.provider
            existing.spec.region = fresh.spec.region
            existing.spec.zone = fresh.spec.zone
            existing.metadata.labels.update(fresh.metadata.labels)
            existing.status.resource_summary = fresh.status.resource_summary
            try:
                self.store.update(existing, check_rv=True)
                break
            except ConflictError:
                continue
        self.agent.heartbeat()

    def step(self) -> int:
        """Drain Works the watch stream delivered; heartbeat the lease. The
        settle pass buffers status reports; the explicit flush here commits
        the whole drain's worth as one batch (the coalescer's own timer
        covers the background run() loop between steps)."""
        steps = self.runtime.settle()
        self.agent.flush_status()
        self.agent.heartbeat()
        return steps

    def run(self, interval: float = 1.0) -> None:
        """Background loop: step() every `interval` seconds."""
        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.step()
                except Exception:  # noqa: BLE001 - agent must keep serving
                    import logging

                    logging.getLogger(__name__).exception("agent step")

        self._thread = threading.Thread(
            target=loop, name=f"agent-{self.config.name}", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self.agent.close()  # flush + stop the status coalescer
        self.store.close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

"""Pull-mode member agent (L7, reference: cmd/agent/app/agent.go:73,135,248-433).

The agent runs member-side and owns, for its own cluster only:
- cluster registration (generateClusterInControllerPlane, agent.go:437 — done
  by ControlPlane.join_member for Pull configs, which attaches this agent),
- the execution controller (apply Works from the cluster namespace),
- work status reflection for its Works,
- the cluster Lease heartbeat + resource-summary refresh (the signal the
  control plane's failure detector watches; cluster_status_controller.go:400).

Push clusters never get an agent; the central execution controller serves
them. The split is the sync-mode seam of the reference (ClusterSyncMode
Push/Pull, apis/cluster types.go).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.meta import ObjectMeta
from ..api.work import Work, cluster_of_work_namespace, work_namespace_for_cluster
from ..controllers.execution import (
    EXECUTION_FINALIZER,
    apply_work_manifests,
    remove_work_manifests,
)
from ..api.meta import Condition, set_condition
from ..api.work import WORK_CONDITION_APPLIED
from ..runtime.controller import Controller, DONE, REQUEUE, Runtime
from ..store.store import Store

LEASE_DURATION_SECONDS = 40.0  # cluster lease default (cluster API)

# Ready-condition reason written when the lease detector marks a cluster
# NotReady; the recovery path only reverts NotReady states it caused itself
REASON_LEASE_EXPIRED = "ClusterLeaseExpired"


@dataclass
class Lease:
    """coordination.k8s.io/v1 Lease equivalent for cluster heartbeats."""

    kind: str = "Lease"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    holder: str = ""
    renew_time: float = 0.0
    lease_duration_seconds: float = LEASE_DURATION_SECONDS

    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def namespace(self) -> str:
        return self.metadata.namespace


class KarmadaAgent:
    def __init__(self, store: Store, member, interpreter, runtime: Runtime,
                 status_flush_delay: float = 0.0,
                 metrics_reports: bool = False,
                 search_reports: bool = False):
        """`status_flush_delay` > 0 coalesces the per-Work applied-condition
        status reports through a WriteCoalescer (store/batching.py): a
        settle pass draining N Works writes their conditions as one batch
        call after the delay instead of N round-trips. 0 (the in-process
        default) writes through synchronously. Correctness-bearing writes
        (finalizers, deletion) are never buffered.

        `metrics_reports=True` (the elasticity plane's feed, docs/
        ELASTICITY.md) publishes a WorkloadMetricsReport for this member on
        every heartbeat — riding the SAME coalesced status path when one is
        configured, so utilization reporting costs the fleet no extra
        round-trips beyond the Work conditions it already batches.

        `search_reports=True` (the search plane's remote ingest feed,
        docs/SEARCH.md) publishes a ClusterObjectSummary per registry-
        selected (apiVersion, kind) on every heartbeat — the same coalesced
        status path and the same change-suppression discipline, so a quiet
        cluster costs the plane zero search writes."""
        self.store = store
        self.member = member
        self.interpreter = interpreter
        self.metrics_reports = metrics_reports
        self.search_reports = search_reports
        self._report_cache: dict = {}  # change-suppression, no read RTT
        self._search_cache: dict = {}  # (apiVersion, kind) -> row signature
        self.clock = runtime.clock
        self.namespace = work_namespace_for_cluster(member.name)
        self._status_coalescer = None
        if status_flush_delay > 0:
            from ..store.batching import WriteCoalescer

            self._status_coalescer = WriteCoalescer(
                store, flush_delay=status_flush_delay, path="agent_status",
            )
        self.controller = runtime.register(
            Controller(name=f"agent-{member.name}", reconcile=self._reconcile)
        )
        # scoped to this member's execution namespace: a remote agent's
        # watch stream carries only its own Works across the wire
        store.watch("Work", self._on_work, namespace=self.namespace)

    def _on_work(self, event: str, work: Work) -> None:
        # delivery is already scoped by the namespace-filtered watch above;
        # no per-event re-check needed
        self.controller.enqueue(work.metadata.key())

    def _reconcile(self, key: str) -> str:
        ns, _, name = key.partition("/")
        if cluster_of_work_namespace(ns) != self.member.name:
            return DONE
        work = self.store.try_get("Work", name, ns)
        if work is None:
            return DONE
        if work.metadata.deletion_timestamp is not None:
            if not work.spec.preserve_resources_on_deletion:
                remove_work_manifests(work, self.member)
            if EXECUTION_FINALIZER in work.metadata.finalizers:
                work.metadata.finalizers.remove(EXECUTION_FINALIZER)
                self.store.update(work)
            return DONE
        if EXECUTION_FINALIZER not in work.metadata.finalizers:
            work.metadata.finalizers.append(EXECUTION_FINALIZER)
            work = self.store.update(work)
        if work.spec.suspend_dispatching:
            return DONE
        import time as _time

        t_apply0 = _time.time()
        results = apply_work_manifests(work, self.member, self.interpreter)
        t_apply1 = _time.time()
        errors = [r.message for r in results if not r.ok]
        if set_condition(
            work.status.conditions,
            Condition(
                type=WORK_CONDITION_APPLIED,
                status="False" if errors else "True",
                reason="AppliedFailed" if errors else "AppliedSuccessful",
                message="; ".join(errors) if errors else "Manifest has been successfully applied",
            ),
        ):
            # distributed tracing: stamp the apply timing onto the Work so
            # it rides THIS status write (the coalesced agent-status path —
            # zero extra round-trips) to the plane, where the TraceCollector
            # lifts it into the binding's member_apply span. The id is
            # derived from (work uid, generation), so a coalescer replay or
            # redirect re-send of the same report dedups to ONE span.
            from ..tracing import APPLY_SPAN_ANNOTATION, tracer

            if tracer.enabled:
                import json as _json

                work.metadata.annotations[APPLY_SPAN_ANNOTATION] = _json.dumps({
                    "id": f"apply-{work.metadata.uid}-g{work.metadata.generation}",
                    "cluster": self.member.name,
                    "start": t_apply0, "end": t_apply1,
                })
            # the applied-condition report is level-triggered and idempotent
            # — the one write that may ride the coalescing buffer
            if self._status_coalescer is not None:
                self._status_coalescer.apply(work)
            else:
                self.store.update(work)
        if any(not r.ok and r.retryable for r in results):
            # same policy as the push-mode controller: only retryable
            # failures re-dispatch (faults/policy — the agent shares the
            # queue's bounded retry budget)
            return REQUEUE
        return DONE

    def flush_status(self) -> int:
        """Commit buffered status reports now (the session's step boundary);
        no-op when coalescing is off. Returns how many writes flushed."""
        if self._status_coalescer is None:
            return 0
        return self._status_coalescer.flush()

    def close(self) -> None:
        if self._status_coalescer is not None:
            self._status_coalescer.close()

    # -- heartbeat (cluster lease + status refresh) -----------------------

    def heartbeat(self) -> None:
        """Renew the cluster Lease and refresh the reported ResourceSummary
        (the agent's clusterStatus controller). Skipped when the member is
        down — that is exactly the failure the lease detector catches."""
        if not self.member.healthy:
            return
        lease = self.store.try_get("Lease", self.member.name, self.namespace)
        if lease is None:
            lease = Lease(
                metadata=ObjectMeta(name=self.member.name, namespace=self.namespace),
                holder=self.member.name,
            )
            lease.renew_time = self.clock.now()
            self.store.create(lease)
        else:
            lease.renew_time = self.clock.now()
            self.store.update(lease)
        cluster = self.store.try_get("Cluster", self.member.name)
        if cluster is not None and cluster.status.resource_summary is not None:
            alloc = dict(self.member.config.allocatable)
            if cluster.status.resource_summary.allocatable != alloc:
                cluster.status.resource_summary.allocatable = alloc
                self.store.update(cluster)
        if self.metrics_reports:
            # the elasticity feed: per-workload utilization for this member,
            # change-suppressed and coalesced with the Work status batch
            from ..elastic.aggregator import build_metrics_report, publish_report

            publish_report(
                self.store,
                build_metrics_report(self.member, self.clock.now()),
                coalescer=self._status_coalescer,
                cache=self._report_cache,
            )
        if self.search_reports:
            self._publish_search_summaries()

    def _publish_search_summaries(self) -> None:
        """Per-(apiVersion, kind) ClusterObjectSummary feed for the search
        plane (docs/SEARCH.md). Level-triggered: each summary wholly
        replaces its (cluster, gvk) index slice, a deselected gvk is
        retracted with an empty-rows summary, and unchanged summaries are
        suppressed agent-side so the plane sees no write at all."""
        from ..api.search import (
            ClusterObjectSummary,
            ObjectSummaryRow,
            summary_name,
        )
        from ..search.columnar import field_pairs_of
        from ..search.search import selection_map

        owed = {gvk for gvk, clusters in selection_map(self.store).items()
                if self.member.name in clusters}
        rows_by_gvk: dict[tuple, list] = {gvk: [] for gvk in owed}
        for obj in self.member.objects():
            bucket = rows_by_gvk.get((obj.api_version, obj.kind))
            if bucket is None:
                continue
            manifest = obj.to_dict()
            bucket.append(ObjectSummaryRow(
                namespace=obj.namespace,
                name=obj.name,
                uid=obj.metadata.uid,
                labels=dict(obj.metadata.labels),
                fields=field_pairs_of(manifest),
                manifest=manifest,
            ))
        # retract slices this cluster no longer owes (registry drift)
        for gvk in set(self._search_cache) - owed:
            rows_by_gvk.setdefault(gvk, [])
        for (av, kind), rows in sorted(rows_by_gvk.items()):
            rows.sort(key=lambda r: (r.namespace, r.name))
            sig = [(r.namespace, r.name, r.uid, r.labels, r.fields,
                    r.manifest) for r in rows]
            if self._search_cache.get((av, kind)) == sig:
                continue
            summary = ClusterObjectSummary(
                metadata=ObjectMeta(name=summary_name(self.member.name, av, kind)),
                cluster=self.member.name,
                api_version=av,
                object_kind=kind,
                rows=rows,
                reported_at=self.clock.now(),
            )
            if self._status_coalescer is not None:
                self._status_coalescer.apply(summary)
            else:
                self.store.apply(summary)
            if sig:
                self._search_cache[(av, kind)] = sig
            else:
                self._search_cache.pop((av, kind), None)


class LeaseFailureDetector:
    """Control-plane side: a cluster whose lease expired goes NotReady; a
    cluster whose lease is current again is restored to Ready, matching the
    reference cluster-status controller's behavior on resumed heartbeats
    (cluster_status_controller.go lease monitoring + condition cache)."""

    def __init__(self, store: Store, runtime: Runtime, on_not_ready=None, on_ready=None):
        self.store = store
        self.clock = runtime.clock
        self.on_not_ready = on_not_ready  # callback(cluster_name)
        self.on_ready = on_ready  # callback(cluster_name), recovery path

    def _ready_condition(self, cluster_name: str):
        from ..api.cluster import CLUSTER_CONDITION_READY
        from ..api.meta import get_condition

        cluster = self.store.try_get("Cluster", cluster_name)
        if cluster is None:
            return None
        return get_condition(cluster.status.conditions, CLUSTER_CONDITION_READY)

    def check(self) -> list[str]:
        expired = []
        now = self.clock.now()
        for lease in self.store.list("Lease"):
            cluster_name = lease.holder
            if now - lease.renew_time > lease.lease_duration_seconds:
                expired.append(cluster_name)
                if self.on_not_ready is not None:
                    self.on_not_ready(cluster_name)
            elif self.on_ready is not None:
                cond = self._ready_condition(cluster_name)
                # only revert a NotReady this detector set itself: a health
                # probe or operator action that marked the cluster NotReady
                # for another reason must not be overridden by a live lease
                if (
                    cond is not None
                    and cond.status != "True"
                    and cond.reason == REASON_LEASE_EXPIRED
                ):
                    self.on_ready(cluster_name)
        return expired

from .agent import KarmadaAgent

__all__ = ["KarmadaAgent"]

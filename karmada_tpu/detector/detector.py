"""Resource detector: templates × policies → ResourceBindings.

Parity with pkg/detector (detector.go:112 Start, :233 Reconcile, :362/:394
LookForMatchedPolicy, :422/:514 ApplyPolicy, :940/:1011 policy reconcile,
:1051/:1087 deletion): watches every non-Karmada kind in the store, matches
templates against PropagationPolicy / ClusterPropagationPolicy resource
selectors with the reference's precedence (explicit priority, then name-match
over label-selector specificity, then alphabetical), claims the template with
the policy's permanent id, and creates/updates the ResourceBinding with
replicas + requirements extracted through the resource interpreter
(BuildResourceBinding detector.go:730-805).
"""
from __future__ import annotations

from typing import Optional

from ..features import POLICY_PREEMPTION
from ..api.policy import (
    ClusterPropagationPolicy,
    PropagationPolicy,
    ResourceSelector,
)
from ..api.unstructured import Unstructured
from ..api.work import (
    BindingSpec,
    GANG_NAME_LABEL,
    GANG_SIZE_LABEL,
    ObjectReference,
    ResourceBinding,
    RESOURCE_BINDING_PERMANENT_ID_LABEL,
    SCHEDULE_PRIORITY_LABEL,
)
from ..interpreter.interpreter import ResourceInterpreter
from ..runtime.controller import Controller, DONE, Runtime
from ..store.store import DELETED, Store
from ..utils.names import binding_name

POLICY_ID_LABEL = "propagationpolicy.karmada.io/permanent-id"
CLUSTER_POLICY_ID_LABEL = "clusterpropagationpolicy.karmada.io/permanent-id"
POLICY_NAME_ANNOTATION = "policy.karmada.io/name"
POLICY_NAMESPACE_ANNOTATION = "policy.karmada.io/namespace"

# Kinds that are part of the control plane itself, never propagated
# (detector.go isSelectorMatches / api exclusions).
CONTROL_PLANE_KINDS = {
    "Cluster",
    "PropagationPolicy",
    "ClusterPropagationPolicy",
    "OverridePolicy",
    "ClusterOverridePolicy",
    "ResourceBinding",
    "ClusterResourceBinding",
    "Work",
    "WorkloadRebalancer",
    "FederatedResourceQuota",
}


def selector_matches(sel: ResourceSelector, obj: Unstructured, policy_namespace: str) -> int:
    """Returns implicit priority: 0 = no match, 1 = kind/label match,
    2 = exact-name match (pkg/detector implicit priority ordering)."""
    if sel.api_version != obj.api_version or sel.kind != obj.kind:
        return 0
    ns = sel.namespace or policy_namespace
    if ns and obj.namespace and ns != obj.namespace:
        return 0
    if sel.name:
        return 2 if sel.name == obj.name else 0
    if sel.label_selector is not None:
        return 1 if sel.label_selector.matches(obj.metadata.labels) else 0
    return 1


class ResourceDetector:
    def __init__(
        self,
        store: Store,
        interpreter: ResourceInterpreter,
        runtime: Runtime,
        gates=None,
    ) -> None:
        self.store = store
        self.interpreter = interpreter
        self.gates = gates
        self.controller = runtime.register(
            Controller(name="detector", reconcile=self._reconcile)
        )
        store.watch_all(self._on_any_event, replay=True)
        store.watch("PropagationPolicy", self._on_policy_event, replay=False)
        store.watch("ClusterPropagationPolicy", self._on_policy_event, replay=False)

    # -- event plumbing ---------------------------------------------------

    @staticmethod
    def _key(obj: Unstructured) -> str:
        return f"{obj.api_version}|{obj.kind}|{obj.namespace}|{obj.name}"

    def _on_any_event(self, kind: str, event: str, obj) -> None:
        if not isinstance(obj, Unstructured) or obj.kind in CONTROL_PLANE_KINDS:
            return
        self.controller.enqueue(self._key(obj))

    def _on_policy_event(self, event: str, policy) -> None:
        """Policy add/update/delete → re-sweep every template (the reference
        re-enqueues via its waiting list; a full sweep is the same fixpoint)."""
        for kind in self.store.kinds():
            for obj in self.store.list(kind):
                if isinstance(obj, Unstructured) and obj.kind not in CONTROL_PLANE_KINDS:
                    self.controller.enqueue(self._key(obj))
        if event == DELETED:
            self._cleanup_policy_bindings(policy)

    # -- reconcile --------------------------------------------------------

    def _reconcile(self, key: str) -> str:
        api_version, kind, namespace, name = key.split("|")
        obj = self.store.try_get(f"{api_version}/{kind}", name, namespace)
        if obj is None or obj.metadata.deletion_timestamp is not None:
            self._delete_binding_for(kind, namespace, name)
            return DONE
        policy = self._look_for_matched_policy(obj)
        if policy is None:
            self._delete_binding_for(kind, namespace, name)
            return DONE
        policy = self._resolve_claim(obj, policy)
        if policy is None:
            self._delete_binding_for(kind, namespace, name)
            return DONE
        self._apply_policy(obj, policy)
        return DONE

    def _resolve_claim(self, obj: Unstructured, best):
        """Claim stability + preemption (pkg/detector/preemption.go under the
        PropagationPolicyPreemption α gate): a template already claimed by a still-
        matching policy keeps it; a different policy takes over only when the
        gate is on, it declares `preemption: Always`, AND its explicit
        priority is strictly higher (preemption.go preemption conditions).
        Without a valid current claim, the best match wins outright."""
        current = self._claimed_policy(obj)
        if current is None:
            return best
        if current.metadata.uid == best.metadata.uid:
            return best
        preemption_on = self.gates is not None and self.gates.enabled(POLICY_PREEMPTION)
        if (
            preemption_on
            and best.spec.preemption == "Always"
            and best.spec.priority > current.spec.priority
        ):
            return best
        # current claim persists if it still matches the template
        ns = current.metadata.namespace if isinstance(current, PropagationPolicy) else ""
        still_matches = any(
            selector_matches(s, obj, ns) for s in current.spec.resource_selectors
        )
        return current if still_matches else best

    def _claimed_policy(self, obj: Unstructured):
        """The policy currently holding the template's claim labels."""
        name = obj.metadata.annotations.get(POLICY_NAME_ANNOTATION)
        if not name:
            return None
        if obj.metadata.labels.get(POLICY_ID_LABEL):
            ns = obj.metadata.annotations.get(POLICY_NAMESPACE_ANNOTATION, obj.namespace)
            pol = self.store.try_get("PropagationPolicy", name, ns)
            if pol is not None and pol.metadata.uid == obj.metadata.labels[POLICY_ID_LABEL]:
                return pol
        if obj.metadata.labels.get(CLUSTER_POLICY_ID_LABEL):
            pol = self.store.try_get("ClusterPropagationPolicy", name)
            if pol is not None and pol.metadata.uid == obj.metadata.labels[CLUSTER_POLICY_ID_LABEL]:
                return pol
        return None

    def _look_for_matched_policy(self, obj: Unstructured):
        """Namespaced PropagationPolicies win over ClusterPropagationPolicies
        (detector.go:362 then :394); within a tier: explicit priority desc,
        implicit selector priority desc, name asc."""
        best = None
        for policy in self.store.list("PropagationPolicy"):
            if obj.namespace and policy.metadata.namespace != obj.namespace:
                continue
            m = max(
                (selector_matches(s, obj, policy.metadata.namespace) for s in policy.spec.resource_selectors),
                default=0,
            )
            if m == 0:
                continue
            rank = (policy.spec.priority, m, _neg_name(policy.name))
            if best is None or rank > best[0]:
                best = (rank, policy)
        if best is not None:
            return best[1]
        for policy in self.store.list("ClusterPropagationPolicy"):
            m = max(
                (selector_matches(s, obj, "") for s in policy.spec.resource_selectors),
                default=0,
            )
            if m == 0:
                continue
            rank = (policy.spec.priority, m, _neg_name(policy.name))
            if best is None or rank > best[0]:
                best = (rank, policy)
        return best[1] if best else None

    def _apply_policy(self, obj: Unstructured, policy) -> None:
        """Claim + BuildResourceBinding (detector.go:422,730-805)."""
        is_cluster_policy = isinstance(policy, ClusterPropagationPolicy)
        id_label = CLUSTER_POLICY_ID_LABEL if is_cluster_policy else POLICY_ID_LABEL

        # claim the template (dropping any previous claim on preemption)
        other_label = POLICY_ID_LABEL if is_cluster_policy else CLUSTER_POLICY_ID_LABEL
        fresh = self.store.get(f"{obj.api_version}/{obj.kind}", obj.name, obj.namespace)
        if (
            fresh.metadata.labels.get(id_label) != policy.metadata.uid
            or other_label in fresh.metadata.labels
        ):
            fresh.metadata.labels.pop(other_label, None)
            fresh.metadata.labels[id_label] = policy.metadata.uid
            fresh.metadata.annotations[POLICY_NAME_ANNOTATION] = policy.name
            if not is_cluster_policy:
                fresh.metadata.annotations[POLICY_NAMESPACE_ANNOTATION] = (
                    policy.metadata.namespace
                )
            self.store.update(fresh)
            obj = fresh

        replicas, requirements = self.interpreter.get_replicas(obj)
        rb_name = binding_name(obj.kind, obj.name)
        existing = self.store.try_get("ResourceBinding", rb_name, obj.namespace)
        if (
            policy.spec.activation_preference == "Lazy"
            and existing is not None
            and existing.spec.resource.resource_version == obj.metadata.generation
        ):
            # Lazy activation (propagation_types.go ActivationPreference):
            # policy updates take effect only on the NEXT template change —
            # an unchanged template keeps its current binding spec
            return
        rb = existing or ResourceBinding()
        rb.metadata.name = rb_name
        rb.metadata.namespace = obj.namespace
        rb.metadata.labels[id_label] = policy.metadata.uid
        if RESOURCE_BINDING_PERMANENT_ID_LABEL not in rb.metadata.labels:
            rb.metadata.labels[RESOURCE_BINDING_PERMANENT_ID_LABEL] = (
                rb.metadata.uid or f"{obj.namespace}.{rb_name}"
            )
        # workload-class plumbing (sched/preemption.py): gang membership and
        # priority flow from the claiming policy, with template labels
        # overriding per workload — several templates under one policy can
        # then form one gang, and a single workload can out-rank its
        # policy's default priority
        labels = obj.metadata.labels
        gang_name = labels.get(GANG_NAME_LABEL, policy.spec.gang_name)
        gang_size = policy.spec.gang_size
        if GANG_SIZE_LABEL in labels:
            try:
                gang_size = int(labels[GANG_SIZE_LABEL])
            except ValueError:
                pass  # malformed label: keep the policy's declaration
        schedule_priority = policy.spec.scheduler_priority
        if SCHEDULE_PRIORITY_LABEL in labels:
            try:
                schedule_priority = int(labels[SCHEDULE_PRIORITY_LABEL])
            except ValueError:
                pass
        new_spec = BindingSpec(
            resource=ObjectReference(
                api_version=obj.api_version,
                kind=obj.kind,
                namespace=obj.namespace,
                name=obj.name,
                uid=obj.metadata.uid,
                # Template spec changes bump this, so the RB spec changes and
                # the binding controller regenerates Works (the reference
                # records Resource.ResourceVersion in BuildResourceBinding;
                # generation is the spec-only equivalent — status writes from
                # the aggregation loop must not churn RBs).
                resource_version=obj.metadata.generation,
            ),
            replicas=replicas,
            replica_requirements=requirements,
            placement=policy.spec.placement,
            schedule_priority=schedule_priority,
            preemption_policy=policy.spec.scheduler_preemption,
            gang_name=gang_name,
            gang_size=gang_size,
            scheduler_name=policy.spec.scheduler_name,
            propagate_deps=policy.spec.propagate_deps,
            conflict_resolution=policy.spec.conflict_resolution,
            failover=policy.spec.failover,
            clusters=existing.spec.clusters if existing else [],
            graceful_eviction_tasks=existing.spec.graceful_eviction_tasks if existing else [],
            reschedule_triggered_at=existing.spec.reschedule_triggered_at if existing else None,
        )
        if policy.spec.suspension is not None:
            from ..api.work import BindingSuspension

            new_spec.suspension = BindingSuspension(
                dispatching=policy.spec.suspension.dispatching,
                scheduling=policy.spec.suspension.scheduling,
            )
        if existing is None:
            rb.spec = new_spec
            created = self.store.create(rb)
            if created.metadata.labels[RESOURCE_BINDING_PERMANENT_ID_LABEL].startswith(
                f"{obj.namespace}."
            ):
                created.metadata.labels[RESOURCE_BINDING_PERMANENT_ID_LABEL] = created.metadata.uid
                self.store.update(created)
        elif existing.spec != new_spec:  # full dataclass comparison
            rb.spec = new_spec
            self.store.update(rb)

    # -- deletion ---------------------------------------------------------

    def _delete_binding_for(self, kind: str, namespace: str, name: str) -> None:
        rb_name = binding_name(kind, name)
        if self.store.try_get("ResourceBinding", rb_name, namespace) is not None:
            self.store.delete("ResourceBinding", rb_name, namespace)

    def _cleanup_policy_bindings(self, policy) -> None:
        id_label = (
            CLUSTER_POLICY_ID_LABEL
            if isinstance(policy, ClusterPropagationPolicy)
            else POLICY_ID_LABEL
        )
        for rb in self.store.list("ResourceBinding"):
            if rb.metadata.labels.get(id_label) == policy.metadata.uid:
                # another policy may re-claim on the sweep; delete and let the
                # sweep recreate if so (level-triggered fixpoint)
                self.store.delete("ResourceBinding", rb.name, rb.namespace)


def _neg_name(name: str) -> tuple:
    """Ascending-name preference inside a descending-rank comparison."""
    return tuple(-ord(ch) for ch in name)

from .adapter import (
    WORKLOAD_LABEL,
    CustomMetricInfo,
    CustomMetricsProvider,
    ExternalMetricsProvider,
    ExternalMetricsUnsupportedError,
    MetricNotFoundError,
    MetricValue,
    MetricsAdapter,
    NodeMetrics,
    PodMetrics,
    ResourceMetricsProvider,
    WorkloadMetrics,
)

__all__ = [
    "CustomMetricInfo",
    "CustomMetricsProvider",
    "ExternalMetricsProvider",
    "ExternalMetricsUnsupportedError",
    "MetricNotFoundError",
    "MetricValue",
    "MetricsAdapter",
    "NodeMetrics",
    "PodMetrics",
    "ResourceMetricsProvider",
    "WorkloadMetrics",
]

from .adapter import MetricsAdapter, WorkloadMetrics

__all__ = ["MetricsAdapter", "WorkloadMetrics"]

"""karmada-metrics-adapter (A4, reference: pkg/metricsadapter/ — the
custom-metrics aggregated API that fans a metric query out to every member
cluster and merges the answers; consumed by the FederatedHPA controller).

Here the fan-out is over the in-memory members' simulated metrics-server
feeds; the merged answer is the federation-wide pod metric set."""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class WorkloadMetrics:
    """Merged pod metrics for one workload across the federation."""

    ready_pods: int = 0
    # per-cluster: cluster name -> (pods, per-pod usage dict)
    by_cluster: dict = field(default_factory=dict)
    # federation-wide totals per resource
    total_usage: dict[str, float] = field(default_factory=dict)

    def average_usage(self, resource: str) -> float:
        if self.ready_pods == 0:
            return 0.0
        return self.total_usage.get(resource, 0.0) / self.ready_pods


class MetricsAdapter:
    def __init__(self, members: dict):
        self.members = members

    def collect(self, kind: str, namespace: str, name: str) -> WorkloadMetrics:
        """Fan out to every member (the adapter's multi-cluster query path)
        and merge: total usage = Σ pods × per-pod usage."""
        out = WorkloadMetrics()
        for cname, member in self.members.items():
            pods, usage = member.pod_metrics(kind, namespace, name)
            if pods <= 0 or usage is None:
                continue
            out.ready_pods += pods
            out.by_cluster[cname] = (pods, dict(usage))
            for res, v in usage.items():
                out.total_usage[res] = out.total_usage.get(res, 0.0) + pods * v
        return out

"""karmada-metrics-adapter (A4, reference: pkg/metricsadapter/ 1546 LoC).

The reference runs three aggregated-API providers, each fanning a query out
to every member cluster and merging the answers:

- **ResourceMetricsProvider** (provider/resourcemetrics.go): metrics.k8s.io
  pod/node metrics by name or label selector, merged across clusters.
- **CustomMetricsProvider** (provider/custommetrics.go): custom.metrics.k8s.io
  object metrics; same-named objects in multiple clusters have their values
  SUMMED (custommetrics.go:100-110,139-156).
- **ExternalMetricsProvider** (provider/externalmetrics.go): declared but
  unsupported — queries error, the metric list is empty.

`MetricsAdapter` bundles the three; the FederatedHPA controller consumes
pod metrics through the same by-selector query path an API user would
(`adapter.resource.pod_metrics_by_selector`), not a bespoke feed.

Member side, the in-memory clusters expose the two feeds a real member's
metrics-server / custom-metrics pipeline would: per-pod resource usage
synthesized from workload status, and seeded custom metrics
(`member.set_custom_metric`).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


class MetricNotFoundError(KeyError):
    """provider.NewMetricNotFoundError equivalent."""


class ExternalMetricsUnsupportedError(RuntimeError):
    """externalmetrics.go:38: external metrics queries are not supported."""


@dataclass(frozen=True)
class CustomMetricInfo:
    """provider.CustomMetricInfo: which resource the metric describes."""

    group_resource: str = "pods"  # e.g. "pods", "deployments.apps"
    metric: str = ""
    namespaced: bool = True


@dataclass
class MetricValue:
    """custom_metrics.MetricValue: one described object's metric answer."""

    kind: str = ""
    namespace: str = ""
    name: str = ""
    metric: str = ""
    value: float = 0.0
    # which member clusters contributed (values summed across them)
    clusters: list[str] = field(default_factory=list)


@dataclass
class PodMetrics:
    """metrics.k8s.io PodMetrics row, cluster-qualified after the merge."""

    cluster: str = ""
    namespace: str = ""
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    usage: dict[str, float] = field(default_factory=dict)


@dataclass
class NodeMetrics:
    cluster: str = ""
    name: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    usage: dict[str, float] = field(default_factory=dict)
    allocatable: dict[str, float] = field(default_factory=dict)


def _selector_matches(selector: Optional[dict], labels: dict) -> bool:
    if not selector:
        return True
    return all(labels.get(k) == v for k, v in selector.items())


class ResourceMetricsProvider:
    """metrics.k8s.io across the fleet (resourcemetrics.go)."""

    def __init__(self, members: dict):
        self.members = members

    def pod_metrics_by_selector(self, namespace: str = "",
                                selector: Optional[dict] = None) -> list[PodMetrics]:
        """Fan out to every member's pod-metrics feed and merge; rows carry
        their cluster so same-named pods never collide."""
        out: list[PodMetrics] = []
        for cname, member in sorted(self.members.items()):
            for pm in member.list_pod_metrics(namespace=namespace):
                if not _selector_matches(selector, pm.labels):
                    continue
                pm.cluster = cname
                out.append(pm)
        return out

    def pod_metrics_by_name(self, namespace: str, name: str) -> list[PodMetrics]:
        """One row per cluster holding a pod of that name (the reference
        returns every member's same-named pod)."""
        return [
            pm for pm in self.pod_metrics_by_selector(namespace=namespace)
            if pm.name == name
        ]

    def node_metrics_by_selector(self, selector: Optional[dict] = None) -> list[NodeMetrics]:
        out: list[NodeMetrics] = []
        for cname, member in sorted(self.members.items()):
            for nm in member.list_node_metrics():
                if not _selector_matches(selector, nm.labels):
                    continue
                nm.cluster = cname
                out.append(nm)
        return out

    def node_metrics_by_name(self, name: str) -> list[NodeMetrics]:
        return [n for n in self.node_metrics_by_selector() if n.name == name]


class CustomMetricsProvider:
    """custom.metrics.k8s.io across the fleet (custommetrics.go)."""

    def __init__(self, members: dict):
        self.members = members

    def get_metric_by_name(self, namespace: str, name: str,
                           info: CustomMetricInfo,
                           metric_selector: Optional[dict] = None) -> MetricValue:
        """Query every member for one object's metric; an object present in
        multiple clusters answers the SUM (custommetrics.go:100-110)."""
        merged: Optional[MetricValue] = None
        for cname, member in sorted(self.members.items()):
            for mv in member.query_custom_metrics(
                info.group_resource, info.metric,
                namespace=namespace if info.namespaced else "",
                name=name, metric_selector=metric_selector,
            ):
                if merged is None:
                    merged = mv
                    merged.clusters = [cname]
                else:
                    merged.value += mv.value
                    merged.clusters.append(cname)
        if merged is None:
            raise MetricNotFoundError(
                f"{info.group_resource}/{name}: metric {info.metric} not found"
            )
        return merged

    def get_metric_by_selector(self, namespace: str, selector: Optional[dict],
                               info: CustomMetricInfo,
                               metric_selector: Optional[dict] = None) -> list[MetricValue]:
        """Selector query; same-named described objects across clusters are
        merged by summing (custommetrics.go:139-156)."""
        merged: dict[str, MetricValue] = {}
        for cname, member in sorted(self.members.items()):
            for mv in member.query_custom_metrics(
                info.group_resource, info.metric,
                namespace=namespace if info.namespaced else "",
                selector=selector, metric_selector=metric_selector,
            ):
                prev = merged.get(mv.name)
                if prev is None:
                    mv.clusters = [cname]
                    merged[mv.name] = mv
                else:
                    prev.value += mv.value
                    prev.clusters.append(cname)
        if not merged:
            raise MetricNotFoundError(
                f"{info.group_resource}: metric {info.metric} not found"
            )
        return [merged[k] for k in sorted(merged)]

    def list_all_metrics(self) -> list[CustomMetricInfo]:
        """Every (resource, metric) any member currently serves."""
        seen: set[CustomMetricInfo] = set()
        for member in self.members.values():
            for gr, metric in member.list_custom_metric_names():
                seen.add(CustomMetricInfo(group_resource=gr, metric=metric))
        return sorted(seen, key=lambda i: (i.group_resource, i.metric))


class ExternalMetricsProvider:
    """Declared but unsupported, like the reference
    (externalmetrics.go:38-45)."""

    def get_external_metric(self, namespace: str, selector, info) -> None:
        raise ExternalMetricsUnsupportedError(
            "karmada-metrics-adapter does not support external metrics"
        )

    def list_all_external_metrics(self) -> list:
        return []


@dataclass
class WorkloadMetrics:
    """Merged pod metrics for one workload across the federation (the
    FHPA controller's consumption shape, computed FROM the query API)."""

    ready_pods: int = 0
    # per-cluster: cluster name -> (pods, per-pod usage dict)
    by_cluster: dict = field(default_factory=dict)
    # federation-wide totals per resource
    total_usage: dict[str, float] = field(default_factory=dict)

    def average_usage(self, resource: str) -> float:
        if self.ready_pods == 0:
            return 0.0
        return self.total_usage.get(resource, 0.0) / self.ready_pods


# the implicit workload label every synthesized pod row carries, so HPA-style
# consumers select a workload's pods the way a label selector would
WORKLOAD_LABEL = "resourcebinding.karmada.io/workload"


def workload_label_value(kind: str, namespace: str, name: str) -> str:
    return f"{kind}.{namespace}.{name}".lower()


class MetricsAdapter:
    """The adapter bundle: three providers behind one object (adapter.go)."""

    def __init__(self, members: dict):
        self.members = members
        self.resource = ResourceMetricsProvider(members)
        self.custom = CustomMetricsProvider(members)
        self.external = ExternalMetricsProvider()

    def collect(self, kind: str, namespace: str, name: str) -> WorkloadMetrics:
        """Workload view used by FederatedHPA — answered THROUGH the pod
        query API (by the workload's implicit selector), merged per cluster."""
        rows = self.resource.pod_metrics_by_selector(
            namespace=namespace,
            selector={WORKLOAD_LABEL: workload_label_value(kind, namespace, name)},
        )
        out = WorkloadMetrics()
        for pm in rows:
            if not pm.usage:
                # a member without a usage feed must not dilute the average
                # toward zero (it would bias FHPA to under-scale); the old
                # bespoke feed skipped non-reporting members the same way
                continue
            out.ready_pods += 1
            pods, usage = out.by_cluster.get(pm.cluster, (0, dict(pm.usage)))
            out.by_cluster[pm.cluster] = (pods + 1, usage)
            for res, v in pm.usage.items():
                out.total_usage[res] = out.total_usage.get(res, 0.0) + v
        return out

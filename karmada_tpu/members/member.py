"""In-memory member clusters: the simulated fleet the execution plane pushes
to.

Plays the role the kind clusters play in the reference's e2e environment
(hack/local-up-karmada.sh) and the fake clientsets play in its unit tests: a
member is a Store plus a tiny "kubelet" that fills workload status when
manifests are applied, with health/failure injection for failover tests
(SURVEY §5 fault injection = deleting/cordoning kind clusters)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api.meta import Resources
from ..api.unstructured import Unstructured
from ..store.store import Store, gvk_of


@dataclass
class MemberConfig:
    name: str
    provider: str = ""
    region: str = ""
    zone: str = ""
    labels: dict[str, str] = field(default_factory=dict)
    allocatable: Resources = field(default_factory=dict)
    allocated: Resources = field(default_factory=dict)
    sync_mode: str = "Push"
    # When set, the member simulates node-level pod placement and exposes an
    # AccurateEstimator (the per-member scheduler-estimator daemon).
    nodes: Optional[list] = None  # list[NodeSpec]


class InMemoryMember:
    """One member cluster: apply/delete manifests; workload controllers are
    simulated synchronously (a Deployment becomes Ready on apply unless the
    member is unhealthy or a failure is injected). With `config.nodes`, ready
    counts come from greedy pod placement over real node capacity."""

    def __init__(self, config: MemberConfig):
        self.config = config
        self.store = Store()
        self.healthy = True
        # kinds that never become ready on this member (failure injection)
        self.failing_kinds: set[str] = set()
        # simulated per-pod resource usage by "kind/ns/name" → {resource: qty}
        # (what metrics-server would report; feeds the metrics adapter)
        self.workload_usage: dict[str, dict[str, float]] = {}
        # custom.metrics.k8s.io samples: (groupResource, metric, ns, name)
        # -> (value, labels); node usage for metrics.k8s.io node rows
        self.custom_metrics: dict[tuple, tuple] = {}
        self.node_usage: dict[str, dict[str, float]] = {}
        self.node_estimator = None
        if config.nodes:
            from ..estimator.accurate import AccurateEstimator

            self.node_estimator = AccurateEstimator(config.nodes)

    @property
    def name(self) -> str:
        return self.config.name

    def apply_manifest(self, manifest: dict) -> Unstructured:
        obj = Unstructured(manifest)
        applied = self.store.apply(obj)
        self._run_controllers(applied)
        return self.store.get(gvk_of(applied), applied.name, applied.namespace)

    def delete_manifest(self, api_version: str, kind: str, namespace: str, name: str) -> None:
        if self.node_estimator is not None:
            # kind-qualified key: deleting e.g. a same-named Service must not
            # free a Deployment's placed pods
            self.node_estimator.unplace(f"{kind}/{namespace}/{name}")
        self.store.delete(f"{api_version}/{kind}", name, namespace)

    def get(self, api_version: str, kind: str, name: str, namespace: str = "") -> Optional[Unstructured]:
        return self.store.try_get(f"{api_version}/{kind}", name, namespace)

    def set_workload_usage(self, kind: str, namespace: str, name: str,
                           usage: dict[str, float]) -> None:
        """Set simulated per-pod usage for a workload (metrics-server feed)."""
        self.workload_usage[f"{kind}/{namespace}/{name}"] = dict(usage)

    @staticmethod
    def ready_pods_of(obj: Unstructured) -> int:
        """Ready-pod count from a workload object already in hand (callers
        holding the object skip the kind-rescan + deepcopy of
        pod_metrics). Per-kind pod count: workloads report readyReplicas;
        Jobs report active/succeeded; DaemonSets numberReady; a bare Pod
        is one pod while running."""
        st = obj.get("status") or {}
        kind = obj.kind
        if "readyReplicas" in st:
            return int(st.get("readyReplicas") or 0)
        if kind == "Job":
            return int(st.get("active") or 0) + int(st.get("succeeded") or 0)
        if kind == "DaemonSet":
            return int(st.get("numberReady") or 0)
        if kind == "Pod":
            return 1 if st.get("phase") in ("Running", "Succeeded") else 0
        return 0

    def pod_metrics(self, kind: str, namespace: str, name: str):
        """(ready_pods, per-pod usage dict or None) for a workload."""
        obj = None
        for gvk in self.store.kinds():
            if gvk.endswith(f"/{kind}"):
                obj = self.store.try_get(gvk, name, namespace)
                if obj is not None:
                    break
        if obj is None:
            return 0, None
        return (self.ready_pods_of(obj),
                self.workload_usage.get(f"{kind}/{namespace}/{name}"))

    # -- metrics feeds (what a real member's metrics-server and
    # custom-metrics pipeline would serve; queried by the metrics adapter) --

    _POD_KINDS = ("Deployment", "StatefulSet", "Job", "DaemonSet", "Pod")

    def list_pod_metrics(self, namespace: str = ""):
        """metrics.k8s.io pod rows, synthesized per ready pod of each
        workload. Rows carry the workload's labels plus the implicit
        workload label so selector queries (and HPA) can address them."""
        from ..metricsadapter.adapter import (
            WORKLOAD_LABEL,
            PodMetrics,
            workload_label_value,
        )

        out = []
        for gvk in list(self.store.kinds()):
            kind = gvk.rsplit("/", 1)[-1]
            if kind not in self._POD_KINDS:
                continue
            for obj in self.store.list(gvk, namespace):
                ready, usage = self.pod_metrics(kind, obj.namespace, obj.name)
                if ready <= 0:
                    continue
                labels = dict(obj.metadata.labels)
                labels[WORKLOAD_LABEL] = workload_label_value(
                    kind, obj.namespace, obj.name
                )
                for i in range(ready):
                    out.append(PodMetrics(
                        namespace=obj.namespace,
                        name=f"{obj.name}-{i}",
                        labels=dict(labels),
                        usage=dict(usage or {}),
                    ))
        return out

    def list_node_metrics(self):
        """metrics.k8s.io node rows from the simulated node pool."""
        from ..metricsadapter.adapter import NodeMetrics

        out = []
        for n in self.config.nodes or []:
            out.append(NodeMetrics(
                name=n.name,
                labels=dict(n.labels),
                usage=dict(self.node_usage.get(n.name, {})),
                allocatable=dict(n.allocatable),
            ))
        return out

    def set_node_usage(self, node: str, usage: dict[str, float]) -> None:
        self.node_usage[node] = dict(usage)

    def set_custom_metric(self, group_resource: str, metric: str, value: float,
                          *, namespace: str = "", name: str = "",
                          labels: Optional[dict] = None) -> None:
        """Seed one custom.metrics.k8s.io sample on this member."""
        self.custom_metrics[(group_resource, metric, namespace, name)] = (
            float(value), dict(labels or {})
        )

    def query_custom_metrics(self, group_resource: str, metric: str, *,
                             namespace: str = "", name: str = "",
                             selector: Optional[dict] = None,
                             metric_selector: Optional[dict] = None):
        """The member-side custom-metrics query (by name or selector)."""
        from ..metricsadapter.adapter import MetricValue, _selector_matches

        out = []
        for (gr, m, ns, n), (value, labels) in sorted(self.custom_metrics.items()):
            if gr != group_resource or m != metric:
                continue
            if namespace and ns != namespace:
                continue
            if name and n != name:
                continue
            if not _selector_matches(selector, labels):
                continue
            if not _selector_matches(metric_selector, labels):
                continue
            out.append(MetricValue(
                kind=group_resource, namespace=ns, name=n,
                metric=metric, value=value,
            ))
        return out

    def list_custom_metric_names(self):
        return sorted({(gr, m) for (gr, m, _, _) in self.custom_metrics})

    def objects(self) -> list[Unstructured]:
        """Every object on the member, across kinds (proxy/CLI listing)."""
        out: list[Unstructured] = []
        for kind in self.store.kinds():
            out.extend(self.store.list(kind))
        return out

    def _run_controllers(self, obj: Unstructured) -> None:
        """Simulated member-side controllers: set status on workloads."""
        key = f"{obj.api_version}/{obj.kind}"
        fresh = self.store.get(key, obj.name, obj.namespace)
        ok = self.healthy and obj.kind not in self.failing_kinds
        if obj.kind in ("Deployment", "StatefulSet"):
            replicas = int(fresh.get("spec", "replicas", default=1) or 0)
            fit = replicas
            if self.node_estimator is not None:
                from ..interpreter.interpreter import _pod_template_requirements

                rr = _pod_template_requirements(
                    fresh.get("spec", "template", "spec", default={}) or {},
                    fresh.namespace,
                )
                fit = self.node_estimator.place(
                    f"{fresh.kind}/{fresh.namespace}/{fresh.name}",
                    replicas,
                    rr.resource_request,
                    claim=rr.node_claim,
                )
            ready = fit if ok else 0
            fresh.status = {
                "observedGeneration": fresh.metadata.generation,
                "replicas": replicas,
                "readyReplicas": ready,
                "availableReplicas": ready,
                "updatedReplicas": replicas,
            }
            if fit < replicas:
                fresh.status["unavailableReplicas"] = replicas - fit
            self.store.update(fresh)
        elif obj.kind == "Job":
            parallelism = int(fresh.get("spec", "parallelism", default=1) or 0)
            fresh.status = {
                "active": parallelism if ok else 0,
                "conditions": [] if ok else [{"type": "Failed", "status": "True"}],
            }
            self.store.update(fresh)
        if obj.kind in ("Service", "Deployment", "StatefulSet"):
            self._sync_endpoint_slices(obj.namespace)

    def _sync_endpoint_slices(self, namespace: str) -> None:
        """Member-side endpoint controller: every Service with a selector gets
        an EndpointSlice with one ready endpoint per ready pod of the
        workloads it selects (what kube's endpointslice controller maintains;
        these are what the control plane collects for MCS/ServiceExport)."""
        for svc in self.store.list("v1/Service", namespace):
            selector = svc.get("spec", "selector", default=None)
            if not selector:
                continue
            ready_total = 0
            for kind in ("apps/v1/Deployment", "apps/v1/StatefulSet"):
                for wl in self.store.list(kind, namespace):
                    pod_labels = wl.get("spec", "template", "metadata", "labels", default={}) or {}
                    if all(pod_labels.get(k) == v for k, v in selector.items()):
                        ready_total += int(wl.get("status", "readyReplicas", default=0) or 0)
            slice_name = f"{svc.name}-{self.config.name}"
            manifest = {
                "apiVersion": "discovery.k8s.io/v1",
                "kind": "EndpointSlice",
                "metadata": {
                    "name": slice_name,
                    "namespace": namespace,
                    "labels": {"kubernetes.io/service-name": svc.name},
                },
                "addressType": "IPv4",
                "endpoints": [
                    {"addresses": [f"10.244.0.{i + 1}"], "conditions": {"ready": True}}
                    for i in range(ready_total)
                ],
                "ports": [
                    {"name": p.get("name", ""), "port": p.get("port", 0)}
                    for p in (svc.get("spec", "ports", default=[]) or [])
                ],
            }
            self.store.apply(Unstructured(manifest))

    def set_healthy(self, healthy: bool) -> None:
        """Flip member health and re-run controllers over existing workloads
        (level-triggered: status converges to the new health)."""
        self.healthy = healthy
        for kind in list(self.store.kinds()):
            for obj in self.store.list(kind):
                if isinstance(obj, Unstructured):
                    self._run_controllers(obj)


def cluster_object_for(config: MemberConfig, *, modeling: bool = False):
    """Build the Cluster API object a joining member reports: health, API
    enablements, node/resource summaries, optional grade-histogram resource
    models (syncClusterStatus in one step, cluster_status_controller.go:
    181,544-679). Shared by ControlPlane.join_member (push/local pull) and
    the remote pull agent's self-registration (agent.go:437
    generateClusterInControllerPlane)."""
    from ..api.cluster import (
        CLUSTER_CONDITION_READY,
        Cluster,
        ClusterSpec,
        ClusterStatus,
        DEFAULT_API_ENABLEMENTS,
        NodeSummary,
        ResourceSummary,
    )
    from ..api.meta import Condition, ObjectMeta, set_condition

    if config.nodes and not config.allocatable:
        # derive the ResourceSummary from node capacity (the status
        # collector's NodeSummary/ResourceSummary path)
        alloc: dict[str, float] = {}
        for n in config.nodes:
            for k, v in n.allocatable.items():
                alloc[k] = alloc.get(k, 0.0) + v
        alloc.setdefault("pods", float(sum(n.allowed_pods for n in config.nodes)))
        config.allocatable = alloc

    resource_models = []
    modelings = []
    if config.nodes and modeling:
        from ..modeling.modeling import GradeHistogram, default_resource_models

        resource_models = default_resource_models()
        hist = GradeHistogram(resource_models)
        hist.add_nodes([dict(n.allocatable) for n in config.nodes])
        modelings = hist.to_allocatable_modelings()

    cluster = Cluster(
        metadata=ObjectMeta(name=config.name, labels=dict(config.labels)),
        spec=ClusterSpec(
            sync_mode=config.sync_mode,
            provider=config.provider,
            region=config.region,
            zone=config.zone,
            resource_models=resource_models,
        ),
        status=ClusterStatus(
            kubernetes_version="v1.30.0",
            api_enablements=list(DEFAULT_API_ENABLEMENTS),
            node_summary=NodeSummary(total_num=10, ready_num=10),
            resource_summary=ResourceSummary(
                allocatable=dict(config.allocatable),
                allocated=dict(config.allocated),
                allocatable_modelings=modelings,
            ),
        ),
    )
    set_condition(
        cluster.status.conditions,
        Condition(type=CLUSTER_CONDITION_READY, status="True", reason="ClusterReady"),
    )
    return cluster

"""Level-triggered controller runtime.

Equivalent of the reference's controller-runtime + util.AsyncWorker stack
(pkg/util/worker.go, cmd/controller-manager/app/controllermanager.go:217-247):
each controller owns a dedup'ing work queue of keys and a reconcile(key)
function; watch handlers enqueue keys. The runtime drains all queues
round-robin until quiescent — deterministic for tests, and re-runnable at any
time (level-triggered: reconcile reads desired state from the store, never from
the event payload).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Optional

# Reconcile outcomes
DONE = "done"
REQUEUE = "requeue"


class WorkQueue:
    """Dedup'ing FIFO with retry backoff bookkeeping (reference:
    workqueue.RateLimitingInterface; backoff envelope 1s→10s per
    scheduling_queue.go:43-51 — in the in-process runtime, backoff is a retry
    counter consulted by the drain loop rather than wall-clock sleeps).

    Thread-safe: watch handlers enqueue from whatever thread mutated the
    store while drain loops pop concurrently (the reference's workqueue is
    the same cross-goroutine seam)."""

    def __init__(self, max_retries: int = 16):
        self._items: OrderedDict[str, None] = OrderedDict()
        self._retries: dict[str, int] = {}
        self._lock = threading.Lock()
        self.max_retries = max_retries
        # enqueue wakeup hook (the streaming scheduler's condition-variable
        # seam, sched/streaming.py): called — OUTSIDE the queue lock — when
        # a key lands in an empty-or-not queue, so an event-driven drain
        # loop can sleep until work exists instead of polling on a tick
        self.on_add: Optional[Callable[[], None]] = None

    def add(self, key: str) -> None:
        with self._lock:
            fresh = key not in self._items
            if fresh:
                self._items[key] = None
        if fresh and self.on_add is not None:
            self.on_add()

    def pop(self) -> Optional[str]:
        with self._lock:
            if not self._items:
                return None
            key, _ = self._items.popitem(last=False)
            return key

    def readd(self, key: str) -> None:
        """Interface parity with PrioritySchedulingQueue.readd (store-free
        re-admit of a drained key); add() is already store-free here."""
        self.add(key)

    def drain(self, limit: Optional[int] = None) -> list[str]:
        """Pop up to `limit` keys (all, when None) in FIFO order — the
        micro-batch former's one-lock-hold alternative to a pop loop."""
        out: list[str] = []
        with self._lock:
            while self._items and (limit is None or len(out) < limit):
                key, _ = self._items.popitem(last=False)
                out.append(key)
        return out

    def retry(self, key: str) -> bool:
        with self._lock:
            n = self._retries.get(key, 0) + 1
            self._retries[key] = n
            if n > self.max_retries:
                return False
            readded = key not in self._items
            if readded:
                self._items[key] = None
        if readded and self.on_add is not None:
            self.on_add()
        return True

    def forget(self, key: str) -> None:
        with self._lock:
            self._retries.pop(key, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)


@dataclass
class Controller:
    name: str
    reconcile: Callable[[str], str]  # key -> DONE | REQUEUE
    queue: WorkQueue = field(default_factory=WorkQueue)
    errors: dict[str, Exception] = field(default_factory=dict)

    def enqueue(self, key: str) -> None:
        self.queue.add(key)

    def step(self) -> bool:
        """Process one item; returns True if work was done.

        Reconcile exceptions are retried (up to queue.max_retries) instead of
        propagating, matching controller-runtime: one bad object must not halt
        every other controller sharing the drain loop. The last error per key
        is kept for inspection/tests."""
        key = self.queue.pop()
        if key is None:
            return False
        try:
            outcome = self.reconcile(key)
        except Exception as e:  # noqa: BLE001 - reconcile errors are retried
            self.errors[key] = e
            self.queue.retry(key)
            return True
        self.errors.pop(key, None)
        if outcome == REQUEUE:
            self.queue.retry(key)
        else:
            self.queue.forget(key)
        return True


@dataclass
class BatchingController(Controller):
    """Controller that drains its whole queue into ONE reconcile call — the
    host-side hook that turns per-key events into the scheduler's batched
    [B,C] device solve. reconcile_batch(keys) returns keys to requeue."""

    reconcile_batch: Optional[Callable[[list[str]], list[str]]] = None

    def step(self) -> bool:
        keys = self.queue.drain()
        if not keys:
            return False
        try:
            requeue = self.reconcile_batch(keys) or []
        except Exception:
            # Per-key error isolation: one bad item must not burn the whole
            # batch's retry budget (the reference retries bindings
            # individually). Fall back to singleton batches; only the
            # offender is retried/dropped.
            for k in keys:
                try:
                    solo_requeue = self.reconcile_batch([k]) or []
                except Exception as e:  # noqa: BLE001
                    self.errors[k] = e
                    self.queue.retry(k)
                    continue
                if k in solo_requeue:
                    self.queue.retry(k)
                else:
                    self.queue.forget(k)
                    self.errors.pop(k, None)
            return True
        for k in keys:
            if k in requeue:
                self.queue.retry(k)
            else:
                self.queue.forget(k)
                self.errors.pop(k, None)
        return True


class Runtime:
    """Holds all controllers; `settle()` drains every queue until quiescent.

    Time-based behaviors (descheduler cadence, toleration windows, graceful
    eviction grace periods) take an explicit `now` from a Clock so tests can
    advance time deterministically (the reference relies on wall clocks +
    RequeueAfter; we make time injectable instead)."""

    def __init__(self, clock: Optional["Clock"] = None):
        self.controllers: list[Controller] = []
        self.clock = clock or Clock()

    def register(self, controller: Controller) -> Controller:
        self.controllers.append(controller)
        return controller

    def settle(self, max_steps: int = 100_000) -> int:
        steps = 0
        progressed = True
        while progressed:
            progressed = False
            for c in self.controllers:
                while c.step():
                    steps += 1
                    progressed = True
                    if steps >= max_steps:
                        raise RuntimeError(
                            f"runtime did not settle in {max_steps} steps; "
                            f"queues: {[(x.name, len(x.queue)) for x in self.controllers]}"
                        )
        return steps


class Clock:
    """Injectable clock; real by default, steppable in tests."""

    def __init__(self, fixed: Optional[float] = None):
        self._fixed = fixed

    def now(self) -> float:
        return self._fixed if self._fixed is not None else time.time()

    def advance(self, seconds: float) -> None:
        if self._fixed is None:
            self._fixed = time.time()
        self._fixed += seconds

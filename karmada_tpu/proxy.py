"""Aggregated-apiserver cluster proxy (U9, reference: pkg/aggregatedapiserver +
pkg/registry/cluster — the `cluster/proxy` subresource: kubectl through the
control plane into a member, with unified-auth impersonation).

`ClusterProxy.request()` is the Connect handler: method + resource path routed
to the member's API surface under an allowed subject.
"""
from __future__ import annotations

from typing import Any, Optional

from .api.unstructured import Unstructured


class ProxyError(Exception):
    pass


class ForbiddenError(ProxyError):
    pass


class ClusterProxy:
    def __init__(self, store, members: dict, unified_auth=None):
        self.store = store
        self.members = members
        self.unified_auth = unified_auth

    def _authorize(self, subject: Optional[dict]) -> None:
        """With unified auth wired, only granted subjects may proxy
        (unifiedauth Q3; no auth configured = open, like a kubeconfig admin)."""
        if self.unified_auth is None or subject is None:
            return
        if subject not in self.unified_auth.subjects:
            raise ForbiddenError(
                f"subject {subject.get('kind')}/{subject.get('name')} is not "
                "granted cluster proxy access"
            )

    def _member(self, cluster: str):
        member = self.members.get(cluster)
        if member is None:
            raise ProxyError(f"cluster {cluster} not found")
        cluster_obj = self.store.try_get("Cluster", cluster)
        if cluster_obj is None:
            raise ProxyError(f"cluster {cluster} not registered")
        return member

    def request(
        self,
        cluster: str,
        method: str,
        api_version: str,
        kind: str,
        name: str = "",
        namespace: str = "",
        body: Optional[dict] = None,
        subject: Optional[dict] = None,
        handler: Optional[Any] = None,
    ) -> Any:
        """The Connect handler (registry/cluster/storage/proxy.go):
        GET/LIST/WATCH/POST/PUT/DELETE against one member through the
        control plane. WATCH takes `handler(event, obj)` and returns an
        unsubscribe callable; current objects replay as ADDED first."""
        self._authorize(subject)
        member = self._member(cluster)
        method = method.upper()
        if method == "WATCH":
            if handler is None:
                raise ProxyError("WATCH requires a handler")
            gvk = f"{api_version}/{kind}"

            def filt(event: str, obj: Any) -> None:
                if namespace and obj.metadata.namespace != namespace:
                    return
                if name and obj.metadata.name != name:
                    return
                handler(event, obj)

            member.store.watch(gvk, filt, replay=True)
            return lambda: member.store.unwatch(gvk, filt)
        if method == "GET":
            if not name:
                return member.store.list(f"{api_version}/{kind}", namespace)
            obj = member.get(api_version, kind, name, namespace)
            if obj is None:
                raise ProxyError(f"{kind} {namespace}/{name} not found in {cluster}")
            return obj
        if method == "LIST":
            return member.store.list(f"{api_version}/{kind}", namespace)
        if method in ("POST", "PUT"):
            if body is None:
                raise ProxyError(f"{method} requires a body")
            return member.apply_manifest(dict(body))
        if method == "DELETE":
            member.delete_manifest(api_version, kind, namespace, name)
            return None
        raise ProxyError(f"unsupported method {method}")

    # kubectl-style conveniences used by karmadactl exec/logs/top
    def logs(self, cluster: str, namespace: str, pod_or_workload: str,
             subject: Optional[dict] = None) -> str:
        self._authorize(subject)
        member = self._member(cluster)
        for gvk in ("apps/v1/Deployment", "apps/v1/StatefulSet", "batch/v1/Job"):
            obj = member.store.try_get(gvk, pod_or_workload, namespace)
            if obj is not None:
                ready = obj.get("status", "readyReplicas", default=0)
                return (
                    f"[{cluster}/{namespace}/{pod_or_workload}] "
                    f"ready={ready} generation={obj.metadata.generation}"
                )
        raise ProxyError(f"workload {namespace}/{pod_or_workload} not found in {cluster}")

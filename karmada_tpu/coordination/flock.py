"""Exclusive data-dir lock: a second server on one --data-dir fails fast.

Two server processes appending the same WAL interleave records and corrupt
each other's snapshots (etcd refuses a locked member directory the same
way). `lock_data_dir` takes a non-blocking `flock(LOCK_EX)` on a lockfile
inside the directory and holds it for the process lifetime — flock locks
die with the holder, so a SIGKILL'd server never leaves a stale lock the
way a pidfile would.
"""
from __future__ import annotations

import os
from typing import IO, Optional

LOCK_FILE = ".lock"


class DataDirLockedError(RuntimeError):
    """Another live process holds the data directory."""


def lock_data_dir(data_dir: str) -> Optional[IO[str]]:
    """Acquire the exclusive lock on `data_dir`, creating it if needed.

    Returns the open lockfile handle — the caller must keep it referenced
    for the life of the process (closing it drops the lock). Raises
    DataDirLockedError when another process holds it. On platforms without
    flock (non-POSIX) returns None and the caller proceeds unlocked."""
    try:
        import fcntl
    except ImportError:  # non-POSIX: no advisory locking available
        return None
    os.makedirs(data_dir, exist_ok=True)
    path = os.path.join(data_dir, LOCK_FILE)
    f = open(path, "a+")
    try:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        f.seek(0)
        holder = f.read().strip() or "unknown pid"
        f.close()
        raise DataDirLockedError(
            f"data dir {data_dir!r} is locked by another running server "
            f"({holder}): two servers on one --data-dir would corrupt the "
            f"WAL. Stop the other process or use a different --data-dir."
        ) from None
    f.seek(0)
    f.truncate()
    f.write(f"pid {os.getpid()}\n")
    f.flush()
    return f

"""Client-side leader election: acquire -> jittered renew loop -> callbacks.

The shape of client-go's leaderelection.LeaderElector (OnStartedLeading /
OnStoppedLeading, renew at ~duration/3, release-on-exit), over either
transport:

- `LocalLeaseClient(coordinator)` — in-process against a ControlPlane's
  LeaseCoordinator (the controller-manager self-election, tests);
- `RemoteStore` — over the serving API (/leases/acquire|renew|release);
  its lease methods match the same protocol.

Safety rules the loop enforces:
- a renew rejected with Conflict (deposed, expired, token mismatch) drops
  leadership IMMEDIATELY and falls back to candidate;
- a leader that cannot REACH the plane steps down once its last successful
  renew is older than the lease duration — it can no longer prove the
  lease is still its own (client-go's RenewDeadline);
- `stop()` releases the lease so a standby takes over without waiting out
  the TTL.

`step()` runs one acquire-or-renew attempt synchronously — daemons call it
once at startup so single-instance deployments lead immediately, and tests
drive elections deterministically with an injected clock.
"""
from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, Optional

from ..api.coordination import DEFAULT_LEASE_DURATION, LEADER_LEASE_NAMESPACE
from ..metrics import (
    leader_election_is_leader,
    leader_election_renew_duration,
    leader_election_transitions,
    timed,
)
from ..store.store import ConflictError, NotFoundError

log = logging.getLogger(__name__)


class LocalLeaseClient:
    """The elector-facing lease protocol over an in-process coordinator."""

    def __init__(self, coordinator):
        self._coordinator = coordinator

    def acquire_lease(self, name: str, identity: str,
                      duration: float = DEFAULT_LEASE_DURATION,
                      namespace: str = LEADER_LEASE_NAMESPACE):
        return self._coordinator.acquire(name, identity, duration,
                                         namespace=namespace)

    def renew_lease(self, name: str, identity: str, token: int,
                    namespace: str = LEADER_LEASE_NAMESPACE):
        return self._coordinator.renew(name, identity, token,
                                       namespace=namespace)

    def release_lease(self, name: str, identity: str, token: int,
                      namespace: str = LEADER_LEASE_NAMESPACE) -> None:
        self._coordinator.release(name, identity, token, namespace=namespace)


def default_identity() -> str:
    """hostname_pid — the reference uses hostname + uniquifier."""
    import os
    import socket

    return f"{socket.gethostname()}_{os.getpid()}"


class Elector:
    def __init__(
        self,
        client,
        name: str,
        identity: str,
        *,
        namespace: str = LEADER_LEASE_NAMESPACE,
        lease_duration: float = DEFAULT_LEASE_DURATION,
        renew_interval: Optional[float] = None,
        retry_interval: Optional[float] = None,
        on_started_leading: Optional[Callable[[int], None]] = None,
        on_stopped_leading: Optional[Callable[[str], None]] = None,
        jitter: float = 0.2,
        monotonic: Callable[[], float] = time.monotonic,
    ):
        self.client = client
        self.name = name
        self.namespace = namespace
        self.identity = identity
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval or lease_duration / 3.0
        self.retry_interval = retry_interval or self.renew_interval
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.jitter = jitter
        self._monotonic = monotonic
        self.is_leader = False
        self.token = 0  # 0 = no fence (not leading, or legacy plane)
        self._last_ok = 0.0  # monotonic stamp of the last proven-held lease
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._legacy_warned = False

    # -- one election round ------------------------------------------------

    def step(self) -> bool:
        """One synchronous acquire-or-renew attempt; returns is_leader."""
        try:
            if self.is_leader:
                with timed(leader_election_renew_duration, lease=self.name):
                    self.client.renew_lease(
                        self.name, self.identity, self.token,
                        namespace=self.namespace,
                    )
                self._last_ok = self._monotonic()
            else:
                lease, acquired = self.client.acquire_lease(
                    self.name, self.identity, self.lease_duration,
                    namespace=self.namespace,
                )
                if acquired:
                    self._last_ok = self._monotonic()
                    self._promote(lease.spec.fencing_token)
        except ConflictError as e:
            self._demote(f"lease lost: {e}")
        except NotFoundError:
            # a plane without /leases routes (pre-coordination daemon):
            # behave like the un-elected legacy topology — lead, unfenced
            if not self._legacy_warned:
                self._legacy_warned = True
                log.warning(
                    "elector %s: control plane has no lease API; assuming "
                    "single-instance leadership (no fencing)", self.name,
                )
            if not self.is_leader:
                self._promote(0)
            self._last_ok = self._monotonic()
        except Exception as e:  # noqa: BLE001 - transport errors
            # cannot prove the lease is still ours; step down once the TTL
            # has certainly elapsed since the last successful renew
            if self.is_leader and (
                self._monotonic() - self._last_ok > self.lease_duration
            ):
                self._demote(f"lease unverifiable past TTL: {e}")
            else:
                log.warning("elector %s: lease call failed: %s", self.name, e)
        return self.is_leader

    def _promote(self, token: int) -> None:
        self.is_leader = True
        self.token = token
        leader_election_is_leader.set(
            1.0, lease=self.name, identity=self.identity
        )
        leader_election_transitions.inc(lease=self.name)
        log.info("elector %s: %s acquired leadership (fencing token %d)",
                 self.name, self.identity, token)
        if self.on_started_leading is not None:
            self.on_started_leading(token)

    def _demote(self, reason: str) -> None:
        if not self.is_leader:
            return
        self.is_leader = False
        self.token = 0
        leader_election_is_leader.set(
            0.0, lease=self.name, identity=self.identity
        )
        log.warning("elector %s: %s stopped leading (%s)",
                    self.name, self.identity, reason)
        if self.on_stopped_leading is not None:
            self.on_stopped_leading(reason)

    # -- background loop ---------------------------------------------------

    def run(self) -> None:
        """Start the renew/acquire loop on a daemon thread."""
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"elector-{self.name}", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            interval = (
                self.renew_interval if self.step() else self.retry_interval
            )
            # jitter desynchronizes a fleet of candidates hammering acquire
            interval *= 1.0 + random.uniform(-self.jitter, self.jitter)
            self._stop.wait(max(interval, 0.05))

    def stop(self, release: bool = True) -> None:
        """Stop the loop; release the lease (if held) so a standby takes
        over immediately instead of waiting out the TTL."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        was_leader, token = self.is_leader, self.token
        self._demote("elector stopped")
        if was_leader and release and token:
            try:
                self.client.release_lease(
                    self.name, self.identity, token, namespace=self.namespace
                )
            except Exception as e:  # noqa: BLE001 - best-effort on exit
                log.warning("elector %s: release failed: %s", self.name, e)

"""Lease-based coordination: CAS acquire/renew and write fencing.

`LeaseCoordinator` is the control-plane half of leader election — the role
etcd's compare-and-swap plays for client-go's resourcelock. All mutations
go through the store's optimistic-concurrency path (`check_rv=True` +
retry), so two daemons racing an acquire resolve to exactly one holder no
matter how their requests interleave; the store's RLock makes each
individual CAS atomic.

Fencing (the Chubby/Kafka "sequencer" pattern): every acquisition mints a
strictly larger `fencing_token` for that lease name. A leader stamps its
mutating requests with its token (`X-Karmada-Fencing` on the wire); once a
standby has taken over, the token advanced, and `check_fence` rejects the
deposed leader's in-flight writes with a Conflict (HTTP 409) — a paused
process resuming after its TTL cannot double-patch placements.

Release clears the holder but keeps the token counter and the lease object
itself: deleting the lease would reset the counter and break monotonicity,
which is the entire safety argument.
"""
from __future__ import annotations

from typing import Optional

from ..api.coordination import (
    DEFAULT_LEASE_DURATION,
    KIND_LEADER_LEASE,
    LEADER_LEASE_NAMESPACE,
    LeaderLease,
    LeaderLeaseSpec,
)
from ..api.meta import ObjectMeta
from ..store.store import ConflictError

_CAS_ATTEMPTS = 16


class StaleLeaseError(ConflictError):
    """Renew/release by a caller that no longer holds the lease."""


class FencingError(ConflictError):
    """A mutating request carried a fencing token older than the lease's
    current one — the caller was deposed; the write must not land."""


class LeaseCoordinator:
    def __init__(self, store, clock=None):
        self.store = store
        self._clock = clock

    def _now(self) -> float:
        if self._clock is not None:
            return self._clock.now()
        import time

        return time.time()

    # -- acquire / renew / release ----------------------------------------

    def acquire(
        self,
        name: str,
        identity: str,
        duration: float = DEFAULT_LEASE_DURATION,
        namespace: str = LEADER_LEASE_NAMESPACE,
    ) -> tuple[LeaderLease, bool]:
        """Try to take (or keep) leadership of `name` as `identity`.

        Returns (lease, acquired). Semantics per attempt:
        - no lease yet            -> create it held by identity (token 1)
        - held by identity, live  -> renew in place (token unchanged)
        - expired or released     -> take over; token += 1; transitions += 1
                                     when the holder actually changed. The
                                     SAME identity re-acquiring its own
                                     expired lease also mints a fresh token:
                                     its old token spent time beyond the TTL
                                     and must fence.
        - held by another, live   -> (current lease, False)
        """
        if not identity:
            raise ValueError("elector identity must be non-empty")
        for _ in range(_CAS_ATTEMPTS):
            lease = self.store.try_get(KIND_LEADER_LEASE, name, namespace)
            now = self._now()
            if lease is None:
                fresh = LeaderLease(
                    metadata=ObjectMeta(name=name, namespace=namespace),
                    spec=LeaderLeaseSpec(
                        holder_identity=identity,
                        lease_duration_seconds=duration,
                        acquire_time=now,
                        renew_time=now,
                        fencing_token=1,
                    ),
                )
                try:
                    return self.store.create(fresh), True
                except ConflictError:
                    continue  # lost the create race: re-read and re-judge
            spec = lease.spec
            expired = lease.expired(now)
            if spec.holder_identity == identity and not expired:
                spec.renew_time = now
                spec.lease_duration_seconds = duration
            elif expired:
                if spec.holder_identity and spec.holder_identity != identity:
                    spec.lease_transitions += 1
                spec.holder_identity = identity
                spec.lease_duration_seconds = duration
                spec.acquire_time = now
                spec.renew_time = now
                spec.fencing_token += 1
            else:
                return lease, False
            try:
                return self.store.update(lease, check_rv=True), True
            except ConflictError:
                continue  # concurrent CAS won: re-read and re-judge
        raise ConflictError(f"lease {namespace}/{name}: CAS contention")

    def renew(
        self,
        name: str,
        identity: str,
        token: int,
        namespace: str = LEADER_LEASE_NAMESPACE,
    ) -> LeaderLease:
        """Extend a held lease. Strict: the caller must still be the holder
        with the CURRENT token, and the lease must not have expired — a
        leader paused past its TTL is forced back through acquire() (which
        mints a fresh token) instead of silently resuming on its old one."""
        for _ in range(_CAS_ATTEMPTS):
            lease = self.store.try_get(KIND_LEADER_LEASE, name, namespace)
            if lease is None:
                raise StaleLeaseError(
                    f"lease {namespace}/{name}: gone (renew by {identity!r})"
                )
            spec = lease.spec
            if spec.holder_identity != identity or spec.fencing_token != token:
                raise StaleLeaseError(
                    f"lease {namespace}/{name}: held by "
                    f"{spec.holder_identity!r} (token {spec.fencing_token}), "
                    f"not {identity!r} (token {token})"
                )
            now = self._now()
            if lease.expired(now):
                raise StaleLeaseError(
                    f"lease {namespace}/{name}: expired "
                    f"{now - spec.renew_time:.1f}s ago; re-acquire required"
                )
            spec.renew_time = now
            try:
                return self.store.update(lease, check_rv=True)
            except ConflictError:
                continue
        raise ConflictError(f"lease {namespace}/{name}: CAS contention")

    def release(
        self,
        name: str,
        identity: str,
        token: int,
        namespace: str = LEADER_LEASE_NAMESPACE,
    ) -> None:
        """Voluntary step-down. Clears the holder (a standby acquires
        immediately instead of waiting out the TTL) but keeps the lease and
        its token counter. A deposed caller's release is a no-op — it must
        not clobber the new leader."""
        for _ in range(_CAS_ATTEMPTS):
            lease = self.store.try_get(KIND_LEADER_LEASE, name, namespace)
            if lease is None:
                return
            spec = lease.spec
            if spec.holder_identity != identity or spec.fencing_token != token:
                return
            spec.holder_identity = ""
            try:
                self.store.update(lease, check_rv=True)
                return
            except ConflictError:
                continue

    # -- fencing -----------------------------------------------------------

    def check_fence(
        self,
        name: str,
        token: int,
        namespace: str = LEADER_LEASE_NAMESPACE,
    ) -> None:
        """Raise FencingError unless `token` is the lease's current fencing
        token. Called by the apiserver on mutating requests that carry
        X-Karmada-Fencing, BEFORE the store operation runs."""
        lease = self.store.try_get(KIND_LEADER_LEASE, name, namespace)
        if lease is None:
            raise FencingError(
                f"fencing: lease {namespace}/{name} does not exist "
                f"(write carried token {token})"
            )
        current = lease.spec.fencing_token
        if token != current:
            raise FencingError(
                f"fencing: stale token {token} for lease {namespace}/{name} "
                f"(current {current}, holder {lease.spec.holder_identity!r})"
            )

    # -- status ------------------------------------------------------------

    def elections(self) -> list[LeaderLease]:
        """Every election lease, all namespaces (the `karmadactl elections`
        view)."""
        return self.store.list(KIND_LEADER_LEASE)


def parse_fence_header(value: str) -> Optional[tuple[str, str, int]]:
    """Parse "namespace/name:token" (namespace optional) into
    (namespace, name, token); None for an empty header, ValueError for a
    malformed one."""
    value = value.strip()
    if not value:
        return None
    ref, sep, tok = value.rpartition(":")
    if not sep or not ref:
        raise ValueError(f"malformed fencing header {value!r}")
    ns, _, name = ref.rpartition("/")
    return ns or LEADER_LEASE_NAMESPACE, name, int(tok)


def format_fence_header(name: str, token: int,
                        namespace: str = LEADER_LEASE_NAMESPACE) -> str:
    return f"{namespace}/{name}:{token}"

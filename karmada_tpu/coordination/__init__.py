"""Coordination plane: leader election, write fencing, data-dir locking.

The robustness layer for the daemonized topology (VERDICT r5 missing #1):
every role that mutates shared state elects exactly one active instance
per identity over a `LeaderLease` (api/coordination.py), stamps its writes
with the lease's fencing token, and fails fast on a locked --data-dir.
See docs/HA.md for the deployment topology.
"""
from ..api.coordination import (  # noqa: F401 - re-exports
    DEFAULT_LEASE_DURATION,
    KIND_LEADER_LEASE,
    LEADER_LEASE_NAMESPACE,
    LEASE_CONTROLLER_MANAGER,
    LEASE_DESCHEDULER,
    LEASE_SCHEDULER,
    LeaderLease,
    LeaderLeaseSpec,
    agent_lease_name,
)
from .elector import Elector, LocalLeaseClient, default_identity  # noqa: F401
from .flock import DataDirLockedError, lock_data_dir  # noqa: F401
from .lease import (  # noqa: F401
    FencingError,
    LeaseCoordinator,
    StaleLeaseError,
    format_fence_header,
    parse_fence_header,
)

"""Scheduler-estimator daemon: `python -m karmada_tpu.estimator ...`.

The reference's cmd/scheduler-estimator binary: a gRPC server a stock
karmada-scheduler's --enable-scheduler-estimator fan-out calls on the
reference's own method paths (estimator/service.py). One process serves
one or more member clusters' estimators.

Node inventory per cluster comes from either a JSON file (an out-of-band
exporter's dump: [{"name", "labels", "allocatable": {"cpu": ..}, ...}])
or a synthetic fleet (--nodes) for benches/demos. mTLS flags mirror the
reference's grpcconnection ServerConfig.

Example:
    python -m karmada_tpu.estimator --cluster m1 --nodes 500 --port 10352
    python -m karmada_tpu.estimator --cluster m1=nodes-m1.json --cluster m2=nodes-m2.json
"""
from __future__ import annotations

import argparse
import json
import sys


def _nodes_from_file(path: str):
    from ..models.nodes import NodeSpec

    with open(path) as f:
        docs = json.load(f)
    if not isinstance(docs, list):
        raise SystemExit(f"{path}: expected a JSON list of node objects")
    return [
        NodeSpec(
            name=d.get("name", f"node-{i}"),
            labels=dict(d.get("labels") or {}),
            allocatable={k: float(v) for k, v in
                         (d.get("allocatable") or {}).items()},
            allowed_pods=int(d.get("allowedPods", 110)),
        )
        for i, d in enumerate(docs)
    ]


def _synthetic_nodes(n: int):
    from ..models.nodes import NodeSpec

    GiB = 1024.0**3
    return [
        NodeSpec(
            name=f"node-{i}",
            allocatable={"cpu": 16.0, "memory": 64 * GiB,
                         "ephemeral-storage": 500 * GiB},
            allowed_pods=110,
        )
        for i in range(n)
    ]


def main() -> None:
    ap = argparse.ArgumentParser(prog="python -m karmada_tpu.estimator")
    ap.add_argument("--cluster", action="append", required=True,
                    metavar="NAME[=NODES.json]",
                    help="serve this member cluster; repeatable. With "
                         "=FILE, nodes load from the JSON dump; otherwise "
                         "--nodes synthetic nodes are used")
    ap.add_argument("--nodes", type=int, default=100,
                    help="synthetic node count for clusters without a file")
    ap.add_argument("--port", type=int, default=0,
                    help="gRPC port (0 = ephemeral, printed on stdout)")
    ap.add_argument("--cert-file", default="")
    ap.add_argument("--key-file", default="")
    ap.add_argument("--client-ca-file", default="",
                    help="require client certs signed by this CA (mTLS)")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform for the estimate kernels; 'cpu' "
                         "(default) never touches an ambient TPU tunnel")
    args = ap.parse_args()

    if args.platform == "cpu":
        from ..testing.cpumesh import force_cpu_mesh

        force_cpu_mesh(1)
    elif args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from .accurate import AccurateEstimator
    from .grpcconnection import ServerConfig
    from .service import EstimatorServer

    estimators = {}
    for spec in args.cluster:
        name, sep, path = spec.partition("=")
        nodes = _nodes_from_file(path) if sep else _synthetic_nodes(args.nodes)
        estimators[name] = AccurateEstimator(nodes)
        print(f"cluster {name}: {len(nodes)} nodes", flush=True)

    config = None
    if args.cert_file or args.key_file or args.client_ca_file:
        if not (args.cert_file and args.key_file):
            raise SystemExit(
                "TLS needs BOTH --cert-file and --key-file "
                "(--client-ca-file additionally enables mTLS)"
            )
        config = ServerConfig(
            cert_file=args.cert_file, key_file=args.key_file,
            client_auth_ca_file=args.client_ca_file,
        )
    srv = EstimatorServer(estimators, port=args.port, server_config=config)
    port = srv.start()
    mode = "insecure"
    if config is not None and config.secure:
        mode = "mTLS" if config.client_auth_ca_file else "TLS"
    print(f"karmada-tpu scheduler-estimator serving on :{port} ({mode})",
          flush=True)
    try:
        import time

        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    sys.exit(main())

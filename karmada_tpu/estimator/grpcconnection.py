"""gRPC channel security for the estimator seam (U3).

Parity with pkg/util/grpcconnection/config.go:34-160: ServerConfig /
ClientConfig carry cert file paths; empty cert config means insecure (the
reference returns a bare grpc.Server / insecure credentials the same way).
The D2 seam is the advertised Go-interop boundary, so the knobs mirror the
reference flags one-to-one:

  server: --grpc-auth-cert-file/--grpc-auth-key-file
          --grpc-client-ca-file (+ InsecureSkipClientVerify)
  client: --grpc-client-cert-file/--grpc-client-key-file
          --grpc-server-ca-file (+ InsecureSkipServerVerify)

grpc-python notes: require_client_auth maps RequireAndVerifyClientCert;
python's ssl_channel_credentials has no InsecureSkipVerify — skipping server
verification entirely is not offered by grpc-python, so
InsecureSkipServerVerify=True without a CA falls back to the system trust
store (documented divergence; the reference marks that mode test-only).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import grpc


def _read(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


@dataclass
class ServerConfig:
    """config.go:34-49 ServerConfig."""

    cert_file: str = ""
    key_file: str = ""
    client_auth_ca_file: str = ""
    insecure_skip_client_verify: bool = False

    @property
    def secure(self) -> bool:
        return bool(self.cert_file and self.key_file)

    def bind(self, server: grpc.Server, address: str) -> int:
        """NewServer (config.go:71-103) + port bind: plain when no cert pair
        is configured; TLS otherwise; mutual TLS when a client CA is given
        and InsecureSkipClientVerify is off."""
        if not self.secure:
            return server.add_insecure_port(address)
        root = _read(self.client_auth_ca_file) if self.client_auth_ca_file else None
        creds = grpc.ssl_server_credentials(
            [(_read(self.key_file), _read(self.cert_file))],
            root_certificates=root,
            require_client_auth=bool(root) and not self.insecure_skip_client_verify,
        )
        return server.add_secure_port(address, creds)


@dataclass
class ClientConfig:
    """config.go:51-69 ClientConfig."""

    server_auth_ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    insecure_skip_server_verify: bool = False

    @property
    def secure(self) -> bool:
        return bool(self.server_auth_ca_file) or self.insecure_skip_server_verify

    def channel(self, address: str) -> grpc.Channel:
        """DialWithTimeOut's credential selection (config.go:105-136):
        insecure when neither a server CA nor skip-verify is set; TLS with
        the CA as root otherwise; mutual TLS when a client cert pair is
        also configured."""
        if not self.secure:
            return grpc.insecure_channel(address)
        root = _read(self.server_auth_ca_file) if self.server_auth_ca_file else None
        key = _read(self.key_file) if self.key_file else None
        chain = _read(self.cert_file) if self.cert_file else None
        creds = grpc.ssl_channel_credentials(
            root_certificates=root, private_key=key, certificate_chain=chain
        )
        return grpc.secure_channel(address, creds)


INSECURE_CLIENT = ClientConfig()
INSECURE_SERVER = ServerConfig()

"""Estimator-server plugin framework + the ResourceQuota estimate plugin.

Parity with pkg/estimator/server/framework (EST4 gap from round 2):
- `RunEstimateReplicasPlugins` min-merges every plugin's answer into the
  node-level estimate (interface.go:31-41, runtime/framework.go:115-134);
- the ResourceQuota plugin bounds the answer by the namespace's free quota
  (hard − used over compute resources), honoring the PriorityClass scope
  and gated by the ResourceQuotaEstimate feature
  (plugins/resourcequota/resourcequota.go:47-180).

The result/merge state machine is kept bit-for-bit: Error > Unschedulable >
all-NoOperation > Success (interface.go:118-152); plugin answers count into
the min only on Success or Unschedulable (runtime/framework.go:126-131).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional, Protocol, Sequence

from ..api.meta import Resources
from ..api.work import ReplicaRequirements
from ..features import RESOURCE_QUOTA_ESTIMATE, FeatureGates, default_gates
from ..models.fleet import to_int_units

MAX_INT32 = 2**31 - 1

# Result codes (framework/interface.go:84-97)
SUCCESS = 0
UNSCHEDULABLE = 1
NO_OPERATION = 2
ERROR = 3

_CODE_NAMES = ["Success", "Unschedulable", "Nooperation", "Error"]


@dataclass
class Result:
    code: int = SUCCESS
    reasons: list[str] = field(default_factory=list)
    err: Optional[str] = None

    @property
    def is_success(self) -> bool:
        return self.code == SUCCESS

    @property
    def is_unschedulable(self) -> bool:
        return self.code == UNSCHEDULABLE

    @property
    def is_noop(self) -> bool:
        return self.code == NO_OPERATION

    def name(self) -> str:
        return _CODE_NAMES[self.code]


def merge_results(results: dict[str, Result]) -> Result:
    """PluginToResult.Merge (interface.go:118-152)."""
    if not results:
        return Result(NO_OPERATION, ["plugin results are empty"])
    final = Result(SUCCESS)
    has_unschedulable = False
    all_noop = True
    for r in results.values():
        if r.code == ERROR:
            final.err = r.err
        elif r.code == UNSCHEDULABLE:
            has_unschedulable = True
        if r.code != NO_OPERATION:
            all_noop = False
        final.reasons.extend(r.reasons)
    if final.err is not None:
        final.code = ERROR
    elif has_unschedulable:
        final.code = UNSCHEDULABLE
    elif all_noop:
        final.code = NO_OPERATION
    else:
        final.code = SUCCESS
    return final


class EstimateReplicasPlugin(Protocol):
    name: str

    def estimate(
        self, requirements: Optional[ReplicaRequirements]
    ) -> tuple[int, Result]:
        """Replica bound for the given requirements; MAX_INT32 = no opinion."""
        ...


class EstimatorFramework:
    """The configured plugin set of one estimator server
    (runtime/framework.go frameworkImpl)."""

    def __init__(self, plugins: Sequence[EstimateReplicasPlugin] = ()):
        self.plugins = list(plugins)

    def run_estimate_replicas_plugins(
        self, requirements: Optional[ReplicaRequirements]
    ) -> tuple[int, Result]:
        replica = MAX_INT32
        results: dict[str, Result] = {}
        for pl in self.plugins:
            pl_replica, ret = pl.estimate(requirements)
            if (ret.is_success or ret.is_unschedulable) and pl_replica < replica:
                replica = pl_replica
            results[pl.name] = ret
        return replica, merge_results(results)


# -- ResourceQuota plugin ----------------------------------------------------

# quota scope names (corev1.ResourceQuotaScope*)
SCOPE_TERMINATING = "Terminating"
SCOPE_NOT_TERMINATING = "NotTerminating"
SCOPE_BEST_EFFORT = "BestEffort"
SCOPE_NOT_BEST_EFFORT = "NotBestEffort"
SCOPE_PRIORITY_CLASS = "PriorityClass"
SCOPE_CROSS_NS_AFFINITY = "CrossNamespacePodAffinity"

SCOPE_OP_IN = "In"
SCOPE_OP_NOT_IN = "NotIn"
SCOPE_OP_EXISTS = "Exists"
SCOPE_OP_DOES_NOT_EXIST = "DoesNotExist"

_REQUESTS_PREFIX = "requests."
_LIMITS_PREFIX = "limits."

# computeResources (resourcequota.go:306-313): only these quota rows bound
# pod replicas; storage/object-count rows are skipped
_COMPUTE_RESOURCES = frozenset(
    ["cpu", "memory", "requests.cpu", "requests.memory",
     "limits.cpu", "limits.memory"]
)


@dataclass
class ScopedSelectorRequirement:
    scope_name: str
    operator: str = SCOPE_OP_EXISTS
    values: list[str] = field(default_factory=list)


@dataclass
class ResourceQuota:
    """Member-side v1.ResourceQuota slice: the spec scopes + status
    hard/used rows the estimator consumes (keys are quota resource names
    like "requests.cpu"; values in the float units of api.meta.Resources)."""

    name: str
    namespace: str
    scopes: list[str] = field(default_factory=list)
    scope_selector: list[ScopedSelectorRequirement] = field(default_factory=list)
    hard: Resources = field(default_factory=dict)
    used: Resources = field(default_factory=dict)


def _matches_scope(sel: ScopedSelectorRequirement, priority_class: str) -> bool:
    """matchesScope (resourcequota.go:240-265): only the PriorityClass scope
    can match; every other scope rejects the quota."""
    if sel.scope_name != SCOPE_PRIORITY_CLASS:
        return False
    if sel.operator == SCOPE_OP_EXISTS:
        return bool(priority_class)
    if sel.operator == SCOPE_OP_IN:
        return priority_class in sel.values
    if sel.operator == SCOPE_OP_NOT_IN:
        return bool(priority_class) and priority_class not in sel.values
    if sel.operator == SCOPE_OP_DOES_NOT_EXIST:
        return not priority_class
    return False


def _is_extended_resource(name: str) -> bool:
    """corev1helper.IsExtendedResourceName: a domain-qualified name outside
    the kubernetes.io native namespace (nvidia.com/gpu etc.). Prefixed-native
    domains like node.kubernetes.io/* CONTAIN kubernetes.io and are not
    extended (IsPrefixedNativeResource uses a contains check)."""
    return (
        "/" in name
        and "kubernetes.io/" not in name
        and not name.startswith(_REQUESTS_PREFIX)
        and not name.startswith(_LIMITS_PREFIX)
    )


def _matches_compute(rname: str) -> bool:
    """matchingResources (resourcequota.go:306-335): the fixed compute set
    plus extended resources, bare or requests./limits.-prefixed."""
    if rname in _COMPUTE_RESOURCES:
        return True
    base = rname
    for pref in (_REQUESTS_PREFIX, _LIMITS_PREFIX):
        if rname.startswith(pref):
            base = rname[len(pref):]
            break
    return _is_extended_resource(base)


def _free_resources(rq: ResourceQuota) -> dict[str, float]:
    """calculateFreeResources (resourcequota.go:185-215): hard − used over
    matching compute/extended rows; limits.* skipped; requests.* merged with
    the bare name (requests.cpu == cpu)."""
    free: dict[str, float] = {}
    for rname in rq.hard:
        if not _matches_compute(rname):
            continue
        if rname.startswith(_LIMITS_PREFIX):
            continue
        if rname not in rq.used:
            continue
        trimmed = rname[len(_REQUESTS_PREFIX):] if rname.startswith(
            _REQUESTS_PREFIX) else rname
        free[trimmed] = rq.hard[rname] - rq.used[rname]
    return free


def _max_divided(free: dict[str, float], request: Resources) -> int:
    """util.Resource.MaxDivided over the quota-covered request rows
    (resourcequota.go:157-180): resources absent from the quota don't
    constrain; integer division in canonical units."""
    allowed = 2**63 - 1
    for rname, req in request.items():
        if rname not in free:
            continue
        req_units = to_int_units(rname, req)
        if req_units <= 0:
            continue
        free_units = max(to_int_units(rname, free[rname]), 0)
        allowed = min(allowed, free_units // req_units)
    return allowed


class ResourceQuotaEstimatorPlugin:
    """plugins/resourcequota (resourcequota.go:47-135). `quota_lister` is a
    callable namespace -> quotas (the informer-lister seam; tests and the
    member store both fit)."""

    name = "ResourceQuotaEstimator"

    def __init__(
        self,
        quota_lister: Callable[[str], Sequence[ResourceQuota]],
        gates: Optional[FeatureGates] = None,
    ):
        self.quota_lister = quota_lister
        self.gates = gates or default_gates

    @property
    def enabled(self) -> bool:
        return self.gates.enabled(RESOURCE_QUOTA_ESTIMATE)

    def estimate(
        self, requirements: Optional[ReplicaRequirements]
    ) -> tuple[int, Result]:
        replica = MAX_INT32
        if not self.enabled:
            return replica, Result(
                NO_OPERATION, [f"{self.name} is disabled"]
            )
        namespace = requirements.namespace if requirements else ""
        priority_class = (
            requirements.priority_class_name if requirements else ""
        )
        request = requirements.resource_request if requirements else {}
        for rq in self.quota_lister(namespace):
            # scope selection (getScopeSelectorsFromQuota): spec.scopes as
            # Exists requirements + explicit scopeSelector expressions; the
            # FIRST matching selector with compute rows binds the quota
            selectors = [
                ScopedSelectorRequirement(scope_name=s) for s in rq.scopes
            ] + list(rq.scope_selector)
            # NOTE (parity): an UNscoped quota yields no selectors and thus
            # never constrains — the reference evaluator only ever matches
            # the PriorityClass scope (resourcequota.go:132-151, 240-265)
            for sel in selectors:
                if not _matches_scope(sel, priority_class):
                    continue
                free = _free_resources(rq)
                if not free:
                    continue
                allowed = _max_divided(free, request)
                if allowed > MAX_INT32:
                    break  # avoid the int32 overflow (resourcequota.go:171)
                if allowed < replica:
                    replica = int(allowed)
                break
        if replica == MAX_INT32:
            return replica, Result(
                NO_OPERATION,
                [f"{self.name} has no operation on input replicaRequirements"],
            )
        if replica == 0:
            return replica, Result(
                UNSCHEDULABLE, [f"zero replica is estimated by {self.name}"]
            )
        return replica, Result(SUCCESS)

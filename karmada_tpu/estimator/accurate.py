"""Accurate estimator core: the karmada-scheduler-estimator daemon's brain.

Parity with pkg/estimator/server (EST4): per member cluster, a node/pod
snapshot answers MaxAvailableReplicas = Σ over affinity+toleration-feasible
nodes of min((allocatable−requested)/request, free pod slots)
(estimate.go:36-112), and GetUnschedulableReplicas counts replicas pending
longer than a threshold (server.go:228). The node math runs as a jitted array
kernel (ops/estimate.py); node-affinity string matching is host-evaluated with
per-claim dedup.
"""
from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np

from ..api.meta import Resources
from ..api.work import ReplicaRequirements
from ..models.nodes import (
    NodeArrays,
    NodeEncoder,
    NodeSpec,
    node_claim_matches,
    tolerations_cover_node_taints,
)
from ..native import first_fit_place

_I32_MAX = np.int64(2**31 - 1)
_estimator_uid = iter(range(1, 2**62))


def _np_cluster_estimate(alloc, requested, pod_count, allowed_pods, request, node_ok):
    """numpy twin of ops/estimate.cluster_estimate — bit-identical integer
    math (estimate.go:59-112), kept host-side for member-local calls."""
    rest = alloc - requested  # i64[N,R]
    has_req = request > 0  # [B,R]
    req = np.maximum(request, 1)[:, None, :]  # [B,1,R]
    per_res = np.where(has_req[:, None, :], rest[None, :, :] // req, _I32_MAX)
    per_node = per_res.min(-1)  # [B,N]
    pods_left = np.maximum(allowed_pods - pod_count.astype(np.int64), 0)
    per_node = np.minimum(per_node, pods_left[None, :])
    per_node = np.clip(per_node, 0, _I32_MAX)
    per_node = np.where(node_ok, per_node, 0)
    return np.clip(per_node.sum(-1), 0, _I32_MAX).astype(np.int32)


class AccurateEstimator:
    """One member cluster's estimator. Also serves as the member's pod
    placement simulator (the test fixture role — SURVEY §4 synthetic fleet)."""

    def __init__(self, nodes: Sequence[NodeSpec], clock=None, framework=None):
        self.clock = clock  # injectable (tests advance time deterministically)
        # EstimateReplicas plugin framework (estimate.go:78-101): plugin
        # answers min-merge into the node-level sum; Unschedulable short-
        # circuits to 0. None = no plugins configured.
        self.framework = framework
        self.encoder = NodeEncoder()
        self.specs = list(nodes)
        self.arrays: NodeArrays = self.encoder.encode(self.specs)
        # pods placed per workload key: list of (node_idx, count, req_vec)
        self._pods: dict[str, list[tuple[int, int, np.ndarray]]] = {}
        self._node_ok_cache: dict[str, np.ndarray] = {}
        self._pending: dict[str, tuple[int, float]] = {}  # key -> (count, since)
        # bumped on every node-state mutation (pod placement); lets fleet-
        # level caches (client.MemberEstimators) know when to re-snapshot.
        # uid is a process-monotonic identity: id() recycles after GC, which
        # would let a rejoined cluster alias a stale fleet snapshot.
        self.version = 0
        self.uid = next(_estimator_uid)

    # -- estimation (the gRPC answer) -------------------------------------

    def _node_ok(self, requirements: Optional[ReplicaRequirements]) -> np.ndarray:
        """Claim → node feasibility mask, deduped per distinct claim (most
        rows in a batch share a claim — typically None); node labels/taints
        are fixed at construction so the cache never invalidates."""
        claim = requirements.node_claim if requirements else None
        key = repr(claim)
        cached = self._node_ok_cache.get(key)
        if cached is not None:
            return cached
        N = self.arrays.n_nodes
        ok = np.ones(N, bool)
        tolerations = claim.tolerations if claim else []
        for i, spec in enumerate(self.specs):
            if not node_claim_matches(claim, spec.labels):
                ok[i] = False
            elif not tolerations_cover_node_taints(tolerations, spec.taints):
                ok[i] = False
        self._node_ok_cache[key] = ok
        return ok

    def max_available_replicas(self, requirements: Optional[ReplicaRequirements]) -> int:
        return self.max_available_replicas_batch([requirements])[0]

    def max_available_replicas_batch(
        self, requirements_list: Sequence[Optional[ReplicaRequirements]]
    ) -> list[int]:
        """All B requests against this cluster's nodes in ONE kernel call —
        the batched form the scheduler's per-round estimate sweep uses."""
        if self.arrays.n_nodes == 0:
            return [0] * len(requirements_list)
        request = np.stack(
            [
                self.encoder.request_vector(r.resource_request if r else {})
                for r in requirements_list
            ]
        )
        node_ok = np.stack([self._node_ok(r) for r in requirements_list])
        # Member-side compute runs in plain numpy ON PURPOSE: the estimator
        # daemon lives on the member cluster's CPUs in the reference
        # deployment, and these [B, N, R] slabs are tiny — routing each call
        # through jax would ship them to the control plane's accelerator
        # (per-call dispatch + tunnel RTT dominated BASELINE config 3 by
        # ~8x). The device-resident form of this math is the scheduler-side
        # capacity matrix (ops/estimate.fleet_estimate + general_estimate).
        out = _np_cluster_estimate(
            self.arrays.alloc,
            self.arrays.requested,
            self.arrays.pod_count,
            self.arrays.allowed_pods,
            request,
            node_ok,
        )
        res = [int(v) for v in out]
        if self.framework is not None:
            # RunEstimateReplicasPlugins min-merge (estimate.go:78-101):
            # Unschedulable => 0; Success bounds the node sum; NoOperation
            # leaves it untouched; plugin errors surface the node answer
            # (the reference returns an error — our gRPC layer maps that to
            # the -1 discard sentinel upstream, so keep the node sum here)
            for i, req in enumerate(requirements_list):
                replicas, ret = self.framework.run_estimate_replicas_plugins(req)
                if ret.is_unschedulable:
                    res[i] = 0
                elif ret.is_success and replicas < res[i]:
                    res[i] = replicas
        return res

    def get_unschedulable_replicas(
        self, workload_key: str, threshold_seconds: float, now: Optional[float] = None
    ) -> int:
        """Replicas of the workload pending longer than the threshold
        (server.go:228: owner-chained pods Pending > threshold)."""
        pending = self._pending.get(workload_key)
        if pending is None:
            return 0
        count, since = pending
        if now is None:
            now = self.clock.now() if self.clock else time.time()
        return count if now - since >= threshold_seconds else 0

    # -- pod placement simulation (member-side "kubelet/scheduler") -------

    def place(
        self,
        workload_key: str,
        replicas: int,
        request: Resources,
        now: Optional[float] = None,
        claim=None,
    ) -> int:
        """Greedy first-fit of `replicas` pods over claim-feasible nodes
        (taints/selector respected, like the real kube-scheduler would);
        returns how many fit. The remainder is recorded as pending (feeds
        GetUnschedulableReplicas); the pending-since timestamp survives
        re-placement so the unschedulable threshold can actually elapse."""
        prev_pending = self._pending.get(workload_key)
        self.unplace(workload_key)
        req = self.encoder.request_vector(request)
        a = self.arrays
        # claim feasibility reuses the deduped node_ok cache; the greedy scan
        # itself runs in the native kernel (numpy fallback inside)
        fake_req = ReplicaRequirements(node_claim=claim) if claim else None
        node_ok = self._node_ok(fake_req)
        n_placed, fits = first_fit_place(
            a.alloc, a.requested, a.pod_count, a.allowed_pods,
            node_ok, req.astype(np.int64), replicas,
        )
        self.version += 1
        placed = [
            (i, int(fits[i]), req) for i in np.nonzero(fits)[0]
        ]
        remaining = replicas - n_placed
        self._pods[workload_key] = placed
        if remaining > 0:
            if now is None:
                now = self.clock.now() if self.clock else time.time()
            since = prev_pending[1] if prev_pending else now
            self._pending[workload_key] = (remaining, since)
        else:
            self._pending.pop(workload_key, None)
        return replicas - remaining

    def unplace(self, workload_key: str) -> None:
        removed = self._pods.pop(workload_key, [])
        for i, count, req in removed:
            self.arrays.requested[i] -= req * count
            self.arrays.pod_count[i] -= count
        if removed:
            self.version += 1
        self._pending.pop(workload_key, None)

"""Estimator client registry + min-merge.

Parity with pkg/estimator/client (EST1/EST3): a pluggable registry of
ReplicaEstimator / UnschedulableReplicaEstimator implementations; the
scheduler takes the MIN across estimators per cluster, with
UnauthenticReplica = -1 meaning "discard my answer" (interface.go:27-55,
core/util.go:72-100). The in-process MemberEstimators adapter plays the role
of the per-cluster gRPC connection cache (accurate.go:34-68); the real gRPC
client lives in service.py.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Protocol, Sequence

import numpy as np

from ..api.work import ReplicaRequirements, ResourceBinding

UNAUTHENTIC_REPLICA = -1


def _fleet_rows_kernel(alloc, requested, pod_count, allowed_pods, cluster_id,
                       claimless_ok, request, num_clusters: int):
    """jitted fleet-wide estimate. claimless_ok is the per-node feasibility
    of a claim-free pod (node taints still exclude nodes — the same
    tolerations_cover_node_taints([]) filter the per-cluster path applies)."""
    import jax

    global _fleet_rows_jit
    if _fleet_rows_jit is None:
        import jax.numpy as jnp

        from ..ops.estimate import fleet_estimate

        def body(alloc, requested, pod_count, allowed_pods, cluster_id,
                 claimless_ok, request, num_clusters: int):
            node_ok = jnp.broadcast_to(
                claimless_ok[None, :], (request.shape[0], alloc.shape[0])
            )
            return fleet_estimate(
                alloc, requested, pod_count, allowed_pods, cluster_id,
                request, node_ok, num_clusters,
            )

        _fleet_rows_jit = jax.jit(body, static_argnames=("num_clusters",))
    return _fleet_rows_jit(
        alloc, requested, pod_count, allowed_pods, cluster_id, claimless_ok,
        request, num_clusters=num_clusters,
    )


_fleet_rows_jit = None


class SchedulerEstimatorRegistry(Protocol):
    """What the scheduler daemon requires of an estimator registry — typed,
    so degraded-mode detection reads a declared attribute instead of
    duck-probing with getattr (the probe silently went dark whenever a
    registry forgot the attribute).

    `last_sweep_open` lists the member clusters whose circuit breaker was
    OPEN during the most recent `batch_estimates` sweep (empty while the
    fleet is healthy — the default for a registry that never degrades).
    Under the pipelined round executor each chunk-shard sweep resets it, so
    callers snapshot it immediately after the sweep that produced it."""

    last_sweep_open: list[str]

    def batch_estimates(
        self,
        bindings: Sequence["ResourceBinding"],
        clusters: Sequence[str],
    ) -> Optional[np.ndarray]:
        ...

    def sweep_round(self):
        """Context manager scoping N chunk-shard sweeps as ONE round (the
        pipelined executor's prefetch stage) — see EstimatorRegistry."""
        ...


class ReplicaEstimator(Protocol):
    def max_available_replicas(
        self,
        clusters: Sequence[str],
        requirements: Optional[ReplicaRequirements],
        replicas: int,
    ) -> list[int]:
        """Per-cluster estimate; UNAUTHENTIC_REPLICA to discard."""
        ...


class UnschedulableReplicaEstimator(Protocol):
    def get_unschedulable_replicas(
        self, clusters: Sequence[str], resource, threshold_seconds: float
    ) -> list[int]:
        """resource: api/work.ObjectReference (full GVK+name — the gRPC wire
        needs apiVersion for a stock Go server to resolve the workload)."""
        ...


def parse_estimator_flags(specs: list[str]) -> dict[str, str]:
    """`--estimator CLUSTER=HOST:PORT` values (repeatable daemon flag) →
    address map. Register the resulting GrpcSchedulerEstimator ONCE in a
    registry — the client fans out per cluster itself via the address map;
    per-cluster registration would multiply every sweep's RPC load."""
    addresses: dict[str, str] = {}
    for spec in specs:
        cluster, sep, addr = spec.partition("=")
        if not sep or not cluster or not addr:
            raise SystemExit(f"--estimator {spec!r}: want CLUSTER=HOST:PORT")
        addresses[cluster] = addr
    return addresses


class EstimatorRegistry:
    """replicaEstimators / unschedulableReplicaEstimators registries
    (interface.go:38-55). The GeneralEstimator equivalent is fused into the
    device kernel; registered estimators contribute the extra min-merge term."""

    def __init__(self, breakers=None, staleness=None) -> None:
        """`breakers`: faults.BreakerRegistry shared with the estimator
        clients — when a member's breaker is open, its column of the [B,C]
        answer matrix is served from the staleness cache (last fresh answers
        decayed by the pure-array penalty, faults/staleness.py) instead of
        the discard sentinel, so degraded rounds keep steering away from the
        dark member without stalling the batched solve."""
        self.replica_estimators: dict[str, ReplicaEstimator] = {}
        self.unschedulable_estimators: dict[str, UnschedulableReplicaEstimator] = {}
        self.breakers = breakers
        if staleness is None and breakers is not None:
            from ..faults.staleness import StalenessTracker

            staleness = StalenessTracker()
        self.staleness = staleness
        # per-sweep degraded bookkeeping (consumed by the scheduler daemon's
        # karmada_degraded_rounds_total accounting)
        self.last_sweep_open: list[str] = []
        self.last_sweep_stale: list[str] = []

    def sweep_round(self):
        """Scope a pipelined round's N chunk-shard sweeps as ONE logical
        sweep for the staleness cache: fresh snapshots merge across the
        round's chunks and each open member's staleness epoch advances once
        per round — a chunked degraded round then serves exactly the
        penalized columns a whole-round sweep would (docs/ROBUSTNESS.md)."""
        from contextlib import contextmanager

        @contextmanager
        def scope():
            if self.staleness is None:
                yield
                return
            self.staleness.begin_round()
            try:
                yield
            finally:
                self.staleness.end_round()

        return scope()

    def register_replica_estimator(self, name: str, est: ReplicaEstimator) -> None:
        self.replica_estimators[name] = est

    def register_unschedulable_estimator(
        self, name: str, est: UnschedulableReplicaEstimator
    ) -> None:
        self.unschedulable_estimators[name] = est

    def batch_estimates(
        self,
        bindings: Sequence[ResourceBinding],
        clusters: Sequence[str],
    ) -> Optional[np.ndarray]:
        """extra_avail i32[B,C]: min across registered estimators, -1 where
        every estimator discarded (the device kernel min-merges this with the
        GeneralEstimator column)."""
        self.last_sweep_open = []
        self.last_sweep_stale = []
        if not self.replica_estimators:
            return None
        from ..models.batch import AGGREGATED, DYNAMIC_WEIGHT, strategy_code
        from ..sched.spread import should_ignore_spread_constraint

        B, C = len(bindings), len(clusters)
        # Only dynamic strategies consume availability; Duplicated/static
        # rows must not pay B×C estimator calls (core/util.go:63-70 skips
        # non-workloads; the reference only estimates inside dynamic assign).
        dyn_rows = [
            b
            for b, rb in enumerate(bindings)
            if strategy_code(rb.spec.placement, rb.spec.replicas)
            in (DYNAMIC_WEIGHT, AGGREGATED)
            # spread-constrained rows need availability for group scoring
            # regardless of strategy (group_clusters.go:143-330) — unless the
            # constraint is statically ignored (select_clusters.go:63-77)
            or (
                rb.spec.placement is not None
                and rb.spec.placement.spread_constraints
                and rb.spec.replicas > 0
                and not should_ignore_spread_constraint(rb.spec.placement)
            )
        ]
        if not dyn_rows:
            return None
        merged = np.full((B, C), np.iinfo(np.int32).max, np.int64)
        authentic = np.zeros((B, C), bool)

        def merge_row(b: int, res) -> None:
            row = np.asarray(res, np.int64)
            ok = row != UNAUTHENTIC_REPLICA
            merged[b] = np.where(ok, np.minimum(merged[b], row), merged[b])
            authentic[b] |= ok

        reqs = [bindings[b].spec.replica_requirements for b in dyn_rows]
        for est in self.replica_estimators.values():
            rows_fn = getattr(est, "max_available_replicas_rows", None)
            if rows_fn is not None:  # batched path: one kernel per cluster
                for b, res in zip(dyn_rows, rows_fn(clusters, reqs)):
                    merge_row(b, res)
            else:
                for b in dyn_rows:
                    merge_row(
                        b,
                        est.max_available_replicas(
                            clusters,
                            bindings[b].spec.replica_requirements,
                            bindings[b].spec.replicas,
                        ),
                    )
        out = np.where(authentic, merged, UNAUTHENTIC_REPLICA).astype(np.int32)
        if self.breakers is not None:
            self._overlay_stale_columns(bindings, clusters, out)
        return out

    def _overlay_stale_columns(self, bindings, clusters, out: np.ndarray) -> None:
        """Degraded-mode column repair: a member whose breaker is OPEN after
        this sweep answered the discard sentinel on its member legs — fold
        the staleness cache's decayed last-fresh answers into its column
        (rows stay in the [B,C] matrix; the round completes in one launch).
        Healthy columns refresh the cache and reset their staleness epoch.

        The fold is a MIN-merge, not an overwrite: other registered
        estimators (e.g. the model-based one) may still be answering live
        for this cluster, and stale member data may only TIGHTEN or fill a
        live bound — a decayed snapshot must never loosen one."""
        # ONE shared tuple per sweep: the staleness snapshots alias it, so
        # the unchanged-binding-set fast path is an identity check
        uids = tuple(rb.metadata.uid for rb in bindings)
        for j, c in enumerate(clusters):
            br = self.breakers.get(c)
            if br is not None and br.is_open:
                self.last_sweep_open.append(c)
                col = self.staleness.fill_stale(c, uids)
                if col is not None:
                    cur = out[:, j]
                    out[:, j] = np.where(
                        cur >= 0,
                        np.where(col >= 0, np.minimum(cur, col), cur),
                        col,
                    )
                    self.last_sweep_stale.append(c)
            elif (out[:, j] != UNAUTHENTIC_REPLICA).any():
                # an all-sentinel column under a CLOSED breaker is a blip
                # (or a row set with nothing to estimate) — never wipe the
                # last-fresh cache for it
                self.staleness.record_fresh(c, uids, out[:, j])

    def min_unschedulable(
        self,
        clusters: Sequence[str],
        resource,
        threshold_seconds: float,
    ) -> list[int]:
        """Min across unschedulable estimators (descheduler/core/helper.go:62-96)."""
        C = len(clusters)
        merged = [np.iinfo(np.int32).max] * C
        authentic = [False] * C
        for est in self.unschedulable_estimators.values():
            res = est.get_unschedulable_replicas(clusters, resource, threshold_seconds)
            for i, v in enumerate(res):
                if v != UNAUTHENTIC_REPLICA:
                    merged[i] = min(merged[i], v)
                    authentic[i] = True
        return [m if a else 0 for m, a in zip(merged, authentic)]


class MemberEstimators:
    """In-process adapter: routes estimator calls to each member's
    AccurateEstimator with concurrent fan-out (accurate.go:139-162's
    goroutine-per-cluster becomes a thread pool; answers for members without
    node state are discarded with the -1 sentinel).

    The per-round batched sweep (`max_available_replicas_rows`) runs as ONE
    device kernel over the whole fleet's concatenated node arrays
    (ops/estimate.fleet_estimate — SURVEY §5's capacity-matrix refresh)
    whenever no row carries a node claim: 1000 per-cluster Python calls
    became the 8.4 s wall of BASELINE config 3. The snapshot is device-
    resident and version-checked against each member's estimator, so steady
    rounds ship only the [B,R] request matrix.

    `max_workers` pins the per-cluster fan-out pool size; the default
    (None) scales with each sweep's actual fan-out width — floor 16, cap
    64, growing as members join (the members dict is usually EMPTY at
    construction time, so boot-time sizing would freeze the pool at the
    floor forever; the old hardcoded 16 starved the pipelined round's
    estimate-prefetch stage on large fleets, serializing hundreds of
    per-cluster legs 16 at a time while the device sat idle). Plumbed
    through the server daemon as `--estimator-workers`."""

    DEFAULT_MIN_WORKERS = 16
    DEFAULT_MAX_WORKERS = 64

    def __init__(self, members: dict, breakers=None,
                 max_workers: Optional[int] = None):
        self.members = members
        self.breakers = breakers  # faults.BreakerRegistry, shared
        if max_workers is not None and max_workers <= 0:
            raise ValueError(f"max_workers={max_workers}: must be positive")
        self.max_workers = max_workers
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pool_width = 0
        # optional sched.shards.fairness.ClusterFairnessBudget: with N
        # in-process shard leaders sweeping concurrently, this caps each
        # member cluster's AGGREGATE estimator concurrency so one hot
        # shard cannot starve its siblings' legs (installed by ShardPlane)
        self.fairness = None
        self._fleet_key = None
        self._fleet_dev = None  # (alloc, requested, pod_count, allowed, cid, claimless_ok)
        self._no_node_cols = None  # bool[C] clusters without node state

    def _pool_for(self, width: int) -> ThreadPoolExecutor:
        """The fan-out pool, (re)sized for a sweep over `width` clusters:
        explicit max_workers pins it; the default grows with the widest
        sweep seen (floor/cap above), replacing the executor only when it
        must widen — only the sweep thread uses it, so the swap is safe."""
        want = self.max_workers or min(
            self.DEFAULT_MAX_WORKERS, max(self.DEFAULT_MIN_WORKERS, width)
        )
        if self._pool is None or want > self._pool_width:
            if self._pool is not None:
                self._pool.shutdown(wait=False)
            self._pool = ThreadPoolExecutor(max_workers=want)
            self._pool_width = want
        return self._pool

    def _estimator_for(self, cluster: str):
        member = self.members.get(cluster)
        return getattr(member, "node_estimator", None) if member else None

    def _guarded(self, cluster: str, fn, sentinel):
        """One member-estimator leg under the unified fault policy: the
        in-process stand-in for the gRPC boundary — breaker admission,
        chaos injection (BOUNDARY_GRPC), typed failure metric, breaker
        feedback. Failures answer `sentinel`, never raise — per-cluster
        error isolation, like the wire client."""
        from .. import faults
        from ..metrics import estimator_rpc_errors

        br = (
            self.breakers.for_member(cluster)
            if self.breakers is not None else None
        )
        if br is not None and not br.allow():
            return sentinel
        from contextlib import nullcontext

        # cross-shard fairness (sched/shards/fairness.py): hold one of the
        # cluster's aggregate concurrency slots for the duration of the leg
        leg = (
            self.fairness.leg(cluster) if self.fairness is not None
            else nullcontext()
        )
        try:
            with leg:
                faults.check(faults.BOUNDARY_GRPC, cluster)
                out = fn()
        except faults.InjectedFault as e:
            estimator_rpc_errors.inc(cluster=cluster, code=e.code)
            if br is not None:
                br.record_failure()
            return sentinel
        except Exception:  # noqa: BLE001 - degrade per cluster, don't fail sweep
            estimator_rpc_errors.inc(cluster=cluster, code="MEMBER_ERROR")
            if br is not None:
                br.record_failure()
            return sentinel
        if br is not None:
            br.record_success()
        return out

    def _guards_engaged(self, clusters) -> bool:
        """True when the per-cluster boundary must be exercised (a fault
        plan with grpc-boundary rules is installed, or any breaker is not
        at rest) — the batched fleet kernel bypasses member boundaries, so
        those sweeps route per-cluster instead. A plan that only targets
        other boundaries (http/apply) leaves the fused one-launch path
        alone: chaos must not change the shape of what it isn't injecting
        into."""
        from .. import faults
        from ..faults.policy import CLOSED

        inj = faults.active()
        if inj is not None and inj.plan.has_boundary(faults.BOUNDARY_GRPC):
            return True
        if self.breakers is None:
            return False
        return any(
            br is not None and br.state != CLOSED
            for br in (self.breakers.get(c) for c in clusters)
        )

    def max_available_replicas(self, clusters, requirements, replicas) -> list[int]:
        def one(cluster: str) -> int:
            est = self._estimator_for(cluster)
            if est is None:
                return UNAUTHENTIC_REPLICA
            return self._guarded(
                cluster,
                lambda: est.max_available_replicas(requirements),
                UNAUTHENTIC_REPLICA,
            )

        return list(self._pool_for(len(clusters)).map(one, clusters))

    def _fleet_snapshot(self, clusters):
        """Concatenated node arrays for the fleet kernel, rebuilt only when
        membership or any estimator's version changes; None when a member's
        estimator runs plugins (their answers aren't expressible as node
        math — those fall back to the per-cluster path)."""
        import jax

        ests = [self._estimator_for(c) for c in clusters]
        if any(e is not None and e.framework is not None for e in ests):
            return None
        key = tuple(
            (c, e.uid, e.version) if e is not None else (c, -1, -1)
            for c, e in zip(clusters, ests)
        )
        if key == self._fleet_key:
            return self._fleet_dev
        allocs, reqs, pods, allowed, cids, oks = [], [], [], [], [], []
        no_node = np.zeros(len(clusters), bool)
        for ci, e in enumerate(ests):
            if e is None:
                no_node[ci] = True
                continue
            a = e.arrays
            if a.n_nodes == 0:
                continue
            allocs.append(a.alloc)
            reqs.append(a.requested)
            pods.append(a.pod_count)
            allowed.append(a.allowed_pods)
            cids.append(np.full(a.n_nodes, ci, np.int32))
            # claim-free node feasibility (taints still filter nodes,
            # exactly like the per-cluster path's _node_ok(None))
            oks.append(e._node_ok(None))
        if not allocs:
            return None
        self._fleet_dev = tuple(
            jax.device_put(np.concatenate(x))
            for x in (allocs, reqs, pods, allowed, cids, oks)
        )
        self._no_node_cols = no_node
        self._fleet_key = key
        return self._fleet_dev

    def max_available_replicas_rows(self, clusters, requirements_list):
        """Batched per-round sweep: [B][C] answers. Clusters without node
        state are discarded via the sentinel."""
        claimless = all(
            r is None or r.node_claim is None for r in requirements_list
        )
        # the fleet kernel fuses every member into one launch, which skips
        # the per-member boundary — with a chaos plan installed or a breaker
        # not at rest, route per-cluster so faults/breakers apply per member
        fleet = (
            self._fleet_snapshot(clusters)
            if claimless and not self._guards_engaged(clusters) else None
        )
        if fleet is not None:
            import jax

            from ..models.nodes import NodeEncoder
            from ..ops.estimate import fleet_estimate

            enc = NodeEncoder()
            B = len(requirements_list)
            Bp = 8
            while Bp < B:
                Bp *= 2
            request = np.zeros((Bp, len(enc.resources)), np.int64)
            for i, r in enumerate(requirements_list):
                request[i] = enc.request_vector(r.resource_request if r else {})
            out = _fleet_rows_kernel(
                *fleet, jax.device_put(request), num_clusters=len(clusters)
            )
            rows = np.asarray(jax.device_get(out))[:B]
            if self._no_node_cols.any():
                rows = np.where(
                    self._no_node_cols[None, :], UNAUTHENTIC_REPLICA, rows
                )
            return rows

        def one(cluster: str) -> list[int]:
            sentinel = [UNAUTHENTIC_REPLICA] * len(requirements_list)
            est = self._estimator_for(cluster)
            if est is None:
                return sentinel
            return self._guarded(
                cluster,
                lambda: est.max_available_replicas_batch(requirements_list),
                sentinel,
            )

        columns = np.asarray(list(self._pool_for(len(clusters)).map(one, clusters)))  # [C,B]
        return columns.T

    def get_unschedulable_replicas(self, clusters, resource, threshold_seconds) -> list[int]:
        key = f"{resource.kind}/{resource.namespace}/{resource.name}"

        def one(cluster: str) -> int:
            est = self._estimator_for(cluster)
            if est is None:
                return UNAUTHENTIC_REPLICA
            return self._guarded(
                cluster,
                lambda: est.get_unschedulable_replicas(key, threshold_seconds),
                UNAUTHENTIC_REPLICA,
            )

        return list(self._pool_for(len(clusters)).map(one, clusters))

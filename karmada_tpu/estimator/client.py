"""Estimator client registry + min-merge.

Parity with pkg/estimator/client (EST1/EST3): a pluggable registry of
ReplicaEstimator / UnschedulableReplicaEstimator implementations; the
scheduler takes the MIN across estimators per cluster, with
UnauthenticReplica = -1 meaning "discard my answer" (interface.go:27-55,
core/util.go:72-100). The in-process MemberEstimators adapter plays the role
of the per-cluster gRPC connection cache (accurate.go:34-68); the real gRPC
client lives in service.py.
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Protocol, Sequence

import numpy as np

from ..api.work import ReplicaRequirements, ResourceBinding

UNAUTHENTIC_REPLICA = -1


class ReplicaEstimator(Protocol):
    def max_available_replicas(
        self,
        clusters: Sequence[str],
        requirements: Optional[ReplicaRequirements],
        replicas: int,
    ) -> list[int]:
        """Per-cluster estimate; UNAUTHENTIC_REPLICA to discard."""
        ...


class UnschedulableReplicaEstimator(Protocol):
    def get_unschedulable_replicas(
        self, clusters: Sequence[str], resource, threshold_seconds: float
    ) -> list[int]:
        """resource: api/work.ObjectReference (full GVK+name — the gRPC wire
        needs apiVersion for a stock Go server to resolve the workload)."""
        ...


class EstimatorRegistry:
    """replicaEstimators / unschedulableReplicaEstimators registries
    (interface.go:38-55). The GeneralEstimator equivalent is fused into the
    device kernel; registered estimators contribute the extra min-merge term."""

    def __init__(self) -> None:
        self.replica_estimators: dict[str, ReplicaEstimator] = {}
        self.unschedulable_estimators: dict[str, UnschedulableReplicaEstimator] = {}

    def register_replica_estimator(self, name: str, est: ReplicaEstimator) -> None:
        self.replica_estimators[name] = est

    def register_unschedulable_estimator(
        self, name: str, est: UnschedulableReplicaEstimator
    ) -> None:
        self.unschedulable_estimators[name] = est

    def batch_estimates(
        self,
        bindings: Sequence[ResourceBinding],
        clusters: Sequence[str],
    ) -> Optional[np.ndarray]:
        """extra_avail i32[B,C]: min across registered estimators, -1 where
        every estimator discarded (the device kernel min-merges this with the
        GeneralEstimator column)."""
        if not self.replica_estimators:
            return None
        from ..models.batch import AGGREGATED, DYNAMIC_WEIGHT, strategy_code
        from ..sched.spread import should_ignore_spread_constraint

        B, C = len(bindings), len(clusters)
        # Only dynamic strategies consume availability; Duplicated/static
        # rows must not pay B×C estimator calls (core/util.go:63-70 skips
        # non-workloads; the reference only estimates inside dynamic assign).
        dyn_rows = [
            b
            for b, rb in enumerate(bindings)
            if strategy_code(rb.spec.placement, rb.spec.replicas)
            in (DYNAMIC_WEIGHT, AGGREGATED)
            # spread-constrained rows need availability for group scoring
            # regardless of strategy (group_clusters.go:143-330) — unless the
            # constraint is statically ignored (select_clusters.go:63-77)
            or (
                rb.spec.placement is not None
                and rb.spec.placement.spread_constraints
                and rb.spec.replicas > 0
                and not should_ignore_spread_constraint(rb.spec.placement)
            )
        ]
        if not dyn_rows:
            return None
        merged = np.full((B, C), np.iinfo(np.int32).max, np.int64)
        authentic = np.zeros((B, C), bool)

        def merge_row(b: int, res) -> None:
            row = np.asarray(res, np.int64)
            ok = row != UNAUTHENTIC_REPLICA
            merged[b] = np.where(ok, np.minimum(merged[b], row), merged[b])
            authentic[b] |= ok

        reqs = [bindings[b].spec.replica_requirements for b in dyn_rows]
        for est in self.replica_estimators.values():
            rows_fn = getattr(est, "max_available_replicas_rows", None)
            if rows_fn is not None:  # batched path: one kernel per cluster
                for b, res in zip(dyn_rows, rows_fn(clusters, reqs)):
                    merge_row(b, res)
            else:
                for b in dyn_rows:
                    merge_row(
                        b,
                        est.max_available_replicas(
                            clusters,
                            bindings[b].spec.replica_requirements,
                            bindings[b].spec.replicas,
                        ),
                    )
        return np.where(authentic, merged, UNAUTHENTIC_REPLICA).astype(np.int32)

    def min_unschedulable(
        self,
        clusters: Sequence[str],
        resource,
        threshold_seconds: float,
    ) -> list[int]:
        """Min across unschedulable estimators (descheduler/core/helper.go:62-96)."""
        C = len(clusters)
        merged = [np.iinfo(np.int32).max] * C
        authentic = [False] * C
        for est in self.unschedulable_estimators.values():
            res = est.get_unschedulable_replicas(clusters, resource, threshold_seconds)
            for i, v in enumerate(res):
                if v != UNAUTHENTIC_REPLICA:
                    merged[i] = min(merged[i], v)
                    authentic[i] = True
        return [m if a else 0 for m, a in zip(merged, authentic)]


class MemberEstimators:
    """In-process adapter: routes estimator calls to each member's
    AccurateEstimator with concurrent fan-out (accurate.go:139-162's
    goroutine-per-cluster becomes a thread pool; answers for members without
    node state are discarded with the -1 sentinel)."""

    def __init__(self, members: dict):
        self.members = members
        self._pool = ThreadPoolExecutor(max_workers=16)

    def _estimator_for(self, cluster: str):
        member = self.members.get(cluster)
        return getattr(member, "node_estimator", None) if member else None

    def max_available_replicas(self, clusters, requirements, replicas) -> list[int]:
        def one(cluster: str) -> int:
            est = self._estimator_for(cluster)
            if est is None:
                return UNAUTHENTIC_REPLICA
            return est.max_available_replicas(requirements)

        return list(self._pool.map(one, clusters))

    def max_available_replicas_rows(self, clusters, requirements_list) -> list[list[int]]:
        """Batched: all B requirements per cluster in one kernel call; returns
        [B][C]. Clusters without node state are discarded via the sentinel."""

        def one(cluster: str) -> list[int]:
            est = self._estimator_for(cluster)
            if est is None:
                return [UNAUTHENTIC_REPLICA] * len(requirements_list)
            return est.max_available_replicas_batch(requirements_list)

        columns = list(self._pool.map(one, clusters))  # [C][B]
        return [[columns[c][b] for c in range(len(clusters))] for b in range(len(requirements_list))]

    def get_unschedulable_replicas(self, clusters, resource, threshold_seconds) -> list[int]:
        key = f"{resource.kind}/{resource.namespace}/{resource.name}"

        def one(cluster: str) -> int:
            est = self._estimator_for(cluster)
            if est is None:
                return UNAUTHENTIC_REPLICA
            return est.get_unschedulable_replicas(key, threshold_seconds)

        return list(self._pool.map(one, clusters))
